"""Fused multi-token decode + speculative serving (PR 8): the on-device
N-step inner loop must stay token-identical to the single-token
reference loop across layouts, depths, and decode modes, while paying
~1/N of its host dispatches."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudist.models.serving import Request, ServeLoop
from tpudist.models.transformer import TransformerConfig, TransformerLM
from tpudist.ops.flash_decode import flash_decode, paged_flash_decode

CFG = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                        num_kv_heads=2, embed_dim=64, max_seq_len=96)
DRAFT_CFG = TransformerConfig(vocab_size=64, num_layers=1, num_heads=2,
                              num_kv_heads=1, embed_dim=32, max_seq_len=96)


@pytest.fixture(scope="module")
def params():
    return TransformerLM(CFG).init(
        jax.random.key(0), jnp.zeros((1, 2), jnp.int32))["params"]


@pytest.fixture(scope="module")
def draft_params():
    return TransformerLM(DRAFT_CFG).init(
        jax.random.key(7), jnp.zeros((1, 2), jnp.int32))["params"]


def _prompt(seed, n):
    return np.asarray(jax.random.randint(
        jax.random.key(seed), (n,), 1, 64, dtype=jnp.int32))


def _reqs():
    return [Request(prompt=_prompt(i, 5 + 3 * i), max_new_tokens=12, rid=i)
            for i in range(4)]


def _serve(params, reqs, **kw):
    loop = ServeLoop(CFG, params, num_slots=2, prefill_chunk=16,
                     stop_tokens=(1,), auto_unstack=False, **kw)
    comps = loop.run(reqs)
    return {c.rid: list(c.tokens) for c in comps}, loop


@pytest.fixture(scope="module")
def reference(params):
    """The single-token loop: one host dispatch per generated token."""
    got, _ = _serve(params, _reqs(), steps_per_sync=1, pipeline_depth=1,
                    decode_attention="dense")
    return got


class TestFusedExactMatch:
    @pytest.mark.parametrize("depth", [1, 2])
    @pytest.mark.parametrize("attn,layout", [
        ("dense", "dense"), ("flash", "dense"),
        ("flash", "paged"), ("dense", "paged")])
    def test_matches_single_token_loop(self, params, reference, depth,
                                       attn, layout):
        kw = dict(steps_per_sync=8, pipeline_depth=depth,
                  decode_attention=attn)
        if layout == "paged":
            kw.update(cache_layout="paged", kv_block_size=16)
        got, loop = _serve(params, _reqs(), **kw)
        assert got == reference
        if loop.pool is not None:
            assert loop.pool.used_blocks == 0
            loop.pool.check()

    def test_fewer_dispatches(self, params):
        """The amortization itself: a fused segment serves the whole
        batch's tokens in ~tokens/steps_per_sync host dispatches."""
        loop = ServeLoop(CFG, params, num_slots=2, prefill_chunk=16,
                         stop_tokens=(1,), auto_unstack=False,
                         steps_per_sync=8, pipeline_depth=2,
                         decode_attention="flash")
        # the obs counter is registry-global (shared across loops in
        # this process) — diff around the run
        before = loop._obs_dispatches.value()
        comps = loop.run(_reqs())
        n_tokens = sum(len(c.tokens) for c in comps)
        n_disp = loop._obs_dispatches.value() - before
        # 4 requests x 12 tokens through 2 slots at N=8: a handful of
        # dispatches (admission waves add a few), never one per token
        assert n_disp <= n_tokens / 4, (n_disp, n_tokens)

    def test_mid_segment_eos(self, params, reference):
        """Requests whose stop token lands mid-segment (not at an N
        boundary) finalize with identical tokens — the in-graph freeze +
        host slice drop everything past the stop."""
        got, _ = _serve(params, _reqs(), steps_per_sync=16,
                        pipeline_depth=2, decode_attention="flash")
        assert got == reference

    def test_tight_pool_reservation(self, params):
        """A pool sized exactly to the concurrent footprint: lanes run
        their reservation to the cap mid-segment, freeze in-graph at
        budget end, and the queued request admits after the refund —
        with exact tokens and a fully drained pool."""
        reqs = [Request(prompt=_prompt(i, 6), max_new_tokens=20, rid=i)
                for i in range(3)]
        want, _ = _serve(params, [Request(prompt=_prompt(i, 6),
                                          max_new_tokens=20, rid=i)
                                  for i in range(3)],
                         steps_per_sync=1, pipeline_depth=1,
                         decode_attention="dense")
        # 2 slots x ceil(26/8)=4 blocks == the whole 8-block pool
        got, loop = _serve(params, reqs, steps_per_sync=16,
                           pipeline_depth=2, decode_attention="flash",
                           cache_layout="paged", kv_block_size=8,
                           kv_num_blocks=8)
        assert got == want
        assert loop.pool.used_blocks == 0
        loop.pool.check()


class TestDeadlineClamp:
    def _state(self, deadline):
        return [{"req": Request(prompt=_prompt(0, 4), max_new_tokens=30,
                                rid=0, deadline_s=deadline),
                 "seq": 0, "tokens": [], "pending_first": False}]

    def test_clamps_to_slack(self, params):
        loop = ServeLoop(CFG, params, num_slots=1, steps_per_sync=32,
                         auto_unstack=False, decode_attention="dense")
        t = [0.0]
        loop._clock = lambda: t[0]
        loop._step_ema = 1.0                      # 1 s/token, forced
        assert loop._plan_steps(self._state(10.0)) == 10
        t[0] = 9.5
        assert loop._plan_steps(self._state(10.0)) == 1
        # no deadline in flight -> full segments
        assert loop._plan_steps(self._state(None)) == 32
        # no EMA yet -> full segments (first dispatch measures it)
        loop._step_ema = None
        assert loop._plan_steps(self._state(0.5)) == 32

    def test_timeout_precision(self, params):
        """With the clamp, a deadline expiring early in a long segment
        is honored within ~a segment of ONE token, not steps_per_sync:
        the killed request keeps at most a couple of tokens."""
        loop = ServeLoop(CFG, params, num_slots=1, steps_per_sync=32,
                         auto_unstack=False, decode_attention="flash",
                         pipeline_depth=1)
        t = [0.0]
        loop._clock = lambda: t[0]
        loop._step_ema = 1.0                      # 1 s/token, forced

        orig = loop._segment

        def ticking_segment(*a):
            out = orig(*a)
            t[0] += float(np.asarray(a[7]))       # n_steps seconds
            return out

        loop._segment = ticking_segment
        [c] = loop.run([Request(prompt=_prompt(3, 5), max_new_tokens=30,
                                rid="d", deadline_s=4.0)])
        assert c.reason == "timeout"
        # 1 token/s against a 4 s deadline: ~4 tokens, never the 30 a
        # full unclamped 32-step segment would have produced
        assert len(c.tokens) <= 6


class TestSpeculativeServe:
    @pytest.mark.parametrize("kw", [
        dict(pipeline_depth=1, decode_attention="dense", num_draft=3),
        dict(pipeline_depth=2, decode_attention="dense",
             num_draft="adaptive", spec_ladder=(2, 4)),
        dict(pipeline_depth=2, decode_attention="flash", num_draft=3),
        dict(pipeline_depth=2, decode_attention="flash", num_draft=3,
             cache_layout="paged", kv_block_size=16),
    ], ids=["dense-k3", "dense-adaptive", "flash-k3", "paged-k3"])
    def test_greedy_exact_match(self, params, draft_params, reference, kw):
        got, loop = _serve(params, _reqs(), steps_per_sync=8,
                           decode_mode="speculative", draft_cfg=DRAFT_CFG,
                           draft_params=draft_params, **kw)
        assert got == reference
        if loop.pool is not None:
            assert loop.pool.used_blocks == 0
            loop.pool.check()

    def test_obs_and_policy_updates(self, params, draft_params):
        got, loop = _serve(params, _reqs(), steps_per_sync=8,
                           pipeline_depth=2, decode_attention="dense",
                           decode_mode="speculative", draft_cfg=DRAFT_CFG,
                           draft_params=draft_params,
                           num_draft="adaptive", spec_ladder=(2, 4))
        assert loop._obs_dispatches.value() > 0
        assert loop._obs_spec_k.value() in (2, 4)
        assert 0.0 <= loop._obs_spec_accept.value() <= 1.0
        assert loop._spec_policy.rounds_seen > 0

    def test_headroom_validation(self, params, draft_params):
        loop = ServeLoop(CFG, params, num_slots=1, auto_unstack=False,
                         decode_attention="dense",
                         decode_mode="speculative", draft_cfg=DRAFT_CFG,
                         draft_params=draft_params, num_draft=8)
        # prompt + max_new + k - 1 = 60 + 30 + 7 = 97 > 96
        with pytest.raises(ValueError, match="speculative serving"):
            loop._validate(Request(prompt=_prompt(0, 60),
                                   max_new_tokens=30))

    def test_requires_draft(self, params):
        with pytest.raises(ValueError, match="draft_cfg"):
            ServeLoop(CFG, params, num_slots=1, auto_unstack=False,
                      decode_mode="speculative")


class TestMultiQueryDecodeKernels:
    """flash_decode / paged_flash_decode with s_q > 1 (the verify
    chunk): per-query side visibility must match s_q independent calls."""

    def _setup(self, b=2, h=4, h_kv=2, d=8, s_cache=32, cap=8):
        ks = jax.random.split(jax.random.key(11), 5)
        flat = h_kv * d
        q = jax.random.normal(ks[0], (b, 3, h, d), jnp.float32)
        k_cache = jax.random.normal(ks[1], (b, s_cache, flat), jnp.float32)
        v_cache = jax.random.normal(ks[2], (b, s_cache, flat), jnp.float32)
        side_k = jax.random.normal(ks[3], (b, cap, flat), jnp.float32)
        side_v = jax.random.normal(ks[4], (b, cap, flat), jnp.float32)
        lens = jnp.array([5, 9], jnp.int32)
        return q, k_cache, v_cache, side_k, side_v, lens, h_kv

    def test_dense_multi_query_matches_per_token(self):
        q, kc, vc, sk, sv, lens, h_kv = self._setup()
        side_len = 6   # AFTER all 3 writes: queries see 4, 5, 6 side slots
        got = flash_decode(q, kc, vc, lens, side_k=sk, side_v=sv,
                           side_len=side_len, packed_kv_heads=h_kv,
                           interpret=True)
        for j in range(3):
            want = flash_decode(q[:, j:j + 1], kc, vc, lens, side_k=sk,
                                side_v=sv, side_len=side_len - (2 - j),
                                packed_kv_heads=h_kv, interpret=True)
            np.testing.assert_allclose(np.asarray(got[:, j:j + 1]),
                                       np.asarray(want), rtol=2e-5,
                                       atol=2e-5)

    def test_multi_query_requires_side(self):
        q, kc, vc, *_ , lens, h_kv = self._setup()
        with pytest.raises(ValueError, match="side buffers"):
            flash_decode(q, kc, vc, lens, packed_kv_heads=h_kv,
                         interpret=True)

    def test_paged_multi_query_matches_per_token(self):
        b, h, h_kv, d, bs = 2, 4, 2, 8, 8
        flat = h_kv * d
        m = 4                                     # blocks per slot
        ks = jax.random.split(jax.random.key(13), 5)
        q = jax.random.normal(ks[0], (b, 3, h, d), jnp.float32)
        pool_k = jax.random.normal(ks[1], (b * m + 1, bs, flat))
        pool_v = jax.random.normal(ks[2], (b * m + 1, bs, flat))
        table = jnp.arange(b * m, dtype=jnp.int32).reshape(b, m)
        side_k = jax.random.normal(ks[3], (b, 8, flat))
        side_v = jax.random.normal(ks[4], (b, 8, flat))
        lens = jnp.array([5, 9], jnp.int32)
        side_len = 5
        got = paged_flash_decode(q, pool_k, pool_v, table, lens,
                                 side_k=side_k, side_v=side_v,
                                 side_len=side_len, packed_kv_heads=h_kv,
                                 interpret=True)
        for j in range(3):
            want = paged_flash_decode(
                q[:, j:j + 1], pool_k, pool_v, table, lens, side_k=side_k,
                side_v=side_v, side_len=side_len - (2 - j),
                packed_kv_heads=h_kv, interpret=True)
            np.testing.assert_allclose(np.asarray(got[:, j:j + 1]),
                                       np.asarray(want), rtol=2e-5,
                                       atol=2e-5)
