"""Continuous-batching serve loop: token parity with the fixed rollouts,
slot reuse, mixed lengths, stop semantics (round-3 verdict item 3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudist.models.generate import greedy_generate
from tpudist.models.serving import Completion, Request, ServeLoop
from tpudist.models.transformer import TransformerConfig, TransformerLM

CFG = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                        num_kv_heads=2, embed_dim=64, max_seq_len=96)


@pytest.fixture(scope="module")
def params():
    return TransformerLM(CFG).init(
        jax.random.key(0), jnp.zeros((1, 2), jnp.int32))["params"]


def _prompt(seed, n):
    return np.asarray(jax.random.randint(jax.random.key(seed), (n,), 0, 64))


def _want(params, prompt, n, **kw):
    out = greedy_generate(CFG, params, jnp.asarray(prompt)[None, :], n, **kw)
    return np.asarray(out)[0, len(prompt):]


class TestParity:
    @pytest.mark.parametrize("attn", ["dense", "flash"])
    def test_single_request_matches_greedy(self, params, attn):
        loop = ServeLoop(CFG, params, num_slots=2, steps_per_sync=5,
                         decode_attention=attn, prefill_chunk=8)
        [c] = loop.run([Request(_prompt(1, 7), 17, rid="a")])
        assert c.rid == "a" and c.reason == "length"
        np.testing.assert_array_equal(c.tokens, _want(params, c.prompt, 17))

    def test_mixed_lengths_and_slot_reuse(self, params):
        """5 requests with different prompt lengths/budgets through 2
        slots: queueing, mid-flight admission into freed slots, and every
        request's tokens still bit-match its own dedicated greedy
        rollout."""
        reqs = [Request(_prompt(10 + i, 3 + 5 * i), 6 + 3 * i, rid=i)
                for i in range(5)]
        loop = ServeLoop(CFG, params, num_slots=2, steps_per_sync=4,
                         decode_attention="flash", prefill_chunk=8)
        comps = loop.run(reqs)
        assert sorted(c.rid for c in comps) == [0, 1, 2, 3, 4]
        for c in comps:
            assert c.reason == "length"
            np.testing.assert_array_equal(
                c.tokens, _want(params, c.prompt, 6 + 3 * c.rid),
                err_msg=f"request {c.rid}")

    def test_independent_of_batch_company(self, params):
        """A request's tokens must not depend on WHICH requests share the
        slots (per-row cache isolation): same request served alone and
        in company yields identical tokens."""
        req = Request(_prompt(33, 9), 12, rid="x")
        alone = ServeLoop(CFG, params, num_slots=1, steps_per_sync=6,
                          prefill_chunk=8, decode_attention="flash")
        [ca] = alone.run([Request(_prompt(33, 9), 12, rid="x")])
        crowd = ServeLoop(CFG, params, num_slots=3, steps_per_sync=6,
                          prefill_chunk=8, decode_attention="flash")
        comps = crowd.run([Request(_prompt(40, 5), 20, rid="other1"),
                           req,
                           Request(_prompt(41, 14), 7, rid="other2")])
        cx = next(c for c in comps if c.rid == "x")
        np.testing.assert_array_equal(cx.tokens, ca.tokens)


class TestStopAndBudget:
    def test_stop_token_completion(self, params):
        prompt = _prompt(5, 6)
        ref = greedy_generate(CFG, params, jnp.asarray(prompt)[None, :],
                              30, stop_tokens=(7,))
        ref_tokens, ref_len = np.asarray(ref[0])[0], int(ref[1][0])
        loop = ServeLoop(CFG, params, num_slots=2, steps_per_sync=4,
                         prefill_chunk=8, stop_tokens=(7,))
        [c] = loop.run([Request(prompt, 30, rid=0)])
        gen = ref_tokens[len(prompt):len(prompt) + ref_len]
        if ref_len < 30:  # the reference hit the stop token
            assert c.reason == "stop"
            np.testing.assert_array_equal(c.tokens, gen)
        else:
            assert c.reason == "length"

    def test_budget_one_completes_at_prefill(self, params):
        loop = ServeLoop(CFG, params, num_slots=1, steps_per_sync=4,
                         prefill_chunk=8)
        [c] = loop.run([Request(_prompt(9, 4), 1, rid=0)])
        assert c.reason == "length" and c.tokens.shape == (1,)
        np.testing.assert_array_equal(c.tokens, _want(params, c.prompt, 1))

    def test_request_validation(self, params):
        loop = ServeLoop(CFG, params, num_slots=1)
        with pytest.raises(ValueError, match="max_seq_len"):
            loop.run([Request(_prompt(1, 90), 20)])
        with pytest.raises(ValueError, match="non-empty"):
            loop.run([Request(np.zeros((0,), np.int32), 5)])
        with pytest.raises(ValueError, match="num_slots"):
            ServeLoop(CFG, params, num_slots=0)


class TestSampling:
    def test_sampled_runs_and_respects_budget(self, params):
        loop = ServeLoop(CFG, params, num_slots=2, steps_per_sync=4,
                         prefill_chunk=8, temperature=0.9,
                         key=jax.random.key(3))
        comps = loop.run([Request(_prompt(20, 4), 9, rid=0),
                          Request(_prompt(21, 11), 5, rid=1)])
        by = {c.rid: c for c in comps}
        assert by[0].tokens.shape == (9,) and by[1].tokens.shape == (5,)
        assert all(int(t) < 64 for c in comps for t in c.tokens)


class TestScannedCheckpoint:
    def test_auto_unstack(self, params):
        import dataclasses

        from tpudist.models import stack_layer_params

        scfg = dataclasses.replace(CFG, scan_layers=True)
        stacked = stack_layer_params(params, CFG.num_layers)
        loop = ServeLoop(scfg, stacked, num_slots=1, steps_per_sync=4,
                         prefill_chunk=8)
        [c] = loop.run([Request(_prompt(2, 5), 8, rid=0)])
        np.testing.assert_array_equal(c.tokens, _want(params, c.prompt, 8))


class TestPadCapRegression:
    def test_prompt_near_cache_end_with_nondividing_chunk(self, params):
        """Review repro: prefill_chunk not dividing max_seq_len and a
        prompt near the cache end — the uncapped pad used to clamp the
        final chunk's write backwards and corrupt real prompt KV."""
        prompt = _prompt(50, 92)  # Lp would be 100 > max_seq_len 96
        loop = ServeLoop(CFG, params, num_slots=1, steps_per_sync=4,
                         prefill_chunk=10)
        [c] = loop.run([Request(prompt, 3, rid=0)])
        np.testing.assert_array_equal(c.tokens, _want(params, prompt, 3))

    def test_bad_request_rejected_before_any_decode(self, params):
        """One malformed request fails run() up front — completed work is
        never silently discarded mid-run."""
        loop = ServeLoop(CFG, params, num_slots=1, steps_per_sync=4,
                         prefill_chunk=8)
        with pytest.raises(ValueError, match="max_seq_len"):
            loop.run([Request(_prompt(1, 4), 8, rid="good"),
                      Request(_prompt(2, 90), 20, rid="bad")])
        # the loop is still usable and state is clean
        [c] = loop.run([Request(_prompt(1, 4), 8, rid="good")])
        np.testing.assert_array_equal(c.tokens, _want(params, c.prompt, 8))


class TestPipelinedDispatch:
    def test_depth_validation(self, params):
        with pytest.raises(ValueError, match="pipeline_depth"):
            ServeLoop(CFG, params, num_slots=1, pipeline_depth=0)

    def test_pipelined_exact_match_mixed_workload(self, params):
        """The staleness contract must not cost a token: the pipelined
        loop (depths 2 and 3) is byte-identical — tokens, finish reasons,
        finish ORDER — to the synchronous loop (depth 1) on a mixed
        prompt-length / stop-token workload with queueing and mid-flight
        slot reuse.  Same instance across depths: shared executables, so
        any divergence is host-scheduling, not numerics."""
        reqs = [Request(_prompt(10 + i, 3 + 5 * i), 25, rid=i)
                for i in range(6)]
        loop = ServeLoop(CFG, params, num_slots=2, steps_per_sync=4,
                         decode_attention="flash", prefill_chunk=8,
                         stop_tokens=(7, 13))

        def sig(comps):
            return [(c.rid, tuple(c.tokens.tolist()), c.reason)
                    for c in comps]

        loop.pipeline_depth = 1
        sync = sig(loop.run(reqs))
        # the workload exercises BOTH finish paths under pipelining
        assert {r for _, _, r in sync} == {"stop", "length"}
        assert sorted(r for r, _, _ in sync) == list(range(6))
        for depth in (2, 3):
            loop.pipeline_depth = depth
            assert sig(loop.run(reqs)) == sync, f"depth {depth} diverged"

    def test_default_depth_is_pipelined(self, params):
        loop = ServeLoop(CFG, params, num_slots=1)
        assert loop.pipeline_depth == 2

    def test_host_wait_recorded(self, params):
        """serve/host_wait must tick on a pipelined run (the fetch time
        the loop actually paid) and serve/pipeline_depth must be live."""
        from tpudist import obs

        loop = ServeLoop(CFG, params, num_slots=2, steps_per_sync=4,
                         prefill_chunk=8)
        before = obs.snapshot()["histograms"].get(
            "serve/host_wait", {}).get("count", 0)
        loop.run([Request(_prompt(60, 5), 9, rid=0),
                  Request(_prompt(61, 8), 6, rid=1)])
        snap = obs.snapshot()
        assert snap["histograms"]["serve/host_wait"]["count"] > before
        assert "serve/pipeline_depth" in snap["gauges"]


class TestPagedCache:
    """cache_layout='paged': the block-pool KV cache must be a pure
    LAYOUT change — token-identical to dense — while its HBM scales with
    reserved tokens and the pool drains back to free."""

    def _sig(self, comps):
        return [(c.rid, tuple(c.tokens.tolist()), c.reason) for c in comps]

    @pytest.mark.parametrize("depth", [1, 2])
    @pytest.mark.parametrize("attn", ["dense", "flash"])
    def test_paged_token_identical_to_dense(self, params, attn, depth):
        """The acceptance bar: paged greedy output is TOKEN-IDENTICAL to
        the dense layout at pipeline depths 1 and 2, on a mixed-length
        workload with queueing, stops, and slot reuse."""
        reqs = [Request(_prompt(70 + i, 3 + 5 * i), 20, rid=i)
                for i in range(5)]
        dense = ServeLoop(CFG, params, num_slots=2, steps_per_sync=4,
                          decode_attention=attn, prefill_chunk=8,
                          stop_tokens=(7, 13), pipeline_depth=depth)
        want = self._sig(dense.run(reqs))
        paged = ServeLoop(CFG, params, num_slots=2, steps_per_sync=4,
                          decode_attention=attn, prefill_chunk=8,
                          stop_tokens=(7, 13), pipeline_depth=depth,
                          cache_layout="paged", kv_block_size=16)
        assert self._sig(paged.run(reqs)) == want
        paged.pool.check()
        assert paged.pool.free_blocks == paged.pool.num_blocks

    def test_small_pool_queues_instead_of_oom(self, params):
        """A pool sized for ~one request at a time must still serve the
        whole workload (capacity gate queues, FIFO) and match dense."""
        reqs = [Request(_prompt(80 + i, 6), 10, rid=i) for i in range(4)]
        dense = ServeLoop(CFG, params, num_slots=2, steps_per_sync=4,
                          decode_attention="dense", prefill_chunk=8)
        want = {c.rid: tuple(c.tokens.tolist()) for c in dense.run(reqs)}
        # 2 blocks of 16 = 32 tokens: fits one request's 16-token
        # reservation (6 + 10), never two slots' worth at once
        paged = ServeLoop(CFG, params, num_slots=2, steps_per_sync=4,
                          decode_attention="dense", prefill_chunk=8,
                          cache_layout="paged", kv_block_size=16,
                          kv_num_blocks=2)
        got = {c.rid: tuple(c.tokens.tolist()) for c in paged.run(reqs)}
        assert got == want
        paged.pool.check()
        assert paged.pool.free_blocks == 2

    def test_paged_hbm_smaller_than_dense(self, params):
        """The point of the PR: at equal slot count, a right-sized pool's
        KV bytes are a fraction of the dense layout's B x S buffers."""
        def kv_bytes(loop):
            total = 0

            def walk(node):
                nonlocal total
                if not isinstance(node, dict):
                    return
                for k, v in node.items():
                    if k in ("cached_key", "cached_value", "paged_key",
                             "paged_value"):
                        total += v.size * v.dtype.itemsize
                    elif isinstance(v, dict):
                        walk(v)

            walk(loop.cache)
            return total

        dense = ServeLoop(CFG, params, num_slots=4, steps_per_sync=4,
                          decode_attention="dense")
        paged = ServeLoop(CFG, params, num_slots=4, steps_per_sync=4,
                          decode_attention="dense", cache_layout="paged",
                          kv_block_size=16, kv_num_blocks=6)
        assert kv_bytes(paged) < kv_bytes(dense) / 2

    def test_paged_validation(self, params):
        with pytest.raises(ValueError, match="cache_layout"):
            ServeLoop(CFG, params, num_slots=1, cache_layout="sparse")
        with pytest.raises(ValueError, match="block_size"):
            ServeLoop(CFG, params, num_slots=1, cache_layout="paged",
                      kv_block_size=12)
        import dataclasses
        wcfg = dataclasses.replace(CFG, attention_window=32)
        with pytest.raises(ValueError, match="sliding-window"):
            ServeLoop(wcfg, params, num_slots=1, cache_layout="paged")
        # a request whose reservation can NEVER fit the pool fails fast
        loop = ServeLoop(CFG, params, num_slots=1, cache_layout="paged",
                         kv_block_size=16, kv_num_blocks=2)
        with pytest.raises(ValueError, match="pool capacity"):
            loop.run([Request(_prompt(1, 40), 20)])

    def test_obs_gauges_live(self, params):
        from tpudist import obs

        loop = ServeLoop(CFG, params, num_slots=2, steps_per_sync=4,
                         prefill_chunk=8, cache_layout="paged",
                         kv_block_size=16)
        loop.run([Request(_prompt(90, 5), 8, rid=0)])
        g = obs.snapshot()["gauges"]
        assert g["serve/kv_blocks_used"]["value"] == 0          # drained
        assert g["serve/kv_blocks_free"]["value"] == loop.pool.num_blocks
        assert "serve/kv_frag" in g


class TestPromptDtypeValidation:
    """Regression: a float prompt used to silently truncate through
    _admit's np.asarray(prompt, np.int32) cast."""

    def test_float_prompt_rejected(self, params):
        loop = ServeLoop(CFG, params, num_slots=1)
        with pytest.raises(ValueError, match="integer token ids"):
            loop.run([Request(np.array([3.7, 5.2]), 4)])

    def test_integer_dtypes_accepted(self, params):
        loop = ServeLoop(CFG, params, num_slots=1, steps_per_sync=4,
                         prefill_chunk=8)
        for dt in (np.int32, np.int64, np.uint8):
            [c] = loop.run([Request(np.array([3, 5, 9], dt), 4, rid=dt)])
            np.testing.assert_array_equal(
                c.tokens, _want(params, c.prompt, 4))


class TestAdmissionControl:
    """ISSUE 6 satellites: bounded queue with load shedding and
    per-request deadlines that refund their KV reservation."""

    def test_max_queue_sheds_newest_as_rejected(self, params):
        import time

        from tpudist import obs

        before = obs.snapshot()["counters"].get(
            "serve/rejected", {}).get("value", 0)
        loop = ServeLoop(CFG, params, num_slots=1, steps_per_sync=4,
                         prefill_chunk=8, max_queue=1)
        reqs = [Request(_prompt(70 + i, 4), 6, rid=f"q{i}")
                for i in range(5)]
        comps = {c.rid: c for c in loop.run(reqs)}
        assert len(comps) == 5  # shed requests still get a Completion
        # q0 fills the slot, q1 holds the one queue place; the NEWEST
        # arrivals are shed (earlier arrivals keep their FIFO place)
        served = {r for r, c in comps.items() if c.reason == "length"}
        shed = {r for r, c in comps.items() if c.reason == "rejected"}
        assert served == {"q0", "q1"} and shed == {"q2", "q3", "q4"}
        for rid in shed:
            assert comps[rid].tokens.shape == (0,)
        for rid in served:
            np.testing.assert_array_equal(
                comps[rid].tokens, _want(params, comps[rid].prompt, 6))
        after = obs.snapshot()["counters"]["serve/rejected"]["value"]
        assert after - before == 3

    def test_max_queue_validation(self, params):
        with pytest.raises(ValueError, match="max_queue"):
            ServeLoop(CFG, params, num_slots=1, max_queue=-1)

    def test_expired_queued_deadline_times_out(self, params):
        import time

        loop = ServeLoop(CFG, params, num_slots=1, steps_per_sync=4,
                         prefill_chunk=8)
        comps = {c.rid: c for c in loop.run([
            Request(_prompt(1, 4), 6, rid="late",
                    deadline_s=time.time() - 100.0),
            Request(_prompt(2, 5), 6, rid="ok"),
        ])}
        assert comps["late"].reason == "timeout"
        assert comps["late"].tokens.shape == (0,)
        assert comps["ok"].reason == "length"
        np.testing.assert_array_equal(
            comps["ok"].tokens, _want(params, comps["ok"].prompt, 6))

    def test_inflight_deadline_refunds_paged_pool(self, params):
        """A request whose deadline passes MID-DECODE must finalize
        reason='timeout' with the tokens it produced so far, and its KV
        blocks must come back to the pool even with segments still in
        flight (the zombie-slot path) — then the loop must serve a fresh
        request exactly (requeue-safe finalize)."""
        import time

        loop = ServeLoop(CFG, params, num_slots=2, steps_per_sync=4,
                         prefill_chunk=8, cache_layout="paged",
                         kv_block_size=16)
        # deterministic expiry: the swappable clock jumps past the
        # deadline on its 9th read — after admission and a few
        # dispatched segments, before the 40-token budget completes
        t0 = time.time()
        calls = [0]

        def clock():
            calls[0] += 1
            return t0 + (1000.0 if calls[0] > 8 else 0.0)

        loop._clock = clock
        [c] = loop.run([Request(_prompt(77, 6), 40, rid="doomed",
                                deadline_s=t0 + 500.0)])
        assert c.reason == "timeout"
        assert 0 < c.tokens.shape[0] < 40  # partial, mid-decode
        # the partial output is a prefix of the uninterrupted rollout
        np.testing.assert_array_equal(
            c.tokens, _want(params, c.prompt, 40)[:c.tokens.shape[0]])
        # no orphaned blocks: the reservation was refunded in full
        loop.pool.check()
        assert loop.pool.free_blocks == loop.pool.num_blocks
        # the loop (and the recycled blocks) still serve exactly
        loop._clock = time.time
        [c2] = loop.run([Request(_prompt(78, 5), 12, rid="next")])
        assert c2.reason == "length"
        np.testing.assert_array_equal(
            c2.tokens, _want(params, c2.prompt, 12))


class TestServiceMode:
    """run(source=..., sink=...): incremental intake for the fleet's
    replica worker, with streaming completions."""

    def test_incremental_intake_streams_to_sink(self, params):
        loop = ServeLoop(CFG, params, num_slots=2, steps_per_sync=4,
                         prefill_chunk=8)
        batches = iter([
            [Request(_prompt(1, 4), 6, rid="a"),
             Request(_prompt(2, 9), 8, rid="b")],
            [],                                     # idle poll
            [Request(_prompt(3, 6), 5, rid="c")],   # late arrival
            None,                                   # close + drain
        ])
        streamed = []
        out = loop.run(source=lambda: next(batches),
                       sink=streamed.append, idle_wait_s=0.0)
        assert sorted(c.rid for c in out) == ["a", "b", "c"]
        assert [c.rid for c in streamed] == [c.rid for c in out]
        for c in out:
            assert c.reason == "length"
            np.testing.assert_array_equal(
                c.tokens,
                _want(params, c.prompt, c.tokens.shape[0]))

    def test_malformed_request_completes_invalid(self, params):
        """Service mode can't raise on a bad wire request (the loop must
        keep serving the fleet) — it completes reason='invalid'."""
        loop = ServeLoop(CFG, params, num_slots=1, steps_per_sync=4,
                         prefill_chunk=8)
        batches = iter([
            [Request(_prompt(1, 90), 20, rid="toolong"),
             Request(_prompt(1, 4), 6, rid="fine")],
            None,
        ])
        comps = {c.rid: c for c in loop.run(source=lambda: next(batches),
                                            idle_wait_s=0.0)}
        assert comps["toolong"].reason == "invalid"
        assert comps["toolong"].tokens.shape == (0,)
        np.testing.assert_array_equal(
            comps["fine"].tokens, _want(params, comps["fine"].prompt, 6))


@pytest.fixture(scope="module")
def params_v2():
    """A SECOND weight set (different init seed): the hot-swap target.
    Same shapes/dtypes as ``params``, so the rebind must not retrace."""
    return TransformerLM(CFG).init(
        jax.random.key(1), jnp.zeros((1, 2), jnp.int32))["params"]


def _want2(params_v2, prompt, n):
    out = greedy_generate(CFG, params_v2, jnp.asarray(prompt)[None, :], n)
    return np.asarray(out)[0, len(prompt):]


class TestWeightHotSwap:
    """request_swap: drain-gated rebind of self.params.  The contract —
    requests admitted before the swap complete on the OLD weights,
    requests admitted after decode on the NEW ones, and no request ever
    straddles versions."""

    def test_midstream_swap_drains_then_rebinds(self, params, params_v2):
        """old0/old1 hold the two slots when the swap arrives (old
        weights); q is QUEUED behind them — never admitted pre-swap, so
        the barrier holds it for the NEW weights; new0/new1 arrive with
        the swap request itself."""
        loop = ServeLoop(CFG, params, num_slots=2, steps_per_sync=4,
                         prefill_chunk=8)
        old = [Request(_prompt(60 + i, 4 + 2 * i), 8 + i, rid=f"old{i}")
               for i in range(2)]
        queued = Request(_prompt(65, 6), 7, rid="q")
        new = [Request(_prompt(70 + i, 5 + i), 6 + 2 * i, rid=f"new{i}")
               for i in range(2)]
        events = []
        polls = {"n": 0}

        def source():
            polls["n"] += 1
            if polls["n"] == 1:
                return old + [queued]
            if polls["n"] == 2:
                # swap requested while old requests are still decoding;
                # the new batch arrives in the SAME poll and must wait
                # behind the admission barrier
                loop.request_swap(
                    lambda: params_v2, version=7,
                    on_swapped=lambda: events.append("swapped"))
                return new
            done = sum(1 for e in events if e != "swapped")
            return None if done == len(old) + len(new) + 1 else []

        comps = {c.rid: c for c in loop.run(
            source=source, sink=lambda c: events.append(c.rid),
            idle_wait_s=0.0)}
        assert len(comps) == 5
        for i, r in enumerate(old):
            np.testing.assert_array_equal(
                comps[r.rid].tokens, _want(params, r.prompt, 8 + i),
                err_msg=f"{r.rid} must decode on the OLD weights")
        np.testing.assert_array_equal(
            comps["q"].tokens, _want2(params_v2, queued.prompt, 7),
            err_msg="a request still queued at swap time is held by the "
                    "admission barrier and decodes on the NEW weights")
        for i, r in enumerate(new):
            np.testing.assert_array_equal(
                comps[r.rid].tokens, _want2(params_v2, r.prompt, 6 + 2 * i),
                err_msg=f"{r.rid} must decode on the NEW weights")
        # ordering: every pre-swap completion lands before on_swapped,
        # every post-swap one after — the drain gate, observed
        swap_at = events.index("swapped")
        assert {e for e in events[:swap_at]} == {r.rid for r in old}
        assert {e for e in events[swap_at + 1:]} == (
            {r.rid for r in new} | {"q"})
        from tpudist import obs
        assert obs.snapshot()["gauges"][
            "serve/weights_version"]["value"] == 7

    def test_swap_between_runs_no_retrace(self, params, params_v2):
        loop = ServeLoop(CFG, params, num_slots=2, steps_per_sync=4,
                         prefill_chunk=8)
        req = Request(_prompt(80, 6), 9, rid="a")
        [c1] = loop.run([Request(_prompt(80, 6), 9, rid="a")])
        np.testing.assert_array_equal(c1.tokens, _want(params, req.prompt, 9))
        traced = (loop._segment._cache_size()
                  if hasattr(loop._segment, "_cache_size") else None)
        loop.request_swap(lambda: params_v2, version=2)
        [c2] = loop.run([Request(_prompt(80, 6), 9, rid="a")])
        np.testing.assert_array_equal(
            c2.tokens, _want2(params_v2, req.prompt, 9))
        if traced is not None:
            # params is a jit ARGUMENT with unchanged avals: the swap
            # must not have grown the executable cache
            assert loop._segment._cache_size() == traced

    @pytest.mark.parametrize("depth", [1, 2])
    def test_swap_paged_layout_drains_pool(self, params, params_v2, depth):
        loop = ServeLoop(CFG, params, num_slots=2, steps_per_sync=4,
                         prefill_chunk=8, cache_layout="paged",
                         kv_block_size=16, pipeline_depth=depth)
        old = [Request(_prompt(82, 7), 8, rid="old")]
        new = [Request(_prompt(83, 5), 7, rid="new")]
        polls = {"n": 0}
        seen = []

        def source():
            polls["n"] += 1
            if polls["n"] == 1:
                return old
            if polls["n"] == 2:
                loop.request_swap(lambda: params_v2, version=3)
                return new
            return None if len(seen) == 2 else []

        comps = {c.rid: c for c in loop.run(
            source=source, sink=seen.append, idle_wait_s=0.0)}
        np.testing.assert_array_equal(
            comps["old"].tokens, _want(params, old[0].prompt, 8))
        np.testing.assert_array_equal(
            comps["new"].tokens, _want2(params_v2, new[0].prompt, 7))
        assert loop.pool.used_blocks == 0  # fully drained through the swap

    def test_failed_restore_keeps_old_weights_and_completes(self, params):
        """params_fn returning None (missing snapshot): the rebind is
        skipped but the swap COMPLETES — on_swapped fires, admission
        resumes, and the queued request decodes on the old weights."""
        loop = ServeLoop(CFG, params, num_slots=1, steps_per_sync=4,
                         prefill_chunk=8)
        fired = []
        loop.request_swap(lambda: None, version=9,
                          on_swapped=lambda: fired.append(True))
        req = Request(_prompt(85, 5), 8, rid="q")
        [c] = loop.run([Request(_prompt(85, 5), 8, rid="q")])
        assert fired == [True]
        np.testing.assert_array_equal(c.tokens, _want(params, req.prompt, 8))

    def test_idle_swap_applies_immediately(self, params, params_v2):
        """No traffic in flight: the swap lands on the next loop tick,
        before any later admission."""
        loop = ServeLoop(CFG, params, num_slots=1, steps_per_sync=4,
                         prefill_chunk=8)
        loop.request_swap(lambda: params_v2, version=1)
        req = Request(_prompt(86, 4), 6, rid="q")
        [c] = loop.run([Request(_prompt(86, 4), 6, rid="q")])
        np.testing.assert_array_equal(
            c.tokens, _want2(params_v2, req.prompt, 6))


class TestOverloadDegradation:
    """ISSUE 9 overload tiers: priority-ordered shedding past the hard
    queue bound, and the soft DEGRADED watermark that clamps
    best-effort budgets before anything must be rejected."""

    def test_shed_takes_lowest_priority_newest_first(self, params):
        """Past ``max_queue`` the victim is the NEWEST request of the
        LOWEST priority class — priority traffic survives overload even
        when it arrived last."""
        loop = ServeLoop(CFG, params, num_slots=1, steps_per_sync=4,
                         prefill_chunk=8, max_queue=1)
        reqs = [Request(_prompt(60 + i, 4), 6, rid=f"q{i}",
                        priority=(1 if i == 4 else 0))
                for i in range(5)]
        comps = {c.rid: c for c in loop.run(reqs)}
        served = {r for r, c in comps.items() if c.reason == "length"}
        shed = {r for r, c in comps.items() if c.reason == "rejected"}
        # q0 fills the slot; q4 (priority 1, newest arrival) outranks
        # the whole best-effort backlog for the one queue place
        assert served == {"q0", "q4"} and shed == {"q1", "q2", "q3"}
        for rid in served:
            np.testing.assert_array_equal(
                comps[rid].tokens, _want(params, comps[rid].prompt, 6))

    def test_degraded_clamps_best_effort_not_priority(self, params):
        """Past the soft watermark, best-effort admissions get a short
        answer (budget clamped to ``degrade_max_new``) instead of a
        later rejection; priority admissions keep their full budget.
        Results stay exact — a clamped request IS a shorter request."""
        from tpudist import obs

        c0 = obs.snapshot()["counters"].get(
            "serve/degrade_clamped", {}).get("value", 0)
        loop = ServeLoop(CFG, params, num_slots=1, steps_per_sync=4,
                         prefill_chunk=8, degrade_queue=0,
                         degrade_max_new=2)
        reqs = [Request(_prompt(50, 4), 6, rid="head"),
                Request(_prompt(51, 4), 6, rid="cheap"),
                Request(_prompt(52, 4), 6, rid="vip", priority=1)]
        comps = {c.rid: c for c in loop.run(reqs)}
        assert all(c.reason == "length" for c in comps.values())
        # head admitted before the backlog built: full budget
        assert comps["head"].tokens.shape == (6,)
        # cheap admitted DEGRADED: clamped, but exact for its budget
        assert comps["cheap"].tokens.shape == (2,)
        np.testing.assert_array_equal(
            comps["cheap"].tokens,
            _want(params, comps["cheap"].prompt, 2))
        # vip admitted from the same degraded backlog: untouched
        assert comps["vip"].tokens.shape == (6,)
        c1 = obs.snapshot()["counters"]["serve/degrade_clamped"]["value"]
        assert c1 - c0 == 1
        # queue emptied at the end: the loop left degraded mode
        assert obs.snapshot()["gauges"]["serve/degraded"]["value"] == 0.0

    def test_degrade_queue_defaults_and_validation(self, params):
        loop = ServeLoop(CFG, params, num_slots=1, max_queue=8)
        assert loop.degrade_queue == 4      # soft watermark: half hard
        loop = ServeLoop(CFG, params, num_slots=1)
        assert loop.degrade_queue is None   # unbounded queue: no tiers
        with pytest.raises(ValueError, match="degrade_queue"):
            ServeLoop(CFG, params, num_slots=1, degrade_queue=-1)
        with pytest.raises(ValueError, match="degrade_max_new"):
            ServeLoop(CFG, params, num_slots=1, degrade_queue=2,
                      degrade_max_new=0)
