"""Scenario specs, workload synthesis/replay, the SLO-envelope checker,
and the offline fleet simulator (ISSUE 11): spec parsing must reject
typos, workloads must be deterministic under their seed, the envelope
checker must treat the emitter's verdict as evidence (not authority),
and the simulator must reproduce a recorded live run's autoscaler
decision sequence within one poll of the breach."""

import json
import os

import numpy as np
import pytest

from tpudist.sim.envelope import check_row, check_rows, scenario_rows
from tpudist.sim.scenario import (
    BUILTIN, Envelope, ScenarioSpec, builtin, names)
from tpudist.sim.workload import (
    Workload, WorkItem, service_rates_from_trace, synthesize,
    workload_from_trace)

FIXTURE = os.path.join(os.path.dirname(__file__), "data",
                       "sim_replay_fixture.json")


def _spec(**over) -> ScenarioSpec:
    base = {"name": "t", "duration_s": 5.0,
            "arrival": {"kind": "constant", "rate": 10.0}}
    base.update(over)
    return ScenarioSpec.from_dict(base)


class TestScenarioSpec:
    def test_minimal_spec_gets_fleet_defaults(self):
        spec = _spec()
        assert spec.fleet["replicas"] == 1
        assert spec.fleet["seconds_per_token"] == pytest.approx(0.002)
        assert spec.fleet["autoscale"] is None
        assert spec.deadline == {"kind": "none"}

    def test_fleet_overrides_merge_not_replace(self):
        spec = _spec(fleet={"replicas": 3})
        assert spec.fleet["replicas"] == 3
        assert spec.fleet["warmup_s"] == pytest.approx(2.0)

    def test_unknown_top_level_key_rejected(self):
        with pytest.raises(ValueError, match="unknown keys.*'rate_limit'"):
            _spec(rate_limit=5)

    def test_unknown_fleet_key_rejected(self):
        # a typo'd knob must fail parsing, not run the default scenario
        with pytest.raises(ValueError, match="unknown keys.*'replica'"):
            _spec(fleet={"replica": 2})

    def test_missing_required_keys_rejected(self):
        with pytest.raises(ValueError, match="missing required keys"):
            ScenarioSpec.from_dict({"name": "t", "duration_s": 1.0})

    def test_arrival_validation(self):
        with pytest.raises(ValueError, match="not in"):
            _spec(arrival={"kind": "bursty", "rate": 1.0})
        with pytest.raises(ValueError, match="rate > 0"):
            _spec(arrival={"kind": "constant", "rate": 0.0})
        with pytest.raises(ValueError, match="base_rate <= peak_rate"):
            _spec(arrival={"kind": "diurnal", "base_rate": 9.0,
                           "peak_rate": 3.0, "period_s": 60.0})
        with pytest.raises(ValueError, match="spike_rate > base_rate"):
            _spec(arrival={"kind": "flash_crowd", "base_rate": 5.0,
                           "spike_rate": 5.0, "spike_width_s": 2.0})

    def test_prompt_and_deadline_validation(self):
        with pytest.raises(ValueError, match="lo <= typical < tail"):
            _spec(prompt={"kind": "longtail", "lo": 4, "typical": 512,
                          "tail": 16})
        with pytest.raises(ValueError, match="tight_s < loose_s"):
            _spec(deadline={"kind": "adversarial", "tight_frac": 0.2,
                            "tight_s": 10.0, "loose_s": 1.0})

    def test_tenant_validation(self):
        with pytest.raises(ValueError, match="missing required keys"):
            _spec(tenants=[{"name": "a"}])
        with pytest.raises(ValueError, match="weight > 0"):
            _spec(tenants=[{"name": "a", "weight": 0.0}])

    def test_roundtrip_through_dict(self):
        spec = builtin("deadline_storm")
        back = ScenarioSpec.from_dict(spec.to_dict())
        assert back == spec

    def test_from_json(self, tmp_path):
        path = tmp_path / "s.json"
        path.write_text(json.dumps(
            {"name": "file-spec", "duration_s": 2.0,
             "arrival": {"kind": "constant", "rate": 3.0}}))
        assert ScenarioSpec.from_json(str(path)).name == "file-spec"

    def test_builtin_matrix_parses_and_is_big_enough(self):
        # the CI gate demands >= 5 named scenarios; every one must parse
        assert len(names()) >= 5
        for name in names():
            spec = builtin(name)
            assert spec.name == name
        with pytest.raises(KeyError, match="unknown scenario"):
            builtin("nope")

    def test_fault_validation(self):
        with pytest.raises(ValueError, match="not in"):
            _spec(faults=[{"kind": "meteor"}])
        with pytest.raises(ValueError, match="missing required keys"):
            _spec(faults=[{"kind": "kill_replica", "at_s": 1.0}])
        with pytest.raises(ValueError, match="unknown keys"):
            _spec(faults=[{"kind": "kill_replica", "at_s": 1.0,
                           "rid": "r0", "for_s": 2.0}])
        with pytest.raises(ValueError, match="for_s > 0"):
            _spec(faults=[{"kind": "coord_brownout", "at_s": 1.0,
                           "for_s": 0.0}])
        with pytest.raises(ValueError, match="at_poll >= 1"):
            _spec(faults=[{"kind": "kill_router", "at_poll": 0}])
        with pytest.raises(ValueError, match="at most one kill_router"):
            _spec(faults=[{"kind": "kill_router", "at_poll": 5},
                          {"kind": "kill_router", "at_poll": 9}])
        spec = _spec(faults=[
            {"kind": "kill_replica", "at_s": 1.0, "rid": "r0"},
            {"kind": "drop_heartbeats", "at_s": 2.0, "for_s": 1.0,
             "rid": "r1"}])
        assert isinstance(spec.faults, tuple) and len(spec.faults) == 2

    def test_chaos_builtins_present_and_faulted(self):
        # the chaos third of the matrix (ISSUE 12): present, parsed,
        # and actually scripting faults
        for name in ("replica_death_storm", "router_failover",
                     "coord_brownout"):
            spec = builtin(name)
            assert spec.faults, name
        back = ScenarioSpec.from_dict(
            builtin("replica_death_storm").to_dict())
        assert back == builtin("replica_death_storm")

    def test_corrupt_replica_validation(self):
        # ISSUE 13: the byzantine-replica verb and its integrity bounds
        with pytest.raises(ValueError, match="missing required keys"):
            _spec(faults=[{"kind": "corrupt_replica", "at_s": 1.0}])
        with pytest.raises(ValueError, match="every >= 1"):
            _spec(faults=[{"kind": "corrupt_replica", "at_s": 1.0,
                           "rid": "r1", "every": 0}])
        with pytest.raises(ValueError, match="count must be >= 1"):
            _spec(faults=[{"kind": "corrupt_replica", "at_s": 1.0,
                           "rid": "r1", "count": 0}])
        spec = _spec(faults=[{"kind": "corrupt_replica", "at_s": 1.0,
                              "rid": "r1", "every": 2, "count": 4}])
        assert spec.faults[0]["count"] == 4
        sc = builtin("silent_corruption")
        assert sc.faults and sc.faults[0]["kind"] == "corrupt_replica"
        assert sc.envelope.max_corrupted_terminals == 0
        assert sc.envelope.min_quarantines >= 1
        assert sc.envelope.min_reinstated >= 1
        assert ScenarioSpec.from_dict(sc.to_dict()) == sc


class TestEnvelope:
    def test_unknown_key_rejected(self):
        with pytest.raises(ValueError, match="unknown keys"):
            Envelope.from_dict({"max_p99": 1.0})
        with pytest.raises(ValueError, match="unknown keys"):
            Envelope.from_dict({"decisions": {"shed": {"atmost": 3}}})

    def test_clean_row_passes(self):
        env = Envelope.from_dict({
            "max_lost": 0, "max_p99_queue_wait_s": 1.0,
            "min_scale_ups": 1,
            "decisions": {"failed": {"max": 0},
                          "completed": {"min": 10}}})
        row = {"lost_requests": 0, "p99_queue_wait_s": 0.2,
               "scale_ups": 2, "decisions_failed": 0,
               "decisions_completed": 50}
        assert env.check(row) == []

    def test_each_bound_reports_its_violation(self):
        env = Envelope.from_dict({
            "max_lost": 0, "max_p99_queue_wait_s": 0.5,
            "max_recovery_s": 10.0, "min_scale_ups": 1,
            "max_priority_bad": 0,
            "decisions": {"completed": {"min": 100},
                          "failed": {"max": 0}}})
        row = {"lost_requests": 2, "p99_queue_wait_s": 3.0,
               "recovery_s": 60.0, "scale_ups": 0, "priority_bad": 4,
               "decisions_completed": 10, "decisions_failed": 1}
        bad = env.check(row)
        assert len(bad) == 7
        assert any("lost_requests" in b for b in bad)
        assert any("decisions_failed" in b for b in bad)

    def test_missing_fields_read_as_zero(self):
        # min bounds still bite on an empty row; max bounds don't
        assert Envelope(min_scale_ups=1).check({}) \
            == ["scale_ups=0 < min 1"]
        assert Envelope(max_p99_queue_wait_s=1.0).check({}) == []

    def test_chaos_bounds(self):
        env = Envelope.from_dict({
            "max_burn_rate_300s": 2.0, "max_replica_deaths": 1,
            "min_router_recoveries": 1})
        good = {"lost_requests": 0, "burn_rate_300s": 0.5,
                "replica_deaths": 1, "router_recoveries": 1}
        assert env.check(good) == []
        bad = env.check({"lost_requests": 0, "burn_rate_300s": 9.0,
                         "replica_deaths": 3, "router_recoveries": 0})
        assert len(bad) == 3
        assert any("burn_rate_300s" in b for b in bad)
        assert any("replica_deaths" in b for b in bad)
        assert any("router_recoveries" in b for b in bad)


class TestWorkloadSynthesis:
    def test_deterministic_under_seed(self):
        spec = _spec(seed=7)
        assert synthesize(spec).items == synthesize(spec).items
        other = _spec(seed=8)
        assert synthesize(spec).items != synthesize(other).items

    def test_arrival_count_tracks_rate(self):
        wl = synthesize(_spec(duration_s=20.0,
                              arrival={"kind": "constant", "rate": 10.0},
                              seed=3))
        # Poisson(200): a 4-sigma band, deterministic under the seed
        assert 140 <= len(wl) <= 260
        ats = [w.at for w in wl.items]
        assert ats == sorted(ats)
        assert all(0.0 <= t < 20.0 for t in ats)

    def test_flash_crowd_concentrates_in_spike(self):
        wl = synthesize(_spec(
            duration_s=20.0, seed=4,
            arrival={"kind": "flash_crowd", "base_rate": 2.0,
                     "spike_rate": 100.0, "spike_at_s": 5.0,
                     "spike_width_s": 2.0}))
        in_spike = sum(1 for w in wl.items if 5.0 <= w.at < 7.0)
        assert in_spike > len(wl) / 2

    def test_longtail_prompts_stay_in_range(self):
        wl = synthesize(_spec(
            duration_s=30.0, seed=5,
            arrival={"kind": "constant", "rate": 20.0},
            prompt={"kind": "longtail", "lo": 4, "typical": 16,
                    "tail": 256, "tail_frac": 0.2}))
        lens = [w.prompt_tokens for w in wl.items]
        assert min(lens) >= 4 and max(lens) <= 256
        assert max(lens) > 16   # the tail actually fires at frac=0.2

    def test_adversarial_deadlines_are_bimodal(self):
        wl = synthesize(_spec(
            duration_s=30.0, seed=6,
            arrival={"kind": "constant", "rate": 20.0},
            deadline={"kind": "adversarial", "tight_frac": 0.3,
                      "tight_s": 0.05, "loose_s": 30.0}))
        vals = {w.rel_deadline_s for w in wl.items}
        assert vals == {0.05, 30.0}

    def test_tenant_mix_rides_items(self):
        wl = synthesize(_spec(
            duration_s=20.0, seed=9,
            arrival={"kind": "constant", "rate": 20.0},
            tenants=[{"name": "sys", "weight": 5.0, "prefix_tokens": 16},
                     {"name": "paid", "weight": 1.0, "priority": 1}]))
        by_tenant = {t: [w for w in wl.items if w.tenant == t]
                     for t in ("sys", "paid")}
        assert len(by_tenant["sys"]) > len(by_tenant["paid"]) > 0
        assert all(w.priority == 1 for w in by_tenant["paid"])
        assert all(w.prefix_tokens == 16 for w in by_tenant["sys"])


class TestWorkloadRequests:
    def test_requests_and_arrivals_align(self):
        wl = synthesize(_spec(
            duration_s=10.0, seed=2,
            arrival={"kind": "constant", "rate": 5.0},
            deadline={"kind": "uniform", "lo": 1.0, "hi": 2.0}))
        reqs, arrivals = wl.requests(base_wall=1000.0)
        assert len(reqs) == len(arrivals) == len(wl)
        for req, at, item in zip(reqs, arrivals, wl.items):
            assert at == item.at
            assert req.prompt.size == item.prompt_tokens
            assert req.max_new_tokens == item.max_new
            # deadlines anchored at the caller's wall clock + arrival
            assert req.deadline_s == pytest.approx(
                1000.0 + item.at + item.rel_deadline_s)
        assert len({r.rid for r in reqs}) == len(reqs)

    def test_tenant_prefix_is_shared_and_stable(self):
        wl = synthesize(_spec(
            duration_s=20.0, seed=9,
            arrival={"kind": "constant", "rate": 20.0},
            prompt={"kind": "uniform", "lo": 32, "hi": 48},
            tenants=[{"name": "sys", "weight": 1.0,
                      "prefix_tokens": 16}]))
        reqs, _ = wl.requests(base_wall=0.0)
        heads = {tuple(r.prompt[:16].tolist()) for r in reqs[:10]}
        assert len(heads) == 1        # one shared system prefix
        reqs2, _ = wl.requests(base_wall=5000.0)
        assert np.array_equal(reqs[0].prompt, reqs2[0].prompt)


class TestTraceReplay:
    def _doc(self):
        return {"schema": "tpudist.events/1", "events": [
            {"t": 100.0, "kind": "enqueue", "trace": "a",
             "prompt_tokens": 8, "max_new": 16, "priority": 0,
             "rel_deadline_s": None},
            {"t": 100.5, "kind": "enqueue", "trace": "b",
             "prompt_tokens": 32, "max_new": 4, "priority": 1,
             "rel_deadline_s": 2.5},
            {"t": 100.1, "kind": "segment", "trace": "a", "src": "r0",
             "steps": 8, "spt": 0.004},
            {"t": 100.2, "kind": "segment", "trace": "a", "src": "r0",
             "steps": 8, "spt": 0.002},
            {"t": 100.3, "kind": "segment", "trace": "b", "src": "r1",
             "steps": 4, "spt": 0.01},
        ]}

    def test_workload_from_trace_normalizes_offsets(self):
        wl = workload_from_trace(self._doc())
        assert [w.at for w in wl.items] == [0.0, 0.5]
        assert wl.items[0].prompt_tokens == 8
        assert wl.items[1].priority == 1
        assert wl.items[1].rel_deadline_s == 2.5

    def test_trace_without_enqueues_is_an_error(self):
        with pytest.raises(ValueError, match="no replayable enqueue"):
            workload_from_trace({"events": [{"kind": "segment"}]})

    def test_service_rates_are_per_source_medians(self):
        rates = service_rates_from_trace(self._doc(), default=0.005)
        assert rates["*"] == pytest.approx(0.005)
        assert rates["r0"] == pytest.approx(0.003)   # median of 4ms/2ms
        assert rates["r1"] == pytest.approx(0.01)


def _passing_row(name: str) -> dict:
    """A summary row comfortably inside the named builtin envelope."""
    env = builtin(name).envelope
    return {"metric": f"scenario/{name}", "scenario": name,
            "lost_requests": 0, "p99_queue_wait_s": 0.05,
            "recovery_s": 5.0,
            "scale_ups": env.min_scale_ups, "drains": env.min_drains,
            "scale_ups_prefill": env.min_scale_ups_prefill,
            "scale_ups_decode": env.min_scale_ups_decode,
            "p99_ttft_s": 0.05,
            "priority_bad": 0, "replica_deaths": 0,
            "preemptions": env.min_preemptions,
            "p99_priority_wait_s": 0.05,
            "router_recoveries": env.min_router_recoveries,
            "quarantines": env.min_quarantines,
            "reinstated": env.min_reinstated,
            "corrupted_terminals": 0,
            "burn_rate_300s": 0.0,
            "decisions_completed": 500,
            "decisions_failed": 0,
            "alerts_fired": sorted(env.alerts.get("must_fire") or []),
            "envelope_ok": True,
            "violations": []}


class TestEnvelopeChecker:
    def test_check_row_rechecks_builtin_from_raw_fields(self):
        # the emitter says ok; the raw fields say otherwise — the
        # checker must recompute, not trust the flag
        row = _passing_row("steady_state")
        row["lost_requests"] = 3
        bad = check_row(row)
        assert bad and "lost_requests" in bad[0]

    def test_check_row_honors_embedded_verdict_for_unknown_scenario(self):
        row = {"scenario": "custom", "envelope_ok": False,
               "violations": ["p99 blew up"]}
        assert check_row(row) == ["p99 blew up"]
        assert check_row({"scenario": "custom", "envelope_ok": True}) == []

    def test_check_rows_demands_the_full_matrix(self):
        rows = [_passing_row(n) for n in names()]
        ok, report = check_rows(rows)
        assert ok, report
        ok, report = check_rows(rows[:-1])
        assert not ok
        assert any("missing" in line for line in report)
        ok, report = check_rows(rows[:3], min_scenarios=5,
                                require_builtin=False)
        assert not ok
        assert any("only 3" in line for line in report)

    def test_scenario_rows_skips_noise(self, tmp_path):
        path = tmp_path / "bench.jsonl"
        path.write_text("\n".join([
            "some log line",
            json.dumps({"metric": "serve/throughput", "value": 1.0}),
            json.dumps(_passing_row("steady_state")),
            "{not json",
        ]) + "\n")
        rows = scenario_rows(str(path))
        assert [r["scenario"] for r in rows] == ["steady_state"]


class TestVirtualClock:
    def test_advance_and_wall(self):
        from tpudist.sim.simulator import VirtualClock

        vc = VirtualClock(wall_base=500.0)
        assert vc.monotonic() == 0.0
        vc.advance(1.5)
        assert vc.monotonic() == pytest.approx(1.5)
        assert vc.wall() == pytest.approx(501.5)
        with pytest.raises(ValueError):
            vc.advance(-0.1)


class TestSimFabricChaos:
    def test_outage_window_gates_client_verbs_not_leases(self):
        from tpudist.runtime.faults import FaultInjected
        from tpudist.sim.fabric import SimFabric
        from tpudist.sim.simulator import VirtualClock

        vc = VirtualClock()
        fab = SimFabric(clock=vc.monotonic)
        fab.add_outage(1.0, 2.0)
        fab.set("k", b"v")                  # before the window: fine
        vc.advance(1.5)
        for op in (lambda: fab.set("k", b"w"), lambda: fab.get("k"),
                   lambda: fab.keys(), lambda: fab.delete("k"),
                   lambda: fab.add("c", 1), lambda: fab.live()):
            with pytest.raises(FaultInjected):
                op()
        # lease flips model SERVER-side state: outage-exempt
        fab.up("ns:r0")
        fab.down("ns:r0")
        vc.advance(1.0)                     # past the window
        assert fab.get("k") == b"v"         # the blind write never landed

    def test_outage_needs_clock_and_sane_window(self):
        from tpudist.sim.fabric import SimFabric
        from tpudist.sim.simulator import VirtualClock

        with pytest.raises(ValueError, match="needs a clock"):
            SimFabric().add_outage(0.0, 1.0)
        with pytest.raises(ValueError, match="bad outage window"):
            SimFabric(clock=VirtualClock().monotonic).add_outage(2.0, 1.0)


class TestFleetSim:
    def _tiny(self, **over):
        base = {"name": "tiny", "duration_s": 4.0,
                "arrival": {"kind": "constant", "rate": 6.0},
                "max_new": {"kind": "const", "value": 8},
                "seed": 21,
                "envelope": {"max_lost": 0, "max_scale_ups": 0}}
        base.update(over)
        return ScenarioSpec.from_dict(base)

    def test_small_scenario_completes_everything(self):
        from tpudist.sim.simulator import FleetSim

        sim = FleetSim(self._tiny())
        row = sim.run()
        assert row["requests"] > 0
        assert row["lost_requests"] == 0
        assert row["completed_ok"] == row["requests"]
        assert row["decisions_completed"] == row["requests"]
        assert row["envelope_ok"], row["violations"]
        # virtual seconds elapsed, in a hurry
        assert row["virtual_s"] >= 4.0
        assert row["sim_wall_s"] < row["virtual_s"]

    def test_same_seed_same_decisions(self):
        from tpudist.sim.simulator import FleetSim

        a = FleetSim(self._tiny()).run()
        b = FleetSim(self._tiny()).run()
        for k in ("requests", "completed_ok", "decisions_completed",
                  "p99_queue_wait_s"):
            assert a[k] == b[k], k

    def test_overload_scales_up_with_real_policy(self):
        from tpudist.sim.simulator import FleetSim

        spec = self._tiny(
            name="hot", duration_s=10.0, seed=22,
            arrival={"kind": "constant", "rate": 40.0},
            fleet={"replicas": 1, "autoscale": {
                "min_replicas": 1, "max_replicas": 3,
                "target_wait_s": 0.3, "low_wait_s": 0.05,
                "quantile": 0.9, "breach_polls": 2, "idle_polls": 50,
                "up_cooldown_s": 5.0, "down_cooldown_s": 600.0,
                "poll_s": 0.5, "max_metric_age_s": 10.0}},
            envelope={"max_lost": 0, "min_scale_ups": 1})
        row = FleetSim(spec).run()
        assert row["scale_ups"] >= 1
        assert row["lost_requests"] == 0
        assert row["final_replicas"] > 1
        assert row["envelope_ok"], row["violations"]

    def test_adversarial_deadlines_shed_not_fail(self):
        from tpudist.sim.simulator import FleetSim

        spec = self._tiny(
            name="storm", duration_s=8.0, seed=23,
            arrival={"kind": "constant", "rate": 30.0},
            deadline={"kind": "adversarial", "tight_frac": 0.4,
                      "tight_s": 0.02, "loose_s": 60.0},
            envelope={"max_lost": 0})
        row = FleetSim(spec).run()
        assert row["lost_requests"] == 0
        assert row["decisions_failed"] == 0
        # impossible deadlines resolve as shed/timeout decisions, and
        # every loose-deadline request still completes
        assert row["decisions_shed"] + row["decisions_timeout"] > 0
        assert row["completed_ok"] > 0


class TestFleetSimChaos:
    """The FaultScript verbs drive the REAL recovery paths on the
    virtual clock: replica death -> redispatch, coord brownout ->
    buffered ride-out, router kill -> journal recovery."""

    def _tiny(self, **over):
        base = {"name": "chaos-tiny", "duration_s": 4.0,
                "arrival": {"kind": "constant", "rate": 6.0},
                "max_new": {"kind": "const", "value": 8},
                "seed": 41, "fleet": {"replicas": 2},
                "envelope": {"max_lost": 0}}
        base.update(over)
        return ScenarioSpec.from_dict(base)

    def test_replica_kill_redispatches_everything(self):
        from tpudist.sim.simulator import FleetSim

        spec = self._tiny(
            name="chaos-kill", seed=42,
            faults=[{"kind": "kill_replica", "at_s": 1.0, "rid": "r1"}],
            envelope={"max_lost": 0, "max_replica_deaths": 1,
                      "decisions": {"failed": {"max": 0}}})
        row = FleetSim(spec).run()
        assert row["lost_requests"] == 0
        assert row["replica_deaths"] == 1
        assert row["decisions_completed"] == row["requests"]
        assert row["envelope_ok"], row["violations"]

    def test_coord_brownout_is_stale_not_lost(self):
        from tpudist.sim.simulator import FleetSim

        spec = self._tiny(
            name="chaos-brownout", seed=43,
            faults=[{"kind": "coord_brownout", "at_s": 1.0,
                     "for_s": 1.5}],
            envelope={"max_lost": 0, "max_replica_deaths": 0,
                      "decisions": {"failed": {"max": 0}}})
        sim = FleetSim(spec)
        row = sim.run()
        assert row["lost_requests"] == 0
        assert row["replica_deaths"] == 0
        assert row["decisions_completed"] == row["requests"]
        # the brownout buffers drained before the fleet shut down
        assert all(not r._done_buf for r in sim.replicas)
        assert row["envelope_ok"], row["violations"]

    def test_router_kill_recovers_with_no_dups(self):
        from tpudist.sim.simulator import FleetSim

        spec = self._tiny(
            name="chaos-failover", seed=44,
            faults=[{"kind": "kill_router", "at_poll": 20}],
            envelope={"max_lost": 0, "min_router_recoveries": 1,
                      "decisions": {"failed": {"max": 0}}})
        sim = FleetSim(spec)
        row = sim.run()
        assert row["lost_requests"] == 0
        assert row["router_recoveries"] == 1
        # exactly-once delivery across the crash: every request decided
        # exactly one terminal, and the journal compacted to empty
        assert row["decisions_completed"] == row["requests"]
        assert sim.fabric.keys(f"{sim.ns}/journal/") == []
        assert row["envelope_ok"], row["violations"]

    def test_drop_heartbeats_comes_back(self):
        from tpudist.sim.simulator import FleetSim

        # a false-positive-death shape: the lease lapses for 1 s, the
        # router redispatches, the replica resumes — nothing is lost
        # and nothing double-delivers
        spec = self._tiny(
            name="chaos-hb", seed=45,
            faults=[{"kind": "drop_heartbeats", "at_s": 1.0,
                     "for_s": 1.0, "rid": "r1"}],
            envelope={"max_lost": 0,
                      "decisions": {"failed": {"max": 0}}})
        row = FleetSim(spec).run()
        assert row["lost_requests"] == 0
        assert row["decisions_completed"] == row["requests"]
        assert row["envelope_ok"], row["violations"]

    def test_silent_corruption_quarantines_before_delivery(self):
        """ISSUE 13's sim acceptance: a replica flipping bits in its
        committed completions is struck into quarantine BEFORE any
        corrupt payload reaches a caller, its work is redispatched,
        and — the injection being capped — golden probes reinstate it.
        Zero lost, zero corrupted terminals, nothing killed."""
        from tpudist.sim.simulator import FleetSim

        row = FleetSim(builtin("silent_corruption")).run()
        assert row["lost_requests"] == 0
        assert row["corrupted_terminals"] == 0
        assert row["checksum_mismatches"] >= 3
        assert row["quarantines"] >= 1
        assert row["reinstated"] >= 1
        assert row["retired"] == 0
        assert row["replica_deaths"] == 0
        assert row["decisions_completed"] == row["requests"]
        assert row["envelope_ok"], row["violations"]


@pytest.mark.skipif(not os.path.exists(FIXTURE),
                    reason="recorded live-run fixture missing")
class TestSimReplayAgreement:
    """The acceptance check, offline: replaying the checked-in recorded
    live run (a 1-replica fleet breaching a millisecond wait target)
    must reproduce the autoscaler's scale-up decision sequence within
    one poll of the first breach — bench.py's sim_replay gate, pinned
    to a fixture so it regresses loudly without needing a live fleet."""

    @staticmethod
    def _first_up_rel(decision_log, action_seq, target_wait_s):
        breaches = [r["poll"] for r in decision_log
                    if r["wait_q"] > target_wait_s]
        ups = [a["poll"] for a in action_seq if a["kind"] == "up"]
        if not breaches or not ups:
            return None
        return ups[0] - breaches[0]

    def test_replay_matches_recorded_decisions(self):
        from tpudist.sim.simulator import FleetSim

        with open(FIXTURE) as f:
            fx = json.load(f)
        assert fx["schema"] == "tpudist.sim_replay_fixture/1"
        sim = FleetSim.from_trace(fx["events"],
                                  autoscale=fx["autoscale"], replicas=1)
        row = sim.run()
        assert row["lost_requests"] == 0

        live_ups = sum(1 for a in fx["action_seq"] if a["kind"] == "up")
        sim_actions = sim.scaler.action_seq()
        sim_ups = sum(1 for a in sim_actions if a["kind"] == "up")
        assert sim_ups == live_ups

        target = fx["autoscale"]["target_wait_s"]
        live_rel = self._first_up_rel(fx["decision_log"],
                                      fx["action_seq"], target)
        sim_rel = self._first_up_rel(sim.scaler.decision_log,
                                     sim_actions, target)
        assert live_rel is not None and sim_rel is not None
        assert abs(live_rel - sim_rel) <= 1

    def test_replay_serves_at_recorded_pace(self):
        rates = service_rates_from_trace(json.load(open(FIXTURE))["events"])
        # the live replica stamped spt on its segments; the replayed r0
        # must inherit it rather than the synthetic default
        assert "r0" in rates
        assert 0.0 < rates["r0"] < 1.0


class TestTimelineSummary:
    def _timelines(self):
        return {"t1": [
            {"t": 0.0, "kind": "enqueue"},
            {"t": 1.0, "kind": "admit"},
            {"t": 1.5, "kind": "segment", "steps": 5},
            {"t": 2.5, "kind": "segment", "steps": 10},
            {"t": 3.0, "kind": "done"},
        ], "t2": [
            {"t": 0.0, "kind": "enqueue"},
            {"t": 0.5, "kind": "dispatch"},
            {"t": 0.6, "kind": "redispatch"},
            {"t": 2.0, "kind": "admit"},
            {"t": 4.0, "kind": "timeout"},
        ], None: [{"t": 0.0, "kind": "noise"}]}

    def test_stage_percentiles(self):
        from tpudist.obs.timeline import summarize_timelines

        s = summarize_timelines(self._timelines())
        assert s["traces"] == 2
        assert s["enqueue_to_admit"]["n"] == 2
        assert s["enqueue_to_admit"]["max"] == pytest.approx(2.0)
        assert s["admit_to_first_token"]["n"] == 1
        assert s["admit_to_first_token"]["p50"] == pytest.approx(0.5)
        # one gap of 1.0s over the later segment's 10 steps
        assert s["inter_token"]["n"] == 1
        assert s["inter_token"]["p50"] == pytest.approx(0.1)
        assert s["enqueue_to_terminal"]["max"] == pytest.approx(4.0)
        assert s["redispatches"] == {0: 1, 1: 1}

    def test_render_handles_empty_stages(self):
        from tpudist.obs.timeline import (
            render_summary, summarize_timelines)

        s = summarize_timelines({"t": [{"t": 0.0, "kind": "enqueue"}]})
        lines = render_summary(s)
        assert any("no samples" in line for line in lines)

    def test_cli_summary_flag(self, tmp_path, capsys):
        from tpudist.obs import timeline as tl

        path = tmp_path / "events.json"
        path.write_text(json.dumps({
            "schema": "tpudist.events/1",
            "events": [dict(e, trace="t1", i=i) for i, e in
                       enumerate(self._timelines()["t1"])]}))
        assert tl.main([str(path), "--summary"]) == 0
        out = capsys.readouterr().out
        assert "per-stage latency percentiles" in out
        assert "enqueue_to_admit" in out


class TestSimCLI:
    def test_spec_file_run_emits_scenario_row(self, tmp_path, capsys):
        from tpudist.sim.__main__ import main as sim_main
        from tpudist.sim.envelope import main as env_main

        spec = {"name": "cli-tiny", "duration_s": 3.0,
                "arrival": {"kind": "constant", "rate": 5.0},
                "max_new": {"kind": "const", "value": 8}, "seed": 31,
                "envelope": {"max_lost": 0}}
        spath = tmp_path / "spec.json"
        spath.write_text(json.dumps(spec))
        jpath = tmp_path / "rows.jsonl"
        assert sim_main(["--spec", str(spath), "--check",
                         "--jsonl", str(jpath)]) == 0
        row = json.loads(capsys.readouterr().out.strip())
        assert row["metric"] == "scenario/cli-tiny"
        assert row["envelope_ok"] is True
        # the written JSONL gates through the shared checker (the
        # builtin-matrix demand relaxed: this is a one-off spec)
        assert env_main([str(jpath), "--min-scenarios", "1",
                         "--no-require-builtin"]) == 0

    def test_check_exit_code_on_violation(self, tmp_path, capsys):
        from tpudist.sim.__main__ import main as sim_main

        spec = {"name": "cli-bad", "duration_s": 3.0,
                "arrival": {"kind": "constant", "rate": 5.0},
                "max_new": {"kind": "const", "value": 8}, "seed": 32,
                "envelope": {"min_scale_ups": 5}}   # cannot happen
        spath = tmp_path / "spec.json"
        spath.write_text(json.dumps(spec))
        assert sim_main(["--spec", str(spath), "--check"]) == 1
        assert "envelope VIOLATED" in capsys.readouterr().err


class TestPrioritySaturation:
    """ISSUE 19's ``priority_saturation`` builtin end-to-end: an
    oversaturated single replica in migrate mode must preempt
    best-effort decodes so priority traffic meets its queue-wait
    ceiling — and the SAME workload with the preemption knob off must
    fail exactly those envelope gates (proof the gate is real, not
    vacuously green)."""

    def test_builtin_envelope_passes_with_preemption(self):
        from tpudist.sim.simulator import FleetSim

        row = FleetSim(builtin("priority_saturation")).run()
        assert row["envelope_ok"], row["violations"]
        assert row["preemptions"] >= 5
        assert row["preempt_resumes"] >= 1
        assert row["lost_requests"] == 0
        assert row["p99_priority_wait_s"] <= 0.5

    def test_degrade_baseline_fails_the_priority_gates(self):
        from tpudist.sim.simulator import FleetSim

        raw = dict(BUILTIN["priority_saturation"])
        raw["fleet"] = dict(raw["fleet"], preempt="degrade")
        row = FleetSim(ScenarioSpec.from_dict(raw)).run()
        assert not row["envelope_ok"]
        viol = " ".join(row["violations"])
        assert "p99_priority_wait_s" in viol
        assert "preemptions" in viol
