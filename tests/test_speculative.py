"""Speculative decoding: exactness, distribution correctness, EOS, stats.

The load-bearing properties:

* greedy speculative decoding emits BIT-IDENTICAL tokens to plain greedy
  decoding of the target, for ANY draft (acceptance only changes speed);
* the accept/resample rule's output distribution is exactly the
  target's (Monte-Carlo against the analytic categorical);
* cache rollback keeps later rounds consistent (covered implicitly by
  the equivalence tests: a bad rollback diverges after the first
  rejection).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudist.models.generate import greedy_generate, sample_generate
from tpudist.models.speculative import (
    _accept_and_next,
    speculative_generate,
)
from tpudist.models.transformer import TransformerConfig, TransformerLM


def _make(cfg, seed):
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.key(seed),
        jnp.zeros((1, 2), jnp.int32))["params"]
    return params


TARGET_CFG = TransformerConfig(
    vocab_size=64, num_layers=2, num_heads=4, embed_dim=64,
    max_seq_len=96)
DRAFT_CFG = TransformerConfig(
    vocab_size=64, num_layers=1, num_heads=2, embed_dim=32,
    max_seq_len=96)


class TestGreedyExactness:
    @pytest.mark.parametrize("num_draft", [1, 3, 4])
    def test_matches_greedy_any_draft(self, num_draft):
        """An UNRELATED random draft (acceptance ~ chance) must still
        reproduce the target's greedy tokens exactly — the accept rule
        plus rollback, not draft quality, carries correctness."""
        tp = _make(TARGET_CFG, 0)
        dp = _make(DRAFT_CFG, 1)
        prompt = jax.random.randint(jax.random.key(2), (3, 5), 0, 64)
        want = greedy_generate(TARGET_CFG, tp, prompt, 20)
        got = speculative_generate(
            TARGET_CFG, tp, DRAFT_CFG, dp, prompt, 20,
            num_draft=num_draft)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_matches_greedy_draft_is_target(self):
        """With draft == target every draft is accepted; output is still
        exactly greedy, and the stats confirm full acceptance."""
        tp = _make(TARGET_CFG, 0)
        prompt = jax.random.randint(jax.random.key(3), (2, 4), 0, 64)
        want = greedy_generate(TARGET_CFG, tp, prompt, 24)
        got, stats = speculative_generate(
            TARGET_CFG, tp, TARGET_CFG, tp, prompt, 24, num_draft=4,
            return_stats=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        rounds = int(stats["rounds"])
        assert int(stats["draft_accepted"]) == 4 * rounds * 2  # K * rounds * batch
        # full acceptance advances 5 tokens/round: ceil(23 / 5) rounds
        # after the prefill token
        assert rounds == -(-23 // 5)

    def test_jittable(self):
        tp = _make(TARGET_CFG, 0)
        dp = _make(DRAFT_CFG, 1)
        prompt = jnp.ones((2, 4), jnp.int32)
        fn = jax.jit(lambda t, d, p: speculative_generate(
            TARGET_CFG, t, DRAFT_CFG, d, p, 12, num_draft=3))
        want = greedy_generate(TARGET_CFG, tp, prompt, 12)
        np.testing.assert_array_equal(
            np.asarray(fn(tp, dp, prompt)), np.asarray(want))


class TestAcceptRule:
    def test_output_distribution_is_target(self):
        """Monte-Carlo: for fixed p != q, the emitted token at the first
        position (accepted draft or residual resample) must follow p
        exactly — the core speculative-sampling identity."""
        v = 8
        key = jax.random.key(0)
        p_row = jax.nn.softmax(jax.random.normal(jax.random.key(1), (v,)))
        q_row = jax.nn.softmax(
            jax.random.normal(jax.random.key(2), (v,)) * 1.5)
        n = 200_000
        # one draft position (K=1), n independent rows
        p = jnp.broadcast_to(p_row, (n, 2, v))  # [B, K+1, V]
        q = jnp.broadcast_to(q_row, (n, 1, v))  # [B, K, V]
        k1, k2 = jax.random.split(key)
        draft = jax.random.categorical(
            k1, jnp.log(q_row), shape=(n, 1))

        # Evaluate the rule row-wise (batch size 1 per row, so the
        # lockstep batch-min is the row's own acceptance).  The FIRST
        # emitted token is the draft when accepted, the residual
        # resample otherwise — that is the token whose law must be p.
        def one(pr, qr, dr, kk):
            _, e, acc = _accept_and_next(pr[None], qr[None], dr[None], kk)
            return jnp.where(acc[0] > 0, dr[0], e[0])

        keys = jax.random.split(k2, n)
        first_tok = jax.vmap(one)(p, q, draft, keys)
        counts = np.bincount(np.asarray(first_tok), minlength=v) / n
        np.testing.assert_allclose(counts, np.asarray(p_row), atol=0.006)

    def test_greedy_rule(self):
        """Zero-temperature (one-hot) p/q: accept iff draft == target
        argmax, emit the target argmax on rejection."""
        v = 6
        p_tok, q_tok = 2, 4
        p = jnp.broadcast_to(jax.nn.one_hot(p_tok, v), (1, 2, v))
        q = jnp.broadcast_to(jax.nn.one_hot(q_tok, v), (1, 1, v))
        # draft proposes q's argmax (4) which is NOT p's argmax (2)
        m, emit, accepted = _accept_and_next(
            p, q, jnp.array([[q_tok]]), jax.random.key(0))
        assert int(m) == 0 and int(accepted[0]) == 0
        assert int(emit[0]) == p_tok
        # and acceptance when they agree
        q2 = jnp.broadcast_to(jax.nn.one_hot(p_tok, v), (1, 1, v))
        m, emit, accepted = _accept_and_next(
            p, q2, jnp.array([[p_tok]]), jax.random.key(0))
        assert int(m) == 1 and int(accepted[0]) == 1
        # bonus token after full acceptance: p[:, K] argmax
        assert int(emit[0]) == p_tok


class TestSampling:
    def test_rollout_marginal_matches_plain_sampling(self):
        """End-to-end distribution check: over many independent batch
        rows, the SECOND generated token's marginal (which goes through
        a full draft/verify round, including lockstep-min coupling and
        rejection resampling) must match plain sampled decoding's."""
        v = 12
        cfg = TransformerConfig(vocab_size=v, num_layers=1, num_heads=2,
                                embed_dim=16, max_seq_len=16)
        dcfg = TransformerConfig(vocab_size=v, num_layers=1, num_heads=1,
                                 embed_dim=8, max_seq_len=16)
        tp = _make(cfg, 0)
        dp = _make(dcfg, 1)
        n = 8192
        prompt = jnp.ones((n, 3), jnp.int32)
        spec = speculative_generate(
            cfg, tp, dcfg, dp, prompt, 2, num_draft=2, temperature=1.0,
            key=jax.random.key(11))
        plain = sample_generate(
            cfg, tp, prompt, 2, jax.random.key(22), temperature=1.0)
        h_spec = np.bincount(np.asarray(spec[:, 4]), minlength=v) / n
        h_plain = np.bincount(np.asarray(plain[:, 4]), minlength=v) / n
        # total-variation distance between two 8192-sample empirical
        # distributions over 12 tokens; null-hypothesis TV measured
        # ~0.018 at this n, so 0.06 flags a systematic distribution
        # error with a wide margin over sampling noise
        tv = 0.5 * np.abs(h_spec - h_plain).sum()
        assert tv < 0.06, (tv, h_spec, h_plain)

    def test_sampled_rollout_plausible(self):
        """Sampled speculative rollout: tokens are valid, vary with the
        key, and with draft == target the acceptance is total (sampling
        from identical distributions accepts with probability 1)."""
        tp = _make(TARGET_CFG, 0)
        prompt = jnp.ones((2, 4), jnp.int32)
        toks, stats = speculative_generate(
            TARGET_CFG, tp, TARGET_CFG, tp, prompt, 16, num_draft=4,
            temperature=1.0, key=jax.random.key(7), return_stats=True)
        assert toks.shape == (2, 20)
        assert int(stats["draft_accepted"]) == 4 * int(stats["rounds"]) * 2
        toks2 = speculative_generate(
            TARGET_CFG, tp, TARGET_CFG, tp, prompt, 16, num_draft=4,
            temperature=1.0, key=jax.random.key(8))
        assert not np.array_equal(np.asarray(toks), np.asarray(toks2))

    def test_matches_vocab_range(self):
        tp = _make(TARGET_CFG, 0)
        dp = _make(DRAFT_CFG, 1)
        prompt = jnp.ones((2, 3), jnp.int32)
        toks = speculative_generate(
            TARGET_CFG, tp, DRAFT_CFG, dp, prompt, 10, num_draft=2,
            temperature=0.8, top_k=8, key=jax.random.key(1))
        t = np.asarray(toks)
        assert t.min() >= 0 and t.max() < 64


class TestStopTokens:
    def test_eos_freezes_and_lengths(self):
        tp = _make(TARGET_CFG, 0)
        dp = _make(DRAFT_CFG, 1)
        prompt = jnp.ones((2, 4), jnp.int32)
        plain, plain_len = greedy_generate(
            TARGET_CFG, tp, prompt, 16, stop_tokens=(3,), pad_token=0)
        spec, spec_len = speculative_generate(
            TARGET_CFG, tp, DRAFT_CFG, dp, prompt, 16, num_draft=3,
            stop_tokens=(3,), pad_token=0)
        np.testing.assert_array_equal(np.asarray(spec), np.asarray(plain))
        np.testing.assert_array_equal(
            np.asarray(spec_len), np.asarray(plain_len))


class TestTensorParallel:
    def test_tp_speculative_matches_unsharded(self, devices8):
        """TP target + replicated draft: same tokens as the unsharded
        speculative rollout AND as plain greedy, dense and flash."""
        from tpudist.models.speculative import tp_speculative_generate
        from tpudist.runtime.mesh import make_mesh

        tcfg = TransformerConfig(vocab_size=48, num_layers=2, num_heads=4,
                                 num_kv_heads=2, embed_dim=32,
                                 max_seq_len=48)
        dcfg = TransformerConfig(vocab_size=48, num_layers=1, num_heads=2,
                                 embed_dim=16, max_seq_len=48)
        tp = TransformerLM(tcfg).init(
            jax.random.key(0), jnp.zeros((1, 2), jnp.int32))["params"]
        dp = TransformerLM(dcfg).init(
            jax.random.key(1), jnp.zeros((1, 2), jnp.int32))["params"]
        prompt = jnp.asarray(
            np.random.default_rng(5).integers(0, 48, (2, 6)), jnp.int32)
        want = greedy_generate(tcfg, tp, prompt, 14)
        mesh = make_mesh({"data": 4, "model": 2})
        for attn in ("dense", "flash"):
            got, stats = tp_speculative_generate(
                tcfg, tp, dcfg, dp, prompt, 14, mesh, num_draft=3,
                decode_attention=attn, return_stats=True)
            np.testing.assert_array_equal(
                np.asarray(got), np.asarray(want), err_msg=attn)
            assert int(stats["rounds"]) >= 1

    def test_sp_speculative_matches_unsharded(self, devices8):
        """Sequence-sharded target cache + replicated draft: same tokens
        as plain greedy; the verify chunks run on the GSPMD-partitioned
        dense path over the sharded cache."""
        from tpudist.models.speculative import sp_speculative_generate
        from tpudist.runtime.mesh import make_mesh

        tcfg = TransformerConfig(vocab_size=48, num_layers=2, num_heads=4,
                                 num_kv_heads=2, embed_dim=32,
                                 max_seq_len=48)
        dcfg = TransformerConfig(vocab_size=48, num_layers=1, num_heads=2,
                                 embed_dim=16, max_seq_len=48)
        tp = TransformerLM(tcfg).init(
            jax.random.key(0), jnp.zeros((1, 2), jnp.int32))["params"]
        dp = TransformerLM(dcfg).init(
            jax.random.key(1), jnp.zeros((1, 2), jnp.int32))["params"]
        prompt = jnp.asarray(
            np.random.default_rng(6).integers(0, 48, (2, 6)), jnp.int32)
        want = greedy_generate(tcfg, tp, prompt, 14)
        mesh = make_mesh({"data": 4, "seq": 2})
        got = sp_speculative_generate(
            tcfg, tp, dcfg, dp, prompt, 14, mesh, num_draft=3)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        import dataclasses
        bad = dataclasses.replace(tcfg, max_seq_len=50)  # 50 % 4 != 0
        with pytest.raises(ValueError, match="max_seq_len"):
            sp_speculative_generate(
                bad, tp, dcfg, dp, prompt, 4,
                make_mesh({"data": 2, "seq": 4}))

    def test_tp_sp_speculative_matches_unsharded(self, devices8):
        """The 2-D layout: target weights over 'model', cache over heads
        AND sequence, replicated draft — same tokens as plain greedy."""
        from tpudist.models.speculative import tp_sp_speculative_generate
        from tpudist.runtime.mesh import make_mesh

        tcfg = TransformerConfig(vocab_size=48, num_layers=2, num_heads=4,
                                 num_kv_heads=2, embed_dim=32,
                                 max_seq_len=48)
        dcfg = TransformerConfig(vocab_size=48, num_layers=1, num_heads=2,
                                 embed_dim=16, max_seq_len=48)
        tp = TransformerLM(tcfg).init(
            jax.random.key(0), jnp.zeros((1, 2), jnp.int32))["params"]
        dp = TransformerLM(dcfg).init(
            jax.random.key(1), jnp.zeros((1, 2), jnp.int32))["params"]
        prompt = jnp.asarray(
            np.random.default_rng(7).integers(0, 48, (2, 6)), jnp.int32)
        want = greedy_generate(tcfg, tp, prompt, 12)
        mesh = make_mesh({"data": 2, "model": 2, "seq": 2})
        got = tp_sp_speculative_generate(
            tcfg, tp, dcfg, dp, prompt, 12, mesh, num_draft=3)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # both divisibility guards reject with clean errors
        with pytest.raises(ValueError, match="kv_heads"):
            tp_sp_speculative_generate(
                tcfg, tp, dcfg, dp, prompt, 4,
                make_mesh({"data": 1, "model": 4, "seq": 2}))
        import dataclasses
        bad = dataclasses.replace(tcfg, max_seq_len=50)  # 50 % 4 != 0
        with pytest.raises(ValueError, match="max_seq_len"):
            tp_sp_speculative_generate(
                bad, tp, dcfg, dp, prompt, 4,
                make_mesh({"data": 1, "model": 2, "seq": 4}))

    def test_tp_speculative_rejects_indivisible_heads(self, devices8):
        from tpudist.models.speculative import tp_speculative_generate
        from tpudist.runtime.mesh import make_mesh

        tcfg = TransformerConfig(vocab_size=48, num_layers=1, num_heads=4,
                                 num_kv_heads=2, embed_dim=32,
                                 max_seq_len=48)
        with pytest.raises(ValueError, match="kv_heads"):
            tp_speculative_generate(
                tcfg, None, DRAFT_CFG, None, jnp.ones((1, 4), jnp.int32),
                4, make_mesh({"data": 2, "model": 4}))


class TestValidation:
    def test_vocab_mismatch(self):
        bad = TransformerConfig(vocab_size=32, max_seq_len=96)
        with pytest.raises(ValueError, match="vocab"):
            speculative_generate(
                TARGET_CFG, None, bad, None, jnp.ones((1, 2), jnp.int32), 4)

    def test_too_long(self):
        with pytest.raises(ValueError, match="max_seq_len"):
            speculative_generate(
                TARGET_CFG, None, DRAFT_CFG, None,
                jnp.ones((1, 90), jnp.int32), 8)

    def test_bad_num_draft(self):
        with pytest.raises(ValueError, match="num_draft"):
            speculative_generate(
                TARGET_CFG, None, DRAFT_CFG, None,
                jnp.ones((1, 2), jnp.int32), 4, num_draft=0)


class TestAdaptiveDraftPolicy:
    """The acceptance-driven K policy (round-3 verdict item 2): K must
    shrink with acceptance, the estimator must invert the K-truncated
    accept rate, and the segmented rollout must stay distribution-exact."""

    def test_infer_acceptance_roundtrip(self):
        from tpudist.models.speculative import AdaptiveDraftPolicy

        for a in (0.3, 0.6, 0.8, 0.95):
            for k in (2, 4, 16):
                rate = AdaptiveDraftPolicy._per_row_mean(a, k) / k
                got = AdaptiveDraftPolicy.infer_acceptance(rate, k)
                assert abs(got - a) < 1e-6, (a, k, got)

    def test_best_k_monotone_in_acceptance(self):
        from tpudist.models.speculative import AdaptiveDraftPolicy

        pol = AdaptiveDraftPolicy(ladder=(2, 4, 8, 16),
                                  draft_cost_ratio=0.1)
        ks = [pol.best_k(a) for a in (0.2, 0.5, 0.8, 0.99)]
        assert ks == sorted(ks), ks
        assert ks[0] < ks[-1]  # bad drafts get short chunks
        assert pol.best_k(0.99) == 16

    def test_batch_lockstep_shrinks_k(self):
        from tpudist.models.speculative import AdaptiveDraftPolicy

        pol = AdaptiveDraftPolicy(ladder=(2, 4, 8, 16),
                                  draft_cost_ratio=0.1)
        # the batch-min prefix makes long chunks pay off later at B > 1
        assert pol.best_k(0.8, batch=8) <= pol.best_k(0.8, batch=1)

    def test_update_folds_stats_and_guards_zero_rounds(self):
        from tpudist.models.speculative import AdaptiveDraftPolicy

        pol = AdaptiveDraftPolicy(initial_acceptance=0.9)
        pol.update({"rounds": 0, "draft_accepted": 0}, batch=2,
                   num_draft=4)
        assert pol.acceptance == 0.9  # untouched
        # a fully-accepting observation pulls the estimate up to ~1
        pol.update({"rounds": 5, "draft_accepted": 5 * 4 * 2}, batch=2,
                   num_draft=4)
        assert pol.acceptance > 0.95

    def test_measured_costs_override_analytic_prior(self):
        from tpudist.models.speculative import AdaptiveDraftPolicy

        pol = AdaptiveDraftPolicy(ladder=(2, 4, 8, 16),
                                  draft_cost_ratio=0.3)
        # analytic prior at modest acceptance: long chunks look costly
        assert pol.best_k(0.75, batch=4) < 16
        # measured reality: the round cost is nearly K-independent (the
        # verify chunk is cache-stream-bound) — long chunks win
        for k in (2, 4, 8, 16):
            pol.observe_round_cost(k, 1.0 + 0.001 * k)
        assert pol.calibrated
        assert pol.best_k(0.9, batch=4) == 16

    def test_round_cost_linear_fit_interpolates(self):
        from tpudist.models.speculative import AdaptiveDraftPolicy

        pol = AdaptiveDraftPolicy(ladder=(2, 4, 8, 16))
        pol.observe_round_cost(2, 1.2)
        pol.observe_round_cost(16, 2.6)   # slope 0.1, intercept 1.0
        assert abs(pol.round_cost(8) - 1.8) < 1e-9
        assert pol.round_cost(2) == 1.2   # observed points stay exact

    def test_plain_cost_is_ema_smoothed_once_armed(self):
        from tpudist.models.speculative import AdaptiveDraftPolicy

        pol = AdaptiveDraftPolicy(ladder=(2, 4), ema=0.5)
        pol.set_plain_cost(0.1)
        assert pol._plain_tok_s == pytest.approx(0.1)
        pol.set_plain_cost(0.3)   # one noisy timing: damped, not adopted
        assert pol._plain_tok_s == pytest.approx(0.2)

    def test_best_k_allow_plain_false_bypasses_gate(self):
        from tpudist.models.speculative import AdaptiveDraftPolicy

        pol = AdaptiveDraftPolicy(ladder=(2, 4, 8, 16))
        for k in (2, 4, 8, 16):
            pol.observe_round_cost(k, 1.0)
        pol.set_plain_cost(0.1)
        assert pol.best_k(0.05, batch=4) == 0
        # the re-probe path must still get a real ladder K
        assert pol.best_k(0.05, batch=4, allow_plain=False) in (2, 4, 8, 16)

    def test_break_even_gate_falls_back_to_plain(self):
        from tpudist.models.speculative import AdaptiveDraftPolicy

        pol = AdaptiveDraftPolicy(ladder=(2, 4, 8, 16))
        for k in (2, 4, 8, 16):
            pol.observe_round_cost(k, 1.0)   # 1 s per round
        # at near-zero acceptance a round advances ~1 token/s; plain
        # decode at 10 tokens/s wins -> gate says 0 (plain)
        pol.set_plain_cost(0.1)
        assert pol.best_k(0.05, batch=4) == 0
        # at perfect acceptance a K=16 round advances 17 tokens/s > 10
        assert pol.best_k(1.0, batch=4) == 16
        # without the plain cost the gate is disarmed
        pol2 = AdaptiveDraftPolicy(ladder=(2, 4))
        pol2.observe_round_cost(2, 1.0)
        assert pol2.best_k(0.05, batch=4) in (2, 4)

    def test_adaptive_rollout_exactness_and_adaptation(self):
        from tpudist.models.speculative import (
            AdaptiveDraftPolicy,
            adaptive_speculative_generate,
        )

        t_params = _make(TARGET_CFG, 0)
        d_params = _make(DRAFT_CFG, 1)  # random draft: low acceptance
        prompt = jax.random.randint(jax.random.key(2), (2, 4), 0, 64)
        pol = AdaptiveDraftPolicy(ladder=(2, 8), draft_cost_ratio=0.2,
                                  initial_acceptance=0.97)
        toks, stats = adaptive_speculative_generate(
            TARGET_CFG, t_params, DRAFT_CFG, d_params, prompt, 24, pol,
            segment_tokens=8, return_stats=True, probe_plain=False)
        want = greedy_generate(TARGET_CFG, t_params, prompt, 24)
        np.testing.assert_array_equal(np.asarray(toks), np.asarray(want))
        # segments adapted: the random draft's acceptance is near zero,
        # so after the first segment the policy must drop to the short K
        assert stats["ks"][0] == 8          # optimistic start
        assert set(stats["ks"][1:]) == {2}  # measured reality
        assert stats["acceptance"][-1] < 0.3

    def test_plain_probe_arms_gate_and_stays_exact(self):
        """probe_plain (default): segment 2 runs the plain rollout as a
        probe (compile + same-input re-timed run arms the break-even
        gate), all while the output still bit-matches plain greedy."""
        from tpudist.models.speculative import (
            AdaptiveDraftPolicy,
            adaptive_speculative_generate,
        )

        t_params = _make(TARGET_CFG, 0)
        d_params = _make(DRAFT_CFG, 1)  # random draft: near-zero accept
        prompt = jax.random.randint(jax.random.key(2), (2, 4), 0, 64)
        pol = AdaptiveDraftPolicy(ladder=(2, 8),
                                  initial_acceptance=0.97)
        toks, stats = adaptive_speculative_generate(
            TARGET_CFG, t_params, DRAFT_CFG, d_params, prompt, 48, pol,
            segment_tokens=8, return_stats=True)
        want = greedy_generate(TARGET_CFG, t_params, prompt, 48)
        np.testing.assert_array_equal(np.asarray(toks), np.asarray(want))
        assert stats["ks"][1] == 0                          # the probe
        assert pol._plain_tok_s is not None                 # gate armed
        # CPU timing noise decides later segments' K; exactness and the
        # armed gate are the invariants this test pins

    def test_probe_arms_even_when_final_segment_truncates(self):
        """Review repro: max_new=20 / segment_tokens=8 gives lengths
        8, 8, 4 — the probe segment's re-timed same-length run must arm
        the gate even though no two PLAIN segments share a length."""
        from tpudist.models.speculative import (
            AdaptiveDraftPolicy,
            adaptive_speculative_generate,
        )

        t_params = _make(TARGET_CFG, 0)
        d_params = _make(DRAFT_CFG, 1)
        prompt = jax.random.randint(jax.random.key(2), (2, 4), 0, 64)
        pol = AdaptiveDraftPolicy(ladder=(2, 8),
                                  initial_acceptance=0.97)
        toks, stats = adaptive_speculative_generate(
            TARGET_CFG, t_params, DRAFT_CFG, d_params, prompt, 20, pol,
            segment_tokens=8, return_stats=True)
        want = greedy_generate(TARGET_CFG, t_params, prompt, 20)
        np.testing.assert_array_equal(np.asarray(toks), np.asarray(want))
        assert pol._plain_tok_s is not None

    def test_validation(self):
        from tpudist.models.speculative import (
            AdaptiveDraftPolicy,
            adaptive_speculative_generate,
        )

        with pytest.raises(ValueError, match="ladder"):
            AdaptiveDraftPolicy(ladder=())
        pol = AdaptiveDraftPolicy()
        with pytest.raises(ValueError, match="max_new_tokens"):
            adaptive_speculative_generate(
                TARGET_CFG, None, DRAFT_CFG, None,
                jnp.ones((1, 2), jnp.int32), 0, pol)
