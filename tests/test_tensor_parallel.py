"""Tensor parallelism: GSPMD-sharded transformer == single-device math."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpudist.models import TransformerConfig, TransformerLM
from tpudist.ops.losses import cross_entropy
from tpudist.parallel.tensor_parallel import (
    make_spmd_train_step,
    make_tp_state,
    shard_batch,
    spec_tree_from_rules,
    transformer_tp_rules,
)
from tpudist.runtime.mesh import make_mesh
from tpudist.train.state import TrainState

CFG = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                        embed_dim=32, max_seq_len=16)


def _model_and_batch():
    model = TransformerLM(CFG)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, CFG.vocab_size, (8, 16)), jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    params = model.init(jax.random.key(0), tokens)["params"]
    return model, params, tokens, targets


def _loss_fn(model):
    def loss_fn(params, batch, rng):
        tokens, targets = batch
        logits = model.apply({"params": params}, tokens)
        loss = cross_entropy(logits.reshape(-1, logits.shape[-1]),
                             targets.reshape(-1))
        return loss, {}
    return loss_fn


def test_spec_rules_cover_transformer():
    _, params, _, _ = _model_and_batch()
    specs = spec_tree_from_rules(params, transformer_tp_rules())
    flat = jax.tree.leaves_with_path(specs)
    named = {"/".join(str(k.key) for k in path): spec for path, spec in flat}
    assert named["block0/attn/qkv/kernel"] == P(None, "model")
    assert named["block0/attn/proj/kernel"] == P("model", None)
    assert named["block1/mlp/up/kernel"] == P(None, "model")
    assert named["block1/mlp/down/kernel"] == P("model", None)
    assert named["tok_embed/embedding"] == P("model", None)
    # norms replicate
    assert named["ln_f/scale"] == P()


@pytest.mark.parametrize("mesh_axes", [{"data": 1, "model": 4},
                                       {"data": 2, "model": 2},
                                       {"data": 4, "model": 1}])
def test_tp_matches_single_device(devices8, mesh_axes):
    model, params, tokens, targets = _model_and_batch()
    loss_fn = _loss_fn(model)

    # Single-device ground truth: two plain steps.
    ref_state = TrainState.create(model.apply, params, optax.sgd(0.1))
    for _ in range(2):
        (ref_loss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            ref_state.params, (tokens, targets), ref_state.rng)
        ref_state = ref_state.apply_gradients(grads)

    n = np.prod(list(mesh_axes.values()))
    mesh = make_mesh(mesh_axes, devices8[:n])
    state, specs = make_tp_state(model.apply, params, optax.sgd(0.1), mesh)
    step = make_spmd_train_step(loss_fn, mesh, specs)
    batch = shard_batch((tokens, targets), mesh)
    for _ in range(2):
        state, metrics = step(state, *batch)

    assert np.isclose(float(metrics["loss"]), float(ref_loss), atol=1e-4)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                atol=2e-4, rtol=2e-3),
        state.params, ref_state.params)


def test_transformer_forward_shapes():
    model, params, tokens, _ = _model_and_batch()
    logits = model.apply({"params": params}, tokens)
    assert logits.shape == (8, 16, CFG.vocab_size)
    assert logits.dtype == jnp.float32


def test_causal_masking_blocks_future():
    """Changing a future token must not change past logits."""
    model, params, tokens, _ = _model_and_batch()
    logits = model.apply({"params": params}, tokens)
    perturbed = tokens.at[:, -1].set((tokens[:, -1] + 1) % CFG.vocab_size)
    logits2 = model.apply({"params": params}, perturbed)
    np.testing.assert_allclose(np.asarray(logits[:, :-1]),
                               np.asarray(logits2[:, :-1]), atol=1e-6)
    assert not np.allclose(np.asarray(logits[:, -1]),
                           np.asarray(logits2[:, -1]))


def test_tp_rules_cover_gqa_projections(devices8):
    """GQA param names (attn/q, attn/kv) must get the Megatron column
    layout, not fall through to replicated."""
    import numpy as np

    from tpudist.models import TransformerConfig, TransformerLM
    from tpudist.parallel.tensor_parallel import (
        spec_tree_from_rules, transformer_tp_rules,
    )

    cfg = TransformerConfig(vocab_size=32, num_layers=1, num_heads=4,
                            num_kv_heads=2, embed_dim=32, max_seq_len=16)
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = TransformerLM(cfg).init(jax.random.key(0), tokens)["params"]
    specs = spec_tree_from_rules(params, transformer_tp_rules())
    attn = specs["block0"]["attn"]
    assert attn["q"]["kernel"] == P(None, "model")
    assert attn["kv"]["kernel"] == P(None, "model")
    assert attn["proj"]["kernel"] == P("model", None)
