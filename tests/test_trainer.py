"""End-to-end Trainer slice on the 8-device mesh: learns, snapshots, resumes
(`mnist_ddp_elastic.py` Trainer parity, SURVEY.md §7 step 4-5)."""

import jax
import numpy as np
import optax

from tpudist.data.loader import ShardedLoader
from tpudist.data.mnist import synthetic_mnist
from tpudist.models import MLP
from tpudist.runtime.mesh import data_mesh
from tpudist.train.trainer import Trainer, TrainerConfig


def _make_trainer(tmp_path, epochs=2, n=512, **config_overrides):
    mesh = data_mesh(8)
    train_ds = synthetic_mnist("train", n=n)
    test_ds = synthetic_mnist("test", n=256)
    train_loader = ShardedLoader(
        [train_ds.images, train_ds.labels], global_batch=64, mesh=mesh, shuffle=True
    )
    test_loader = ShardedLoader([test_ds.images, test_ds.labels], global_batch=64, mesh=mesh)
    model = MLP(hidden_layers=1, features=64)
    params = model.init(jax.random.key(0), train_ds.images[:1])["params"]
    config = TrainerConfig(
        total_epochs=epochs,
        save_every=1,
        batch_size=64,
        snapshot_path=str(tmp_path / "snapshot.npz"),
        log_every=1000,
        **config_overrides,
    )
    return Trainer(
        config, model.apply, params, optax.adam(1e-3), mesh, train_loader, test_loader
    ), mesh


def test_trainer_learns_and_snapshots(tmp_path):
    trainer, _ = _make_trainer(tmp_path, epochs=3)
    summary = trainer.train()
    assert summary["test_accuracy"] > 0.9  # synthetic digits are easy
    assert (tmp_path / "snapshot.npz").exists()
    assert summary["images_per_sec"] > 0


def test_trainer_resumes_from_snapshot(tmp_path):
    trainer, _ = _make_trainer(tmp_path, epochs=2)
    trainer.train()
    step_after = int(jax.device_get(trainer.state.step))

    resumed, _ = _make_trainer(tmp_path, epochs=2)
    # snapshot said 2 epochs already ran -> nothing left to do
    assert resumed.epochs_run == 2
    assert int(jax.device_get(resumed.state.step)) == step_after
    np.testing.assert_allclose(
        np.asarray(jax.tree.leaves(resumed.state.params)[0]),
        np.asarray(jax.tree.leaves(trainer.state.params)[0]),
    )
    # training further continues from epoch 2
    resumed.train(max_epochs=3)
    assert resumed.epochs_run == 3


def test_trainer_eval_exact_with_padded_loader(tmp_path):
    """A drop_last=False test loader over N=250 samples on 8 shards pads 6
    wrap-around duplicates; test() must equal the single-pass accuracy over
    the 250 true samples exactly (VERDICT r1 weak #7)."""
    mesh = data_mesh(8)
    train_ds = synthetic_mnist("train", n=256)
    test_ds = synthetic_mnist("test", n=250)
    train_loader = ShardedLoader(
        [train_ds.images, train_ds.labels], global_batch=64, mesh=mesh)
    test_loader = ShardedLoader(
        [test_ds.images, test_ds.labels], global_batch=64, mesh=mesh,
        drop_last=False)
    model = MLP(hidden_layers=1, features=64)
    params = model.init(jax.random.key(0), train_ds.images[:1])["params"]
    config = TrainerConfig(
        total_epochs=1, save_every=1, batch_size=64,
        snapshot_path=str(tmp_path / "snap.npz"), log_every=1000)
    trainer = Trainer(config, model.apply, params, optax.adam(1e-3), mesh,
                      train_loader, test_loader)

    acc = trainer.test()
    logits = model.apply(
        {"params": jax.device_get(trainer.state.params)}, test_ds.images)
    expected_correct = int(
        (np.argmax(np.asarray(logits), -1) == test_ds.labels).sum())
    assert acc == expected_correct / 250


def test_trainer_profile_dir_writes_trace(tmp_path):
    """profile_dir captures a jax.profiler trace of the first trained epoch
    (SURVEY.md §5: tracing the reference never had)."""
    trainer, _ = _make_trainer(tmp_path, epochs=1, n=128)
    trace_dir = tmp_path / "trace"
    trainer.config.profile_dir = str(trace_dir)
    trainer.config.eval_every_epoch = False
    trainer.train()
    assert any(p.is_file() for p in trace_dir.rglob("*")), "no trace files written"


def test_trainer_fused_dispatch_matches_stepwise(tmp_path):
    """steps_per_dispatch>1 (lax.scan fused loop + tail steps) must produce
    the same trained params as the stepwise path."""
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    # n=448, batch 64 -> 7 steps/epoch: with steps_per_dispatch=3 that is
    # 2 fused groups + 1 stepwise tail step per epoch.
    trainer_a, _ = _make_trainer(tmp_path / "a", epochs=2, n=448)
    summary_a = trainer_a.train()

    trainer_b, _ = _make_trainer(
        tmp_path / "b", epochs=2, n=448, steps_per_dispatch=3)
    assert trainer_b.train_loop is not None
    summary_b = trainer_b.train()

    # epoch-mean metrics weight every optimizer step equally on both paths
    np.testing.assert_allclose(summary_a["loss"], summary_b["loss"], rtol=1e-6)

    assert int(trainer_a.state.step) == int(trainer_b.state.step) == 14
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        trainer_a.state.params, trainer_b.state.params)


def test_trainer_device_prefetch_loss_parity(tmp_path):
    """Device-input pipelining is a scheduling change only: the same
    per-step numerics — epoch-mean loss and trained params bitwise —
    with and without the prefetch iterator."""
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    trainer_a, _ = _make_trainer(tmp_path / "a", epochs=2, n=256,
                                 device_prefetch=0)
    summary_a = trainer_a.train()
    trainer_b, _ = _make_trainer(tmp_path / "b", epochs=2, n=256,
                                 device_prefetch=3)
    summary_b = trainer_b.train()
    np.testing.assert_array_equal(summary_a["loss"], summary_b["loss"])
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        trainer_a.state.params, trainer_b.state.params)


def test_trainer_async_snapshot_durable_with_meta(tmp_path):
    """train() returning implies the (async) snapshot is on disk, with
    the device step scalar resolved to a JSON int on the writer thread."""
    import json

    trainer, _ = _make_trainer(tmp_path, epochs=1)
    assert trainer.config.async_snapshot  # the default path IS async
    trainer.train()
    assert (tmp_path / "snapshot.npz").exists()
    meta = json.loads((tmp_path / "snapshot.meta.json").read_text())
    assert meta["epochs_run"] == 1
    assert meta["step"] == int(jax.device_get(trainer.state.step))


def test_trainer_sync_snapshot_opt_out(tmp_path):
    trainer, _ = _make_trainer(tmp_path, epochs=1, async_snapshot=False)
    trainer.train()
    assert (tmp_path / "snapshot.npz").exists()
