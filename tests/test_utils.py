"""Utility-layer coverage: config CLI override, metrics, tree helpers."""

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudist.utils.config import cli_override, config_field
from tpudist.utils.metrics import MetricLogger, ThroughputMeter, maybe_profile
from tpudist.utils.trees import (
    flatten_with_names,
    host_to_leaf,
    leaf_to_host,
    tree_size_bytes,
    tree_to_numpy,
    unflatten_like,
)


@dataclasses.dataclass
class _Cfg:
    epochs: int = config_field(3, "total epochs")
    lr: float = config_field(0.1, "learning rate")
    name: str = config_field("run", "run name")
    bf16: bool = config_field(False, "bfloat16 compute")


class TestConfig:
    def test_defaults(self):
        cfg = cli_override(_Cfg, [])
        assert cfg == _Cfg()

    def test_override_each_type(self):
        cfg = cli_override(
            _Cfg, ["--epochs", "7", "--lr", "3e-4", "--name", "x",
                   "--bf16", "true"])
        assert cfg.epochs == 7 and isinstance(cfg.epochs, int)
        assert cfg.lr == pytest.approx(3e-4)
        assert cfg.name == "x"
        assert cfg.bf16 is True

    def test_bool_false_spellings(self):
        for spelling in ("0", "false", "no"):
            assert cli_override(_Cfg, ["--bf16", spelling]).bf16 is False


class TestMetrics:
    def test_throughput_meter_excludes_warmup(self):
        m = ThroughputMeter(warmup_steps=1)
        m.start()
        time.sleep(0.05)   # warmup step: excluded from the rate
        m.step(1000)
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 0.01:
            pass
        m.step(100)
        rate = m.items_per_sec
        assert 0 < rate < 100 / 0.0099
        assert m.mean_step_time > 0

    def test_metric_logger_means(self):
        ml = MetricLogger()
        ml.update(loss=2.0, acc=0.5)
        ml.update(loss=4.0, acc=1.0)
        means = ml.means()
        assert means["loss"] == pytest.approx(3.0)
        assert means["acc"] == pytest.approx(0.75)
        ml.reset()
        assert ml.means() == {}

    def test_maybe_profile_noop_and_trace(self, tmp_path):
        with maybe_profile(None):
            pass  # no-op path
        with maybe_profile(str(tmp_path / "trace")):
            jnp.zeros((4,)).block_until_ready()
        assert any((tmp_path / "trace").rglob("*")), "no trace written"


class TestTrees:
    def _tree(self):
        return {
            "a": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
            "nest": {"b": jnp.ones((4,), jnp.int32)},
            "key": jax.random.key(0),
            "scalar": jnp.float32(2.5),
        }

    def test_numpy_roundtrip_including_prng_keys(self):
        tree = self._tree()
        host = tree_to_numpy(tree)
        back = jax.tree.map(host_to_leaf, tree, host)
        assert jnp.issubdtype(back["key"].dtype, jax.dtypes.prng_key)
        np.testing.assert_array_equal(
            jax.random.key_data(back["key"]),
            jax.random.key_data(tree["key"]))
        np.testing.assert_array_equal(np.asarray(back["a"]),
                                      np.asarray(tree["a"]))

    def test_flatten_unflatten_roundtrip(self):
        tree = self._tree()
        named = flatten_with_names(tree_to_numpy(tree))
        assert all(isinstance(k, str) for k in named)
        back = unflatten_like(tree, named)
        np.testing.assert_array_equal(
            np.asarray(back["nest"]["b"]), np.asarray(tree["nest"]["b"]))

    def test_unflatten_shape_mismatch_raises(self):
        tree = self._tree()
        named = flatten_with_names(tree_to_numpy(tree))
        bad = dict(named)
        first = next(k for k in bad if "a" in k)
        bad[first] = np.zeros((9, 9), np.float32)
        with pytest.raises((ValueError, AssertionError)):
            unflatten_like(tree, bad)

    def test_tree_size_bytes(self):
        assert tree_size_bytes(
            {"x": jnp.zeros((2, 3), jnp.float32)}) == 24
