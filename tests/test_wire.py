"""Checksummed wire format (ISSUE 13): round-trips over every record
kind, corruption/truncation detection, legacy plain-JSON fallback, and
the typed WireError attribution the router's strike ledger consumes."""

import json

import pytest

from tpudist.runtime import wire

DOCS = {
    "request": {"key": "00000007", "prompt": [3, 1, 4],
                "max_new_tokens": 9, "deadline_s": None, "priority": 0},
    "completion": {"key": "00000007", "tokens": [5, 6],
                   "reason": "length", "replica": "r1"},
    "journal": {"schema": "tpudist.journal/1", "rid": "caller",
                "assigned": None, "attempts": 0, "terminal": None},
    "heartbeat": {"replica": "r0", "served": 12, "clean": True},
    "prefix": {"replica": "r0", "hashes": [12345678901, 42]},
    "kv_migration": {"key": "00000007", "rid": "caller",
                     "prompt": [3, 1, 4], "max_new_tokens": 9,
                     "first": 5, "true_len": 3, "block_size": 8,
                     "chain": [], "published_at": 12.5,
                     "layers": [{"k": {"b64": "AAAA", "dtype": "float32",
                                       "shape": [1, 8, 4]},
                                 "v": {"b64": "AAAA", "dtype": "float32",
                                       "shape": [1, 8, 4]}}]},
    "pullreq": {"key": "00000007", "prompt": [3, 1, 4]},
    "pulldone": {"key": "00000007", "ref": "ns/kv/pull-00000007",
                 "owner": "r0"},
}


class TestRoundTrip:
    @pytest.mark.parametrize("kind", sorted(wire.WIRE_KINDS))
    def test_every_kind_round_trips(self, kind):
        doc = DOCS[kind]
        payload = wire.encode_record(kind, doc)
        assert payload.startswith(wire.WIRE_MAGIC)
        assert wire.decode_record(payload) == doc
        assert wire.decode_record(payload, expect=kind) == doc

    @pytest.mark.parametrize("kind", sorted(wire.WIRE_KINDS))
    def test_every_single_bit_flip_is_caught(self, kind):
        """The property the whole subsystem rests on: NO single-bit
        flip anywhere past the magic survives decode.  (A flip inside
        the magic makes the payload legacy-JSON-shaped garbage, which
        surfaces as a WireError too — json instead of checksum.)"""
        payload = wire.encode_record(kind, DOCS[kind])
        for pos in range(len(payload)):
            for bit in (0x01, 0x80):
                bad = (payload[:pos] + bytes([payload[pos] ^ bit])
                       + payload[pos + 1:])
                with pytest.raises(wire.WireError):
                    wire.decode_record(bad)

    def test_unknown_kind_rejected_at_encode(self):
        with pytest.raises(ValueError, match="unknown wire record kind"):
            wire.encode_record("probe", {"x": 1})

    def test_crc32c_known_vector(self):
        # the iSCSI check vector: crc32c(b"123456789") == 0xE3069283
        assert wire.crc32c(b"123456789") == 0xE3069283
        # incremental == one-shot
        assert wire.crc32c(b"6789", wire.crc32c(b"12345")) \
            == wire.crc32c(b"123456789")


class TestFailureModes:
    def test_truncated_frame(self):
        payload = wire.encode_record("completion", DOCS["completion"])
        with pytest.raises(wire.WireError) as ei:
            wire.decode_record(payload[:6])
        assert ei.value.reason == "truncated"

    def test_checksum_mismatch_reason_and_attribution(self):
        payload = wire.encode_record("completion", DOCS["completion"])
        bad = payload[:-1] + bytes([payload[-1] ^ 0x10])
        with pytest.raises(wire.WireError) as ei:
            wire.decode_record(bad, expect="completion", namespace="ns",
                               key="00000007", replica="r1")
        err = ei.value
        assert err.reason == "checksum"
        assert (err.namespace, err.key, err.replica) \
            == ("ns", "00000007", "r1")
        assert "r1" in str(err) and "checksum" in str(err)

    def test_kind_mismatch(self):
        payload = wire.encode_record("journal", DOCS["journal"])
        with pytest.raises(wire.WireError) as ei:
            wire.decode_record(payload, expect="completion")
        assert ei.value.reason == "kind"
        assert ei.value.kind == "journal"

    def test_unknown_tag_is_schema(self):
        # a future writer's tag: rebuild the frame with a valid crc
        # over an unknown tag so the schema check (not the checksum)
        # is what fires
        import struct

        body = bytes([99]) + json.dumps({"v": 2}).encode()
        payload = (wire.WIRE_MAGIC
                   + struct.pack(">I", wire.crc32c(body)) + body)
        with pytest.raises(wire.WireError) as ei:
            wire.decode_record(payload)
        assert ei.value.reason == "schema"

    def test_non_dict_body_is_json_error(self):
        import struct

        body = bytes([wire.WIRE_KINDS["request"]]) + b"[1, 2]"
        payload = (wire.WIRE_MAGIC
                   + struct.pack(">I", wire.crc32c(body)) + body)
        with pytest.raises(wire.WireError) as ei:
            wire.decode_record(payload)
        assert ei.value.reason == "json"


class TestLegacyFallback:
    def test_plain_json_still_decodes(self):
        """Pre-integrity writers (and tests that plant done keys by
        hand) send unframed JSON — it must decode without a checksum,
        and ``expect`` must not be enforced (legacy carries no kind)."""
        doc = {"key": "k", "tokens": [1], "reason": "length",
               "replica": "r9"}
        raw = json.dumps(doc).encode()
        assert wire.decode_record(raw) == doc
        assert wire.decode_record(raw, expect="completion") == doc
        assert wire.decode_record(raw, expect="journal") == doc

    def test_legacy_garbage_is_json_error(self):
        for raw in (b"not json", b"[1, 2, 3]", b"\xff\xfe garbage"):
            with pytest.raises(wire.WireError) as ei:
                wire.decode_record(raw, key="k", replica="r2")
            assert ei.value.reason == "json"
            assert ei.value.replica == "r2"
