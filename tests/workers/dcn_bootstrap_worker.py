"""Worker for the true multi-process DCN bootstrap test: calls the
PUBLIC ``tpudist.runtime.initialize()`` with NO arguments — the world
description comes from the launcher env contract (TPUDIST_COORDINATOR /
TPUDIST_NUM_PROCESSES / TPUDIST_PROCESS_ID, the RANK/WORLD_SIZE analog of
`mnist_ddp_elastic.py:44-45`) — then proves the joined world with a
compiled cross-process psum.
"""

import json
import os
import sys

from tpudist.runtime.simulate import force_cpu_devices

force_cpu_devices(1, check=False)

import jax  # noqa: E402
import numpy as np  # noqa: E402

import tpudist  # noqa: E402


def main() -> int:
    ctx = tpudist.runtime.initialize()  # env-driven: the DCN bootstrap path
    out = {
        "process_index": ctx.process_index,
        "process_count": ctx.process_count,
        "global_devices": ctx.global_device_count,
        "local_devices": ctx.local_device_count,
        "is_coordinator": ctx.is_coordinator,
    }

    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
    sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("data"))
    local = np.full((1, 4), ctx.process_index + 1, np.float32)
    x = jax.make_array_from_process_local_data(
        sh, local, (ctx.global_device_count, 4))

    @jax.jit
    def allsum(x):
        def f(x):
            return jax.lax.psum(x, "data")
        return jax.shard_map(f, mesh=mesh,
                             in_specs=jax.sharding.PartitionSpec("data"),
                             out_specs=jax.sharding.PartitionSpec("data"))(x)

    summed = allsum(x)
    out["psum"] = float(np.asarray(summed.addressable_shards[0].data)[0, 0])
    out["hlo_all_reduce"] = "all-reduce" in jax.jit(
        lambda x: allsum(x)).lower(x).compile().as_text()

    with open(os.path.join(os.environ["WORKER_OUT_DIR"],
                           f"dcn_{ctx.process_index}.json"), "w") as fh:
        json.dump(out, fh)
    jax.distributed.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
