"""Worker for the true multi-process DCN bootstrap test: calls the
PUBLIC ``tpudist.runtime.initialize()`` with NO arguments — the world
description comes from the launcher env contract (TPUDIST_COORDINATOR /
TPUDIST_NUM_PROCESSES / TPUDIST_PROCESS_ID, the RANK/WORLD_SIZE analog of
`mnist_ddp_elastic.py:44-45`) — then proves the joined world with a
compiled cross-process psum.
"""

import json
import os
import sys

from tpudist.runtime.simulate import force_cpu_devices

force_cpu_devices(int(os.environ.get("WORKER_LOCAL_DEVICES", "1")),
                  check=False)

import jax  # noqa: E402
import numpy as np  # noqa: E402

import tpudist  # noqa: E402


def main() -> int:
    ctx = tpudist.runtime.initialize()  # env-driven: the DCN bootstrap path
    out = {
        "process_index": ctx.process_index,
        "process_count": ctx.process_count,
        "global_devices": ctx.global_device_count,
        "local_devices": ctx.local_device_count,
        "is_coordinator": ctx.is_coordinator,
    }

    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
    sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("data"))
    local = np.full((ctx.local_device_count, 4), ctx.process_index + 1,
                    np.float32)
    x = jax.make_array_from_process_local_data(
        sh, local, (ctx.global_device_count, 4))

    @jax.jit
    def allsum(x):
        def f(x):
            return jax.lax.psum(x, "data")
        return jax.shard_map(f, mesh=mesh,
                             in_specs=jax.sharding.PartitionSpec("data"),
                             out_specs=jax.sharding.PartitionSpec("data"))(x)

    summed = allsum(x)
    out["psum"] = float(np.asarray(summed.addressable_shards[0].data)[0, 0])
    out["hlo_all_reduce"] = "all-reduce" in jax.jit(
        lambda x: allsum(x)).lower(x).compile().as_text()

    if os.environ.get("WORKER_HYBRID"):
        # the 2-axis DCN×ICI mesh (the reference's nodes × procs flagship
        # shape, `mnist_ddp_elastic.py:5-6`): axis 0 spans PROCESSES
        # (DCN on real pods), axis 1 each process's own devices (ICI);
        # the gradient-style reduction runs over BOTH axes in one
        # compiled program — XLA inserts the cross-process collective
        P_, L_ = ctx.process_count, ctx.local_device_count
        mesh2 = jax.sharding.Mesh(
            np.array(jax.devices()).reshape(P_, L_), ("dcn", "ici"))
        spec2 = jax.sharding.PartitionSpec(("dcn", "ici"))
        sh2 = jax.sharding.NamedSharding(mesh2, spec2)
        x2 = jax.make_array_from_process_local_data(
            sh2, local, (ctx.global_device_count, 4))

        @jax.jit
        def allsum2(x):
            def f(x):
                return jax.lax.psum(x, ("dcn", "ici"))
            return jax.shard_map(f, mesh=mesh2, in_specs=spec2,
                                 out_specs=spec2)(x)

        s2 = allsum2(x2)
        out["hybrid_psum"] = float(
            np.asarray(s2.addressable_shards[0].data)[0, 0])
        out["hybrid_hlo_all_reduce"] = "all-reduce" in jax.jit(
            lambda x: allsum2(x)).lower(x2).compile().as_text()

    with open(os.path.join(os.environ["WORKER_OUT_DIR"],
                           f"dcn_{ctx.process_index}.json"), "w") as fh:
        json.dump(out, fh)
    jax.distributed.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
