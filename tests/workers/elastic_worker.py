"""Worker for the end-to-end elastic test: a 2-process DP training job that
checkpoints, gets killed mid-run, and resumes across a gang restart — the
TorchElastic lifecycle (`mnist_ddp_elastic.py:5-6` + snapshot/resume
`:54-68`) over real process boundaries.

Each gang attempt:
* joins the world via the launcher's TPUDIST_* env (jax.distributed),
* restores the newest durable checkpoint (fresh start if none),
* trains to TOTAL_STEPS, rank 0 checkpointing every CKPT_EVERY steps,
* on attempt 0, rank 1 exits(7) at FAIL_AT_STEP — the launcher tears the
  gang down and restarts it; attempt 1 must resume from the last commit,
  not from scratch.
"""

import os
import sys

from tpudist.runtime.simulate import force_cpu_devices

# check=False: the probe would initialize the backend before
# distributed.initialize below, which jax forbids
force_cpu_devices(1, check=False)
import jax  # noqa: E402

import numpy as np  # noqa: E402
import optax  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from tpudist.elastic.checkpoint import Checkpointer  # noqa: E402
from tpudist.models import MLP  # noqa: E402
from tpudist.ops.losses import cross_entropy  # noqa: E402
from tpudist.parallel.data_parallel import (  # noqa: E402
    broadcast_params,
    make_dp_train_step,
)
from tpudist.runtime import distributed  # noqa: E402
from tpudist.train.state import TrainState  # noqa: E402

TOTAL_STEPS = 20
CKPT_EVERY = 5
FAIL_AT_STEP = 12


def batch_for(step: int, mesh: Mesh, ctx):
    """Deterministic per-step global batch, assembled from per-process
    shards (the DistributedSampler contract: same epoch seed everywhere,
    disjoint slices per rank)."""
    rng = np.random.default_rng(1000 + step)
    gx = rng.standard_normal((8, 28 * 28)).astype(np.float32)
    gy = rng.integers(0, 10, 8)
    n = ctx.process_count
    lo = ctx.process_index * (8 // n)
    x = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), gx[lo : lo + 8 // n], (8, 28 * 28)
    )
    y = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), gy[lo : lo + 8 // n], (8,)
    )
    return x, y


def main() -> int:
    ctx = distributed.initialize()
    mesh = Mesh(np.asarray(jax.devices()), ("data",))
    attempt = int(os.environ["TPUDIST_RESTART_ATTEMPT"])

    model = MLP(hidden_layers=1, features=32)
    params = model.init(jax.random.key(0), np.zeros((1, 28 * 28), np.float32))["params"]

    def loss_fn(p, batch, rng):
        x, y = batch
        return cross_entropy(model.apply({"params": p}, x), y), {}

    state = TrainState.create(
        model.apply, broadcast_params(params, mesh), optax.sgd(0.1), rng=0
    )
    ckpt = Checkpointer(os.environ["WORKER_CKPT_DIR"], keep=2)
    template = {"params": state.params, "opt_state": state.opt_state}
    restored = ckpt.restore_latest(template)
    start_step = 0
    if restored is not None:
        start_step, tree, _meta = restored
        state = state.replace(
            params=broadcast_params(tree["params"], mesh),
            opt_state=broadcast_params(tree["opt_state"], mesh),
        )
    if ctx.process_index == 0:
        with open(os.path.join(os.environ["WORKER_CKPT_DIR"],
                               f"start_attempt{attempt}.txt"), "w") as fh:
            fh.write(str(start_step))

    step_fn = make_dp_train_step(loss_fn, mesh, donate=False)
    for step in range(start_step, TOTAL_STEPS):
        state, metrics = step_fn(state, *batch_for(step, mesh, ctx))
        done = step + 1
        if done % CKPT_EVERY == 0 and ctx.process_index == 0:
            ckpt.save(done, {"params": state.params, "opt_state": state.opt_state})
        if (attempt == 0 and done == FAIL_AT_STEP
                and os.environ.get("WORKER_INJECT_FAILURE") == "1"
                and ctx.process_index == 1):
            print("rank 1 simulating preemption", flush=True)
            return 7

    if ctx.process_index == 0:
        loss = float(jax.device_get(metrics["loss"]))
        with open(os.path.join(os.environ["WORKER_CKPT_DIR"], "final.txt"), "w") as fh:
            fh.write(f"{TOTAL_STEPS} {loss}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
