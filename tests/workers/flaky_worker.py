"""Minimal (jax-free) gang worker for launcher blacklist tests: records
its stable spawn id / attempt / world, then fails iff
``WORKER_FAIL_SPAWN_IDS`` lists its spawn id — either bare (``"1"``, a
persistently bad "host") or pinned to one attempt (``"1@0"``, a host
that is bad only then — lets tests steer exactly which attempts fail)."""

import json
import os
import sys

sid = os.environ.get("TPUDIST_SPAWN_ID", "?")
attempt = int(os.environ["TPUDIST_RESTART_ATTEMPT"])
out = os.environ.get("WORKER_OUT_DIR")
if out:
    with open(os.path.join(out, "events.jsonl"), "a") as fh:
        fh.write(json.dumps({
            "sid": sid,
            "attempt": attempt,
            "world": int(os.environ["TPUDIST_NUM_PROCESSES"]),
            "rank": int(os.environ["TPUDIST_PROCESS_ID"]),
        }) + "\n")
fail_ids = os.environ.get("WORKER_FAIL_SPAWN_IDS", "").split(",")
sys.exit(3 if sid in fail_ids or f"{sid}@{attempt}" in fail_ids else 0)
