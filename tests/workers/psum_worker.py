"""Worker script for launcher tests: join the multi-process world spawned by
``tpudist.runtime.launch``, run one cross-process ``psum``, verify it, and
report success via exit code — the smallest real multi-host program.

Standalone (not collected by pytest): runs in a fresh interpreter per
worker, so it does its own platform forcing (the ambient environment may
register a real TPU backend; workers must stay on simulated CPU devices).
"""

import os
import sys

from tpudist.runtime.simulate import force_cpu_devices

# check=False: the probe would initialize the backend before
# distributed.initialize below, which jax forbids
force_cpu_devices(1, check=False)
import jax  # noqa: E402

import numpy as np  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from tpudist.runtime import distributed  # noqa: E402


def main() -> int:
    ctx = distributed.initialize()  # reads the TPUDIST_* launcher env
    nprocs = int(os.environ["TPUDIST_NUM_PROCESSES"])
    assert ctx.process_count == nprocs, (ctx, nprocs)
    assert ctx.global_device_count == nprocs * ctx.local_device_count

    # One global psum: every process contributes (rank + 1); the total must
    # be identical everywhere — the DDP gradient-allreduce shape.
    mesh = Mesh(np.asarray(jax.devices()), ("data",))
    f = jax.jit(jax.shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
                              in_specs=P("data"), out_specs=P()))
    local = np.full((ctx.local_device_count,), ctx.process_index + 1, np.float32)
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), local, (ctx.global_device_count,)
    )
    total = float(np.asarray(jax.device_get(f(arr).addressable_data(0)))[0])
    expected = ctx.local_device_count * nprocs * (nprocs + 1) / 2
    assert total == expected, (total, expected)

    # Optional: fail on the first gang attempt to exercise restart logic.
    if os.environ.get("WORKER_FAIL_ON_ATTEMPT") == os.environ.get(
            "TPUDIST_RESTART_ATTEMPT") and ctx.process_index == 0:
        print("worker 0 injecting failure", flush=True)
        return 17

    # Optional: a world that only works below a size threshold — exercises
    # the launcher's elastic shrink (min_nprocs / discover_cmd).
    limit = os.environ.get("WORKER_FAIL_IF_WORLD_GT")
    if limit and nprocs > int(limit) and ctx.process_index == nprocs - 1:
        print(f"worker {ctx.process_index}: world {nprocs} too big", flush=True)
        return 13

    out_dir = os.environ.get("WORKER_OUT_DIR")
    if out_dir:
        with open(os.path.join(out_dir, f"rank{ctx.process_index}.txt"), "w") as fh:
            fh.write(f"{total}\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
