"""Worker for the TTL-heartbeat elastic test: a 3-process DP job where one
worker SIGKILLs itself mid-training; the survivors must detect the loss via
the coordination service's TTL leases (NOT launcher exit-code polling),
roll back to the last commit, re-rendezvous at world 2, fire the lr-rescale
reset callback, and finish — the capability torchrun's c10d rendezvous
(`mnist_ddp_elastic.py:5-6`) and Horovod's elastic driver
(`horovod_mnist_elastic.py:55,108`) deliver, re-built on
``tpudist.runtime.coord`` + ``tpudist.elastic.worker``.

Gradient sync rides ``HostCollectives`` (dynamic membership) rather than a
fixed compiled mesh, which is exactly what lets the world shrink without a
process restart.  Every worker appends JSON events to
``$WORKER_OUT_DIR/events_<spawn_id>.jsonl`` for the test to assert on.
"""

import json
import os
import signal
import sys
import time

from tpudist.runtime.simulate import force_cpu_devices

force_cpu_devices(1, check=False)
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from tpudist.elastic.state import ElasticState, HostDataState  # noqa: E402
from tpudist.elastic.worker import (  # noqa: E402
    OverlappedGradSync,
    run_elastic_worker,
)
from tpudist.models import MLP  # noqa: E402
from tpudist.ops.losses import cross_entropy  # noqa: E402
from tpudist.train.state import TrainState  # noqa: E402

TOTAL_STEPS = 30
COMMIT_EVERY = 5
GLOBAL_BATCH = 12  # divisible by both world sizes (3 and 2)
BASE_LR = 0.1

SPAWN_ID = os.environ.get("TPUDIST_PROCESS_ID", "x")
# comma-separated spawn_id:step pairs, e.g. "2:13,1:22" for a double kill;
# only armed on the FIRST gang attempt — a launcher-restarted gang (the
# full-gang-loss test) must run to completion
KILL_PLAN = dict(
    pair.split(":") for pair in
    os.environ.get("WORKER_KILL_PLAN", "").split(",") if pair)
KILL_SPAWN_ID = os.environ.get("WORKER_KILL_SPAWN_ID")
KILL_AT_STEP = int(os.environ.get("WORKER_KILL_AT_STEP", "13"))
if KILL_SPAWN_ID is not None:
    KILL_PLAN[KILL_SPAWN_ID] = str(KILL_AT_STEP)
if int(os.environ.get("TPUDIST_RESTART_ATTEMPT", "0")) > 0:
    KILL_PLAN = {}
CKPT_DIR = os.environ.get("WORKER_CKPT_DIR")
STEP_DELAY = float(os.environ.get("WORKER_STEP_DELAY", "0"))
OUT = os.environ["WORKER_OUT_DIR"]
# "host" (store-backed allreduce) or "ici" (compiled XLA pmean over a
# per-round jax.distributed world) — the train_fn below is IDENTICAL for
# both: ctx.collectives carries the same allreduce_mean API either way
DATA_PLANE = os.environ.get("WORKER_DATA_PLANE", "host")
# overlap mode: submit the allreduce async and prepare the next batch
# while the wire time elapses — same collective, same result, so every
# checksum assertion of the sync tests must keep holding
OVERLAP = os.environ.get("WORKER_OVERLAP", "") not in ("", "0", "false")
# bucketed backward-order overlap: stream per-layer grads (reverse leaf
# order, the backward-hook order) through OverlappedGradSync buckets that
# fire their allreduce as soon as the last member lands; the value is the
# bucket size in bytes
BUCKETED = int(os.environ.get("WORKER_BUCKETED", "0") or "0")


def emit(event: str, **fields) -> None:
    with open(os.path.join(OUT, f"events_{SPAWN_ID}.jsonl"), "a") as fh:
        fh.write(json.dumps({"event": event, **fields}) + "\n")


def global_batch(step: int):
    rng = np.random.default_rng(5000 + step)
    x = rng.standard_normal((GLOBAL_BATCH, 28 * 28)).astype(np.float32)
    y = rng.integers(0, 10, GLOBAL_BATCH)
    return x, y


def main() -> int:
    model = MLP(hidden_layers=1, features=32)
    params = model.init(jax.random.key(0),
                        np.zeros((1, 28 * 28), np.float32))["params"]
    # inject_hyperparams makes the lr part of opt_state, so the reset
    # callback can rescale it in place (the `on_state_reset` contract,
    # `horovod_mnist_elastic.py:80-82`)
    tx = optax.inject_hyperparams(optax.sgd)(learning_rate=BASE_LR)
    train_state = TrainState.create(model.apply, params, tx, rng=0)
    ckpt = None
    if CKPT_DIR:
        # per-worker directory: each process is its own orbax "host" here
        # (independent runtimes), so they must not race on one manager dir
        from tpudist.elastic.orbax_ckpt import OrbaxCheckpointer

        ckpt = OrbaxCheckpointer(
            os.path.join(CKPT_DIR, f"w{SPAWN_ID}"), keep=3)
    state = ElasticState(train_state, host=HostDataState(),
                         checkpointer=ckpt)
    if state.restored_step is not None:
        emit("restored", step=state.restored_step,
             batch=state.host.batch)

    def on_reset(s: ElasticState, old: int, new: int) -> None:
        lr = float(s.state.opt_state.hyperparams["learning_rate"]) * new / old
        s.state.opt_state.hyperparams["learning_rate"] = jnp.asarray(
            lr, jnp.float32)
        emit("reset", old_world=old, new_world=new, lr=lr)

    state.register_reset_callbacks([on_reset])

    @jax.jit
    def local_grads(params, x, y):
        def loss_fn(p):
            return cross_entropy(model.apply({"params": p}, x), y)

        return jax.value_and_grad(loss_fn)(params)

    def train_fn(state: ElasticState, ctx) -> None:
        emit("round", round=ctx.round, rank=ctx.rank, world=ctx.world_size,
             resume_batch=state.host.batch)
        # fresh sync per round: the step-1 plan is recorded against THIS
        # round's membership and collectives instance
        bucketed = (OverlappedGradSync(ctx.collectives, bucket_bytes=BUCKETED)
                    if BUCKETED else None)
        shard = GLOBAL_BATCH // ctx.world_size
        last_loss = float("nan")
        hlo_emitted = False
        for step in range(state.host.batch, TOTAL_STEPS):
            if STEP_DELAY:
                time.sleep(STEP_DELAY)  # stretch the run for join tests
            gx, gy = global_batch(step)
            lo = ctx.rank * shard
            loss, grads = local_grads(
                state.state.params, gx[lo:lo + shard], gy[lo:lo + shard])
            # one fused allreduce syncs grads AND the scalar loss (the
            # XLA-fusion analog on the control plane: one payload)
            payload = (grads, np.asarray(float(loss), np.float32))
            if bucketed is not None:
                # stream leaves in REVERSE flatten order — the order a
                # backward pass emits them (output layer first); each
                # bucket's allreduce fires mid-"backward", overlapping
                # the remaining grad_ready calls
                flat, treedef = jax.tree_util.tree_flatten_with_path(grads)
                for path, leaf in reversed(flat):
                    bucketed.grad_ready(jax.tree_util.keystr(path), leaf)
                bucketed.grad_ready(
                    "loss", np.asarray(float(loss), np.float32))
                out = bucketed.reduce(mean=True)
                grads = jax.tree_util.tree_unflatten(
                    treedef,
                    [out[jax.tree_util.keystr(p)] for p, _ in flat])
                gloss = out["loss"]
            elif OVERLAP:
                # async submit; the next step's batch generation (host
                # work) rides the allreduce's wire time.  wait() returns
                # the identical tree the sync call would — errors
                # (PeerLost/WorldChanged) re-raise here, where the
                # elastic loop's handlers expect them
                handle = ctx.collectives.allreduce_mean_async(payload)
                if step + 1 < TOTAL_STEPS:
                    global_batch(step + 1)
                grads, gloss = handle.wait()
            else:
                grads, gloss = ctx.collectives.allreduce_mean(payload)
            if ctx.data_plane == "ici" and not hlo_emitted:
                # the proof the verdict asked for: this round's gradient
                # sync is a compiled XLA all-reduce, not store traffic
                emit("hlo", round=ctx.round, world=ctx.world_size,
                     all_reduce="all-reduce" in
                     (ctx.collectives.last_hlo or ""))
                hlo_emitted = True
            state.state = state.state.apply_gradients(grads)
            state.host.batch = step + 1
            last_loss = float(gloss)
            if KILL_PLAN.get(SPAWN_ID) == str(step + 1):
                emit("suicide", step=step + 1)
                os.kill(os.getpid(), signal.SIGKILL)  # kill -9, no cleanup
            if (step + 1) % COMMIT_EVERY == 0:
                state.commit()
                emit("commit", step=step + 1)
                ctx.check()  # the per-commit membership poll
        state.commit()
        checksum = float(
            sum(jnp.sum(jnp.abs(l)) for l in jax.tree.leaves(
                state.state.params)))
        emit("done", steps=TOTAL_STEPS, loss=last_loss, checksum=checksum,
             lr=float(state.state.opt_state.hyperparams["learning_rate"]),
             world=ctx.world_size)

    run_elastic_worker(train_fn, state, worker_id=f"w{SPAWN_ID}",
                       ttl_s=1.5, heartbeat_interval_s=0.3,
                       data_plane=DATA_PLANE)
    return 0


if __name__ == "__main__":
    sys.exit(main())
