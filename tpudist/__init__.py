"""tpudist — a TPU-native distributed training framework.

A from-scratch re-design (JAX / XLA / pjit / shard_map / pallas) of the
capabilities demonstrated by the ``pytorch_distributed_examples`` reference
suite (see SURVEY.md):

* data-parallel training with explicit gradient ``psum`` over ICI
  (the DDP / Horovod ring-allreduce equivalent),
* elastic training: checkpoint / commit / rollback / resume with
  world-size-change hooks (the TorchElastic / Horovod-elastic equivalent),
* micro-batched pipeline model parallelism on a mesh axis
  (the RPC + distributed-autograd ResNet50 pipeline equivalent),
* parameter-server-style hybrid parallelism: a model-axis-sharded embedding
  table feeding data-parallel dense layers (the RemoteModule / HybridModel
  equivalent),
* a runtime layer: mesh construction, multi-host bootstrap, a native (C++)
  rendezvous / coordination store, data sharding, checkpointing, metrics.

The reference's mechanisms (process groups, RPC, RRefs, distributed autograd)
dissolve on TPU: sharding is a compiler annotation and ``jax.grad``
differentiates across devices natively.  What remains — and what this package
provides — are the *capabilities*, re-expressed mesh-first.
"""

from tpudist.utils.compat import install_jax_compat

install_jax_compat()  # before any module touches renamed jax symbols

from tpudist import data, elastic, models, obs, ops, parallel, runtime, train, utils
from tpudist.runtime.mesh import (
    MeshSpec,
    data_mesh,
    data_model_mesh,
    get_devices,
    make_mesh,
    pipeline_mesh,
)
from tpudist.train.state import TrainState
from tpudist.train.trainer import Trainer, TrainerConfig

__version__ = "0.1.0"

__all__ = [
    "MeshSpec",
    "TrainState",
    "Trainer",
    "TrainerConfig",
    "data",
    "data_mesh",
    "data_model_mesh",
    "elastic",
    "get_devices",
    "make_mesh",
    "models",
    "obs",
    "ops",
    "parallel",
    "pipeline_mesh",
    "runtime",
    "train",
    "utils",
]
