"""Loader for the native (C++) runtime library.

``native/`` holds the C++ sources for the host-side runtime components (the
coordination/rendezvous service and the data-loader core — the TPU-native
equivalents of the reference suite's external native dependencies: c10d
TCPStore, Horovod's C++ elastic controller, DataLoader workers; SURVEY.md
§2.2).  This module builds the shared library on demand (``g++`` is assumed
present, as on any TPU VM image) and exposes it via ctypes.

All callers must tolerate ``load() is None`` — every native component has a
pure-Python fallback so the framework degrades gracefully rather than
hard-failing on exotic hosts.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
_NATIVE_DIR = _REPO / "native"
_SOURCES = ("coord.cpp", "dataload.cpp")
_LIB = _NATIVE_DIR / "build" / "libtpudist_native.so"

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False


def _stale() -> bool:
    if not _LIB.exists():
        return True
    lib_mtime = _LIB.stat().st_mtime
    return any(
        (_NATIVE_DIR / s).exists() and (_NATIVE_DIR / s).stat().st_mtime > lib_mtime
        for s in _SOURCES
    )


def _build() -> bool:
    srcs = [str(_NATIVE_DIR / s) for s in _SOURCES if (_NATIVE_DIR / s).exists()]
    if not srcs:
        return False
    _LIB.parent.mkdir(parents=True, exist_ok=True)
    # Compile to a process-unique temp path, then atomically rename: a
    # concurrent process must never dlopen a half-written library.
    tmp = _LIB.with_suffix(f".so.tmp.{os.getpid()}")
    cmd = ["g++", "-O2", "-g", "-std=c++17", "-fPIC", "-Wall", "-pthread",
           "-shared", "-o", str(tmp), *srcs]
    try:
        res = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        if res.returncode != 0:
            return False
        os.replace(tmp, _LIB)
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        tmp.unlink(missing_ok=True)
    return True


def _bind(lib: ctypes.CDLL) -> None:
    c = ctypes
    sigs = {
        # coord.cpp
        "tcs_server_start": ([c.c_uint16], c.c_void_p),
        "tcs_server_port": ([c.c_void_p], c.c_int),
        "tcs_server_stop": ([c.c_void_p], None),
        "tcs_connect": ([c.c_char_p, c.c_uint16, c.c_int], c.c_void_p),
        "tcs_set": ([c.c_void_p, c.c_char_p, c.c_void_p, c.c_uint32], c.c_int),
        "tcs_get": ([c.c_void_p, c.c_char_p, c.c_void_p, c.c_uint32,
                     c.POINTER(c.c_uint32)], c.c_int),
        "tcs_add": ([c.c_void_p, c.c_char_p, c.c_longlong], c.c_longlong),
        "tcs_wait": ([c.c_void_p, c.c_char_p, c.c_int], c.c_int),
        "tcs_barrier": ([c.c_void_p, c.c_char_p, c.c_int, c.c_int], c.c_int),
        "tcs_heartbeat": ([c.c_void_p, c.c_char_p, c.c_int], c.c_int),
        "tcs_live": ([c.c_void_p, c.c_char_p, c.c_uint32,
                      c.POINTER(c.c_uint32)], c.c_int),
        "tcs_keys": ([c.c_void_p, c.c_char_p, c.c_char_p, c.c_uint32,
                      c.POINTER(c.c_uint32)], c.c_int),
        "tcs_del": ([c.c_void_p, c.c_char_p], c.c_int),
        "tcs_close": ([c.c_void_p], None),
        # dataload.cpp
        "tdl_pool_create": ([c.c_int], c.c_void_p),
        "tdl_submit": ([c.c_void_p, c.c_int, c.POINTER(c.c_void_p),
                        c.POINTER(c.c_longlong), c.POINTER(c.c_longlong),
                        c.c_longlong, c.POINTER(c.c_void_p)], c.c_longlong),
        "tdl_wait": ([c.c_void_p, c.c_longlong, c.c_int], c.c_int),
        "tdl_pool_destroy": ([c.c_void_p], None),
        "tdl_idx_info": ([c.c_char_p, c.POINTER(c.c_int), c.POINTER(c.c_int),
                          c.POINTER(c.c_longlong)], c.c_int),
        "tdl_idx_read": ([c.c_char_p, c.c_void_p, c.c_longlong], c.c_longlong),
    }
    for name, (argtypes, restype) in sigs.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = restype


def load() -> ctypes.CDLL | None:
    """Return the bound native library, building it first if needed; None if
    the toolchain or sources are unavailable."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _load_failed:
            return None
        if _stale() and not _build():
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(str(_LIB))
            _bind(lib)
        except OSError:
            _load_failed = True
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None
