"""Installation sanity check — the ``horovodrun --check-build`` twin.

The reference's build environment proves itself with ``horovodrun
--check-build`` plus per-framework import checks (`horovod/Dockerfile:79-85`).
``python -m tpudist.check_build`` is the equivalent for this framework: it
reports which subsystems are actually usable in this environment and exits
non-zero if a required one is broken.

Checked, in dependency order:
* jax + backend (platform, device count),
* the compute stack: one jitted ``psum`` over every local device,
* pallas (TPU kernels; reported, optional on CPU hosts),
* the native (C++) runtime library: builds/loads, coordination server
  round-trip, threaded gather round-trip,
* multi-host bootstrap configuration (reported only).
"""

from __future__ import annotations

import sys
import traceback


def _check(name: str, fn, required: bool, results: list) -> None:
    try:
        detail = fn()
        results.append((name, True, detail or "ok", required))
    except Exception as e:  # noqa: BLE001 - report, don't crash
        tb = traceback.format_exc().strip().rsplit("\n", 1)[-1]
        results.append((name, False, f"{type(e).__name__}: {e or tb}", required))


def _jax_backend() -> str:
    import jax

    devs = jax.devices()
    return f"{len(devs)} x {devs[0].platform} ({jax.__version__})"


def _collectives() -> str:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = np.asarray(jax.devices())
    mesh = Mesh(devs, ("data",))
    f = jax.shard_map(
        lambda x: jax.lax.psum(x, "data"), mesh=mesh,
        in_specs=P("data"), out_specs=P(),
    )
    x = jnp.ones((len(devs),), jnp.float32)
    got = jax.jit(f)(jax.device_put(x, NamedSharding(mesh, P("data"))))
    assert float(got[0]) == len(devs)
    return f"psum over {len(devs)} devices"


def _pallas() -> str:
    from jax.experimental import pallas  # noqa: F401

    import jax

    platform = jax.devices()[0].platform
    if platform != "tpu":
        return f"importable (kernels need TPU; backend is {platform})"
    # Compile + run the flash kernel on the live backend and check numerics
    # against the plain-XLA reference (interpreter mode can't catch Mosaic
    # lowering regressions; this can).
    import jax.numpy as jnp

    from tpudist.models.transformer import sdpa
    from tpudist.ops.flash_attention import flash_attention

    q, k, v = (
        jax.random.normal(jax.random.key(i), (1, 256, 2, 128), jnp.bfloat16)
        for i in range(3)
    )
    got = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))(q, k, v)
    want = sdpa(q, k, v, causal=True)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32))))
    if err >= 0.05:
        raise RuntimeError(f"flash kernel numerics off: max err {err}")
    return f"flash kernel runs on tpu, max err {err:.4f} vs reference"


def _native_lib() -> str:
    from tpudist import _native

    lib = _native.load()
    if lib is None:
        raise RuntimeError("libtpudist_native.so failed to build/load")
    return "built + loaded"


def _native_coord() -> str:
    from tpudist.runtime.coord import CoordClient, CoordServer

    with CoordServer(0) as s, CoordClient("127.0.0.1", s.port) as c:
        c.set("k", b"v")
        assert c.get("k") == b"v"
        assert c.add("n", 2) == 2
    return "kv round-trip on localhost"


def _native_gather() -> str:
    import numpy as np

    from tpudist.data.native import GatherPool

    pool = GatherPool(2)
    try:
        data = np.arange(100, dtype=np.float32).reshape(20, 5)
        idx = np.asarray([3, 1, 4, 1, 5])
        (got,) = pool.gather([data], idx)
        np.testing.assert_array_equal(got, data[idx])
    finally:
        pool.close()
    return "threaded gather round-trip"


def _multihost() -> str:
    from tpudist.runtime.distributed import world_info

    w = world_info()
    return (f"process {w.process_index}/{w.process_count}, "
            f"{w.local_device_count} local / {w.global_device_count} global devices")


def main(argv: list[str] | None = None) -> int:
    del argv
    results: list = []
    _check("jax backend", _jax_backend, True, results)
    _check("XLA collectives", _collectives, True, results)
    try:
        import jax

        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001 - backend failure already reported above
        on_tpu = False
    # With a TPU present, a broken kernel stack must FAIL the build check.
    _check("pallas", _pallas, on_tpu, results)
    _check("native library", _native_lib, False, results)
    if any(n == "native library" and ok for n, ok, *_ in results):
        _check("native coordination service", _native_coord, False, results)
        _check("native data loader", _native_gather, False, results)
    _check("multi-host bootstrap", _multihost, False, results)

    width = max(len(n) for n, *_ in results)
    failed_required = False
    for name, ok, detail, required in results:
        mark = "OK  " if ok else ("FAIL" if required else "WARN")
        print(f"[{mark}] {name:<{width}}  {detail}")
        failed_required |= required and not ok
    print()
    if failed_required:
        print("tpudist check-build: FAILED (required subsystem broken)")
        return 1
    print("tpudist check-build: all required subsystems available")
    return 0


if __name__ == "__main__":
    sys.exit(main())
