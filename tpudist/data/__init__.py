"""Data layer: datasets, per-host sharding, device feeding.

TPU-native replacement of the reference's L2 data stack (torchvision MNIST +
DataLoader + DistributedSampler — SURVEY.md §1 L2): pure-numpy ingestion, an
epoch-seeded sharding sampler with DistributedSampler-compatible semantics,
and batch iterators that land data directly in the right device sharding.
"""

from tpudist.data.device_prefetch import DevicePrefetch, device_prefetch
from tpudist.data.loader import ShardedLoader
from tpudist.data.mnist import MNIST_MEAN, MNIST_STD, Dataset, load_mnist
from tpudist.data.sampler import ShardedSampler
from tpudist.data.synthetic import ragged_embedding_batches, synthetic_images

__all__ = [
    "Dataset",
    "DevicePrefetch",
    "MNIST_MEAN",
    "MNIST_STD",
    "ShardedLoader",
    "ShardedSampler",
    "device_prefetch",
    "load_mnist",
    "ragged_embedding_batches",
    "synthetic_images",
]
