"""Device-input pipelining — keep N batches' transfers in flight ahead
of the step.

The device-side twin of the native gather pool (:mod:`tpudist.data.native`):
the pool overlaps HOST batch assembly with device compute, this module
overlaps the *pull* — a background thread drives the wrapped iterator
(whose ``jax.device_put`` calls are async dispatches) so that by the time
the training loop asks for batch ``k``, batches ``k..k+depth-1`` have
already had their host→device copies initiated and the step dispatch
never waits on input.  This is the DataLoader-worker + pin_memory role
(`mnist_ddp_elastic.py:185-189`) folded into one iterator.

Instrumentation (see docs/OBSERVABILITY.md):

* ``data/input_stall`` counter — cumulative seconds the consumer has
  been blocked waiting for input (the time the accelerator would have
  idled on the host; near-zero when the pipeline keeps up).  A counter,
  not a gauge: a fresh wrapper is created per epoch, and the shared
  counter keeps the series monotonic across instances (rate() works;
  no per-epoch saw-tooth back to zero);
* ``data/input_stall_s`` histogram — per-fetch stall distribution;
* ``data/prefetch_depth`` gauge — the configured look-ahead.

Thread discipline: exceptions raised by the wrapped iterator propagate
to the consumer at the corresponding ``__next__``; abandoning the
iterator early (``break`` / exception) stops the worker and closes the
underlying generator so prefetch-pool jobs and buffers are reaped
(:meth:`ShardedLoader.epoch` has the matching ``finally``).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator

from tpudist import obs

__all__ = ["DevicePrefetch", "device_prefetch"]

_ITEM, _ERR, _END = 0, 1, 2


class DevicePrefetch:
    """Iterator that materializes ``depth`` items of ``iterable`` ahead
    of the consumer on a background thread.

    Args:
      iterable: source of batches.  When it is a :class:`ShardedLoader`
        epoch generator, each ``next`` already lands the batch in the
        mesh sharding via ``jax.device_put`` — pulling ahead therefore
        keeps ``depth`` transfers in flight.
      depth: batches to keep ready (0 disables prefetch: the iterator
        degrades to plain synchronous iteration).
      put: optional transform applied to every item ON THE WORKER
        THREAD (e.g. a ``jax.device_put`` for host-only sources).
    """

    def __init__(self, iterable: Iterable, depth: int = 2,
                 put: Callable[[Any], Any] | None = None) -> None:
        if depth < 0:
            raise ValueError(f"prefetch depth must be >= 0, got {depth}")
        self.depth = depth
        self._it = iter(iterable)
        self._put = put
        self._done = False
        self._stall = obs.counter("data/input_stall", unit="s")
        self._stall_hist = obs.histogram("data/input_stall_s", unit="s")
        self._depth_gauge = obs.gauge("data/prefetch_depth")
        self._depth_gauge.set(depth)
        self._thread: threading.Thread | None = None
        if depth > 0:
            self._stop = threading.Event()
            self._q: queue.Queue = queue.Queue(maxsize=depth)
            self._thread = threading.Thread(
                target=self._worker, name="tpudist-device-prefetch",
                daemon=True)
            self._thread.start()

    # -- worker side -------------------------------------------------------

    def _push(self, msg: tuple) -> None:
        while not self._stop.is_set():
            try:
                self._q.put(msg, timeout=0.1)
                return
            except queue.Full:
                continue

    def _worker(self) -> None:
        try:
            for item in self._it:
                if self._stop.is_set():
                    break
                if self._put is not None:
                    item = self._put(item)
                self._push((_ITEM, item))
        except BaseException as e:  # noqa: BLE001 - relayed to the consumer
            self._push((_ERR, e))
        finally:
            # close the source in the thread that iterated it, so an
            # abandoned ShardedLoader epoch reaps its in-flight pool jobs
            close = getattr(self._it, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
            self._push((_END, None))

    # -- consumer side -----------------------------------------------------

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._thread is None:  # depth == 0: synchronous passthrough
            item = next(self._it)
            return self._put(item) if self._put is not None else item
        if self._done:
            raise StopIteration
        t0 = time.perf_counter()
        kind, val = self._q.get()
        stall = time.perf_counter() - t0
        self._stall.inc(stall)
        self._stall_hist.record(stall)
        if kind == _END:
            self._done = True
            self._thread.join()
            raise StopIteration
        if kind == _ERR:
            self._done = True
            self._thread.join()
            raise val
        return val

    def close(self) -> None:
        """Stop the worker and release the source (idempotent)."""
        if self._thread is None or self._done:
            self._done = True
            return
        self._done = True
        self._stop.set()
        # unblock a worker stuck on a full queue, then reap it
        while self._thread.is_alive():
            try:
                self._q.get_nowait()
            except queue.Empty:
                self._thread.join(timeout=0.1)
        self._thread.join()

    def __del__(self) -> None:  # best-effort; device_prefetch() is preferred
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


def device_prefetch(iterable: Iterable, depth: int = 2,
                    put: Callable[[Any], Any] | None = None) -> Iterator:
    """Generator wrapper around :class:`DevicePrefetch` whose ``finally``
    guarantees worker shutdown when the consumer stops early."""
    pf = DevicePrefetch(iterable, depth=depth, put=put)
    try:
        yield from pf
    finally:
        pf.close()
