"""Batch iterator that lands global batches in a mesh's data sharding.

DataLoader+DistributedSampler equivalent (`mnist_ddp_elastic.py:178-189`):
one :class:`ShardedSampler` per data-mesh shard, batches assembled host-side
in ``[global_batch, ...]`` order such that slicing along the batch axis by
the data axis yields exactly each shard's sampler stream, then transferred
once with :func:`jax.device_put` under a ``P('data', ...)`` sharding (the
moral equivalent of pin_memory + per-rank loaders, minus the processes).
"""

from __future__ import annotations

from typing import Iterator, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist.data.sampler import ShardedSampler


class ShardedLoader:
    """Iterates ``(epoch-seeded, sharded)`` global batches of numpy arrays.

    Args:
      arrays: dataset arrays, all with leading dim N (e.g. images, labels).
      global_batch: total batch across the data axis; must divide by the
        data-axis size.
      mesh / data_axis: where batches should land. If ``mesh`` is None the
        loader yields host numpy arrays (useful for tests and host-only eval).
      shuffle / seed / drop_last: sampler behavior (DistributedSampler
        semantics, see :mod:`tpudist.data.sampler`).
    """

    def __init__(
        self,
        arrays: Sequence[np.ndarray],
        global_batch: int,
        mesh: Mesh | None = None,
        data_axis: str = "data",
        shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = True,
    ) -> None:
        n = len(arrays[0])
        for a in arrays:
            if len(a) != n:
                raise ValueError("all arrays must share leading dimension")
        self.arrays = list(arrays)
        self.mesh = mesh
        self.data_axis = data_axis
        self.num_shards = mesh.shape[data_axis] if mesh is not None else 1
        if global_batch % self.num_shards:
            raise ValueError(
                f"global batch {global_batch} not divisible by {self.num_shards} shards"
            )
        self.global_batch = global_batch
        self.local_batch = global_batch // self.num_shards
        self.samplers = [
            ShardedSampler(n, self.num_shards, s, shuffle=shuffle, seed=seed,
                           drop_last=drop_last)
            for s in range(self.num_shards)
        ]
        self.drop_last = drop_last
        self._shardings = None
        if mesh is not None:
            self._shardings = [
                NamedSharding(mesh, P(data_axis, *([None] * (a.ndim - 1))))
                for a in self.arrays
            ]

    def __len__(self) -> int:
        return self.steps_per_epoch

    @property
    def steps_per_epoch(self) -> int:
        shard_len = self.samplers[0].shard_size
        if self.drop_last:
            return shard_len // self.local_batch
        return -(-shard_len // self.local_batch)

    def epoch(self, epoch: int) -> Iterator[tuple]:
        """Yield one epoch of batches; ``epoch`` seeds the shuffle
        (the ``sampler.set_epoch`` contract, `mnist_ddp_elastic.py:84`)."""
        per_shard = [s.indices(epoch) for s in self.samplers]
        for step in range(self.steps_per_epoch):
            lo = step * self.local_batch
            idx = np.concatenate([p[lo : lo + self.local_batch] for p in per_shard])
            batch = tuple(a[idx] for a in self.arrays)
            if self._shardings is not None:
                batch = tuple(
                    jax.device_put(b, s) for b, s in zip(batch, self._shardings)
                )
            yield batch

    def __iter__(self) -> Iterator[tuple]:
        return self.epoch(0)
