"""Batch iterator that lands global batches in a mesh's data sharding.

DataLoader+DistributedSampler equivalent (`mnist_ddp_elastic.py:178-189`):
one :class:`ShardedSampler` per data-mesh shard, batches assembled host-side
in ``[global_batch, ...]`` order such that slicing along the batch axis by
the data axis yields exactly each shard's sampler stream, then transferred
once with :func:`jax.device_put` under a ``P('data', ...)`` sharding (the
moral equivalent of pin_memory + per-rank loaders, minus the processes).
"""

from __future__ import annotations

from typing import Iterator, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist.data.sampler import ShardedSampler


class ShardedLoader:
    """Iterates ``(epoch-seeded, sharded)`` global batches of numpy arrays.

    Args:
      arrays: dataset arrays, all with leading dim N (e.g. images, labels).
      global_batch: total batch across the data axis; must divide by the
        data-axis size.
      mesh / data_axis: where batches should land. If ``mesh`` is None the
        loader yields host numpy arrays (useful for tests and host-only eval).
      shuffle / seed / drop_last: sampler behavior (DistributedSampler
        semantics, see :mod:`tpudist.data.sampler`).
      prefetch: batches to materialize ahead, overlapping host batch
        assembly with device compute — the DataLoader-worker/pin-memory
        role (`mnist_ddp_elastic.py:185-189`). 0 = synchronous numpy
        gather.  When the native (C++) gather pool
        (:mod:`tpudist.data.native`) is available, gathers ride it;
        otherwise the configured depth is honored by a Python-thread
        fallback (:mod:`tpudist.data.device_prefetch`) that drives the
        same generator ahead of the consumer — ``self.prefetch`` always
        reflects the configured value, never a silent 0.
    """

    def __init__(
        self,
        arrays: Sequence[np.ndarray],
        global_batch: int,
        mesh: Mesh | None = None,
        data_axis: str | tuple = "data",
        shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = True,
        prefetch: int = 0,
    ) -> None:
        n = len(arrays[0])
        for a in arrays:
            if len(a) != n:
                raise ValueError("all arrays must share leading dimension")
        self.arrays = list(arrays)
        self.mesh = mesh
        self.data_axis = data_axis
        # a tuple axis shards the batch over several mesh axes at once —
        # the composed dp×fsdp layout (PartitionSpec(("dp", "fsdp")))
        axes = (data_axis,) if isinstance(data_axis, str) else tuple(data_axis)
        self.num_shards = (
            int(np.prod([mesh.shape[a] for a in axes]))
            if mesh is not None else 1)
        if global_batch % self.num_shards:
            raise ValueError(
                f"global batch {global_batch} not divisible by {self.num_shards} shards"
            )
        self.global_batch = global_batch
        self.local_batch = global_batch // self.num_shards
        self.samplers = [
            ShardedSampler(n, self.num_shards, s, shuffle=shuffle, seed=seed,
                           drop_last=drop_last)
            for s in range(self.num_shards)
        ]
        self.drop_last = drop_last
        self._pool = None
        if prefetch > 0:
            from tpudist.data import native as _dnative

            if _dnative.available():
                self._pool = _dnative.GatherPool()
                # The C++ gather computes offsets from shape, not strides.
                self.arrays = [np.ascontiguousarray(a) for a in self.arrays]
        self.prefetch = prefetch
        self._shardings = None
        if mesh is not None:
            self._shardings = [
                NamedSharding(mesh, P(data_axis, *([None] * (a.ndim - 1))))
                for a in self.arrays
            ]

    def __len__(self) -> int:
        return self.steps_per_epoch

    @property
    def thread_prefetch(self) -> bool:
        """True when :meth:`epoch` / :meth:`epoch_stacked` already wrap
        their stream in the Python-thread :func:`device_prefetch` fallback
        (``prefetch > 0``, native pool unavailable).  Callers that layer
        their own device prefetch (``Trainer._feed``) must check this and
        not wrap a second time: a double wrap spawns two worker threads,
        doubles the batches buffered in host memory, and has both
        instances feeding the same ``data/input_stall`` /
        ``data/prefetch_depth`` metrics."""
        return self._pool is None and self.prefetch > 0

    @property
    def steps_per_epoch(self) -> int:
        shard_len = self.samplers[0].shard_size
        if self.drop_last:
            return shard_len // self.local_batch
        return -(-shard_len // self.local_batch)

    def _batch_indices(self, per_shard: list, step: int) -> np.ndarray:
        lo = step * self.local_batch
        return np.concatenate(
            [p[lo : lo + self.local_batch] for p in per_shard]
        )

    def valid_mask(self, step: int) -> np.ndarray:
        """Boolean mask aligned with batch ``step``'s global assembly order:
        True for real samples, False for the wrap-around padding a
        ``drop_last=False`` sampler appends to equalize shards (the
        DistributedSampler duplicates).  Exact-accuracy evaluation weights
        by this mask so padded duplicates can't skew the numerator or the
        denominator (`Trainer.test`).  Independent of the epoch: padding
        occupies fixed stream positions regardless of the shuffle."""
        lo = step * self.local_batch
        parts = []
        for s in self.samplers:
            hi = min(lo + self.local_batch, s.shard_size)
            j = np.arange(lo, max(hi, lo))
            # element j of shard r sits at stream position r + j*num_shards;
            # positions >= n are wrap-around padding (sampler.indices)
            parts.append(s.shard + j * self.num_shards < s.n)
        return np.concatenate(parts)

    def epoch(self, epoch: int, start_step: int = 0) -> Iterator[tuple]:
        """Yield one epoch of batches; ``epoch`` seeds the shuffle
        (the ``sampler.set_epoch`` contract, `mnist_ddp_elastic.py:84`).
        ``start_step`` skips the first batches (resume / tail-after-stacked
        iteration).

        ``prefetch > 0`` without the native pool wraps the stream in the
        Python-thread :func:`device_prefetch` fallback, so the configured
        look-ahead (including the ``jax.device_put`` per batch) is honored
        either way."""
        it = self._epoch_impl(epoch, start_step)
        if self.thread_prefetch:
            from tpudist.data.device_prefetch import device_prefetch

            return device_prefetch(it, depth=self.prefetch)
        return it

    def _epoch_impl(self, epoch: int, start_step: int) -> Iterator[tuple]:
        per_shard = [s.indices(epoch) for s in self.samplers]

        def batch_idx(step: int) -> np.ndarray:
            return self._batch_indices(per_shard, step)

        def emit(batch: tuple) -> tuple:
            if self._shardings is not None:
                batch = tuple(
                    jax.device_put(b, s) for b, s in zip(batch, self._shardings)
                )
            return batch

        steps = self.steps_per_epoch
        if self._pool is None:
            for step in range(start_step, steps):
                yield emit(tuple(a[batch_idx(step)] for a in self.arrays))
            return

        # Native path: keep `prefetch` gather jobs in flight on the C++ pool.
        def submit(step: int) -> int:
            idx = batch_idx(step)
            out = [np.empty((len(idx),) + a.shape[1:], a.dtype) for a in self.arrays]
            return self._pool.submit(self.arrays, idx, out)

        jobs = [submit(s) for s in
                range(start_step, min(start_step + self.prefetch, steps))]
        try:
            for step in range(start_step, steps):
                ahead = step + self.prefetch
                if ahead < steps:
                    jobs.append(submit(ahead))
                yield emit(tuple(self._pool.wait(jobs.pop(0))))
        finally:
            # Abandoned epoch (break / exception): reap in-flight jobs so
            # neither Python buffers nor C++ job objects leak.
            for job in jobs:
                try:
                    self._pool.wait(job)
                except Exception:
                    pass

    def stacked_groups(self, n_steps: int) -> int:
        """How many FULL ``n_steps`` groups :meth:`epoch_stacked` yields.

        Only full-size batches can stack (a ``drop_last=False`` partial
        final batch has a different shape), so groups count over
        ``shard_size // local_batch`` regardless of ``drop_last``.
        """
        full_batches = self.samplers[0].shard_size // self.local_batch
        return full_batches // n_steps

    def epoch_stacked(self, epoch: int, n_steps: int) -> Iterator[tuple]:
        """Yield FULL groups of ``n_steps`` consecutive batches stacked on a
        leading steps dimension — ``[n_steps, global_batch, ...]`` per
        array, placed under ``P(None, data_axis)`` — the input shape of
        :func:`tpudist.parallel.data_parallel.make_dp_train_loop`.

        Yields :meth:`stacked_groups` groups; drive the remaining batches
        (including any ``drop_last=False`` partial one) with
        ``epoch(epoch, start_step=stacked_groups(n) * n)``.  Group gathers
        ride the native prefetch pool when the loader has one, and the
        Python-thread :func:`device_prefetch` fallback otherwise.
        """
        it = self._epoch_stacked_impl(epoch, n_steps)
        if self.thread_prefetch:
            from tpudist.data.device_prefetch import device_prefetch

            return device_prefetch(it, depth=self.prefetch)
        return it

    def _epoch_stacked_impl(self, epoch: int, n_steps: int) -> Iterator[tuple]:
        per_shard = [s.indices(epoch) for s in self.samplers]
        groups = self.stacked_groups(n_steps)
        shardings = None
        if self.mesh is not None:
            shardings = [
                NamedSharding(
                    self.mesh,
                    P(None, self.data_axis, *([None] * (a.ndim - 1))))
                for a in self.arrays
            ]

        def group_idx(g: int) -> np.ndarray:
            return np.concatenate([
                self._batch_indices(per_shard, s)
                for s in range(g * n_steps, (g + 1) * n_steps)
            ])

        def emit(arrs: tuple) -> tuple:
            batch = tuple(
                a.reshape(n_steps, self.global_batch, *a.shape[1:])
                for a in arrs
            )
            if shardings is not None:
                batch = tuple(
                    jax.device_put(b, s) for b, s in zip(batch, shardings))
            return batch

        if self._pool is None:
            for g in range(groups):
                idx = group_idx(g)
                yield emit(tuple(a[idx] for a in self.arrays))
            return

        def submit(g: int) -> int:
            idx = group_idx(g)
            out = [np.empty((len(idx),) + a.shape[1:], a.dtype)
                   for a in self.arrays]
            return self._pool.submit(self.arrays, idx, out)

        jobs = [submit(g) for g in range(min(self.prefetch, groups))]
        try:
            for g in range(groups):
                ahead = g + self.prefetch
                if ahead < groups:
                    jobs.append(submit(ahead))
                yield emit(tuple(self._pool.wait(jobs.pop(0))))
        finally:
            for job in jobs:
                try:
                    self._pool.wait(job)
                except Exception:
                    pass

    def __iter__(self) -> Iterator[tuple]:
        """Plain iteration == :meth:`epoch` 0: the shuffle is seeded with
        epoch 0 and the configured ``prefetch`` is honored (native pool or
        Python-thread fallback alike).  Multi-epoch training should call
        :meth:`epoch` explicitly so each epoch reseeds; the native gather
        pool is owned by the loader and reused across epochs."""
        return self.epoch(0)
