"""MNIST ingestion without torch/torchvision.

The reference loads MNIST through torchvision with
``Normalize((0.1307,), (0.3081,))`` (`mnist_ddp_elastic.py:166-171`,
`mnist_horovod.py:34-38`).  Here:

* a pure-numpy IDX reader for the standard ``train-images-idx3-ubyte`` files
  (gzipped or raw) when a local copy exists (``TPUDIST_MNIST_DIR`` or
  ``./data/MNIST/raw``), and
* a deterministic synthetic stand-in (class-conditional prototype images +
  noise) for hermetic, zero-download environments, so every trainer, test and
  benchmark runs anywhere.  The synthetic task is learnable to >97% by the
  same models, preserving the reference's accuracy-as-correctness-signal
  strategy (SURVEY.md §4).

Identical normalization constants are applied in both paths so accuracy
numbers stay comparable with the reference recipes.
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import struct
from pathlib import Path

import numpy as np

MNIST_MEAN = 0.1307
MNIST_STD = 0.3081

_FILES = {
    "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}


@dataclasses.dataclass(frozen=True)
class Dataset:
    """An in-memory image-classification dataset, images normalized float32
    [N, 28, 28, 1] (NHWC — the TPU-preferred layout), int32 labels [N]."""

    images: np.ndarray
    labels: np.ndarray
    name: str = "dataset"

    def __len__(self) -> int:
        return len(self.labels)

    @property
    def num_classes(self) -> int:
        return int(self.labels.max()) + 1


def _read_idx(path: Path) -> np.ndarray:
    if path.suffix != ".gz":  # raw files: native C++ parser when available
        from tpudist.data.native import read_idx_native

        arr = read_idx_native(path)
        if arr is not None:
            return arr
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        if zero != 0:
            raise ValueError(f"{path}: bad IDX magic")
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        dtypes = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16, 0x0C: np.int32,
                  0x0D: np.float32, 0x0E: np.float64}
        data = np.frombuffer(f.read(), dtype=np.dtype(dtypes[dtype_code]).newbyteorder(">"))
        return data.reshape(dims)


def _find(directory: Path, stem: str) -> Path | None:
    for cand in (directory / stem, directory / (stem + ".gz")):
        if cand.exists():
            return cand
    return None


def _normalize(images_u8: np.ndarray) -> np.ndarray:
    x = images_u8.astype(np.float32) / 255.0
    x = (x - MNIST_MEAN) / MNIST_STD
    return x.reshape(*x.shape[:3], 1) if x.ndim == 3 else x


def load_mnist_idx(directory: str | os.PathLike, split: str = "train") -> Dataset:
    """Load real MNIST from IDX files in ``directory``."""
    directory = Path(directory)
    img_stem, lbl_stem = _FILES[split]
    img_path, lbl_path = _find(directory, img_stem), _find(directory, lbl_stem)
    if img_path is None or lbl_path is None:
        raise FileNotFoundError(f"MNIST {split} IDX files not found in {directory}")
    images = _normalize(_read_idx(img_path))
    labels = _read_idx(lbl_path).astype(np.int32)
    return Dataset(images=images, labels=labels, name=f"mnist-{split}")


def synthetic_mnist(
    split: str = "train",
    n: int | None = None,
    seed: int = 0,
    noise: float = 0.35,
) -> Dataset:
    """Deterministic MNIST stand-in: 10 fixed random prototype digits, each
    sample = prototype + gaussian noise, squashed to [0,1] then normalized
    exactly like the real data.  Train/test draw disjoint sample streams from
    the same class-conditional distribution."""
    n = n if n is not None else (60_000 if split == "train" else 10_000)
    proto_rng = np.random.default_rng(seed)  # prototypes shared by both splits
    protos = proto_rng.random((10, 28, 28), dtype=np.float32)
    protos = (protos > 0.72).astype(np.float32)  # sparse strokes, MNIST-ish density
    sample_rng = np.random.default_rng(seed + (1 if split == "train" else 2) * 7919)
    labels = sample_rng.integers(0, 10, size=n).astype(np.int32)
    imgs = protos[labels] + noise * sample_rng.standard_normal((n, 28, 28), dtype=np.float32)
    imgs = np.clip(imgs, 0.0, 1.0)
    images = ((imgs - MNIST_MEAN) / MNIST_STD).reshape(n, 28, 28, 1)
    return Dataset(images=images, labels=labels, name=f"synthetic-mnist-{split}")


def load_real_digits(split: str = "train",
                     path: str | os.PathLike | None = None,
                     test_fraction: float = 0.15) -> Dataset:
    """Committed REAL handwritten digits (``data/real_digits.npz``): the
    UCI digits set bundled inside scikit-learn, bilinear-upsampled 8×8 →
    28×28 and stored uint8 (see ``scripts/make_real_digits.py`` for
    provenance).  Not MNIST — but real pen strokes, so learning it is
    genuine evidence the MNIST recipe learns real digits (VERDICT r2 #5),
    independent of any mounted dataset.  Normalization is the exact MNIST
    path (0.1307/0.3081).

    The file stores a fixed shuffle; ``split`` takes the deterministic
    head ("train") or tail ("test", last ``test_fraction``)."""
    if split not in ("train", "test"):
        raise KeyError(f"unknown split {split!r} (expected train|test)")
    p = Path(path) if path else (
        Path(__file__).resolve().parents[2] / "data" / "real_digits.npz")
    with np.load(p) as z:
        images_u8, labels = z["images"], z["labels"].astype(np.int32)
    n_test = int(len(labels) * test_fraction)
    cut = len(labels) - n_test  # not -n_test: slice(None, -0) is empty
    sl = slice(None, cut) if split == "train" else slice(cut, None)
    return Dataset(images=_normalize(images_u8[sl]), labels=labels[sl],
                   name=f"real-digits-{split}")


def load_mnist(split: str = "train", data_dir: str | None = None, n: int | None = None) -> Dataset:
    """Real MNIST when IDX files are available, synthetic stand-in otherwise."""
    candidates = [
        data_dir,
        os.environ.get("TPUDIST_MNIST_DIR"),
        "data/MNIST/raw",
        "data",
    ]
    for cand in candidates:
        if cand and Path(cand).is_dir():
            try:
                ds = load_mnist_idx(cand, split)
                return Dataset(ds.images[:n], ds.labels[:n], ds.name) if n else ds
            except FileNotFoundError:
                continue
    return synthetic_mnist(split, n=n)
