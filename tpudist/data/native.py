"""ctypes bindings for the native data-loader core (``native/dataload.cpp``).

Provides :class:`GatherPool` — asynchronous multi-threaded row-gather
(``dst[i] = src[idx[i]]``) so batch materialization runs on C++ worker
threads and overlaps device compute — and a native IDX-file reader.  This is
the in-repo replacement for the native machinery behind the reference's
input path (DataLoader worker processes + pin-memory copies,
`mnist_ddp_elastic.py:185-189`).  Pure-numpy fallbacks live next to every
call site; nothing hard-requires the native library.
"""

from __future__ import annotations

import ctypes
from pathlib import Path

import numpy as np

from tpudist import _native

_IDX_DTYPES = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16, 0x0C: np.int32,
               0x0D: np.float32, 0x0E: np.float64}


def available() -> bool:
    return _native.available()


class GatherPool:
    """Asynchronous gather on a C++ thread pool.

    ``submit`` queues ``out[k][i] = arrays[k][idx[i]]`` for every array and
    returns a job id; ``wait`` blocks until that job's buffers are filled.
    Sources and destinations must be C-contiguous and stay alive until
    ``wait`` returns (submit keeps references to enforce this).
    """

    def __init__(self, threads: int = 4) -> None:
        lib = _native.load()
        if lib is None:
            raise RuntimeError("native data-loader library unavailable")
        self._lib = lib
        self._h = lib.tdl_pool_create(threads)
        self._pending: dict[int, tuple] = {}  # job id -> kept-alive buffers

    def submit(self, arrays: list[np.ndarray], idx: np.ndarray,
               out: list[np.ndarray]) -> int:
        n = len(arrays)
        if n == 0 or n != len(out):
            raise ValueError(f"arrays/out length mismatch: {n} vs {len(out)}")
        idx64 = np.ascontiguousarray(idx, dtype=np.int64)
        srcs = (ctypes.c_void_p * n)()
        dsts = (ctypes.c_void_p * n)()
        row_bytes = (ctypes.c_longlong * n)()
        for k, (a, o) in enumerate(zip(arrays, out)):
            if not (a.flags.c_contiguous and o.flags.c_contiguous):
                raise ValueError(
                    "GatherPool requires C-contiguous arrays (the C++ gather "
                    "computes row offsets from shape, not strides); pass "
                    "np.ascontiguousarray(...)"
                )
            rb = a.dtype.itemsize * int(np.prod(a.shape[1:], dtype=np.int64))
            if o.shape[0] != len(idx64) or o.dtype != a.dtype or \
                    o.shape[1:] != a.shape[1:]:
                raise ValueError(f"out[{k}] shape/dtype mismatch")
            srcs[k] = a.ctypes.data
            dsts[k] = o.ctypes.data
            row_bytes[k] = rb
        job = self._lib.tdl_submit(
            self._h, n, srcs, row_bytes,
            idx64.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
            len(idx64), dsts,
        )
        if job < 0:
            raise RuntimeError("tdl_submit failed")
        self._pending[job] = (arrays, idx64, out)
        return int(job)

    def wait(self, job: int, timeout_s: float = 60.0) -> list[np.ndarray]:
        rc = self._lib.tdl_wait(self._h, job, int(timeout_s * 1000))
        if rc == 1:
            raise TimeoutError(f"gather job {job} timed out")
        if rc != 0:
            raise RuntimeError(f"gather job {job} unknown/failed")
        return list(self._pending.pop(job)[2])

    def gather(self, arrays: list[np.ndarray], idx: np.ndarray) -> list[np.ndarray]:
        """Synchronous convenience: allocate outputs, submit, wait."""
        out = [np.empty((len(idx),) + a.shape[1:], a.dtype) for a in arrays]
        return self.wait(self.submit(arrays, idx, out))

    def close(self) -> None:
        if self._h:
            self._lib.tdl_pool_destroy(self._h)
            self._h = None
            self._pending.clear()

    def __del__(self) -> None:  # best-effort; explicit close preferred
        try:
            self.close()
        except Exception:
            pass


def read_idx_native(path: str | Path) -> np.ndarray | None:
    """Parse a raw (non-gzipped) IDX file via the native parser; None when
    the native library is unavailable (caller falls back to numpy)."""
    lib = _native.load()
    if lib is None:
        return None
    dtype = ctypes.c_int()
    ndim = ctypes.c_int()
    dims = (ctypes.c_longlong * 8)()
    if lib.tdl_idx_info(str(path).encode(), ctypes.byref(dtype),
                        ctypes.byref(ndim), dims) != 0:
        raise ValueError(f"{path}: not a valid IDX file")
    shape = tuple(int(dims[i]) for i in range(ndim.value))
    if dtype.value not in _IDX_DTYPES:
        raise ValueError(f"{path}: unsupported IDX dtype code {dtype.value:#x}")
    np_dtype = np.dtype(_IDX_DTYPES[dtype.value])
    out = np.empty(shape, np_dtype)
    got = lib.tdl_idx_read(str(path).encode(), out.ctypes.data, out.nbytes)
    if got != out.nbytes:
        raise ValueError(f"{path}: truncated IDX payload")
    return out
