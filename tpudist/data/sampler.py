"""Epoch-seeded sharding sampler — the DistributedSampler equivalent.

Reproduces the semantics the reference relies on
(`mnist_ddp_elastic.py:183-189`, `mnist_horovod.py:41-42` — SURVEY.md §7
hard part (c)):

* the index list is padded by wrap-around so every shard has equal length
  (``ceil(N / world) * world``), exactly like
  ``torch.utils.data.DistributedSampler(drop_last=False)``;
* shard ``r`` takes indices ``perm[r::world]`` (rank-strided);
* ``shuffle=True`` permutes with a generator seeded by ``seed + epoch`` —
  the ``set_epoch`` contract (`mnist_ddp_elastic.py:84,89`);
* ``shuffle=False`` keeps natural order (the DDP example's configuration,
  `mnist_ddp_elastic.py:184-188`).

On TPU the "rank" is a *data-shard index*: with a single controller per host
feeding ``local_device_count`` devices, each host materializes the union of
its devices' shards and the batch is laid out so device d receives shard
``host_offset + d``.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class ShardedSampler:
    n: int
    num_shards: int
    shard: int
    shuffle: bool = False
    seed: int = 0
    drop_last: bool = False

    def __post_init__(self) -> None:
        if not (0 <= self.shard < self.num_shards):
            raise ValueError(f"shard {self.shard} out of range [0, {self.num_shards})")
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    @property
    def shard_size(self) -> int:
        if self.drop_last:
            return self.n // self.num_shards
        return -(-self.n // self.num_shards)  # ceil

    def indices(self, epoch: int | None = None) -> np.ndarray:
        epoch = self.epoch if epoch is None else epoch
        if self.shuffle:
            order = np.random.default_rng(self.seed + epoch).permutation(self.n)
        else:
            order = np.arange(self.n)
        if self.drop_last:
            total = self.shard_size * self.num_shards
            order = order[:total]
        else:
            total = self.shard_size * self.num_shards
            if total > self.n:  # pad by wrap-around, as DistributedSampler does
                order = np.concatenate([order, order[: total - self.n]])
        return order[self.shard :: self.num_shards]
