"""Synthetic data generators for the non-MNIST workloads.

Twins of the reference's synthetic streams:

* random images + one-hot labels for the ResNet50 pipeline
  (`model_parallel_ResNet50.py:208-217`: 3 batches of 32×3×128×128, 1000
  one-hot classes) — here NHWC and any batch size;
* ragged EmbeddingBag batches (`server_model_data_parallel.py:49-68`: 20-50
  indices over 100 embeddings, ragged offsets, 8-class targets), re-expressed
  as *static-shape* padded ``[batch, max_len]`` index matrices + masks,
  because dynamic raggedness defeats XLA; padding + mask is the TPU-native
  encoding of the same information.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np


def synthetic_images(
    batch: int,
    *,
    hw: int = 128,
    channels: int = 3,
    num_classes: int = 1000,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """One batch of random NHWC images and one-hot labels
    (`model_parallel_ResNet50.py:208-217` equivalent)."""
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((batch, hw, hw, channels), dtype=np.float32)
    labels = rng.integers(0, num_classes, size=batch)
    one_hot = np.zeros((batch, num_classes), dtype=np.float32)
    one_hot[np.arange(batch), labels] = 1.0
    return x, one_hot


def ragged_embedding_batches(
    num_batches: int,
    batch: int = 10,
    *,
    num_embeddings: int = 100,
    max_len: int = 10,
    min_len: int = 2,
    num_classes: int = 8,
    seed: int = 0,
) -> Iterator[tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Yield ``(indices [B, max_len], mask [B, max_len], target [B])``.

    Matches the intent of ``get_next_batch`` (`server_model_data_parallel.py:
    49-68`): each sample looks up a random ragged set of embedding rows,
    summed (mode="sum").  The reference's offsets encoding becomes a padding
    mask.  (The reference function as committed has a latent arity bug,
    SURVEY.md §3.5 — the documented intent is implemented, not the bug.)
    """
    rng = np.random.default_rng(seed)
    for _ in range(num_batches):
        lengths = rng.integers(min_len, max_len + 1, size=batch)
        indices = rng.integers(0, num_embeddings, size=(batch, max_len)).astype(np.int32)
        mask = (np.arange(max_len)[None, :] < lengths[:, None]).astype(np.float32)
        target = rng.integers(0, num_classes, size=batch).astype(np.int32)
        yield indices, mask, target
