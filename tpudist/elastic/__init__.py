"""Elastic training: checkpoint/resume, commit/rollback, restart loop.

The reference's signature capability (SURVEY.md §5 "Failure detection"),
delivered there by two external mechanisms (TorchElastic rendezvous +
re-exec, `mnist_ddp_elastic.py:5-6`; Horovod elastic commit/rollback,
`horovod_mnist_elastic.py:55-108`).  tpudist unifies both into one model:
durable checkpoints (:mod:`checkpoint`) + in-memory commits
(:class:`ElasticState`) + a supervising run loop (:func:`elastic_run`) that
rolls back to the last commit and re-enters training when workers fail or
the world resizes, firing reset hooks (lr rescale etc.) on membership change.
"""

from tpudist.elastic.checkpoint import (
    Checkpointer,
    latest_step,
    restore_pytree,
    save_pytree,
)
from tpudist.elastic.state import ElasticState, HostDataState
from tpudist.elastic.loop import WorldChanged, WorkerFailure, elastic_run
from tpudist.elastic.worker import ElasticContext, run_elastic_worker


def __getattr__(name):
    # Lazy (PEP 562): orbax pulls in tensorstore etc. (~seconds of import),
    # a cost every launcher-spawned worker on the npz path would pay.
    if name in ("HAVE_ORBAX", "OrbaxCheckpointer"):
        from tpudist.elastic import orbax_ckpt

        return getattr(orbax_ckpt, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "Checkpointer",
    "HAVE_ORBAX",
    "OrbaxCheckpointer",
    "ElasticContext",
    "ElasticState",
    "HostDataState",
    "WorkerFailure",
    "WorldChanged",
    "elastic_run",
    "latest_step",
    "restore_pytree",
    "run_elastic_worker",
    "save_pytree",
]
