"""Durable pytree checkpoints.

Replaces ``torch.save({MODEL_STATE, EPOCHS_RUN})`` snapshots
(`mnist_ddp_elastic.py:95-104,61-68`) with full-train-state checkpoints
(params + optimizer state + step + RNG — SURVEY.md §5), written atomically
(tmp file + rename) so a preemption mid-write can never corrupt the latest
restore point.  Format: one ``.npz`` archive keyed by pytree paths + a JSON
metadata sidecar; no framework objects are pickled, so checkpoints are
readable by any numpy, and restores are validated leaf-by-leaf against the
template's shapes.

``Checkpointer`` adds step-numbered directories, retention, and optional
async (background-thread) saves.  An async save blocks the caller only to
INITIATE the copies: every device leaf — tree leaves AND device scalars in
``meta`` — is first copied ON DEVICE (breaking any donation alias — the
caller may donate its buffers to the very next step) and its device→host
transfer started asynchronously; the background thread then waits for the
transfers and writes to disk, overlapping both with subsequent compute
(the CheckFreq-style snapshot/persist split).  A background-write failure
is re-raised from the next ``wait()``/``save()``, never swallowed.
``ckpt/save_blocked`` in :mod:`tpudist.obs` records exactly the initiation
time the caller paid.

Two layouts: ``"steps"`` (the default ``<dir>/step_<N>/`` scheme below) and
``"flat"`` — the target path IS one ``.npz`` file, no retention — which is
how ``Trainer`` keeps its single rolling ``snapshot.npz`` on the same save
path the elastic runtime uses.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time
from pathlib import Path
from typing import Any

import numpy as np

from tpudist.utils.trees import flatten_with_names, tree_to_numpy, unflatten_like

_STEP_RE = re.compile(r"^step_(\d+)$")


def _stage_to_host_async(tree: Any) -> Any:
    """Start (without finishing) a defensive copy of every leaf: device
    leaves get an ON-DEVICE copy — which breaks any alias a later donating
    dispatch could reuse, because the copy is ordered on the device stream
    before it — with their device→host transfer kicked off async; host
    leaves are copied eagerly (cheap).  ``tree_to_numpy`` on the result
    (from the background writer thread) blocks only on transfers that have
    been in flight since initiation."""
    import jax
    import jax.numpy as jnp

    def stage(leaf):
        if isinstance(leaf, jax.Array):
            dev_copy = jnp.array(leaf, copy=True)
            try:
                dev_copy.copy_to_host_async()
            except Exception:  # noqa: BLE001 - fetch still happens at write
                pass
            return dev_copy
        return np.array(leaf)

    return jax.tree.map(stage, tree)


def _meta_jsonable(meta: dict | None) -> dict | None:
    """Resolve device / numpy scalars in ``meta`` to plain JSON values, so
    callers can pass UNSYNCED device scalars (e.g. the live step counter)
    and the fetch lands here — on the background thread for async saves —
    instead of stalling the caller.  Async saves run ``meta`` through
    :func:`_stage_to_host_async` first, so by the time this resolves, every
    device scalar is a staged COPY the caller's donating dispatches cannot
    have deleted."""
    if meta is None:
        return None
    out = {}
    for k, v in meta.items():
        if v is None or isinstance(v, (str, bool, int, float)):
            out[k] = v
            continue
        try:
            arr = np.asarray(v)
            out[k] = arr.item() if arr.ndim == 0 else arr.tolist()
        except Exception:  # noqa: BLE001 - keep the save alive
            try:
                out[k] = str(v)
            except Exception:  # noqa: BLE001 - repr itself may raise
                out[k] = f"<unserializable {type(v).__name__}>"
    return out


def save_pytree(path: str | os.PathLike, tree: Any, meta: dict | None = None) -> None:
    """Atomically write ``tree`` (+ JSON-serializable ``meta``) to ``path``
    (a ``.npz`` file; ``<path>.meta.json`` sidecar)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    named = flatten_with_names(tree_to_numpy(tree))
    tmp = path.with_suffix(path.suffix + ".tmp")
    with open(tmp, "wb") as f:
        np.savez(f, **named)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    if meta is not None:
        mtmp = path.with_suffix(".meta.json.tmp")
        mtmp.write_text(json.dumps(meta))
        os.replace(mtmp, path.with_suffix(".meta.json"))


def restore_pytree(path: str | os.PathLike, template: Any) -> tuple[Any, dict]:
    """Load a checkpoint into the structure of ``template`` (shape-checked).
    Returns ``(tree, meta)``."""
    path = Path(path)
    with np.load(path) as archive:
        named = {k: archive[k] for k in archive.files}
    tree = unflatten_like(template, named)
    meta_path = path.with_suffix(".meta.json")
    meta = json.loads(meta_path.read_text()) if meta_path.exists() else {}
    return tree, meta


def latest_step(directory: str | os.PathLike) -> int | None:
    """Highest step with a *complete* checkpoint in ``directory``."""
    directory = Path(directory)
    if not directory.is_dir():
        return None
    steps = []
    for child in directory.iterdir():
        m = _STEP_RE.match(child.name)
        if m and (child / "state.npz").exists() and (child / "COMMITTED").exists():
            steps.append(int(m.group(1)))
    return max(steps) if steps else None


class Checkpointer:
    """Checkpoint save-path manager (one instance per save target).

    ``layout="steps"`` (default): ``<dir>/step_<N>/state.npz`` (+ meta)
    with a ``COMMITTED`` marker written last — readers only trust marked
    checkpoints, making the save atomic at the directory level too — and
    keep-N retention.

    ``layout="flat"``: ``directory`` names one ``.npz`` FILE that every
    save atomically replaces (``save_pytree`` semantics; ``step`` is
    recorded in the meta sidecar, retention does not apply) — the rolling
    single-snapshot contract ``Trainer`` exposes as ``snapshot_path``.

    With ``async_save=True``, :meth:`save` returns after copy INITIATION
    only (see the module docstring); :meth:`wait` joins the in-flight
    write, and every save/restore joins the previous write first.  A
    failed background write is NOT silent: its exception is captured and
    re-raised (once) from the next :meth:`wait` / :meth:`save` /
    :meth:`restore_latest`, so a caller that joins before declaring the
    snapshot durable gets the same failure the sync path would have raised.
    """

    def __init__(self, directory: str | os.PathLike, keep: int = 3,
                 async_save: bool = False, layout: str = "steps") -> None:
        if layout not in ("steps", "flat"):
            raise ValueError(f"layout must be 'steps' or 'flat', got {layout!r}")
        self.directory = Path(directory)
        self.keep = keep
        self.async_save = async_save
        self.layout = layout
        self._thread: threading.Thread | None = None
        self._error: BaseException | None = None

    def wait(self) -> None:
        """Join the in-flight async write; re-raises the exception a failed
        background write captured (then clears it), so returning normally
        means the last save is durable on disk."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def save(self, step: int, tree: Any, meta: dict | None = None) -> None:
        t0 = time.perf_counter()
        if self.async_save:
            # Initiate the defensive copies (device-side, so a donating
            # dispatch right after we return cannot clobber them), then
            # hand the transfer-wait AND the disk write to the thread.
            # Meta rides the same staging: its values may be live device
            # scalars (the trainer's step counter) that the caller's next
            # donating dispatch would delete before the writer resolves
            # them — the on-device copy breaks that alias too.
            staged = _stage_to_host_async(tree)
            staged_meta = _stage_to_host_async(meta) if meta is not None else None
            self.wait()
            self._thread = threading.Thread(
                target=self._finish_async, args=(step, staged, staged_meta),
                daemon=True)
            self._thread.start()
        else:
            # Synchronous: full device→host copy before returning — the
            # caller may mutate/donate the device buffers immediately.
            host_tree = tree_to_numpy(tree)
            self.wait()
            self._write(step, host_tree, _meta_jsonable(meta))
        try:
            from tpudist import obs

            obs.histogram("ckpt/save_blocked", unit="s").record(
                time.perf_counter() - t0)
        except Exception:  # noqa: BLE001 - metrics never fail a save
            pass

    def _finish_async(self, step: int, staged: Any, meta: dict | None) -> None:
        try:
            # blocks on the in-flight d2h transfers HERE, not in the caller
            host_tree = tree_to_numpy(staged)
            self._write(step, host_tree, _meta_jsonable(meta))
        except BaseException as e:  # noqa: BLE001 - surfaced from wait()
            self._error = e

    def _write(self, step: int, host_tree: Any, meta: dict | None) -> None:
        if self.layout == "flat":
            save_pytree(self.directory, host_tree, meta)
            return
        step_dir = self.directory / f"step_{step}"
        save_pytree(step_dir / "state.npz", host_tree, meta)
        (step_dir / "COMMITTED").touch()
        self._retain()

    def _retain(self) -> None:
        steps = sorted(
            int(m.group(1))
            for child in self.directory.iterdir()
            if (m := _STEP_RE.match(child.name))
        )
        for old in steps[: -self.keep] if self.keep else []:
            shutil.rmtree(self.directory / f"step_{old}", ignore_errors=True)

    def restore_latest(self, template: Any) -> tuple[int, Any, dict] | None:
        """Return ``(step, tree, meta)`` for the newest complete checkpoint,
        or None when the target holds none (fresh start)."""
        self.wait()
        if self.layout == "flat":
            if not self.directory.exists():
                return None
            tree, meta = restore_pytree(self.directory, template)
            return int(meta.get("step", 0)), tree, meta
        step = latest_step(self.directory)
        if step is None:
            return None
        tree, meta = restore_pytree(self.directory / f"step_{step}" / "state.npz", template)
        return step, tree, meta
