"""Durable pytree checkpoints.

Replaces ``torch.save({MODEL_STATE, EPOCHS_RUN})`` snapshots
(`mnist_ddp_elastic.py:95-104,61-68`) with full-train-state checkpoints
(params + optimizer state + step + RNG — SURVEY.md §5), written atomically
(tmp file + rename) so a preemption mid-write can never corrupt the latest
restore point.  Format: one ``.npz`` archive keyed by pytree paths + a JSON
metadata sidecar; no framework objects are pickled, so checkpoints are
readable by any numpy, and restores are validated leaf-by-leaf against the
template's shapes.

``Checkpointer`` adds step-numbered directories, retention, and optional
async (background-thread) saves — the device→host copy happens synchronously
(cheap) and the disk write overlaps the next steps, which is what makes
frequent elastic commits affordable.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
from pathlib import Path
from typing import Any

import numpy as np

from tpudist.utils.trees import flatten_with_names, tree_to_numpy, unflatten_like

_STEP_RE = re.compile(r"^step_(\d+)$")


def save_pytree(path: str | os.PathLike, tree: Any, meta: dict | None = None) -> None:
    """Atomically write ``tree`` (+ JSON-serializable ``meta``) to ``path``
    (a ``.npz`` file; ``<path>.meta.json`` sidecar)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    named = flatten_with_names(tree_to_numpy(tree))
    tmp = path.with_suffix(path.suffix + ".tmp")
    with open(tmp, "wb") as f:
        np.savez(f, **named)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    if meta is not None:
        mtmp = path.with_suffix(".meta.json.tmp")
        mtmp.write_text(json.dumps(meta))
        os.replace(mtmp, path.with_suffix(".meta.json"))


def restore_pytree(path: str | os.PathLike, template: Any) -> tuple[Any, dict]:
    """Load a checkpoint into the structure of ``template`` (shape-checked).
    Returns ``(tree, meta)``."""
    path = Path(path)
    with np.load(path) as archive:
        named = {k: archive[k] for k in archive.files}
    tree = unflatten_like(template, named)
    meta_path = path.with_suffix(".meta.json")
    meta = json.loads(meta_path.read_text()) if meta_path.exists() else {}
    return tree, meta


def latest_step(directory: str | os.PathLike) -> int | None:
    """Highest step with a *complete* checkpoint in ``directory``."""
    directory = Path(directory)
    if not directory.is_dir():
        return None
    steps = []
    for child in directory.iterdir():
        m = _STEP_RE.match(child.name)
        if m and (child / "state.npz").exists() and (child / "COMMITTED").exists():
            steps.append(int(m.group(1)))
    return max(steps) if steps else None


class Checkpointer:
    """Step-numbered checkpoint directory manager.

    Layout: ``<dir>/step_<N>/state.npz`` (+ meta) with a ``COMMITTED``
    marker written last — readers only trust marked checkpoints, making the
    save atomic at the directory level too.
    """

    def __init__(self, directory: str | os.PathLike, keep: int = 3,
                 async_save: bool = False) -> None:
        self.directory = Path(directory)
        self.keep = keep
        self.async_save = async_save
        self._thread: threading.Thread | None = None

    def wait(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def save(self, step: int, tree: Any, meta: dict | None = None) -> None:
        # Snapshot to host synchronously: the caller may mutate/donate the
        # device buffers immediately after we return.
        host_tree = tree_to_numpy(tree)
        self.wait()
        if self.async_save:
            self._thread = threading.Thread(
                target=self._write, args=(step, host_tree, meta), daemon=True
            )
            self._thread.start()
        else:
            self._write(step, host_tree, meta)

    def _write(self, step: int, host_tree: Any, meta: dict | None) -> None:
        step_dir = self.directory / f"step_{step}"
        save_pytree(step_dir / "state.npz", host_tree, meta)
        (step_dir / "COMMITTED").touch()
        self._retain()

    def _retain(self) -> None:
        steps = sorted(
            int(m.group(1))
            for child in self.directory.iterdir()
            if (m := _STEP_RE.match(child.name))
        )
        for old in steps[: -self.keep] if self.keep else []:
            shutil.rmtree(self.directory / f"step_{old}", ignore_errors=True)

    def restore_latest(self, template: Any) -> tuple[int, Any, dict] | None:
        """Return ``(step, tree, meta)`` for the newest complete checkpoint,
        or None when the directory holds none (fresh start)."""
        self.wait()
        step = latest_step(self.directory)
        if step is None:
            return None
        tree, meta = restore_pytree(self.directory / f"step_{step}" / "state.npz", template)
        return step, tree, meta
