"""The elastic run loop — ``@hvd.elastic.run`` and TorchElastic's
restart-on-membership-change, unified (SURVEY.md §5).

Two failure models, one mechanism:

* **In-process** (worker drop/add detected while the controller survives,
  Horovod-elastic style): training raises :class:`WorldChanged` /
  :class:`WorkerFailure`; :func:`elastic_run` rolls the state back to its
  last commit, fires reset callbacks on a resize, and re-enters the train
  function — `horovod_mnist_elastic.py:55-77` semantics.
* **Process-restart** (TorchElastic style, `mnist_ddp_elastic.py:5-6`): the
  process dies; on relaunch the trainer restores the newest durable
  checkpoint (``Checkpointer.restore_latest``) and resumes — granularity =
  the commit interval instead of the reference's epoch granularity.

Fault injection for tests: pass ``fault=`` a callable invoked before every
train attempt; tests raise on chosen (epoch, batch) positions to prove
rollback exactness (the reference has no fault injection at all, SURVEY.md
§4/§5).
"""

from __future__ import annotations

from typing import Callable

from tpudist.elastic.state import ElasticState
from tpudist.utils.logging import get_logger

log = get_logger(__name__)


class WorkerFailure(RuntimeError):
    """A worker died; world size is unchanged after recovery."""


class WorldChanged(RuntimeError):
    """Membership changed; carries the new world size."""

    def __init__(self, new_world_size: int, msg: str = "") -> None:
        super().__init__(msg or f"world resized to {new_world_size}")
        self.new_world_size = new_world_size


def elastic_run(
    train_fn: Callable[[ElasticState], None],
    state: ElasticState,
    max_restarts: int = 10,
) -> ElasticState:
    """Run ``train_fn(state)`` to completion, restarting on elastic events.

    ``train_fn`` must call ``state.commit()`` at its commit points and read
    its starting position from ``state.host`` (epoch/batch) — exactly the
    contract of the reference's ``train(state)``
    (`horovod_mnist_elastic.py:55-77`).
    """
    restarts = 0
    while True:
        try:
            train_fn(state)
            return state
        except WorldChanged as e:
            restarts += 1
            if restarts > max_restarts:
                raise
            log.warning(
                "world resized %d -> %d; rolling back to commit #%d "
                "(epoch %d, batch %d)",
                state.world_size, e.new_world_size, state.commits,
                state._committed_host.epoch, state._committed_host.batch,
            )
            state.on_world_change(e.new_world_size)
        except WorkerFailure as e:
            restarts += 1
            if restarts > max_restarts:
                raise
            log.warning("worker failure (%s); rolling back to last commit", e)
            state.rollback()
