"""Orbax-backed checkpointing: the multi-host durable-commit path.

The npz :class:`tpudist.elastic.checkpoint.Checkpointer` gathers every leaf
to one host — exactly what the reference's ``torch.save`` snapshot does
(`mnist_ddp_elastic.py:95-104`) and fine on one machine, but wrong at pod
scale where params are sharded across hosts.  :class:`OrbaxCheckpointer`
exposes the SAME interface (``save(step, tree, meta)`` /
``restore_latest(template)`` / ``wait()``) on top of
``orbax.checkpoint.CheckpointManager``, which writes each host's shards in
parallel (distributed, sharding-aware save/restore), checkpoints
atomically, retains ``keep`` steps, and overlaps saves with training when
``async_save=True`` — so elastic commits (`tpudist.elastic.state`) scale
from one chip to a multi-host slice by swapping the checkpointer.

Restore honors the template's shardings: pass a state whose leaves are
jax.Arrays (or ShapeDtypeStructs with shardings) and each host reloads
only its shards.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any

from tpudist.utils.logging import get_logger

log = get_logger(__name__)

_LOGICAL_KEY = "__logical_step__"

try:  # orbax is in the image; guard anyway so npz remains self-sufficient
    import orbax.checkpoint as ocp

    HAVE_ORBAX = True
except Exception:  # pragma: no cover - exercised only without orbax
    ocp = None
    HAVE_ORBAX = False


class OrbaxCheckpointer:
    """Drop-in :class:`tpudist.elastic.checkpoint.Checkpointer` alternative
    backed by ``orbax.checkpoint.CheckpointManager``."""

    def __init__(self, directory: str | os.PathLike, keep: int = 3,
                 async_save: bool = False) -> None:
        if not HAVE_ORBAX:  # pragma: no cover
            raise ImportError(
                "orbax-checkpoint is unavailable; use "
                "tpudist.elastic.checkpoint.Checkpointer instead")
        self.directory = Path(directory).absolute()
        # Highest physical step issued by THIS process.  With async_save,
        # ``latest_step()`` may not yet include an in-flight save, so the
        # collision remap in :meth:`save` must not rely on it alone: two
        # quick commits with non-increasing logical steps could otherwise
        # compute the same physical step and the second would be skipped.
        self._last_physical: int | None = None
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep or None,
                enable_async_checkpointing=async_save,
                create=True,
            ),
        )

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    def save(self, step: int, tree: Any, meta: dict | None = None) -> None:
        # npz-Checkpointer semantics: every save lands, even when the step
        # number repeats (elastic commits between optimizer steps) or
        # regresses (a fresh ElasticState after a gang restart counts
        # commits from 1).  Orbax would silently skip step <= latest and
        # deleting-then-rewriting would break crash atomicity, so a
        # colliding step is written as ``latest + 1`` with the caller's
        # step preserved in the metadata — saves stay atomic (new
        # directory + rename) and no durable commit is ever dropped.
        issued = [s for s in (self._mngr.latest_step(), self._last_physical)
                  if s is not None]
        latest = max(issued) if issued else None
        physical = step if latest is None or step > latest else latest + 1
        self._last_physical = physical
        saved = self._mngr.save(
            physical,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(tree),
                meta=ocp.args.JsonSave({**(meta or {}), _LOGICAL_KEY: step}),
            ),
        )
        if not saved:  # pragma: no cover - monotonic steps always save
            raise RuntimeError(
                f"orbax skipped checkpoint save at step {physical}")

    def restore_latest(self, template: Any) -> tuple[int, Any, dict] | None:
        """Return ``(step, tree, meta)`` for the newest complete checkpoint
        (sharded per the template's leaves), or None on a fresh start."""
        self.wait()
        step = self._mngr.latest_step()
        if step is None:
            return None
        restored = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(template),
                meta=ocp.args.JsonRestore(),
            ),
        )
        meta = dict(restored["meta"] or {})
        step = meta.pop(_LOGICAL_KEY, step)
        return step, restored["state"], meta

    def close(self) -> None:
        self._mngr.close()
