"""Commit/rollback elastic state — the ``hvd.elastic.TorchState`` twin.

The reference wraps model+optimizer+counters in a ``TorchState`` whose
``commit()`` is a consistency barrier + in-memory backup, rolled back when
membership changes (`horovod_mnist_elastic.py:104,71-72` — SURVEY.md §3.3).
Here the device-side train state is one pytree, so commit = device→host
snapshot (and optionally a durable checkpoint via
:class:`tpudist.elastic.checkpoint.Checkpointer`), rollback = re-placement of
the committed pytree.

The reference's committed batch index lags the true position by one batch and
only protects the first resumed epoch (quirk documented in SURVEY.md §3.3);
here ``HostDataState`` is committed atomically with the device state, so
resume lands exactly on the committed (epoch, batch).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import jax

from tpudist.elastic.checkpoint import Checkpointer
from tpudist.utils.trees import host_to_leaf, tree_to_numpy

ResetCallback = Callable[["ElasticState", int, int], None]


@dataclasses.dataclass
class HostDataState:
    """Host-side progress counters committed with the device state
    (epoch + batch offset, the ``TorchState(batch=0, epoch=0)`` fields,
    `horovod_mnist_elastic.py:104`)."""

    epoch: int = 0
    batch: int = 0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class ElasticState:
    """Holds the live train-state pytree plus host counters, with
    commit/rollback and world-size reset callbacks."""

    def __init__(
        self,
        state: Any,
        host: HostDataState | None = None,
        checkpointer: Checkpointer | None = None,
        world_size: int = 1,
        restore: bool = True,
    ) -> None:
        self.state = state
        self.host = host or HostDataState()
        self.checkpointer = checkpointer
        self.world_size = world_size
        self._reset_callbacks: list[ResetCallback] = []
        self._committed_state: Any = None
        self._committed_host: HostDataState | None = None
        self.commits = 0
        self.rollbacks = 0
        # Durable resume (``restore=False`` opts out): adopt the latest
        # checkpoint BEFORE the initial commit, so a restarted gang — even
        # a FULL-gang loss, where no surviving peer can re-broadcast the
        # state — resumes from the last durable commit rather than from
        # scratch (the torch.save/load snapshot contract,
        # `mnist_ddp_elastic.py:95-104`, extended to elastic restarts).
        self.restored_step: int | None = None
        if checkpointer is not None and restore:
            hit = checkpointer.restore_latest(self.state)
            if hit is not None:
                step, tree, meta = hit
                self.state = tree
                self.host.epoch = int(meta.get("epoch", self.host.epoch))
                self.host.batch = int(meta.get("batch", self.host.batch))
                if "world_size" in meta:
                    self.world_size = int(meta["world_size"])
                self.restored_step = step
        # initial state is always restorable; when it was JUST restored,
        # skip the durable re-write (identical content — re-saving on
        # every restart would churn the keep window and pay a full
        # serialize for zero new durability)
        self.commit(durable=self.restored_step is None)

    def register_reset_callbacks(self, callbacks: Sequence[ResetCallback]) -> None:
        """`state.register_reset_callbacks([on_state_reset])` parity
        (`horovod_mnist_elastic.py:105`)."""
        self._reset_callbacks.extend(callbacks)

    def commit(self, durable: bool = True) -> None:
        """Consistency point: snapshot device state to host memory; also a
        durable checkpoint when a checkpointer is attached (strictly stronger
        than the reference's memory-only commit)."""
        self._committed_state = tree_to_numpy(self.state)
        self._committed_host = dataclasses.replace(self.host)
        self.commits += 1
        if durable and self.checkpointer is not None:
            self.checkpointer.save(
                int(jax.device_get(self.state.step)) if hasattr(self.state, "step")
                else self.commits,
                self.state,
                meta={**self.host.as_dict(), "world_size": self.world_size},
            )

    def rollback(self) -> None:
        """Restore the last committed (device state, host counters)."""
        if self._committed_state is None:
            raise RuntimeError("nothing committed")
        template = self.state
        self.state = jax.tree.map(host_to_leaf, template, self._committed_state)
        self.host = dataclasses.replace(self._committed_host)
        self.rollbacks += 1

    def on_world_change(self, new_world_size: int) -> None:
        """Rollback + fire reset callbacks — what ``@hvd.elastic.run`` does on
        worker add/drop (`horovod_mnist_elastic.py:80-82`: lr/√N rescale)."""
        old = self.world_size
        self.rollback()
        self.world_size = new_world_size
        for cb in self._reset_callbacks:
            cb(self, old, new_world_size)

    def apply_world(self, new_world_size: int) -> None:
        """Adopt a world size WITHOUT rolling back, firing reset callbacks
        if it differs.  The rendezvous-exit hook: a restarted gang that
        restored a durable commit taken at a different world (or a gang
        that lost ANOTHER member while re-rendezvousing) must still
        rescale its world-dependent hyperparameters."""
        if new_world_size == self.world_size:
            return
        old = self.world_size
        self.world_size = new_world_size
        for cb in self._reset_callbacks:
            cb(self, old, new_world_size)
