"""The TTL-driven elastic worker loop — rendezvous-driven recovery.

This is the piece that makes the coordination service (``native/coord.cpp``
+ :mod:`tpudist.runtime.coord`) *drive* elastic training the way the
reference's control planes do: torchrun's c10d rendezvous re-forms the world
on membership change (`pytorch_elastic/mnist_ddp_elastic.py:5-6`) and
Horovod's elastic driver rolls back and re-assembles on host add/drop
(`horovod/horovod_mnist_elastic.py:55,108`).  Here, in one loop per worker:

1. heartbeat (TTL lease) starts before anything else — liveness IS
   membership;
2. :meth:`~tpudist.runtime.coord.Rendezvous.join_live` forms the round from
   whatever workers are alive (world size *discovered*, not prescribed);
3. committed state is broadcast from the round's rank 0 so every
   participant resumes bitwise identically;
4. training runs with :class:`~tpudist.runtime.collectives.HostCollectives`
   whose waits poll :meth:`ElasticMonitor.check` — a ``kill -9``'d peer
   surfaces as :class:`WorldChanged` at the next commit point OR mid-
   allreduce, whichever comes first (TTL detection, no exit-code polling);
5. on :class:`WorldChanged`: rollback to the last commit, fire reset
   callbacks (lr/√N rescale, `horovod_mnist_elastic.py:80-82`), bump the
   round, re-rendezvous at the new size, resume — within one commit
   interval of the pre-failure state.

Round agreement: the store key ``{ns}/round`` publishes the active round.
A fresh worker joining mid-run reads it and registers for ``round + 1``;
its heartbeat makes the incumbents' next ``check()`` raise
:class:`WorldChanged`, and everyone converges on ``round + 1`` — the grow
path and the shrink path are the same code.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable

import jax
import numpy as np

from tpudist import obs
from tpudist.elastic.loop import WorldChanged
from tpudist.elastic.state import ElasticState
from tpudist.runtime.collectives import (
    CollectiveConfig, Handle, HostCollectives, PeerLost,
)
from tpudist.runtime.coord import CoordClient, ElasticMonitor, Rendezvous
from tpudist.runtime.ici import host_snapshot
from tpudist.utils.logging import get_logger
from tpudist.utils.trees import host_to_leaf, tree_to_numpy

log = get_logger(__name__)


@dataclasses.dataclass
class ElasticContext:
    """Per-round handles passed to the train function.

    ``collectives`` is the round's DATA plane: :class:`HostCollectives`
    (store-backed, ``data_plane="host"``) or
    :class:`~tpudist.runtime.ici.IciCollectives` (compiled XLA
    ``pmean`` over ``mesh``, ``data_plane="ici"``) — same
    ``allreduce_mean`` API either way, so train functions are
    plane-agnostic."""

    rank: int
    world_size: int
    round: int
    collectives: Any
    monitor: ElasticMonitor
    mesh: Any = None
    data_plane: str = "host"

    def check(self) -> None:
        """Membership probe — call at commit points (the Horovod per-commit
        poll); raises :class:`WorldChanged` on TTL-detected add/drop."""
        self.monitor.check()


# train_fn(state, ctx) trains from state.host's position, calling
# state.commit() + ctx.check() at its commit points and
# ctx.collectives.allreduce_mean(...) for gradient sync.
TrainFn = Callable[[ElasticState, ElasticContext], None]


class OverlappedGradSync:
    """Microbatch gradient sync that overlaps wire time with compute —
    the ``hvd.DistributedOptimizer`` pattern (`mnist_horovod.py:53`:
    allreduce of microbatch ``m`` rides the background worker while the
    caller computes microbatch ``m+1``), with the final state BITWISE
    identical to the synchronous path.

    Usage inside a train function::

        sync = OverlappedGradSync(ctx.collectives)
        for mb in microbatches:
            sync.push(grad_fn(params, mb))   # starts the allreduce
            # ... next microbatch's forward/backward overlaps it ...
        total = sync.reduce()                # waits, sums in push order

    Determinism: handles are waited in submission order and summed in
    that same fixed order, so the result equals summing the synchronous
    allreduce outputs — no stale-gradient pipelining, no reordering.
    Falls back to synchronous allreduce when the plane has no async API
    (:class:`~tpudist.runtime.ici.IciCollectives` before PR 4, custom
    planes).

    **Bucketed backward-order mode** (``bucket_bytes`` set): instead of
    pushing whole trees per microbatch, the train loop streams named
    gradients in the order the backward pass produces them::

        sync = OverlappedGradSync(ctx.collectives, bucket_bytes=1 << 20)
        for name, g in backward_order_grads():   # hooks, reverse topo
            sync.grad_ready(name, g)             # may fire a bucket
        total = sync.reduce(mean=True)           # dict name -> array

    Step 1 records arrival order and greedily packs consecutive names
    into buckets of ``>= bucket_bytes``; :meth:`reduce` freezes that
    plan.  From step 2 on, a bucket's allreduce is submitted the moment
    its LAST member gradient lands — communication of early (deep)
    layers overlaps the rest of the backward pass.  Buckets are always
    SUBMITTED in plan order (a ready bucket waits for its predecessors),
    so every rank issues the same collectives in the same sequence even
    under arrival jitter — the op-id agreement `HostCollectives`
    requires.  The recorded order itself must match across ranks, which
    SPMD replicas guarantee (same model, same backward).  Each name
    belongs to exactly one bucket and cross-bucket results are merely
    merged (no arithmetic), so the result is bitwise what per-name
    synchronous allreduces would return."""

    def __init__(self, collectives: Any,
                 bucket_bytes: int | None = None) -> None:
        self._coll = collectives
        self._async = getattr(collectives, "allreduce_sum_async", None)
        self._handles: list[tuple[Handle | Any, bool]] = []
        if bucket_bytes is not None and bucket_bytes < 1:
            raise ValueError(f"bucket_bytes must be >= 1: {bucket_bytes}")
        self._bucket_bytes = bucket_bytes
        self._plan: list[list[str]] | None = None   # frozen after step 1
        self._member: dict[str, int] = {}           # name -> bucket index
        self._order: list[str] = []                 # step-1 arrival order
        self._pending: dict[str, np.ndarray] = {}   # landed, not yet fired
        self._counts: dict[str, int] = {}           # local adds per name
        self._inflight: list[tuple[int, Any, bool]] = []  # (bi, h, is_async)
        self._next_fire = 0                         # plan-order cursor
        self._open_bytes = 0                        # step-1 greedy packing

    def push(self, tree: Any) -> None:
        """Submit one microbatch's gradient tree for summing across ranks."""
        if self._pending or self._inflight or self._order \
                or self._plan is not None:
            raise ValueError(
                "push() cannot be mixed with grad_ready() on one "
                "OverlappedGradSync: the instance is in bucketed mode")
        if self._async is not None:
            self._handles.append((self._async(tree), True))
        else:
            self._handles.append((self._coll.allreduce_sum(tree), False))

    # -- bucketed backward-order mode ---------------------------------------

    def grad_ready(self, name: str, value: Any) -> None:
        """Hand over one named gradient in backward order; fires the
        owning bucket's allreduce once every member has landed (and all
        earlier-plan buckets have fired).  A repeat ``grad_ready`` for a
        name still pending accumulates locally (gradient accumulation
        across microbatches); repeats after the bucket fired are an
        error — stream each name's final contribution before its bucket
        closes."""
        if self._bucket_bytes is None:
            raise ValueError(
                "bucketed mode needs OverlappedGradSync(..., bucket_bytes=)")
        if self._handles:
            raise ValueError(
                "grad_ready() cannot be mixed with push() in one step")
        value = np.asarray(value)
        if name in self._pending:
            self._pending[name] = self._pending[name] + value
            self._counts[name] += 1
            return
        if self._plan is not None and name not in self._member:
            raise ValueError(
                f"unknown gradient {name!r}: not in the step-1 plan "
                f"({sorted(self._member)})")
        if self._plan is None and name in self._member:
            raise ValueError(
                f"gradient {name!r} already fired this step; stream each "
                f"name once per step while its bucket is open")
        self._pending[name] = value
        self._counts[name] = 1
        if self._plan is None:
            self._order.append(name)
            self._member[name] = -1  # recorded; bucket assigned at freeze
            self._open_bytes += value.nbytes
            if self._open_bytes >= self._bucket_bytes:
                self._fire(list(self._order[len(self._order)
                                            - self._open_count():]))
        else:
            self._fire_ready()

    def _open_count(self) -> int:
        """Names recorded but not yet fired (the open step-1 bucket)."""
        return len(self._pending)

    def _fire(self, names: list[str]) -> None:
        """Submit one bucket's allreduce (step-1 path: bucket = the open
        run of names)."""
        tree = {n: self._pending.pop(n) for n in names}
        bi = self._next_fire
        self._next_fire += 1
        for n in names:
            self._member[n] = bi
        if self._plan is None:
            self._open_bytes = 0
        if self._async is not None:
            self._inflight.append((bi, self._async(tree), True))
        else:
            self._inflight.append((bi, self._coll.allreduce_sum(tree), False))

    def _fire_ready(self) -> None:
        """Step >= 2: submit every plan-order-consecutive bucket whose
        members have all landed — plan order, not arrival order, so the
        collective sequence is rank-agreed."""
        assert self._plan is not None
        while self._next_fire < len(self._plan):
            names = self._plan[self._next_fire]
            if not all(n in self._pending for n in names):
                return
            self._fire(names)

    def reduce(self, mean: bool = False) -> Any:
        """Wait for every pushed allreduce (in push order) and return the
        elementwise sum; ``mean=True`` divides by ``pushes × world``.
        In bucketed mode: fire any trailing bucket, wait all in-flight
        buckets, and return ``{name: array}`` (``mean=True`` divides each
        by ``local_adds × world``).  Worker-thread errors (``PeerLost`` /
        ``WorldChanged``) re-raise here, exactly where the synchronous
        path would have raised."""
        if self._order or self._inflight or self._pending:
            return self._reduce_bucketed(mean)
        if not self._handles:
            raise ValueError("reduce() with no pushed gradients")
        handles, self._handles = self._handles, []
        total = None
        for h, is_handle in handles:
            out = h.wait() if is_handle else h
            total = out if total is None else jax.tree.map(
                np.add, total, out)
        if mean:
            scale = len(handles) * getattr(self._coll, "world", 1)
            total = jax.tree.map(lambda x: x / scale, total)
        return total

    def _reduce_bucketed(self, mean: bool) -> dict[str, np.ndarray]:
        if self._plan is None:
            # freeze the step-1 plan: fired prefixes + the open remainder
            if self._pending:
                self._fire([n for n in self._order if n in self._pending])
            plan: list[list[str]] = [[] for _ in range(self._next_fire)]
            for n in self._order:
                plan[self._member[n]].append(n)
            self._plan = plan
        elif self._pending or self._next_fire < len(self._plan):
            missing = [n for names in self._plan[self._next_fire:]
                       for n in names if n not in self._pending]
            raise ValueError(
                f"reduce() before every gradient landed; missing: {missing}")
        inflight, self._inflight = self._inflight, []
        out: dict[str, np.ndarray] = {}
        for _bi, h, is_async in inflight:
            out.update(h.wait() if is_async else h)
        counts, self._counts = self._counts, {}
        self._order = []
        self._next_fire = 0
        self._open_bytes = 0
        if mean:
            world = getattr(self._coll, "world", 1)
            out = {n: v / (counts[n] * world) for n, v in out.items()}
        return out


def _next_round(client: CoordClient, round_id: int) -> int:
    """The round-advance rule, shared by every re-rendezvous path: strictly
    forward, and past the highest round the gang already formed (the
    published ``elastic/round``) — a laggard must chase the gang, never
    re-form a round behind it."""
    raw = client.get("elastic/round")
    published = -1 if raw is None else int(raw)
    return max(round_id + 1, published + 1)


def _drop_ici_world(ici: Any, data_coll: Any, state: ElasticState,
                    exc: BaseException) -> None:
    """Free a dead round's distributed world INSIDE the failure handler —
    before re-rendezvous, not at the next round's formation.

    This ordering is load-bearing for detection symmetry: when a member
    dies mid-collective, the peer adjacent to it in the gloo ring gets an
    instant connection-reset, but a non-adjacent survivor stays BLOCKED
    waiting on data that must transit the detector — it only unblocks
    when the detector's old sockets actually close.  Tearing down here
    (executables released, traceback frames dropped so nothing pins the
    dead client, then ``clear_backends`` + collect) closes them within
    milliseconds; deferring to the next ``form()`` would leave the
    blocked peer out of the new rendezvous for the whole live-grace
    window and splinter the gang into world-of-1 rounds (observed before
    this ordering was fixed)."""
    if ici is None:
        return
    _, restore = host_snapshot(state.state)
    exc.__traceback__ = None  # tb frames pin the dead world's arrays
    from tpudist.runtime.ici import IciCollectives

    if isinstance(data_coll, IciCollectives):
        data_coll.release()
    ici.teardown()
    state.state = restore()


def _coord_client(coord_addr: str | None) -> CoordClient:
    addr = coord_addr or os.environ.get("TPUDIST_COORD_ADDR")
    if not addr:
        raise ValueError(
            "no coordination service address: pass coord_addr or launch "
            "under tpudist.runtime.launch (which exports TPUDIST_COORD_ADDR)")
    host, port = addr.rsplit(":", 1)
    return CoordClient(host, int(port))


def run_elastic_worker(
    train_fn: TrainFn,
    state: ElasticState,
    coord_addr: str | None = None,
    worker_id: str | None = None,
    ttl_s: float = 2.0,
    heartbeat_interval_s: float = 0.5,
    max_rounds: int = 10,
    rendezvous_timeout_s: float = 60.0,
    data_plane: str = "host",
    coll_config: CollectiveConfig | None = None,
) -> ElasticState:
    """Run ``train_fn`` under TTL-heartbeat elastic supervision.

    Returns the final state after ``train_fn`` completes at some world
    size.  Raises after ``max_rounds`` re-rendezvous attempts (torchrun's
    ``--max-restarts``).

    ``data_plane`` selects where gradient bytes travel:

    * ``"host"`` — store-backed :class:`HostCollectives` (the reference's
      gloo-on-CPU parity path, dynamic membership with zero backend
      state);
    * ``"ici"`` — each round bootstraps a ``jax.distributed`` world sized
      to the rendezvous and ``ctx.collectives`` runs compiled
      ``jax.lax.pmean`` over ``ctx.mesh`` (XLA collectives: ICI/DCN on
      TPU, gloo TCP on the CPU backend).  The store then carries ONLY
      control traffic (rendezvous, address agreement, state broadcast at
      round formation) — the role split of ``native/coord.cpp:11-13``.
      A peer dying mid-collective surfaces as a catchable runtime error
      (see :mod:`tpudist.runtime.ici`) and is handled exactly like
      :class:`PeerLost` on the host plane.

    ``coll_config`` tunes the host collectives (algorithm / bucket_bytes /
    compression — see :class:`~tpudist.runtime.collectives
    .CollectiveConfig`); ``None`` reads the ``TPUDIST_COLL_*``
    environment, so launcher-spawned gangs agree on a plan without
    plumbing.
    """
    if data_plane not in ("host", "ici"):
        raise ValueError(f"unknown data_plane {data_plane!r}")
    client = _coord_client(coord_addr)
    wid = worker_id or f"w{os.getpid()}"
    monitor = ElasticMonitor(client, wid, ttl_s=ttl_s,
                             interval_s=heartbeat_interval_s)
    monitor.start(None)  # beat first: liveness is membership
    rdzv = Rendezvous(client)
    ici = None
    if data_plane == "ici":
        from tpudist.runtime.ici import IciDataPlane

        ici = IciDataPlane(client)
    raw = client.get("elastic/round")
    round_id = 0 if raw is None else int(raw) + 1
    # soft assembly target for round 0: the launcher-declared gang size
    min_world = int(os.environ.get("TPUDIST_NUM_PROCESSES", "1"))
    rounds = 0
    first_round = True

    def recover(exc: BaseException, new_size: int) -> tuple[int, int]:
        """The shared WorldChanged/peer-loss recovery tail: drop the dead
        ICI world FIRST (ordering is load-bearing — see
        :func:`_drop_ici_world`), roll back + fire reset callbacks, close
        the round's store keys, advance the round.  Returns the next
        ``(round_id, min_world)``.  Reads ``data_coll``/``coll``/
        ``round_id`` late-bound so it always acts on the current round."""
        _drop_ici_world(ici, data_coll, state, exc)
        state.on_world_change(new_size)
        coll.close_round()
        return _next_round(client, round_id), new_size

    try:
        while True:
            try:
                rank, world, members = rdzv.join_live(
                    round_id, wid, timeout_s=rendezvous_timeout_s,
                    min_world=min_world, superseded_key="elastic/round")
            except TimeoutError:
                obs.counter("elastic/rendezvous_timeouts").inc()
                rounds += 1
                if rounds > max_rounds:
                    raise
                round_id = _next_round(client, round_id)
                continue
            obs.counter("elastic/rounds").inc()
            obs.gauge("elastic/world_size", unit="workers").set(world)
            obs.recorder.record("elastic_round", round=round_id, rank=rank,
                                world=world)
            monitor.resize(world)
            if rank == 0:
                # publish forward only: a lagging splinter round must never
                # regress the counter fresh joiners key off.  Best-effort
                # (get-then-set, not CAS): join_live's defer-while-live-
                # non-members rule keeps concurrent round formation out of
                # the steady state, and a racy regression only costs the
                # next joiner one extra WorldChanged cycle.
                raw = client.get("elastic/round")
                if raw is None or int(raw) < round_id:
                    client.set("elastic/round", str(round_id))
            coll = HostCollectives(client, rank, world, round_id,
                                   on_wait=monitor.check,
                                   config=coll_config)
            try:
                mesh = None
                data_coll: Any = coll
                if ici is not None:
                    # the backend swap: everything device-side goes to
                    # host, the distributed world re-forms at this
                    # round's size, the tree comes back typed on the new
                    # backend.  restore() runs even when form() fails so
                    # the rollback path never maps over dead arrays.
                    _, restore = host_snapshot(state.state)
                    try:
                        mesh = ici.form(round_id, rank, world,
                                        on_wait=monitor.check)
                    finally:
                        state.state = restore()
                    from tpudist.runtime.ici import IciCollectives

                    data_coll = IciCollectives(mesh,
                                               on_check=monitor.check)
                # bitwise state agreement across the new world (the
                # hvd.broadcast_parameters / TorchState re-broadcast role) —
                # INCLUDING the host position: a freshly-joined worker starts
                # from scratch and must adopt the root's (epoch, batch), or
                # its step stream would misalign with the incumbents'.  This
                # runs INSIDE the WorldChanged/PeerLost handler: the full
                # model state is transferred here, so a peer dying mid-
                # broadcast must trigger re-rendezvous, not a crash.
                fresh = first_round and state.restored_step is None
                # ROOT ELECTION: broadcast from the member with the MOST
                # committed progress (non-fresh beats fresh, then epoch,
                # then batch; ties -> lowest rank) — NOT blindly rank 0.
                # After a partial restart, the relaunched from-scratch
                # worker can sort back to rank 0; rooting there would
                # broadcast its initial state over the incumbents' and
                # silently wipe the run's progress.
                score = np.zeros(world, np.int64)
                score[rank] = ((0 if fresh else 1) << 52
                               | min(int(state.host.epoch),
                                     (1 << 20) - 1) << 32
                               | min(int(state.host.batch), (1 << 32) - 1))
                root = int(np.argmax(coll.allreduce_sum(score)))
                synced = coll.broadcast(
                    {"state": tree_to_numpy(state.state),
                     "host": np.asarray([state.host.epoch, state.host.batch,
                                         state.world_size, int(fresh)])},
                    root=root)
                state.state = jax.tree.map(
                    host_to_leaf, state.state, synced["state"])
                state.host.epoch = int(synced["host"][0])
                state.host.batch = int(synced["host"][1])
                # The rescale decision is keyed on the ROOT's flags, not
                # the local ones: everyone just adopted the root's state
                # (incl. the lr inside opt_state), so a rank-local decision
                # would let ranks with asymmetric checkpoint availability
                # apply different rescales to the identical synced state.
                if int(synced["host"][3]):
                    # the elected root is fresh only when EVERY member is
                    # (election prefers any non-fresh state): initial
                    # formation — base hyperparameters are DEFINED for
                    # this world
                    state.world_size = world
                else:
                    # root's recorded world is the uniform "old" for the
                    # rescale (a restored durable commit may carry a world
                    # the restarted gang no longer has; a second death
                    # during re-rendezvous shifts it again)
                    state.world_size = int(synced["host"][2])
                    state.apply_world(world)  # fires reset callbacks if !=
                first_round = False
                state.commit()  # the agreed state is the rollback point
                log.info("round %d: rank %d of %d (%s)", round_id, rank,
                         world, ",".join(members))
                train_fn(state, ElasticContext(
                    rank, world, round_id, data_coll, monitor,
                    mesh=mesh, data_plane=data_plane))
                coll.barrier()  # all ranks finish before anyone leaves
                if ici is not None:
                    # the distributed world dies with the run; hand the
                    # caller host-resident state (documented contract;
                    # typed PRNG keys survive via host_snapshot, exactly
                    # as on the failure path).  finalize = disconnect,
                    # barrier, reap service procs.
                    _, restore = host_snapshot(state.state)
                    try:
                        state.state = None
                        ici.finalize(rank, coll.barrier)
                    finally:
                        # restore even when finalize raises (a peer dying
                        # at the final barrier) — the recovery handlers
                        # must never see a None state tree
                        state.state = restore()
                coll.close()  # stop async worker/prefetcher threads
                return state
            except WorldChanged as e:
                obs.counter("elastic/world_changed").inc()
                obs.recorder.record("world_changed", round=round_id,
                                    old=world, new=e.new_world_size)
                rounds += 1
                if rounds > max_rounds:
                    raise
                log.warning(
                    "round %d: world %d -> %d; rolling back to commit #%d "
                    "(epoch %d, batch %d)", round_id, world,
                    e.new_world_size, state.commits,
                    state._committed_host.epoch, state._committed_host.batch)
                mesh = None  # the Mesh itself pins the dead world's client
                round_id, min_world = recover(e, e.new_world_size)
                data_coll = coll
            except Exception as e:
                # PeerLost: a host-plane wait deadline fired before the
                # TTL did.  On the ICI plane the same event surfaces as a
                # failed compiled collective ("Gloo all-reduce failed:
                # Connection reset by peer") or a FormationTimeout —
                # every one of them is a membership change at the
                # currently-live size; anything else is a real bug and
                # propagates.
                peerish = isinstance(e, PeerLost)
                if not peerish and ici is not None:
                    from tpudist.runtime.ici import (
                        FormationTimeout, is_collective_failure,
                    )

                    peerish = (isinstance(e, FormationTimeout)
                               or is_collective_failure(e))
                if not peerish:
                    raise
                obs.counter("elastic/peer_lost").inc()
                obs.recorder.record("peer_lost", round=round_id,
                                    error=str(e)[:200])
                rounds += 1
                if rounds > max_rounds:
                    raise
                # A failed collective says SOMETHING changed, not what: a
                # connection-reset arrives within milliseconds of a peer's
                # death, before its TTL lease expires, so an immediate
                # live() sample would still count the corpse and this
                # member's reset callbacks would fire with a stale world.
                # Poll until the lease drops or one TTL passes (transient
                # failures with nobody dead exit at the deadline with the
                # unchanged size and simply re-form).
                deadline = time.monotonic() + ttl_s + heartbeat_interval_s
                live = len(client.live())
                while live >= world and time.monotonic() < deadline:
                    time.sleep(heartbeat_interval_s / 2)
                    live = len(client.live())
                log.warning("round %d: %s; re-rendezvous at %d", round_id,
                            e, live)
                mesh = None  # the Mesh itself pins the dead world's client
                round_id, min_world = recover(e, live)
                data_coll = coll
    except BaseException as e:
        # the flight-recorder contract: an exception that escapes the
        # elastic supervision (max_rounds exhausted, a real bug) dumps a
        # post-mortem bundle before propagating; a failing dump must
        # never mask the original exception
        try:
            path = obs.recorder.dump(exc=e, context={
                "component": "elastic_worker", "worker": wid,
                "round": round_id, "rounds_survived": rounds})
            log.error("elastic worker crashed (%s: %s); post-mortem "
                      "bundle: %s", type(e).__name__, str(e)[:200], path)
        except Exception:  # noqa: BLE001
            pass
        raise
    finally:
        if ici is not None:
            try:
                ici.teardown()  # idempotent; frees the distributed world
            except Exception:  # noqa: BLE001 - teardown must not mask the cause
                pass
        monitor.stop(graceful=True)
