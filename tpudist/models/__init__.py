"""Model zoo (flax.linen).

One shared zoo replaces the reference's copy-pasted model definitions
(SURVEY.md §2.1 duplication note): MLP (`mnist_ddp_elastic.py:133-159`),
LeNet-style ConvNet (`mnist_horovod.py:9-25` ≡ `horovod_mnist_elastic.py:
16-32`), two-stage ResNet50 (`model_parallel_ResNet50.py:43-139`), and the
EmbeddingBag+Linear hybrid (`server_model_data_parallel.py:34-46`).
"""

from tpudist.models.beam import beam_search_generate
from tpudist.models.convnet import ConvNet
from tpudist.models.embedding import EmbeddingBagClassifier
from tpudist.models.generate import (
    greedy_generate,
    sample_generate,
    sp_generate,
    tp_generate,
    tp_sp_generate,
)
from tpudist.models.kv_pages import BlockPool, blocks_for
from tpudist.models.mlp import MLP
from tpudist.models.speculative import (
    AdaptiveDraftPolicy,
    adaptive_speculative_generate,
    sp_speculative_generate,
    speculative_generate,
    tp_sp_speculative_generate,
    tp_speculative_generate,
)
from tpudist.models.moe import MoEConfig, MoEMLP, MoETransformerLM
from tpudist.models.resnet import ResNet50, resnet50_stages
from tpudist.models.serving import Completion, Request, ServeLoop
from tpudist.models.transformer import (
    TransformerConfig,
    TransformerLM,
    repeat_kv,
    sdpa,
    stack_layer_params,
    unstack_layer_params,
)

__all__ = [
    "AdaptiveDraftPolicy",
    "BlockPool",
    "blocks_for",
    "Completion",
    "ConvNet",
    "Request",
    "ServeLoop",
    "adaptive_speculative_generate",
    "beam_search_generate",
    "EmbeddingBagClassifier",
    "MLP",
    "MoEConfig",
    "MoEMLP",
    "MoETransformerLM",
    "ResNet50",
    "TransformerConfig",
    "TransformerLM",
    "greedy_generate",
    "sample_generate",
    "sp_generate",
    "sp_speculative_generate",
    "speculative_generate",
    "tp_generate",
    "tp_sp_generate",
    "tp_sp_speculative_generate",
    "tp_speculative_generate",
    "resnet50_stages",
    "sdpa",
    "stack_layer_params",
    "unstack_layer_params",
]
