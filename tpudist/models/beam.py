"""Beam-search decoding — the deterministic search rollout.

Completes the decode-strategy family (`generate.py`: greedy, sampled;
`speculative.py`: draft/verify): width-W beam search as ONE compiled
``lax.scan``, TPU-shaped like everything else in the serving story:

* beams fold into the batch dimension — the model runs on ``[B·W]``
  rows, so the whole search is the same cached decode program greedy
  uses, W× wider;
* each step expands every live beam over the vocab, takes the top-W of
  ``[B, W·V]`` joint scores, and GATHERS the KV cache rows to the
  winning parents (``take_along_axis`` over the folded batch dim — the
  standard TPU/t5x-style cache reindex; traffic = one cache copy per
  step, the price of exact search with static shapes);
* EOS beams freeze: a finished beam contributes exactly one child (its
  own continuation via ``pad_token`` at unchanged score) so it competes
  with live beams but stops growing — no dynamic shapes anywhere.

Reference scope note: the reference suite is training-only (SURVEY.md
§2); this module extends the serving surface tpudist adds beyond parity.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from tpudist.models.generate import (
    _blank_cache,
    _is_stop,
    _prefill,
    _stop_array,
    sequence_lengths,
)
from tpudist.models.transformer import TransformerConfig, TransformerLM

_NEG_INF = -1.0e9


def beam_search_generate(
    cfg: TransformerConfig,
    params: Any,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    *,
    beam_size: int = 4,
    length_penalty: float = 0.0,
    decode_attention: str = "dense",
    prefill_chunk: int | None = None,
    stop_tokens: Sequence[int] | None = None,
    pad_token: int = 0,
    return_scores: bool = False,
    auto_unstack: bool = True,
):
    """Beam-search ``max_new_tokens`` past ``prompt``.

    Args:
      beam_size: beams kept per batch row (W).
      length_penalty: GNMT-style ``((5 + len) / 6) ** alpha`` score
        normalization applied at the FINAL ranking (0 = rank by raw
        log-probability).  Only meaningful with ``stop_tokens`` (without
        EOS every hypothesis has the same length).
      stop_tokens: EOS set; a beam that emits one freezes (its later
        positions are ``pad_token`` and its score stops accumulating).
      return_scores: also return the per-beam log-probabilities.

    Returns ``[B, W, prompt_len + max_new_tokens]`` int32 hypotheses
    sorted best-first per batch row (beam 0 is the argmax of the
    length-normalized score), plus ``[B, W]`` raw log-prob scores when
    ``return_scores`` is set.  With ``stop_tokens`` the return becomes
    ``(tokens, lengths[, scores])`` as elsewhere.
    """
    if auto_unstack:
        from tpudist.models.generate import serving_layout

        cfg, params = serving_layout(cfg, params)
    if beam_size < 1:
        raise ValueError(f"beam_size must be >= 1, got {beam_size}")
    if beam_size > cfg.vocab_size:
        raise ValueError(
            f"beam_size {beam_size} exceeds vocab_size {cfg.vocab_size} "
            "(top-k over the next-token distribution cannot seed more "
            "beams than there are tokens)")
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}")
    b, prompt_len = prompt.shape
    if prompt_len < 1:
        raise ValueError("prompt must hold at least one token")
    w = beam_size
    stop_arr = _stop_array(stop_tokens)
    total = prompt_len + max_new_tokens
    if total > cfg.max_seq_len:
        raise ValueError(
            f"prompt_len + max_new_tokens = {total} exceeds "
            f"max_seq_len {cfg.max_seq_len}")
    v = cfg.vocab_size

    model = TransformerLM(cfg, decode=True, decode_attention=decode_attention)
    # Prefill ONCE on the [B] batch (every beam shares the prompt), then
    # tile each K/V leaf W× along its folded-batch axis — byte-identical
    # to prefilling [B·W] rows at 1/W the compute and peak memory
    cache, logits = _prefill(
        model, params, _blank_cache(model, b), prompt, prefill_chunk)
    cache = jax.tree.map(
        lambda leaf: (jnp.repeat(leaf, w, axis=leaf.ndim - 3)
                      if leaf.ndim >= 3 else leaf), cache)
    logp0 = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32))  # [B, V]

    # first expansion: top-W tokens of the prompt's next-token dist seed
    # the beams (all beams were identical until here)
    scores, first = lax.top_k(logp0, w)                    # [B, W] each
    done = (_is_stop(first, stop_arr) if stop_arr is not None
            else jnp.zeros((b, w), bool))

    out0 = jnp.full((b, w, max_new_tokens), pad_token, jnp.int32)
    out0 = out0.at[:, :, 0].set(first)

    def step(carry, t):
        cache, prev, scores, done, out = carry
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            prev.reshape(b * w)[:, None],
            positions=jnp.full((b * w, 1), prompt_len + t - 1, jnp.int32),
            mutable=["cache"])
        cache = mut["cache"]
        logp = jax.nn.log_softmax(
            logits[:, -1].astype(jnp.float32)).reshape(b, w, v)
        # live beams expand over the vocab; finished beams contribute ONE
        # child: themselves continued by pad_token at unchanged score
        cand = scores[:, :, None] + logp                   # [B, W, V]
        frozen = jnp.full((b, w, v), _NEG_INF
                          ).at[:, :, pad_token].set(0.0) + scores[:, :, None]
        cand = jnp.where(done[:, :, None], frozen, cand)
        scores, flat_idx = lax.top_k(cand.reshape(b, w * v), w)  # [B, W]
        parent = flat_idx // v                              # [B, W]
        token = (flat_idx % v).astype(jnp.int32)            # [B, W]

        # reindex every per-beam buffer to the winning parents.  K/V
        # leaves are PACKED [·, S, Hkv*D] and carry the folded batch on
        # axis 0 unrolled ([B·W, S, F]) and axis 1 under scan_layers
        # ([L, B·W, S, F]) — i.e. always axis ndim-3; cache_index
        # scalars are beam-uniform and skip the gather.
        gather = lambda x: jnp.take_along_axis(x, parent, axis=1)
        row = (jnp.arange(b)[:, None] * w + parent).reshape(-1)  # [B·W]
        cache = jax.tree.map(
            lambda leaf: (jnp.take(leaf, row, axis=leaf.ndim - 3)
                          if leaf.ndim >= 3 else leaf), cache)
        out = jnp.take_along_axis(
            out, parent[:, :, None], axis=1).at[:, :, t].set(
                jnp.where(gather(done), jnp.int32(pad_token), token))
        done = gather(done)
        if stop_arr is not None:
            done = done | _is_stop(token, stop_arr)
        return (cache, token, scores, done, out), None

    carry = (cache, first, scores, done, out0)
    if max_new_tokens > 1:
        carry, _ = lax.scan(step, carry,
                            jnp.arange(1, max_new_tokens))
    _, _, scores, done, out = carry

    generated = out                                        # [B, W, N]
    if stop_arr is not None:
        hit = _is_stop(generated, stop_arr)
        after = (jnp.cumsum(hit, axis=-1) - hit) > 0
        generated = jnp.where(after, jnp.int32(pad_token), generated)
        lengths = sequence_lengths(generated, stop_arr, prompt_len)
    else:
        lengths = jnp.full((b, w), total, jnp.int32)

    # final ranking: GNMT length normalization (on generated length)
    norm = ((5.0 + (lengths - prompt_len)) / 6.0) ** length_penalty
    order = jnp.argsort(-scores / norm, axis=1)            # [B, W]
    scores = jnp.take_along_axis(scores, order, axis=1)
    lengths = jnp.take_along_axis(lengths, order, axis=1)
    generated = jnp.take_along_axis(generated, order[:, :, None], axis=1)

    tokens = jnp.concatenate(
        [jnp.broadcast_to(prompt[:, None], (b, w, prompt_len)), generated],
        axis=-1)
    result = (tokens,)
    if stop_arr is not None:
        result += (lengths,)
    if return_scores:
        result += (scores,)
    return result[0] if len(result) == 1 else result

