"""LeNet-style ConvNet — twin of the Horovod examples' ``Net``
(`mnist_horovod.py:9-25`, duplicated at `horovod_mnist_elastic.py:16-32`):
conv(1→10, k5) + maxpool + relu, conv(10→20, k5) + dropout + maxpool + relu,
flatten(320) → fc(50) → dropout → fc(10).

TPU-first choices: NHWC layout (XLA's preferred conv layout on TPU),
channels widened optionally via ``width_mult`` to feed the MXU, returns
*logits* — log_softmax lives in the loss (`tpudist.ops.losses.nll_loss`
composes it), where XLA fuses it with the reduction.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class ConvNet(nn.Module):
    num_classes: int = 10
    width_mult: int = 1
    dropout_rate: float = 0.5
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        w = self.width_mult
        x = x.astype(self.compute_dtype)
        x = nn.Conv(10 * w, (5, 5), padding="VALID", dtype=self.compute_dtype)(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(x)
        x = nn.Conv(20 * w, (5, 5), padding="VALID", dtype=self.compute_dtype)(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(x)
        x = x.reshape(x.shape[0], -1)  # 4*4*20*w = 320*w, like the reference's 320
        x = nn.relu(nn.Dense(50 * w, dtype=self.compute_dtype)(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        logits = nn.Dense(self.num_classes, dtype=self.compute_dtype)(x)
        return logits.astype(jnp.float32)
