"""EmbeddingBag + Linear classifier — twin of ``HybridModel``
(`server_model_data_parallel.py:34-46`): an EmbeddingBag(100, 16, mode="sum")
lookup feeding a Linear(16, 8).

TPU-first encoding: the ragged (indices, offsets) pair becomes a static-shape
``[batch, max_len]`` index matrix + float mask (see
:func:`tpudist.data.synthetic.ragged_embedding_batches`); the bag-sum is a
mask-weighted gather-sum, which XLA lowers to one fused gather+reduce.

The embedding table is declared with its own parameter subtree ("embedding")
so the PS-hybrid strategy can shard it over the model axis while the dense
head replicates over the data axis (`tpudist.parallel.ps_hybrid`).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class EmbeddingBagClassifier(nn.Module):
    num_embeddings: int = 100
    embedding_dim: int = 16
    num_classes: int = 8
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, indices: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        table = self.param(
            "embedding",
            nn.initializers.normal(stddev=1.0),
            (self.num_embeddings, self.embedding_dim),
            jnp.float32,
        )
        # Bag-sum: gather rows then mask-weighted sum over the bag dimension.
        rows = jnp.take(table, indices, axis=0).astype(self.compute_dtype)
        bag = jnp.einsum("blh,bl->bh", rows, mask.astype(self.compute_dtype))
        logits = nn.Dense(self.num_classes, dtype=self.compute_dtype, name="fc")(bag)
        return logits.astype(jnp.float32)
