"""Autoregressive generation with a KV cache — the inference path.

The reference suite is training-only (SURVEY.md §2: no inference or serving
code anywhere); a complete framework needs a decode loop, so tpudist ships
one, TPU-idiomatic end to end: the whole autoregressive rollout is ONE
compiled program (``lax.scan`` over positions, fixed-shape cache buffers
updated with ``dynamic_update_slice``) — no per-token host round-trips, no
dynamic shapes.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from tpudist.models.transformer import (
    TransformerConfig,
    TransformerLM,
    unstack_layer_params,
)

# (logits [B, V], key) -> next token [B] int32
SelectFn = Callable[[jnp.ndarray, jax.Array], jnp.ndarray]


def serving_layout(cfg: TransformerConfig, params: Any,
                   ) -> tuple[TransformerConfig, Any]:
    """Normalize ``(cfg, params)`` to the UNROLLED layout for serving.

    ``scan_layers=True`` is the right layout for TRAINING (depth-
    independent compile size) but the wrong one for token-at-a-time
    decode: every step pays a per-layer dynamic-slice of the stacked
    cache (~4× slower at 8k context, BASELINE.md), and the sharded entry
    points' Megatron rules match per-layer kernel names.  Every serving
    entry point calls this, so a checkpoint trained scanned serves at
    unrolled speed with no manual conversion step: stacked ``blocks``
    params are unstacked (a few slices, free next to any rollout) and the
    config is flipped.  Already-unrolled inputs pass through untouched.
    """
    import dataclasses

    try:
        stacked = "blocks" in params
    except TypeError:  # non-mapping param containers pass through
        stacked = False
    if stacked:
        params = unstack_layer_params(params, cfg.num_layers)
    if cfg.scan_layers:
        cfg = dataclasses.replace(cfg, scan_layers=False)
    return cfg, params


def _stop_array(stop_tokens: Sequence[int] | None) -> jnp.ndarray | None:
    if stop_tokens is None:
        return None
    toks = tuple(int(t) for t in stop_tokens)
    if not toks:
        raise ValueError("stop_tokens must be non-empty when given")
    return jnp.asarray(toks, jnp.int32)


def _is_stop(tokens: jnp.ndarray, stop_arr: jnp.ndarray) -> jnp.ndarray:
    """Membership mask against the stop set, over the last axis appended:
    ``[...]`` int tokens -> ``[...]`` bool."""
    return jnp.any(tokens[..., None] == stop_arr, axis=-1)


def sequence_lengths(generated: jnp.ndarray, stop_arr: jnp.ndarray,
                     prompt_len: int) -> jnp.ndarray:
    """Per-sequence total lengths: prompt + generated up to and INCLUDING
    the first stop token (or all of ``generated`` if none fired).  The
    position axis is the LAST one (works for [B, N] rollouts and
    [B, W, N] beam hypotheses alike)."""
    hit = _is_stop(generated, stop_arr)
    strictly_after = jnp.cumsum(hit, axis=-1) - hit  # stops before position
    return prompt_len + jnp.sum(strictly_after == 0, axis=-1)


def apply_cache_constraint(cache, constraint):
    """Pin a blank cache's layout for sharded decoding: ``constraint``
    maps leaf -> sharding (or None to leave the leaf alone).  The ONE
    copy of the idiom every sharded rollout (plain, speculative) uses."""
    if constraint is None:
        return cache
    return jax.tree.map(
        lambda x: (x if constraint(x) is None
                   else lax.with_sharding_constraint(x, constraint(x))),
        cache)


def _blank_cache(model, batch: int):
    """Fresh zeroed KV cache for ``model`` (cache_index 0, empty slots);
    shapes via ``eval_shape`` — no FLOPs, no throwaway params."""
    struct = jax.eval_shape(
        model.init, jax.random.key(0), jnp.zeros((batch, 1), jnp.int32),
        positions=jnp.zeros((batch, 1), jnp.int32))["cache"]
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), struct)


def _prefill(model, params, cache, prompt: jnp.ndarray,
             prefill_chunk: int | None):
    """Ingest the prompt into the cache in chunks of ``prefill_chunk``
    tokens (None = one shot), each attending causally over everything
    cached so far.  Returns ``(cache, last-chunk logits)`` — the serving
    split's prompt half, shared by the plain and speculative rollouts."""
    prompt_len = prompt.shape[1]
    chunk = prompt_len if prefill_chunk is None else min(
        prefill_chunk, prompt_len)
    for lo in range(0, prompt_len, chunk):
        piece = prompt[:, lo:lo + chunk]
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, piece,
            positions=jnp.arange(lo, lo + piece.shape[1])[None, :],
            mutable=["cache"],
        )
        cache = mutated["cache"]
    return cache, logits


def _rollout(
    cfg: TransformerConfig,
    params: Any,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    select: SelectFn,
    key: jax.Array,
    decode_attention: str = "dense",
    cache_constraint=None,
    prefill_chunk: int | None = None,
    stop_tokens: Sequence[int] | None = None,
    pad_token: int = 0,
    decode_shard=None,
) -> jnp.ndarray | tuple[jnp.ndarray, jnp.ndarray]:
    """Shared KV-cached decode loop; ``select`` picks the next token from
    each step's last-position logits (argmax for greedy, a sampler
    otherwise).  ``cache_constraint`` (leaf -> sharding or None) pins the
    cache layout for sharded decoding (:func:`tp_generate`).

    ``stop_tokens`` enables EOS semantics under static shapes: once a
    sequence emits a stop token, every later emitted position is frozen to
    ``pad_token`` (the model still runs — SIMD lanes can't retire early in
    a ``lax.scan`` — but its selections are masked, so the output is
    deterministic past EOS).  The return becomes ``(tokens, lengths)``
    with ``lengths[b]`` = prompt + generated up to and including the stop.

    ``prefill_chunk`` bounds prefill memory: the prompt is ingested in
    chunks of that many tokens (each attending causally over everything
    cached so far) — with the dense cache attention the peak logits
    buffer is [B, H, chunk, S] instead of [B, H, prompt, S], which is what
    keeps long-context prefill feasible off the flash path (e.g. under
    GSPMD sharding, where the Pallas kernel cannot partition)."""
    b, prompt_len = prompt.shape
    stop_arr = _stop_array(stop_tokens)  # validate before any device work
    if prompt_len < 1:
        raise ValueError("prompt must hold at least one token")
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}")
    total = prompt_len + max_new_tokens
    if total > cfg.max_seq_len:
        raise ValueError(
            f"prompt_len + max_new_tokens = {total} exceeds "
            f"max_seq_len {cfg.max_seq_len}")
    model = TransformerLM(cfg, decode=True, decode_attention=decode_attention,
                          decode_shard=decode_shard)
    cache = apply_cache_constraint(_blank_cache(model, b), cache_constraint)
    keys = jax.random.split(key, max_new_tokens)

    # PREFILL: the prompt through batched forwards (the serving split — at
    # long context this is the difference between streaming the cache once
    # per prompt TOKEN and once per prompt) ...
    cache, logits = _prefill(model, params, cache, prompt, prefill_chunk)
    first = select(logits[:, -1], keys[0]).astype(jnp.int32)
    done0 = (_is_stop(first, stop_arr) if stop_arr is not None
             else jnp.zeros((b,), bool))

    # ... then DECODE one token a step.
    def step(carry, inputs):
        t, step_key = inputs
        cache, prev, done = carry
        logits, mutated = model.apply(
            {"params": params, "cache": cache},
            prev[:, None],
            positions=jnp.full((b, 1), prompt_len + t - 1, jnp.int32),
            mutable=["cache"],
        )
        nxt = select(logits[:, -1], step_key).astype(jnp.int32)
        if stop_arr is not None:
            nxt = jnp.where(done, jnp.int32(pad_token), nxt)
            done = done | _is_stop(nxt, stop_arr)
        return (mutated["cache"], nxt, done), prev

    if max_new_tokens > 1:
        # emits the token it consumes, so `toks` is [g0 .. g_{n-2}] and the
        # final carry holds g_{n-1}
        (_, last, _), toks = lax.scan(
            step, (cache, first, done0),
            (jnp.arange(1, max_new_tokens), keys[1:]))
        generated = jnp.concatenate([toks.T, last[:, None]], axis=1)
    else:
        generated = first[:, None]
    out = jnp.concatenate([prompt, generated], axis=1)
    if stop_arr is None:
        return out
    return out, sequence_lengths(generated, stop_arr, prompt_len)


def greedy_generate(
    cfg: TransformerConfig,
    params: Any,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    decode_attention: str = "dense",
    prefill_chunk: int | None = None,
    stop_tokens: Sequence[int] | None = None,
    pad_token: int = 0,
    auto_unstack: bool = True,
) -> jnp.ndarray | tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy-decode ``max_new_tokens`` past ``prompt``.

    Args:
      cfg: the model configuration the ``params`` were trained with.
      params: TransformerLM parameter pytree (trained with any attention
        implementation — the cache path recomputes attention itself).
      prompt: ``[batch, prompt_len]`` int32 tokens, ``prompt_len >= 1``.
      max_new_tokens: tokens to append.
      stop_tokens: optional EOS set; positions past a sequence's first
        stop token freeze to ``pad_token`` and per-sequence lengths are
        returned alongside the tokens.
      auto_unstack: serve scanned-trained checkpoints through the
        unrolled layout (:func:`serving_layout` — ~4× faster decode).
        Pass False to decode through the stacked layout itself (the
        depth-independent-compile-size trade).

    Returns:
      ``[batch, prompt_len + max_new_tokens]`` int32: prompt + greedy
      continuation (plus ``[batch]`` lengths when ``stop_tokens`` is
      given).  ``prompt_len + max_new_tokens`` must fit in
      ``cfg.max_seq_len``.
    """
    if auto_unstack:
        cfg, params = serving_layout(cfg, params)
    return _rollout(
        cfg, params, prompt, max_new_tokens,
        lambda logits, _key: jnp.argmax(logits, axis=-1),
        jax.random.key(0), decode_attention=decode_attention,
        prefill_chunk=prefill_chunk, stop_tokens=stop_tokens,
        pad_token=pad_token)


def _sharded_generate(cfg, params, prompt, max_new_tokens, mesh, *,
                      cache_spec, decode_shard, decode_attention,
                      prefill_chunk, key, temperature, top_k, top_p,
                      stop_tokens, pad_token):
    """Common tail of the sharded decode entry points (tp / sp / tp_sp):
    a jitted :func:`_rollout` under the mesh, with the 4-D cache buffers
    pinned to ``cache_spec`` and scalars replicated, optionally routing
    the attention through per-shard kernel islands (``decode_shard``).
    Kept in ONE place so the key default, stop-token plumbing, and
    sampling selector can never drift between the three layouts."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    # (cfg, params) arrive NORMALIZED: every public sharded entry point
    # runs serving_layout before computing its shardings — sharded
    # serving requires the unrolled layout (the TP rules regex-match
    # per-layer kernel names and the 5-D stacked cache would escape the
    # cache-sharding constraint)

    def cache_constraint(leaf):
        if leaf.ndim == 3:  # PACKED [B, S, Hkv*D] K/V buffers
            return NamedSharding(mesh, cache_spec)
        return NamedSharding(mesh, P())  # cache_index scalars

    select = _make_select(temperature, top_k, top_p)

    def run(params, prompt):
        return _rollout(
            cfg, params, prompt, max_new_tokens, select,
            key if key is not None else jax.random.key(0),
            decode_attention=decode_attention,
            cache_constraint=cache_constraint,
            prefill_chunk=prefill_chunk, stop_tokens=stop_tokens,
            pad_token=pad_token, decode_shard=decode_shard)

    with mesh:
        return jax.jit(run)(params, prompt)


def tp_generate(
    cfg: TransformerConfig,
    params: Any,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    mesh,
    axis: str = "model",
    rules=None,
    decode_attention: str = "dense",
    prefill_chunk: int | None = 512,
    key: jax.Array | None = None,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    stop_tokens: Sequence[int] | None = None,
    pad_token: int = 0,
) -> jnp.ndarray | tuple[jnp.ndarray, jnp.ndarray]:
    """Tensor-parallel decode (greedy by default; ``temperature``/``top_k``
    / ``top_p`` + ``key`` select sampling): Megatron-layout params sharded over
    ``axis`` and the KV cache sharded over its HEADS dimension, so both
    weight and cache memory scale 1/tp per chip.  The whole rollout is one
    GSPMD program: qkv/up matmuls run column-sharded, the cache update and
    per-head attention stay head-local, and proj/down insert the pair
    all-reduces — no code change to the model, the shardings ARE the
    parallelism (same principle as
    :func:`tpudist.parallel.tensor_parallel.make_spmd_train_step`).

    Requires ``cfg.kv_heads % tp == 0`` (each shard owns whole KV heads).
    Returns the same tokens as :func:`greedy_generate`.
    """
    from jax.sharding import PartitionSpec as P

    from tpudist.parallel.tensor_parallel import (
        shard_tree,
        spec_tree_from_rules,
        transformer_tp_rules,
    )

    # normalize BEFORE the spec computation: the TP rules regex-match
    # per-layer kernel names, which a stacked checkpoint doesn't have
    cfg, params = serving_layout(cfg, params)
    tp = mesh.shape[axis]
    if cfg.kv_heads % tp:
        raise ValueError(
            f"kv_heads {cfg.kv_heads} not divisible by {axis!r} size {tp}")
    # decode_attention="flash" composes via shard_map: GSPMD cannot
    # partition a Pallas call, so the attention kernels run per-shard on
    # each shard's own (whole) KV-head groups inside a shard_map island —
    # the decode twin of the training-side ring_attention pattern
    # (VERDICT r2 #3; the old ValueError is gone).
    specs = spec_tree_from_rules(params, rules or transformer_tp_rules(axis))
    return _sharded_generate(
        cfg, shard_tree(params, mesh, specs), prompt, max_new_tokens, mesh,
        cache_spec=P(None, None, axis),
        decode_shard=((mesh, axis) if decode_attention == "flash"
                      else None),
        decode_attention=decode_attention, prefill_chunk=prefill_chunk,
        key=key, temperature=temperature, top_k=top_k, top_p=top_p,
        stop_tokens=stop_tokens, pad_token=pad_token)


def sp_generate(
    cfg: TransformerConfig,
    params: Any,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    mesh,
    axis: str = "seq",
    prefill_chunk: int | None = 512,
    key: jax.Array | None = None,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    stop_tokens: Sequence[int] | None = None,
    pad_token: int = 0,
    decode_attention: str = "dense",
) -> jnp.ndarray | tuple[jnp.ndarray, jnp.ndarray]:
    """Sequence-sharded-cache decode (greedy by default; the sampling
    controls mirror :func:`sample_generate`): the KV cache's SEQUENCE
    dimension is sharded over ``axis``, so per-chip cache memory is 1/n —
    the layout that serves contexts larger than one chip's HBM (the
    decode-side counterpart of ring attention).  Params stay replicated.

    ``decode_attention="dense"``: GSPMD partitions the cached attention
    into per-shard partial attention + softmax reductions over the
    sharded axis; measured HLO keeps the cache sharded end-to-end
    (all-reduces only — no cache all-gather, and the per-token
    ``dynamic_update_slice`` stays local to the owning shard).
    ``decode_attention="flash"``: per-token steps run
    :func:`tpudist.ops.flash_decode.sp_flash_decode` — each shard's
    flash kernel over its own cache slice, partial softmaxes merged by
    log-sum-exp (prefill stays on the dense partitioned path).  Returns
    the same tokens as :func:`greedy_generate`."""
    from jax.sharding import PartitionSpec as P

    cfg, params = serving_layout(cfg, params)
    if cfg.max_seq_len % mesh.shape[axis]:
        raise ValueError(
            f"max_seq_len {cfg.max_seq_len} not divisible by {axis!r} "
            f"size {mesh.shape[axis]}")
    return _sharded_generate(
        cfg, params, prompt, max_new_tokens, mesh,
        cache_spec=P(None, axis, None),
        decode_shard=((mesh, axis, "seq") if decode_attention == "flash"
                      else None),
        decode_attention=decode_attention, prefill_chunk=prefill_chunk,
        key=key, temperature=temperature, top_k=top_k, top_p=top_p,
        stop_tokens=stop_tokens, pad_token=pad_token)


def tp_sp_generate(
    cfg: TransformerConfig,
    params: Any,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    mesh,
    axis: str = "model",
    seq_axis: str = "seq",
    rules=None,
    decode_attention: str = "flash",
    prefill_chunk: int | None = 512,
    key: jax.Array | None = None,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    stop_tokens: Sequence[int] | None = None,
    pad_token: int = 0,
) -> jnp.ndarray | tuple[jnp.ndarray, jnp.ndarray]:
    """2-D sharded decode — the full distributed-serving layout: params
    Megatron-sharded over ``axis`` (weight memory 1/tp), the KV cache
    sharded over BOTH its head dim (``axis``) and its sequence dim
    (``seq_axis``), so per-chip cache memory is 1/(tp·sp) — contexts
    larger than any single chip's HBM with tensor-parallel weights.

    ``decode_attention="flash"`` (default): each shard runs the flash
    kernel on its own (head-group × cache-slice) block and the partial
    softmaxes merge by log-sum-exp over ``seq_axis`` only — heads need no
    collective.  ``"dense"`` leaves the partitioning to GSPMD.  Prefill
    runs on the dense partitioned path either way (queries must attend
    across every sequence shard).  Same tokens as
    :func:`greedy_generate`; sampling/stop controls as elsewhere."""
    from jax.sharding import PartitionSpec as P

    from tpudist.parallel.tensor_parallel import (
        shard_tree,
        spec_tree_from_rules,
        transformer_tp_rules,
    )

    cfg, params = serving_layout(cfg, params)  # TP rules need per-layer names
    tp, sp = mesh.shape[axis], mesh.shape[seq_axis]
    if cfg.kv_heads % tp:
        raise ValueError(
            f"kv_heads {cfg.kv_heads} not divisible by {axis!r} size {tp}")
    if cfg.max_seq_len % sp:
        raise ValueError(
            f"max_seq_len {cfg.max_seq_len} not divisible by "
            f"{seq_axis!r} size {sp}")
    specs = spec_tree_from_rules(params, rules or transformer_tp_rules(axis))
    return _sharded_generate(
        cfg, shard_tree(params, mesh, specs), prompt, max_new_tokens, mesh,
        cache_spec=P(None, seq_axis, axis),
        decode_shard=((mesh, (axis, seq_axis), "heads_seq")
                      if decode_attention == "flash" else None),
        decode_attention=decode_attention, prefill_chunk=prefill_chunk,
        key=key, temperature=temperature, top_k=top_k, top_p=top_p,
        stop_tokens=stop_tokens, pad_token=pad_token)


def top_k_filter(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Mask all but the k highest logits to -inf (last axis)."""
    if k >= logits.shape[-1]:
        return logits
    kth = lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, -jnp.inf, logits)


def top_p_filter(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """Mask to the nucleus: the smallest prefix of probability-sorted
    tokens whose cumulative probability reaches ``p`` (the argmax is
    always kept)."""
    if p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Keep every token whose PREDECESSORS sum below p; the cutoff is the
    # SMALLEST kept logit (min, not max — max would degenerate to greedy).
    keep_sorted = jnp.concatenate(
        [jnp.zeros_like(cum[..., :1]), cum[..., :-1]], -1) < p
    cutoff = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1,
        keepdims=True)
    return jnp.where(logits < cutoff, -jnp.inf, logits)


def sample_generate(
    cfg: TransformerConfig,
    params: Any,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    key: jax.Array,
    *,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    decode_attention: str = "dense",
    prefill_chunk: int | None = None,
    stop_tokens: Sequence[int] | None = None,
    pad_token: int = 0,
    auto_unstack: bool = True,
) -> jnp.ndarray | tuple[jnp.ndarray, jnp.ndarray]:
    """Sample ``max_new_tokens`` past ``prompt`` with the standard
    controls, all static-shape (one compiled rollout, like greedy):

    * ``temperature`` scales logits (0 → greedy argmax);
    * ``top_k`` keeps only the k highest-probability tokens;
    * ``top_p`` keeps the smallest nucleus whose cumulative probability
      reaches p (applied after top_k when both are set);
    * ``stop_tokens`` freezes a sequence at its first stop token (see
      :func:`greedy_generate`); returns ``(tokens, lengths)`` when set.

    ``auto_unstack``: as in :func:`greedy_generate` — scanned-trained
    checkpoints serve through the unrolled layout by default.
    """
    if auto_unstack:
        cfg, params = serving_layout(cfg, params)
    select = _make_select(temperature, top_k, top_p)
    return _rollout(cfg, params, prompt, max_new_tokens, select, key,
                    decode_attention=decode_attention,
                    prefill_chunk=prefill_chunk, stop_tokens=stop_tokens,
                    pad_token=pad_token)


def _filtered_logits(logits: jnp.ndarray, temperature: float,
                     top_k: Optional[int],
                     top_p: Optional[float]) -> jnp.ndarray:
    """The scale-then-top_k-then-top_p pipeline, in ONE place: both the
    rollout samplers (`_make_select`) and the speculative accept rule
    (`speculative._filtered_probs`) consume it — speculative sampling is
    distribution-exact only while the two see the SAME filtered
    categorical.  Requires ``temperature > 0``."""
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None:
        logits = top_k_filter(logits, top_k)
    if top_p is not None:
        logits = top_p_filter(logits, top_p)
    return logits


def _make_select(temperature: float, top_k: Optional[int],
                 top_p: Optional[float]) -> SelectFn:
    """Validated token-selection fn shared by the local and sharded
    rollouts (``temperature == 0`` reduces to greedy argmax)."""
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0 < top_p <= 1:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")

    def select(logits: jnp.ndarray, step_key: jax.Array) -> jnp.ndarray:
        if temperature == 0.0:
            return jnp.argmax(logits.astype(jnp.float32), axis=-1)
        return jax.random.categorical(
            step_key, _filtered_logits(logits, temperature, top_k, top_p),
            axis=-1)

    return select
