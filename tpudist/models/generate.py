"""Autoregressive generation with a KV cache — the inference path.

The reference suite is training-only (SURVEY.md §2: no inference or serving
code anywhere); a complete framework needs a decode loop, so tpudist ships
one, TPU-idiomatic end to end: the whole autoregressive rollout is ONE
compiled program (``lax.scan`` over positions, fixed-shape cache buffers
updated with ``dynamic_update_slice``) — no per-token host round-trips, no
dynamic shapes.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from tpudist.models.transformer import TransformerConfig, TransformerLM


def greedy_generate(
    cfg: TransformerConfig,
    params: Any,
    prompt: jnp.ndarray,
    max_new_tokens: int,
) -> jnp.ndarray:
    """Greedy-decode ``max_new_tokens`` past ``prompt``.

    Args:
      cfg: the model configuration the ``params`` were trained with.
      params: TransformerLM parameter pytree (trained with any attention
        implementation — the cache path recomputes attention itself).
      prompt: ``[batch, prompt_len]`` int32 tokens, ``prompt_len >= 1``.
      max_new_tokens: tokens to append.

    Returns:
      ``[batch, prompt_len + max_new_tokens]`` int32: prompt + greedy
      continuation.  ``prompt_len + max_new_tokens`` must fit in
      ``cfg.max_seq_len``.
    """
    b, prompt_len = prompt.shape
    total = prompt_len + max_new_tokens
    if total > cfg.max_seq_len:
        raise ValueError(
            f"prompt_len + max_new_tokens = {total} exceeds "
            f"max_seq_len {cfg.max_seq_len}")
    model = TransformerLM(cfg, decode=True)
    # Cache shapes via eval_shape (no FLOPs, no throwaway params), zeros =
    # a blank cache (cache_index 0, empty slots).
    cache_struct = jax.eval_shape(
        model.init, jax.random.key(0), jnp.zeros((b, 1), jnp.int32),
        positions=jnp.zeros((b, 1), jnp.int32))["cache"]
    cache = jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), cache_struct)
    # Prompt padded to the full rollout so the scan reads it with a dynamic
    # index; positions past the prompt take the previous step's argmax.
    prompt_pad = jnp.pad(prompt, ((0, 0), (0, max_new_tokens)))

    def step(carry, t):
        cache, prev = carry
        tok = jnp.where(t < prompt_len, prompt_pad[:, t], prev)
        logits, mutated = model.apply(
            {"params": params, "cache": cache},
            tok[:, None],
            positions=jnp.full((b, 1), t, jnp.int32),
            mutable=["cache"],
        )
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        return (mutated["cache"], nxt), tok

    (_, _), toks = lax.scan(
        step, (cache, jnp.zeros((b,), jnp.int32)), jnp.arange(total))
    return toks.T  # [total, B] -> [B, total]
