"""Paged KV cache: host-side block-pool allocator + page-table layout.

The dense serve cache allocates ``num_slots x max_seq_len`` KV positions
per layer no matter what the requests actually use — at 8k context that
full-context HBM bill per lane is what caps ``num_slots`` (and therefore
decode throughput).  The paged layout (PagedAttention, vLLM SOSP '23)
replaces the per-slot buffers with ONE device-resident block pool per
layer (``[num_blocks, block_size, Hkv*D]``) plus a per-slot PAGE TABLE
(``[num_slots, max_blocks_per_slot]`` int32 pool indices); a slot's
logical position ``p`` lives at ``pool[table[slot, p // block_size],
p % block_size]``.  Serve capacity then scales with the tokens requests
actually RESERVE (prompt + budget), not with ``num_slots x max_seq_len``.

This module is the HOST half: :class:`BlockPool` owns the free list and
the per-slot block lists, and renders the page table the compiled side
consumes.  Allocation policy (all host-side, O(blocks) bookkeeping — no
device syncs anywhere):

* **allocate-on-admit**: admission allocates blocks covering the prompt
  (the insert scatter writes exactly those) and RESERVES the rest of the
  request's worst-case footprint ``min(prompt + max_new_tokens,
  max_seq_len)`` — growth can then never fail mid-flight, which matters
  because the pipelined serve loop learns stop events a segment late and
  must keep growing blindly until the finalize lands;
* **grow-on-decode-boundary**: before each dispatched segment every live
  slot's coverage is advanced by ``steps_per_sync`` tokens (drawn from
  its reservation), so the per-segment side->pool merge always has pages
  under every position it can write;
* **free-on-finalize**: a finished request returns its blocks AND its
  unused reservation immediately — early stops refund capacity the
  moment the host learns of them.

Admission control: :meth:`can_admit` checks the request's FULL
reservation against unreserved free blocks and the serve loop queues the
request instead of OOMing the pool.  Reserving the worst case forgoes
optimistic over-commit (no preemption/swap machinery needed), yet keeps
the capacity win: a short-prompt / small-budget request holds a few
blocks, not a ``max_seq_len`` lane.

The device half lives in :mod:`tpudist.models.transformer`
(``CausalSelfAttention._paged_attend``) and
:func:`tpudist.ops.flash_decode.paged_flash_decode`.
"""

from __future__ import annotations

import numpy as np

from tpudist import obs


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks needed to cover ``tokens`` positions (ceil division)."""
    return -(-int(tokens) // block_size)


class BlockPool:
    """Host-side allocator for the paged KV cache.

    Args:
      num_blocks: pool capacity (the device buffers' leading dim).
      block_size: tokens per block; must be a positive multiple of 8
        (the paged kernel streams one block per grid step and Mosaic
        needs the 8-row sublane tile).
      num_slots: decode lanes (page-table rows).
      max_seq_len: model context; bounds ``max_blocks_per_slot``.

    The page table (:attr:`table`) is a ``[num_slots,
    max_blocks_per_slot]`` int32 array; rows are filled left-to-right
    with the slot's allocated blocks and UNALLOCATED entries hold 0 — a
    valid pool index, so the kernel's page-gather DMA always reads real
    memory (the per-row length mask is what protects correctness).
    """

    def __init__(self, num_blocks: int, block_size: int, num_slots: int,
                 max_seq_len: int) -> None:
        if block_size < 8 or block_size % 8:
            raise ValueError(
                f"block_size must be a positive multiple of 8, got "
                f"{block_size}")
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.num_slots = int(num_slots)
        self.max_blocks_per_slot = blocks_for(max_seq_len, block_size)
        self.max_seq_len = int(max_seq_len)
        # LIFO free list: recently freed (hot) blocks are reused first
        self._free: list[int] = list(range(self.num_blocks - 1, -1, -1))
        self._slot_blocks: list[list[int]] = [[] for _ in range(num_slots)]
        # per-slot tokens covered so far (the grow watermark) and the
        # reservation cap (min(prompt + max_new, max_seq_len))
        self._watermark = [0] * num_slots
        self._cap = [0] * num_slots
        self._reserved_total = 0  # blocks promised but not yet allocated
        self.table = np.zeros(
            (num_slots, self.max_blocks_per_slot), np.int32)
        self._obs_used = obs.gauge("serve/kv_blocks_used", unit="blocks")
        self._obs_free = obs.gauge("serve/kv_blocks_free", unit="blocks")
        self._obs_frag = obs.gauge("serve/kv_frag", unit="fraction")
        self._publish()

    # -- accounting --------------------------------------------------------

    @property
    def free_blocks(self) -> int:
        """Blocks neither allocated nor promised to a live reservation."""
        return len(self._free) - self._reserved_total

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - len(self._free)

    def _publish(self) -> None:
        used = self.used_blocks
        self._obs_used.set(used)
        self._obs_free.set(self.num_blocks - used)
        covered = sum(self._watermark)
        alloc_tokens = used * self.block_size
        # internal fragmentation of the allocated set: the fraction of
        # allocated token slots not under any slot's coverage watermark
        self._obs_frag.set(
            0.0 if not alloc_tokens else 1.0 - covered / alloc_tokens)

    def check(self) -> None:
        """Allocator invariants — cheap enough to run in tests every
        segment: no block on two live slots, no block both free and
        allocated, reservation arithmetic consistent."""
        live = [blk for blks in self._slot_blocks for blk in blks]
        if len(live) != len(set(live)):
            raise AssertionError("a block is referenced by two live slots")
        overlap = set(live) & set(self._free)
        if overlap:
            raise AssertionError(f"blocks both free and live: {overlap}")
        if len(live) + len(self._free) != self.num_blocks:
            raise AssertionError("leaked blocks: live + free != pool")
        if self._reserved_total < 0 or (
                self._reserved_total > len(self._free)):
            raise AssertionError(
                f"reservation {self._reserved_total} outside free list "
                f"{len(self._free)}")

    # -- allocation --------------------------------------------------------

    def request_blocks(self, prompt_len: int, max_new_tokens: int) -> int:
        """The full worst-case footprint of a request, in blocks."""
        total = min(prompt_len + max_new_tokens, self.max_seq_len)
        return blocks_for(total, self.block_size)

    def can_admit(self, prompt_len: int, max_new_tokens: int) -> bool:
        return (self.request_blocks(prompt_len, max_new_tokens)
                <= self.free_blocks)

    def admit(self, slot: int, prompt_len: int,
              max_new_tokens: int) -> None:
        """Allocate blocks covering the prompt and reserve the rest of
        the request's footprint.  Caller must have checked
        :meth:`can_admit` (raises ``RuntimeError`` otherwise)."""
        if self._slot_blocks[slot]:
            raise RuntimeError(f"slot {slot} still holds blocks; "
                               "free_slot it before re-admitting")
        total = self.request_blocks(prompt_len, max_new_tokens)
        now = blocks_for(prompt_len, self.block_size)
        if total > self.free_blocks:
            raise RuntimeError(
                f"admit of {total} blocks exceeds free {self.free_blocks}"
                " (call can_admit first)")
        self._cap[slot] = min(prompt_len + max_new_tokens,
                              self.max_seq_len)
        self._reserved_total += total - now
        self._grow_to(slot, now)
        self._watermark[slot] = prompt_len
        self._publish()

    def grow(self, slot: int, steps: int) -> None:
        """Advance ``slot``'s coverage by ``steps`` decode tokens (capped
        at its reservation), allocating from the reserved budget — this
        can never fail for an admitted slot."""
        target = min(self._watermark[slot] + steps, self._cap[slot])
        need = blocks_for(target, self.block_size)
        have = len(self._slot_blocks[slot])
        if need > have:
            self._reserved_total -= need - have
            self._grow_to(slot, need)
        self._watermark[slot] = target
        self._publish()

    def _grow_to(self, slot: int, count: int) -> None:
        blks = self._slot_blocks[slot]
        while len(blks) < count:
            blk = self._free.pop()
            self.table[slot, len(blks)] = blk
            blks.append(blk)

    def free_slot(self, slot: int) -> None:
        """Return ``slot``'s blocks and its unused reservation to the
        pool (free-on-finalize: the capacity is reusable immediately)."""
        blks = self._slot_blocks[slot]
        held = blocks_for(self._cap[slot], self.block_size) if blks else 0
        self._reserved_total -= max(held - len(blks), 0)
        self._free.extend(reversed(blks))
        blks.clear()
        self.table[slot, :] = 0
        self._watermark[slot] = 0
        self._cap[slot] = 0
        self._publish()
