"""Paged KV cache: host-side block-pool allocator + page-table layout.

The dense serve cache allocates ``num_slots x max_seq_len`` KV positions
per layer no matter what the requests actually use — at 8k context that
full-context HBM bill per lane is what caps ``num_slots`` (and therefore
decode throughput).  The paged layout (PagedAttention, vLLM SOSP '23)
replaces the per-slot buffers with ONE device-resident block pool per
layer (``[num_blocks, block_size, Hkv*D]``) plus a per-slot PAGE TABLE
(``[num_slots, max_blocks_per_slot]`` int32 pool indices); a slot's
logical position ``p`` lives at ``pool[table[slot, p // block_size],
p % block_size]``.  Serve capacity then scales with the tokens requests
actually RESERVE (prompt + budget), not with ``num_slots x max_seq_len``.

This module is the HOST half: :class:`BlockPool` owns the free list and
the per-slot block lists, and renders the page table the compiled side
consumes.  Allocation policy (all host-side, O(blocks) bookkeeping — no
device syncs anywhere):

* **allocate-on-admit**: admission allocates blocks covering the prompt
  (the insert scatter writes exactly those) and RESERVES the rest of the
  request's worst-case footprint ``min(prompt + max_new_tokens,
  max_seq_len)`` — growth can then never fail mid-flight, which matters
  because the pipelined serve loop learns stop events a segment late and
  must keep growing blindly until the finalize lands;
* **grow-on-decode-boundary**: before each dispatched segment every live
  slot's coverage is advanced by ``steps_per_sync`` tokens (drawn from
  its reservation), so the per-segment side->pool merge always has pages
  under every position it can write;
* **free-on-finalize**: a finished request returns its blocks AND its
  unused reservation immediately — early stops refund capacity the
  moment the host learns of them.

Admission control: :meth:`can_admit` checks the request's FULL
reservation against unreserved free blocks and the serve loop queues the
request instead of OOMing the pool.  Reserving the worst case forgoes
optimistic over-commit (no preemption/swap machinery needed), yet keeps
the capacity win: a short-prompt / small-budget request holds a few
blocks, not a ``max_seq_len`` lane.

Prefix sharing (PR 14) adds copy-on-write block aliasing on top:

* every block carries a REFCOUNT (number of slot references); a freed
  slot decrements instead of freeing, and a block returns to the free
  list only when its refcount hits zero and the prefix cache does not
  pin it;
* :meth:`share` aliases an existing block run into a fresh slot's
  leading positions (the shared prefix is strictly read-only for that
  slot — decode and suffix-prefill writes land past it);
* :meth:`cow_write` splits the one legal write into a shared region —
  the LAST shared block, written when a full-prompt cache hit must
  recompute its final position to produce the first output logit — by
  moving the slot onto a private copy (``serve/cow_splits``);
* :class:`PrefixCache` maps rolling token-hash chains (one blake2b
  chain link per full block, so a hash names the block's content AND
  everything before it) to pool blocks, pinning them so idle prefixes
  survive ``free_slot``; eviction is LRU over refcount-0 entries only
  and runs on demand when the free list is empty.

Cached-but-idle blocks (pinned, refcount 0) are RECLAIMABLE capacity:
:attr:`free_blocks` and the ``serve/kv_blocks_free`` gauge count them,
``serve/kv_frag`` measures fragmentation over live (slot-referenced)
blocks only, and :attr:`used_blocks` excludes them — so admission
control, the drain check, and the autoscaler all see truthful pressure.

The device half lives in :mod:`tpudist.models.transformer`
(``CausalSelfAttention._paged_attend``) and
:func:`tpudist.ops.flash_decode.paged_flash_decode`.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from collections.abc import Callable, Sequence

import numpy as np

from tpudist import obs


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks needed to cover ``tokens`` positions (ceil division)."""
    return -(-int(tokens) // block_size)


def _hash_bytes(data: bytes) -> int:
    return int.from_bytes(
        hashlib.blake2b(data, digest_size=8).digest(), "big")


def chain_hashes(tokens: Sequence[int], block_size: int) -> list[int]:
    """Rolling hash chain over ``tokens``, one link per FULL block.

    Link ``j`` hashes block ``j``'s tokens together with link ``j-1``,
    so it names the block's content AND the entire prefix before it —
    two prompts share link ``j`` iff their first ``(j+1)*block_size``
    tokens are identical.  blake2b over the int32 byte encoding keeps
    the chain deterministic across processes (router, replicas, and the
    offline simulator must agree)."""
    toks = np.asarray(tokens, np.int32)
    out: list[int] = []
    prev = b""
    for j in range(len(toks) // block_size):
        prev = hashlib.blake2b(
            prev + toks[j * block_size:(j + 1) * block_size].tobytes(),
            digest_size=8).digest()
        out.append(int.from_bytes(prev, "big"))
    return out


def request_prefix_hash(tokens: Sequence[int]) -> int:
    """Order-64-bit hash of a token span, for wire-level prefix affinity.

    Clients stamp ``Request.prefix_hash`` with this over the shared
    prefix they know about (e.g. a tenant's system prompt); replicas
    publish the hashes they recently admitted; the router steers
    matching requests to a replica that already holds the prefix.  The
    hash is opaque end to end — nothing needs to agree on block sizes."""
    return _hash_bytes(np.asarray(tokens, np.int32).tobytes())


class BlockPool:
    """Host-side allocator for the paged KV cache.

    Args:
      num_blocks: pool capacity (the device buffers' leading dim).
      block_size: tokens per block; must be a positive multiple of 8
        (the paged kernel streams one block per grid step and Mosaic
        needs the 8-row sublane tile).
      num_slots: decode lanes (page-table rows).
      max_seq_len: model context; bounds ``max_blocks_per_slot``.

    The page table (:attr:`table`) is a ``[num_slots,
    max_blocks_per_slot]`` int32 array; rows are filled left-to-right
    with the slot's allocated blocks and UNALLOCATED entries hold 0 — a
    valid pool index, so the kernel's page-gather DMA always reads real
    memory (the per-row length mask is what protects correctness).
    """

    def __init__(self, num_blocks: int, block_size: int, num_slots: int,
                 max_seq_len: int) -> None:
        if block_size < 8 or block_size % 8:
            raise ValueError(
                f"block_size must be a positive multiple of 8, got "
                f"{block_size}")
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.num_slots = int(num_slots)
        self.max_blocks_per_slot = blocks_for(max_seq_len, block_size)
        self.max_seq_len = int(max_seq_len)
        # LIFO free list: recently freed (hot) blocks are reused first
        self._free: list[int] = list(range(self.num_blocks - 1, -1, -1))
        self._slot_blocks: list[list[int]] = [[] for _ in range(num_slots)]
        # per-slot tokens covered so far (the grow watermark) and the
        # reservation cap (min(prompt + max_new, max_seq_len))
        self._watermark = [0] * num_slots
        self._cap = [0] * num_slots
        self._reserved_total = 0  # blocks promised but not yet allocated
        # COW bookkeeping: per-block slot-reference counts, the set of
        # blocks pinned by the prefix cache, and per-slot count of
        # leading blocks that are SHARED (read-only for that slot)
        self._refcount = [0] * self.num_blocks
        self._pinned: set[int] = set()
        self._shared_upto = [0] * num_slots
        self._prompt_len = [0] * num_slots
        # set by PrefixCache: frees >=1 refcount-0 cached block on
        # demand; lets reservations count evictable blocks as capacity
        self._evict_hook: Callable[[], bool] | None = None
        # slots whose KV is mid-migration to another pool: slot -> the
        # frozen block list snapshotted at export_slot().  Until the ack
        # (complete_export) or abort lands, the slot may not be freed,
        # grown, or COW-split — the exporter is still reading the pages.
        self._migrating: dict[int, list[int]] = {}
        self.table = np.zeros(
            (num_slots, self.max_blocks_per_slot), np.int32)
        self._obs_used = obs.gauge("serve/kv_blocks_used", unit="blocks")
        self._obs_free = obs.gauge("serve/kv_blocks_free", unit="blocks")
        self._obs_frag = obs.gauge("serve/kv_frag", unit="fraction")
        self._obs_cow = obs.counter("serve/cow_splits", unit="blocks")
        self._publish()

    # -- accounting --------------------------------------------------------

    def _evictable(self) -> int:
        """Cached-but-idle blocks: pinned by the prefix cache, referenced
        by no slot — reclaimable on demand via the eviction hook."""
        return sum(1 for b in self._pinned if self._refcount[b] == 0)

    @property
    def free_blocks(self) -> int:
        """Blocks neither live nor promised to a reservation.  Counts
        cached-but-idle blocks: they are evicted on demand, so they ARE
        capacity — hiding them would starve admission behind a cache."""
        return len(self._free) + self._evictable() - self._reserved_total

    @property
    def used_blocks(self) -> int:
        """Blocks holding live, non-reclaimable data.  Cached-but-idle
        blocks are excluded: a drained pool with a warm prefix cache is
        still drained."""
        return self.num_blocks - len(self._free) - self._evictable()

    def _publish(self) -> None:
        evictable = self._evictable()
        used = self.num_blocks - len(self._free) - evictable
        self._obs_used.set(used)
        self._obs_free.set(len(self._free) + evictable)
        live = {b for blks in self._slot_blocks for b in blks}
        covered = sum(self._watermark)
        alloc_tokens = len(live) * self.block_size
        # internal fragmentation of the LIVE set: the fraction of live
        # token slots not under any slot's coverage watermark.  Shared
        # blocks are counted once but covered by several watermarks, so
        # the ratio is clamped — sharing is the opposite of waste.
        frag = 0.0 if not alloc_tokens else 1.0 - covered / alloc_tokens
        self._obs_frag.set(min(1.0, max(0.0, frag)))

    def check(self) -> None:
        """Allocator invariants — cheap enough to run in tests every
        segment: refcounts match slot references, nothing both free and
        referenced/pinned, shared blocks only ever aliased read-only,
        reservation arithmetic consistent."""
        counts = [0] * self.num_blocks
        for slot, blks in enumerate(self._slot_blocks):
            if len(blks) != len(set(blks)):
                raise AssertionError(
                    f"slot {slot} references a block twice: {blks}")
            for blk in blks:
                counts[blk] += 1
        if counts != self._refcount:
            bad = [b for b in range(self.num_blocks)
                   if counts[b] != self._refcount[b]]
            raise AssertionError(
                f"refcount drift on blocks {bad}: "
                f"counted {[counts[b] for b in bad]}, "
                f"recorded {[self._refcount[b] for b in bad]}")
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("duplicate blocks on the free list")
        bad = [b for b in free if counts[b] or b in self._pinned]
        if bad:
            raise AssertionError(
                f"blocks both free and referenced/pinned: {bad}")
        live = {b for b in range(self.num_blocks) if counts[b]}
        idle_cached = {b for b in self._pinned if not counts[b]}
        if len(live) + len(idle_cached) + len(free) != self.num_blocks:
            raise AssertionError(
                "leaked blocks: live + cached-idle + free != pool")
        for slot, blks in enumerate(self._slot_blocks):
            for j, blk in enumerate(blks):
                # a slot writes block j only past its shared boundary
                # AND past its prompt (suffix prefill at admission,
                # decode appends after) — any aliased or pinned block
                # in that writable region is a latent corruption
                writable = (j >= self._shared_upto[slot]
                            and (j + 1) * self.block_size
                            > self._prompt_len[slot])
                if writable and counts[blk] > 1:
                    raise AssertionError(
                        f"block {blk} aliased by {counts[blk]} slots but "
                        f"writable from slot {slot} (index {j}, shared "
                        f"boundary {self._shared_upto[slot]}, prompt "
                        f"{self._prompt_len[slot]})")
                if writable and blk in self._pinned:
                    raise AssertionError(
                        f"pinned block {blk} in slot {slot}'s writable "
                        "region — decode writes would corrupt the cache")
        if self._reserved_total < 0 or self._reserved_total > (
                len(self._free) + len(idle_cached)):
            raise AssertionError(
                f"reservation {self._reserved_total} outside reclaimable "
                f"capacity {len(self._free)} + {len(idle_cached)}")
        for slot, snapshot in self._migrating.items():
            if self._slot_blocks[slot] != snapshot:
                raise AssertionError(
                    f"slot {slot} mutated mid-migration: exported "
                    f"{snapshot}, now holds {self._slot_blocks[slot]}")
            bad = [b for b in snapshot if b in free]
            if bad:
                raise AssertionError(
                    f"in-migration blocks of slot {slot} on the free "
                    f"list: {bad}")
            bad = [b for b in snapshot if self._refcount[b] < 1]
            if bad:
                raise AssertionError(
                    f"in-migration blocks of slot {slot} unreferenced: "
                    f"{bad}")

    # -- allocation --------------------------------------------------------

    def request_blocks(self, prompt_len: int, max_new_tokens: int) -> int:
        """The full worst-case footprint of a request, in blocks."""
        total = min(prompt_len + max_new_tokens, self.max_seq_len)
        return blocks_for(total, self.block_size)

    def can_admit(self, prompt_len: int, max_new_tokens: int,
                  shared: int = 0, cow: int = 0) -> bool:
        """``shared`` blocks arrive by aliasing (no allocation); ``cow``
        is the extra private block a copy-on-write split will draw
        immediately after admit (full-prompt cache hits)."""
        return (self.request_blocks(prompt_len, max_new_tokens)
                - shared + cow <= self.free_blocks)

    def _take_block(self) -> int:
        if not self._free and not (
                self._evict_hook is not None and self._evict_hook()):
            raise RuntimeError("block pool exhausted")
        return self._free.pop()

    def admit(self, slot: int, prompt_len: int, max_new_tokens: int,
              shared: Sequence[int] = ()) -> None:
        """Allocate blocks covering the prompt and reserve the rest of
        the request's footprint.  ``shared`` aliases existing blocks
        (refcount++) under the slot's leading positions instead of
        allocating them.  Caller must have checked :meth:`can_admit`
        with the same ``shared`` count (raises ``RuntimeError``
        otherwise)."""
        if self._slot_blocks[slot]:
            raise RuntimeError(f"slot {slot} still holds blocks; "
                               "free_slot it before re-admitting")
        total = self.request_blocks(prompt_len, max_new_tokens)
        now = blocks_for(prompt_len, self.block_size)
        if len(shared) > now:
            raise ValueError(
                f"{len(shared)} shared blocks exceed the prompt's "
                f"{now}-block footprint")
        if total - len(shared) > self.free_blocks:
            raise RuntimeError(
                f"admit of {total - len(shared)} blocks exceeds free "
                f"{self.free_blocks} (call can_admit first)")
        self._cap[slot] = min(prompt_len + max_new_tokens,
                              self.max_seq_len)
        self._reserved_total += total - now
        if shared:
            self.share(slot, shared)
        self._grow_to(slot, now)
        self._watermark[slot] = prompt_len
        self._prompt_len[slot] = prompt_len
        self._publish()

    def share(self, slot: int, blocks: Sequence[int]) -> None:
        """Alias ``blocks`` under ``slot``'s leading positions
        (refcount++ each).  The slot must be empty — a shared prefix is
        by construction the FIRST thing in a sequence — and treats the
        aliased run as read-only: the only legal write into it is the
        :meth:`cow_write` split of its final block."""
        blks = self._slot_blocks[slot]
        if blks:
            raise RuntimeError(
                f"share() into non-empty slot {slot}: a shared prefix "
                "must precede any private blocks")
        for blk in blocks:
            self._refcount[blk] += 1
            self.table[slot, len(blks)] = blk
            blks.append(blk)
        self._shared_upto[slot] = len(blks)

    def cow_write(self, slot: int, block_idx: int) -> int:
        """Make ``slot``'s block at ``block_idx`` privately writable,
        splitting (new private block, old refcount--) if it is aliased
        or pinned.  Only the LAST shared block is a legal target: that
        is the one block the serving protocol ever writes inside a
        shared region (a full-prompt hit recomputing its final position
        for the first output logit).  Returns the block now under the
        slot — the caller re-inserts that block's content from its
        recomputed dense cache, which IS the copy."""
        if slot in self._migrating:
            raise RuntimeError(
                f"cow_write on slot {slot} while its KV is in migration")
        blks = self._slot_blocks[slot]
        old = blks[block_idx]
        if self._refcount[old] == 1 and old not in self._pinned:
            return old  # already private — write in place
        if block_idx != self._shared_upto[slot] - 1:
            raise RuntimeError(
                f"cow_write at index {block_idx} of slot {slot}, but only "
                f"the last shared block "
                f"({self._shared_upto[slot] - 1}) is writable")
        new = self._take_block()
        self._refcount[old] -= 1
        self._refcount[new] = 1
        blks[block_idx] = new
        self.table[slot, block_idx] = new
        self._shared_upto[slot] = block_idx
        self._obs_cow.inc()
        self._publish()
        return new

    def grow(self, slot: int, steps: int) -> None:
        """Advance ``slot``'s coverage by ``steps`` decode tokens (capped
        at its reservation), allocating from the reserved budget — this
        can never fail for an admitted slot."""
        if slot in self._migrating:
            raise RuntimeError(
                f"grow on slot {slot} while its KV is in migration")
        target = min(self._watermark[slot] + steps, self._cap[slot])
        need = blocks_for(target, self.block_size)
        have = len(self._slot_blocks[slot])
        if need > have:
            self._reserved_total -= need - have
            self._grow_to(slot, need)
        self._watermark[slot] = target
        self._publish()

    def _grow_to(self, slot: int, count: int) -> None:
        blks = self._slot_blocks[slot]
        while len(blks) < count:
            blk = self._take_block()
            self._refcount[blk] = 1
            self.table[slot, len(blks)] = blk
            blks.append(blk)

    def free_slot(self, slot: int) -> None:
        """Decrement ``slot``'s block refcounts and return its unused
        reservation; blocks reaching refcount 0 go back to the free list
        unless the prefix cache pins them (those stay resident as
        cached-idle capacity, reclaimed lazily by LRU eviction)."""
        if slot in self._migrating:
            raise RuntimeError(
                f"free_slot on slot {slot} while its KV is in migration; "
                "complete_export or abort_export it first")
        blks = self._slot_blocks[slot]
        held = blocks_for(self._cap[slot], self.block_size) if blks else 0
        self._reserved_total -= max(held - len(blks), 0)
        drop = []
        for blk in blks:
            self._refcount[blk] -= 1
            if not self._refcount[blk] and blk not in self._pinned:
                drop.append(blk)
        self._free.extend(reversed(drop))
        blks.clear()
        self.table[slot, :] = 0
        self._watermark[slot] = 0
        self._cap[slot] = 0
        self._shared_upto[slot] = 0
        self._prompt_len[slot] = 0
        self._publish()

    # -- KV migration (disaggregated prefill/decode) ----------------------

    def export_slot(self, slot: int) -> dict:
        """Begin migrating ``slot``'s KV to another pool.

        Returns the migration manifest — the slot's ORDERED block list
        (pool indices, leftmost = logical position 0), its prompt
        length, coverage watermark, and shared-prefix boundary — and
        freezes the slot: until :meth:`complete_export` (the ack) or
        :meth:`abort_export` lands, the slot may not be freed, grown,
        or COW-split, and :meth:`check` asserts its pages stay off the
        free list.  The caller reads the device pages named by
        ``blocks`` while the freeze holds."""
        blks = self._slot_blocks[slot]
        if not blks:
            raise RuntimeError(f"export_slot on empty slot {slot}")
        if slot in self._migrating:
            raise RuntimeError(f"slot {slot} already in migration")
        self._migrating[slot] = list(blks)
        return {
            "blocks": list(blks),
            "prompt_len": self._prompt_len[slot],
            "watermark": self._watermark[slot],
            "shared_upto": self._shared_upto[slot],
            "block_size": self.block_size,
        }

    def complete_export(self, slot: int) -> None:
        """Ack ``slot``'s migration: the payload has been copied out of
        the pool's pages, so the freeze lifts and the slot frees."""
        if slot not in self._migrating:
            raise RuntimeError(f"slot {slot} is not in migration")
        del self._migrating[slot]
        self.free_slot(slot)

    def abort_export(self, slot: int) -> None:
        """Cancel ``slot``'s migration without freeing it — the slot is
        whole again (the export never mutated it) and the caller decides
        what happens next (resume serving it locally, or free it)."""
        self._migrating.pop(slot, None)

    def adopt_blocks(self, slot: int, prompt_len: int,
                     max_new_tokens: int) -> list[int]:
        """Allocate pages for a migrated-in sequence: ``slot`` receives
        fresh blocks covering ``prompt_len`` positions plus the same
        worst-case reservation :meth:`admit` would take, and the caller
        scatters the received KV bytes into the returned block indices.
        No prefix aliasing — migrated pages are private to the slot
        (the local prefix cache never saw their token chain prefill
        here, so registration happens separately if at all).  Caller
        must have checked :meth:`can_admit` first."""
        self.admit(slot, prompt_len, max_new_tokens)
        return list(self._slot_blocks[slot])

    # -- prefix-cache pinning ---------------------------------------------

    def cache_pin(self, blk: int) -> None:
        self._pinned.add(blk)

    def cache_unpin(self, blk: int) -> None:
        """Drop the cache's pin; if no slot references the block either,
        it returns to the free list immediately."""
        self._pinned.discard(blk)
        if not self._refcount[blk]:
            self._free.append(blk)
        self._publish()

    def alloc_cached_block(self) -> int | None:
        """A plain-free block, taken and PINNED as cached-idle (refcount
        stays 0) — the landing page for a host-tier re-admit or a
        pull-mode install, whose bytes arrive by scatter rather than by
        prefill.  Deliberately never triggers the eviction hook: paging
        one cached block in must not page another cached block out
        (tier thrash), so when only reclaimable-cached capacity is left
        the caller skips the install and re-prefills instead.  Returns
        ``None`` in that case."""
        if not self._free:
            return None
        blk = self._free.pop()
        self._pinned.add(blk)
        self._publish()
        return blk


class PrefixCache:
    """Host-side map from rolling prefix-hash chains to pool blocks.

    One entry per FULL block of a registered prompt: ``chain_hashes(
    prompt)[j] -> block``, where the chain construction guarantees the
    hash names the block's content and its entire prefix.  Matching a
    new prompt walks its own chain left to right and collects blocks
    while hashes keep hitting — the longest cached prefix, always
    block-aligned.

    Entries PIN their blocks in the pool, so an idle prefix survives
    ``free_slot`` and the next same-prefix admission aliases it back in
    via :meth:`BlockPool.share`.  Eviction is LRU and only over entries
    whose block no live slot references (refcount 0) — evicting a block
    under a live slot would tear KV out from under in-flight decode.
    The pool calls :meth:`_evict_for_pool` on demand when its free list
    runs dry, which is what lets cached-idle blocks count as capacity.

    Registration is first-wins: a hash already present keeps its
    original block (the new admission's identical copy stays private to
    its slot and is freed normally).  Content safety: a pinned block is
    written only by the admission that registered it, below its
    prompt's coverage — decode writes land past the prompt, COW splits
    move writers OFF the cached block — so a hit always aliases bytes
    bit-identical to a fresh prefill (greedy determinism holds).
    """

    def __init__(self, pool: BlockPool,
                 capacity_blocks: int | None = None) -> None:
        self.pool = pool
        self.capacity_blocks = capacity_blocks
        self._entries: OrderedDict[int, int] = OrderedDict()
        # chain-parent links (hash -> previous chain hash, None for a
        # prompt's first block): the spill hook forwards them so the
        # host tier can evict by chain suffix
        self._parent: dict[int, int | None] = {}
        # set by the serve loop when a host tier exists: called as
        # ``spill_hook(hash, block, parent)`` just before an evicted
        # block's pin drops (the block is refcount-0, so its page bytes
        # are stable — the hook's one chance to copy them to host RAM)
        self.spill_hook: Callable[[int, int, int | None], None] | None \
            = None
        pool._evict_hook = self._evict_for_pool
        self._obs_hits = obs.counter("serve/prefix_hits", unit="requests")
        self._obs_hit_tokens = obs.counter(
            "serve/prefix_hit_tokens", unit="tokens")
        self._obs_evictions = obs.counter(
            "serve/prefix_evictions", unit="blocks")
        self._obs_cached = obs.gauge(
            "serve/prefix_cached_blocks", unit="blocks")

    def __len__(self) -> int:
        return len(self._entries)

    def match(self, tokens: Sequence[int]) -> list[int]:
        """Blocks covering the longest cached prefix of ``tokens``
        (possibly all of it).  Touches matched entries' LRU recency but
        takes no references — the caller aliases the blocks via
        ``admit(..., shared=...)``, which is what protects them from
        eviction while the request lives."""
        out: list[int] = []
        for h in chain_hashes(tokens, self.pool.block_size):
            blk = self._entries.get(h)
            if blk is None:
                break
            self._entries.move_to_end(h)
            out.append(blk)
        if out:
            self._obs_hits.inc()
            self._obs_hit_tokens.inc(len(out) * self.pool.block_size)
        return out

    def peek(self, tokens: Sequence[int]) -> int:
        """Matched block count WITHOUT touching recency or the hit
        metrics — admission control's capacity precheck (the real
        :meth:`match` runs once, at the admit that follows)."""
        n = 0
        for h in chain_hashes(tokens, self.pool.block_size):
            if h not in self._entries:
                break
            n += 1
        return n

    def register(self, tokens: Sequence[int],
                 slot_blocks: Sequence[int]) -> int:
        """Pin and index ``tokens``'s fully-covered blocks (first-wins
        per hash).  ``slot_blocks`` is the owning slot's block list from
        the admission that just prefilled them.  Returns the number of
        newly registered blocks."""
        added = 0
        hashes = chain_hashes(tokens, self.pool.block_size)
        for j, h in enumerate(hashes):
            if h in self._entries:
                self._entries.move_to_end(h)
                continue
            while (self.capacity_blocks is not None
                   and len(self._entries) >= self.capacity_blocks):
                if not self.evict_one():
                    break
            if (self.capacity_blocks is not None
                    and len(self._entries) >= self.capacity_blocks):
                break
            self._entries[h] = slot_blocks[j]
            self._parent[h] = hashes[j - 1] if j else None
            self.pool.cache_pin(slot_blocks[j])
            added += 1
        self._obs_cached.set(len(self._entries))
        self.pool._publish()
        return added

    def install(self, h: int, blk: int, parent: int | None) -> None:
        """Index an externally-filled cached-idle block under ``h`` —
        the landing half of a host-tier re-admit or a pull-mode
        install.  ``blk`` must come from
        :meth:`BlockPool.alloc_cached_block` (already pinned, refcount
        0) with the page bytes scattered in by the caller; from here on
        the entry is indistinguishable from one :meth:`register` made.
        First-wins like registration: a hash already resident keeps its
        block and the caller must not have allocated for it."""
        if h in self._entries:
            raise RuntimeError(
                f"install of already-resident prefix hash {h}")
        if blk not in self.pool._pinned or self.pool._refcount[blk]:
            raise RuntimeError(
                f"install target block {blk} is not cached-idle "
                "(use alloc_cached_block)")
        self._entries[h] = blk
        self._parent[h] = parent
        self._obs_cached.set(len(self._entries))
        self.pool._publish()

    def evict_one(self) -> bool:
        """Drop the least-recently-used entry whose block no live slot
        references.  Returns False when every entry is in use."""
        for h, blk in self._entries.items():  # OrderedDict: LRU first
            if not self.pool._refcount[blk]:
                del self._entries[h]
                parent = self._parent.pop(h, None)
                if self.spill_hook is not None:
                    # the block is refcount-0 and still pinned: its
                    # page bytes are stable, so the hook can copy them
                    # to the host tier before the pin (and page) drop
                    self.spill_hook(h, blk, parent)
                self.pool.cache_unpin(blk)
                self._obs_evictions.inc()
                self._obs_cached.set(len(self._entries))
                return True
        return False

    def _evict_for_pool(self) -> bool:
        """Pool callback: free at least one block into the free list."""
        return self.evict_one()

    def flush(self) -> None:
        """Drop every entry — cached KV is invalid the moment weights
        hot-swap.  Blocks still referenced by live slots (there are none
        at the drain-gated swap point, but be safe) just lose their pin
        and are freed by their slot's finalize.  Deliberately does NOT
        spill: flush means the bytes are invalid, not cold."""
        for h, blk in list(self._entries.items()):
            del self._entries[h]
            self.pool.cache_unpin(blk)
        self._parent.clear()
        self._obs_cached.set(0)
