"""Host-RAM KV spill tier: the second level of the KV memory hierarchy.

HBM is the fleet's scarcest resource: at any instant most prefix-cached
pages are COLD, yet PR 14's :class:`~tpudist.models.kv_pages.PrefixCache`
frees refcount-0 blocks the moment HBM pressure arrives, and the bytes
are gone — the next same-prefix admission pays a full re-prefill.  The
:class:`HostTier` catches those evictions instead: when the prefix cache
evicts an idle block, its page bytes move to pinned host RAM, keyed by
the SAME rolling chain hash that indexed it in HBM (one entry per full
block; the hash names the block's content and its entire prefix, so a
tier hit is exactly as trustworthy as an HBM cache hit).  A later
admission whose chain walks past the HBM-resident prefix continues into
the tier and re-admits the spilled blocks (host -> HBM scatter, staged
off the dispatch path), turning what would have been re-prefill compute
into a memcpy.

Tier state machine for one chain hash ``h`` (the block-content name, not
a pool index — the pool's page is recycled the moment it spills)::

      (uncached) --register--> HBM-resident --evict+spill--> TIERED
          ^                        ^                            |
          |                        +---------re-admit-----------+
          +-----flush / budget-evict / version-mismatch---------+

A hash is never simultaneously HBM-resident and tiered: the spill
removes it from the cache before :meth:`put`, the re-admit installs it
in the cache before :meth:`take` removes it here.
:meth:`check` asserts that disjointness (alongside the pool's own
live/free/frozen invariants) plus the tier's internal accounting.

Eviction is LRU **by chain suffix**: a chain walk needs CONSECUTIVE
hits, so evicting a mid-chain entry while its extension survives would
leave unreachable bytes — a hole at link ``j`` makes every resident
link past ``j`` dead weight.  The tier therefore only evicts entries
with no tier-resident child (chain leaves), trimming chains from the
deep (cold, long-prefix) end inward; the budget
(``TPUDIST_KV_HOST_TIER_BYTES``) is enforced at :meth:`put` time.

Weight hot-swaps invalidate cached KV; tier entries are stamped with
the serving weights version at :meth:`put` and a lookup under any OTHER
version drops the entry instead of returning it — a post-swap hit can
never adopt pre-swap KV (the serve loop also flushes the tier outright
at the swap point; the stamp is the belt to that suspender).
"""

from __future__ import annotations

import os
from collections import OrderedDict

import numpy as np

from tpudist import obs

__all__ = ["HostTier", "tier_budget_from_env", "DEFAULT_TIER_BYTES"]

# 64 MiB default: plenty for the test/bench models, obviously tunable
# for real fleets via TPUDIST_KV_HOST_TIER_BYTES (0 disables the tier)
DEFAULT_TIER_BYTES = 64 * 1024 * 1024


def tier_budget_from_env(default: int = DEFAULT_TIER_BYTES) -> int:
    """Host-tier byte budget from ``TPUDIST_KV_HOST_TIER_BYTES``;
    ``0`` (or any unparsable value) disables the tier."""
    raw = os.environ.get("TPUDIST_KV_HOST_TIER_BYTES")
    if raw is None:
        return int(default)
    try:
        return max(0, int(raw))
    except ValueError:
        return 0


class HostTier:
    """Bounded host-RAM store of spilled KV blocks, keyed by chain hash.

    One entry per spilled block::

        hash -> {"layers":  [{"k": [bs, F], "v": [bs, F]}, ...],
                 "parent":  previous chain link (None for block 0),
                 "version": weights version the bytes were computed
                            under,
                 "nbytes":  page bytes held}

    ``layers`` follows the migration-payload convention (one dict per
    paged layer in cache-walk order), so tier bytes drop straight into
    the pull-mode export payload or the re-admit scatter without
    reshaping.
    """

    def __init__(self, budget_bytes: int) -> None:
        self.budget_bytes = max(0, int(budget_bytes))
        self._entries: OrderedDict[int, dict] = OrderedDict()
        # resident-children index: _children[h] = tier-resident hashes
        # whose parent link is h.  An entry with resident children is
        # mid-chain and NOT evictable (see module docstring).
        self._children: dict[int, set[int]] = {}
        self._nbytes = 0
        self._obs_blocks = obs.gauge("serve/tier_blocks", unit="blocks")
        self._obs_bytes = obs.gauge("serve/tier_bytes", unit="bytes")
        self._obs_budget = obs.gauge("serve/tier_budget_bytes",
                                     unit="bytes")
        self._obs_hits = obs.counter("serve/tier_hits", unit="blocks")
        self._obs_spills = obs.counter("serve/tier_spills", unit="blocks")
        self._obs_evictions = obs.counter("serve/tier_evictions",
                                          unit="blocks")
        self._obs_readmits = obs.counter("serve/tier_readmits",
                                         unit="blocks")
        self._obs_budget.set(self.budget_bytes)
        self._publish()

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, h: int) -> bool:
        return int(h) in self._entries

    @property
    def nbytes(self) -> int:
        return self._nbytes

    def hashes(self) -> list[int]:
        """Resident chain hashes, LRU-first (for residency summaries)."""
        return list(self._entries)

    def _publish(self) -> None:
        self._obs_blocks.set(len(self._entries))
        self._obs_bytes.set(self._nbytes)

    @staticmethod
    def _layers_nbytes(layers: list[dict]) -> int:
        return int(sum(np.asarray(l["k"]).nbytes
                       + np.asarray(l["v"]).nbytes for l in layers))

    # -- admission ---------------------------------------------------------

    def put(self, h: int, layers: list[dict], *, parent: int | None,
            version: int = 0) -> bool:
        """Admit one spilled block.  First-wins per hash (a resident
        entry keeps its bytes and just refreshes recency).  Returns
        False when the tier is disabled, the entry alone exceeds the
        budget, or eviction cannot make room (every colder entry is
        mid-chain)."""
        h = int(h)
        if h in self._entries:
            self._entries.move_to_end(h)
            return True
        layers = [{"k": np.ascontiguousarray(np.asarray(l["k"])),
                   "v": np.ascontiguousarray(np.asarray(l["v"]))}
                  for l in layers]
        n = self._layers_nbytes(layers)
        if not self.budget_bytes or n > self.budget_bytes:
            return False
        while self._nbytes + n > self.budget_bytes:
            if not self.evict_one():
                return False
        self._entries[h] = {"layers": layers, "parent": parent,
                            "version": int(version), "nbytes": n}
        if parent is not None:
            self._children.setdefault(int(parent), set()).add(h)
        self._nbytes += n
        self._obs_spills.inc()
        self._publish()
        return True

    # -- lookup ------------------------------------------------------------

    def has(self, h: int, *, version: int | None = None) -> bool:
        """Residency probe — no recency touch, no metrics.  A version
        mismatch reads as absent (the entry is dropped lazily by
        :meth:`take`)."""
        e = self._entries.get(int(h))
        if e is None:
            return False
        return version is None or e["version"] == int(version)

    def match_chain(self, hashes, *, version: int | None = None) -> int:
        """Length of the longest leading run of ``hashes`` resident
        under ``version`` — the tier half of a prefix-plan probe."""
        n = 0
        for h in hashes:
            if not self.has(h, version=version):
                break
            n += 1
        return n

    def take(self, h: int, *, version: int | None = None) -> list | None:
        """Remove and return ``h``'s layers for re-admission to HBM
        (ticks ``serve/tier_hits`` + ``serve/tier_readmits``).  A
        version mismatch DROPS the stale entry and returns ``None`` —
        pre-swap bytes must never flow back into the cache."""
        h = int(h)
        e = self._entries.get(h)
        if e is None:
            return None
        if version is not None and e["version"] != int(version):
            self._remove(h)
            self._publish()
            return None
        layers = e["layers"]
        self._remove(h)
        self._obs_hits.inc()
        self._obs_readmits.inc()
        self._publish()
        return layers

    def peek_layers(self, h: int, *,
                    version: int | None = None) -> list | None:
        """``h``'s layers WITHOUT removal (pull-mode export reads tier
        bytes in place — the entry stays resident for local hits).
        Ticks ``serve/tier_hits`` only."""
        e = self._entries.get(int(h))
        if e is None:
            return None
        if version is not None and e["version"] != int(version):
            return None
        self._entries.move_to_end(int(h))
        self._obs_hits.inc()
        return e["layers"]

    # -- eviction ----------------------------------------------------------

    def _remove(self, h: int) -> None:
        e = self._entries.pop(h)
        self._nbytes -= e["nbytes"]
        parent = e["parent"]
        if parent is not None:
            sibs = self._children.get(int(parent))
            if sibs is not None:
                sibs.discard(h)
                if not sibs:
                    del self._children[int(parent)]

    def discard(self, h: int) -> None:
        """Drop ``h`` if resident, silently (no hit/eviction metrics):
        the caller just made the hash HBM-resident again (admission
        ``register`` of a prompt whose re-admit stopped early, or a
        pull install of a link that was also spilled locally), and a
        hash must never be simultaneously tiered and cached.  A
        surviving tier CHILD of ``h`` stays reachable: the chain walk
        continues from the now-HBM-resident parent into the tier."""
        if int(h) in self._entries:
            self._remove(int(h))
            self._publish()

    def evict_one(self) -> bool:
        """Evict the least-recently-used chain LEAF (no tier-resident
        child).  Returns False when every entry is mid-chain — which
        cannot happen while any entry exists (a finite parent forest
        always has leaves), so False means the tier is empty."""
        for h in self._entries:  # OrderedDict: LRU first
            if not self._children.get(h):
                self._remove(h)
                self._obs_evictions.inc()
                self._publish()
                return True
        return False

    def flush(self) -> None:
        """Drop everything — cached KV is invalid the moment weights
        hot-swap (the version stamps are the backstop for entries that
        would somehow survive; this is the front door)."""
        self._entries.clear()
        self._children.clear()
        self._nbytes = 0
        self._publish()

    # -- invariants --------------------------------------------------------

    def check(self, resident_hashes=()) -> None:
        """Tier invariants, cheap enough for every-op test cadence:
        byte accounting exact, budget respected, children index derived
        exactly from resident parent links, and — the cross-structure
        rule — no hash simultaneously tiered and HBM-resident
        (``resident_hashes`` is the prefix cache's key set)."""
        n = sum(e["nbytes"] for e in self._entries.values())
        if n != self._nbytes:
            raise AssertionError(
                f"tier byte drift: entries hold {n}, recorded "
                f"{self._nbytes}")
        for h, e in self._entries.items():
            if e["nbytes"] != self._layers_nbytes(e["layers"]):
                raise AssertionError(f"tier entry {h} nbytes drift")
        if self.budget_bytes and self._nbytes > self.budget_bytes:
            raise AssertionError(
                f"tier over budget: {self._nbytes} > {self.budget_bytes}")
        want: dict[int, set[int]] = {}
        for h, e in self._entries.items():
            if e["parent"] is not None:
                want.setdefault(int(e["parent"]), set()).add(h)
        if want != self._children:
            raise AssertionError(
                f"tier children index drift: derived {want}, "
                f"recorded {self._children}")
        both = set(self._entries) & {int(h) for h in resident_hashes}
        if both:
            raise AssertionError(
                f"hashes simultaneously tiered and HBM-resident: "
                f"{sorted(both)}")
