"""Configurable MLP — twin of the DDP example's ``Model``
(`mnist_ddp_elastic.py:133-159`): 784 → features, then ``hidden_layers`` ×
(features → features), then → 10, ReLU between layers; reference config is
``hidden_layers=5, features=1024`` (`mnist_ddp_elastic.py:172`).

Widths of 1024 are MXU-friendly (multiples of 128 lanes); compute runs in
bfloat16 with float32 params when ``compute_dtype=jnp.bfloat16``.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    hidden_layers: int = 5
    features: int = 1024
    num_classes: int = 10
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.reshape(x.shape[0], -1).astype(self.compute_dtype)
        x = nn.Dense(self.features, dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        for _ in range(self.hidden_layers):
            x = nn.Dense(self.features, dtype=self.compute_dtype)(x)
            x = nn.relu(x)
        logits = nn.Dense(self.num_classes, dtype=self.compute_dtype)(x)
        return logits.astype(jnp.float32)
