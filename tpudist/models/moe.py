"""Mixture-of-Experts layers — the expert-parallel workload of the zoo.

Absent from the reference (SURVEY.md §2.3: "EP — NO"), added so the
framework covers the full parallelism alphabet.  The design follows the
GShard/Switch dense-dispatch formulation, which is the TPU-idiomatic one:
routing is expressed as einsums against a static-shaped one-hot dispatch
tensor (no gather/scatter, no dynamic shapes), so the whole layer lowers to
MXU matmuls, and sharding the expert dimension over an ``expert`` mesh axis
turns the two dispatch einsums into the all-to-alls of expert parallelism
(see :mod:`tpudist.parallel.expert_parallel`).

Capacity semantics: each expert processes at most ``capacity`` tokens per
batch (``capacity_factor × tokens/num_experts``); overflow tokens are
dropped from that expert's contribution (their combine weight is zero), the
residual connection carries them through — standard Switch behavior.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpudist.models.transformer import (
    AttentionFn,
    CausalSelfAttention,
    TransformerConfig,
    sdpa,
)


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 2.0
    aux_loss_weight: float = 1e-2
    # "einsum": the GShard [T, E, C] one-hot dispatch/combine (capacity-
    # bounded, drops overflow tokens; the formulation EP's all-to-all
    # transports).  "ragged": sorted dispatch + jax.lax.ragged_dot grouped
    # matmuls — no [T, E, C] einsums (which at small E cost MORE FLOPs
    # than the experts themselves: measured 6.5× overhead in bench.py),
    # no capacity, no token dropping.  "fused": the ragged layout through
    # the Pallas grouped-matmul kernel (tpudist.ops.moe_dispatch) — both
    # expert matmuls in one kernel, the [T·k, f] intermediate resident in
    # VMEM.  Both non-einsum paths are single-shard only (ep_axis needs
    # the block layout).
    dispatch: str = "einsum"
    # "fused" only: slot rows per kernel block.  Group starts pad to
    # this, wasting up to E*fused_block_rows rows of expert FLOPs — at
    # small token counts (decode-time MoE) shrink it or use "ragged".
    fused_block_rows: int = 128


def _gate_choices(gates: jnp.ndarray, top_k: int):
    """Shared routing head: top-k expert choices with renormalised gate
    mass + the Switch load-balancing aux loss."""
    e = gates.shape[-1]
    top_vals, top_idx = jax.lax.top_k(gates, top_k)
    top_vals = top_vals / jnp.maximum(
        jnp.sum(top_vals, axis=-1, keepdims=True), 1e-9)
    frac_tokens = jnp.mean(
        jax.nn.one_hot(top_idx[:, 0], e, dtype=gates.dtype), axis=0)
    mean_gates = jnp.mean(gates, axis=0)
    aux = jnp.sum(frac_tokens * mean_gates) * e
    return top_vals, top_idx, aux


def _top_k_routing(gates: jnp.ndarray, top_k: int, capacity: int):
    """GShard routing: from router probabilities ``gates [T, E]`` build

    * ``dispatch [T, E, C]`` — one-hot: token t goes to expert e at slot c,
    * ``combine  [T, E, C]`` — dispatch weighted by the (renormalised) gate,
    * ``aux`` — the load-balancing loss (mean fraction·mean gate × E²).
    """
    t, e = gates.shape
    # [T, k] indices of the chosen experts, gate mass renormalised over them.
    top_vals, top_idx, aux = _gate_choices(gates, top_k)

    dispatch = jnp.zeros((t, e, capacity), gates.dtype)
    combine = jnp.zeros((t, e, capacity), gates.dtype)
    # Slots are assigned in token order per expert, k-th choices after the
    # (k-1)-th (Switch/GShard priority), tracked by a running per-expert count.
    counts = jnp.zeros((e,), jnp.int32)
    for k in range(top_k):
        onehot = jax.nn.one_hot(top_idx[:, k], e, dtype=jnp.int32)  # [T, E]
        pos = counts[None, :] + jnp.cumsum(onehot, axis=0) - onehot  # slot idx
        keep = (pos < capacity) & (onehot > 0)
        slot = jax.nn.one_hot(pos, capacity, dtype=gates.dtype)      # [T, E, C]
        sel = slot * keep[..., None].astype(gates.dtype)
        dispatch = dispatch + sel
        combine = combine + sel * top_vals[:, k, None, None]
        counts = counts + jnp.sum(onehot, axis=0)

    return dispatch, combine, aux


def _counting_sort(flat_e: jnp.ndarray, e: int,
                   block_rows: int | None = None):
    """Expert-grouped slot assignment as a COUNTING SORT — the shared
    dispatch bookkeeping of the ragged and fused MoE paths.

    E is small, so the rank of each assignment within its expert comes
    from one LANE-MAJOR ``[E, N]`` one-hot cumsum (the ``[N, E]`` layout
    puts an 8-wide row on the 128-lane axis and measured ~2× the whole
    glue budget in padded cumsum passes), and ``rank + group_start`` is
    its destination slot — which IS the inverse permutation the combine
    needs; one scatter of iota builds the forward order.  No comparison
    sorts, no index gathers (the per-assignment start/rank picks are
    one-hot reductions).  This replaced the round-3 double ``argsort``,
    the bulk of the measured 3.3–3.8× ragged-dispatch overhead.

    ``block_rows`` pads each group's start to a block multiple (the
    fused kernel's block-aligned layout).  Returns
    ``(pos [N], order [NP], group_sizes [E], starts [E], np_pad)`` where
    ``NP = np_pad`` is ``N`` when unpadded.
    """
    n = flat_e.shape[0]
    onehot = (jnp.arange(e)[:, None] == flat_e[None, :]).astype(jnp.int32)
    within = jnp.cumsum(onehot, axis=1) - onehot        # [E, N] lane cumsum
    group_sizes = jnp.sum(onehot, axis=1)               # [E]
    padded = (group_sizes if block_rows is None
              else -(-group_sizes // block_rows) * block_rows)
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(padded)[:-1]])
    pos = jnp.sum((within + starts[:, None]) * onehot, axis=0)   # [N]
    if block_rows is None:
        np_pad = n
    else:
        np_pad = (n // block_rows + e) * block_rows     # static bound
    order = jnp.zeros((np_pad,), jnp.int32).at[pos].set(
        jnp.arange(n, dtype=jnp.int32))
    return pos, order, group_sizes, starts, np_pad


def _ragged_moe(x: jnp.ndarray, w_up: jnp.ndarray, w_down: jnp.ndarray,
                top_idx: jnp.ndarray, top_vals: jnp.ndarray) -> jnp.ndarray:
    """Sorted dispatch + grouped matmuls: every (token, choice) assignment
    is grouped by expert id (:func:`_counting_sort`), expert MLPs run as
    TWO ``jax.lax.ragged_dot`` calls over the contiguous groups, and the
    inverse permutation + gate-weighted sum combines.  Zero [T, E, C]
    one-hots, zero capacity padding, zero dropped tokens."""
    t, d = x.shape
    k = top_idx.shape[1]
    e = w_up.shape[0]
    pos, order, group_sizes, _, _ = _counting_sort(top_idx.reshape(-1), e)
    xs = x[order // k]                                  # slot -> token row
    h = jax.nn.gelu(jax.lax.ragged_dot(xs, w_up, group_sizes))
    ys = jax.lax.ragged_dot(h, w_down, group_sizes)     # [N, d]
    y = ys[pos].reshape(t, k, d)                        # pos IS the inverse
    return jnp.sum(y * top_vals[:, :, None].astype(y.dtype), axis=1)


class MoEMLP(nn.Module):
    """Expert-parallel MLP: router + ``num_experts`` gelu MLPs.

    Input/output ``[tokens, d_model]``; expert weights are single stacked
    arrays ``[E, d, f]`` / ``[E, f, d]`` so the expert dim is shardable.
    Returns ``(out, aux_loss)``.

    ``ep_axis`` selects the EXPLICIT expert-parallel path: the module must
    then run inside a ``shard_map`` over that mesh axis with tokens sharded
    across it and the expert stacks sharded on their leading dim — each
    device routes its local tokens to ALL experts, a ``lax.all_to_all``
    delivers every expert's batch to the device that owns it, the local
    expert MLPs run, and a second all-to-all returns the outputs — the
    canonical EP dispatch, *guaranteed* in the lowering rather than left to
    GSPMD (which prefers replicate-tokens + all-reduce for the dense
    formulation; see ``tests/test_moe.py``).  Initialize the global model
    with ``ep_axis=None``, then shard.
    """

    d_model: int
    d_ff: int
    moe: MoEConfig
    ep_axis: str | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
        t = x.shape[0]
        e = self.moe.num_experts
        capacity = max(
            1, int(self.moe.capacity_factor * t * self.moe.top_k / e))
        gates = jax.nn.softmax(
            nn.Dense(e, use_bias=False, name="router")(x).astype(jnp.float32))
        if self.moe.dispatch in ("ragged", "fused"):
            if self.ep_axis is not None:
                raise ValueError(
                    f"dispatch={self.moe.dispatch!r} is single-shard (the "
                    "EP all-to-all transports the [E, C, d] block layout); "
                    "use dispatch='einsum' with ep_axis")
            top_vals, top_idx, aux = _gate_choices(gates, self.moe.top_k)
            w_up = self.param(
                "w_up", nn.initializers.lecun_normal(),
                (e, self.d_model, self.d_ff)).astype(x.dtype)
            w_down = self.param(
                "w_down", nn.initializers.lecun_normal(),
                (e, self.d_ff, self.d_model)).astype(x.dtype)
            if self.moe.dispatch == "fused":
                from tpudist.ops.moe_dispatch import fused_moe_mlp

                out = fused_moe_mlp(x, w_up, w_down, top_idx, top_vals,
                                    block_rows=self.moe.fused_block_rows)
            else:
                out = _ragged_moe(x, w_up, w_down, top_idx, top_vals)
            return out, aux.astype(jnp.float32)
        if self.moe.dispatch != "einsum":
            raise ValueError(
                f"unknown dispatch {self.moe.dispatch!r} "
                f"(expected einsum|ragged|fused)")
        dispatch, combine, aux = _top_k_routing(
            gates, self.moe.top_k, capacity)

        # Params in float32, compute in the input dtype (the same f32-params/
        # bf16-compute contract nn.Dense(dtype=...) gives the dense layers).
        # Under ep_axis the declared (local) expert count is E / axis size —
        # matching the shard this device holds of the stacked weights.
        n_shards = 1 if self.ep_axis is None else jax.lax.axis_size(
            self.ep_axis)
        if e % n_shards:
            raise ValueError(
                f"num_experts {e} not divisible by {self.ep_axis!r} axis "
                f"size {n_shards}")
        w_up = self.param(
            "w_up", nn.initializers.lecun_normal(),
            (e // n_shards, self.d_model, self.d_ff)).astype(x.dtype)
        w_down = self.param(
            "w_down", nn.initializers.lecun_normal(),
            (e // n_shards, self.d_ff, self.d_model)).astype(x.dtype)

        # dispatch: [T,E,C] × [T,d] -> per-expert batches [E,C,d] ...
        expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
        if self.ep_axis is not None:
            # THE all-to-all of expert parallelism: expert-major blocks
            # scatter to their owners, every shard's token batches gather
            # along capacity -> [E/n, n·C, d]
            expert_in = jax.lax.all_to_all(
                expert_in, self.ep_axis, split_axis=0, concat_axis=1,
                tiled=True)
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, w_up))
        expert_out = jnp.einsum("ecf,efd->ecd", h, w_down)
        if self.ep_axis is not None:
            # return trip: [E/n, n·C, d] -> [E, C, d] back at the sources
            expert_out = jax.lax.all_to_all(
                expert_out, self.ep_axis, split_axis=1, concat_axis=0,
                tiled=True)
        # ... and the combine, weighted by the (renormalised) gates.
        out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_out)
        return out, aux.astype(jnp.float32)


class MoEDecoderBlock(nn.Module):
    cfg: TransformerConfig
    moe: MoEConfig
    attention_fn: AttentionFn = sdpa
    ep_axis: str | None = None

    @nn.compact
    def __call__(self, x, *, causal: bool = True):
        h = nn.LayerNorm(dtype=self.cfg.compute_dtype, name="ln1")(x)
        x = x + CausalSelfAttention(self.cfg, self.attention_fn,
                                    name="attn")(h, causal=causal)
        h = nn.LayerNorm(dtype=self.cfg.compute_dtype, name="ln2")(x)
        b, s, d = h.shape
        out, aux = MoEMLP(d_model=self.cfg.embed_dim,
                          d_ff=self.cfg.mlp_ratio * self.cfg.embed_dim,
                          moe=self.moe, ep_axis=self.ep_axis,
                          name="moe")(h.reshape(b * s, d))
        return x + out.reshape(b, s, d), aux


class MoETransformerLM(nn.Module):
    """Decoder-only LM with an MoE MLP in every block.

    ``tokens [B, S] -> (logits [B, S, vocab] f32, aux_loss scalar)``; add
    ``aux_loss`` (already weighted) to the training loss.
    """

    cfg: TransformerConfig
    moe: MoEConfig
    attention_fn: AttentionFn = sdpa
    ep_axis: str | None = None

    @nn.compact
    def __call__(self, tokens, *, causal: bool = True, positions=None):
        cfg = self.cfg
        if positions is None:
            positions = jnp.arange(tokens.shape[1])[None, :]
        x = nn.Embed(cfg.vocab_size, cfg.embed_dim,
                     dtype=cfg.compute_dtype, name="tok_embed")(tokens)
        x = x + nn.Embed(cfg.max_seq_len, cfg.embed_dim,
                         dtype=cfg.compute_dtype, name="pos_embed")(positions)
        aux_total = jnp.zeros((), jnp.float32)
        for i in range(cfg.num_layers):
            x, aux = MoEDecoderBlock(cfg, self.moe, self.attention_fn,
                                     ep_axis=self.ep_axis,
                                     name=f"block{i}")(x, causal=causal)
            aux_total = aux_total + aux
        x = nn.LayerNorm(dtype=cfg.compute_dtype, name="ln_f")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False,
                          dtype=cfg.compute_dtype, name="lm_head")(x)
        return logits.astype(jnp.float32), self.moe.aux_loss_weight * aux_total
