"""ResNet50, structured as pipeline stages.

Twin of the reference's two-shard ResNet50 (`model_parallel_ResNet50.py:
39-139`): Bottleneck stacks with a 1x1-conv downsample, split after layer2 —
stage 1 = conv7x7/s2 + norm + relu + maxpool + layer1(64×3) + layer2(128×4,s2)
(`:85-114`), stage 2 = layer3(256×6,s2) + layer4(512×3,s2) + avgpool +
fc(2048→1000) (`:117-139`).

TPU-first departures from the reference:

* NHWC layout, bfloat16 compute with float32 params/normalization;
* GroupNorm by default instead of BatchNorm: stateless (no running-stat
  plumbing through the pipeline) and needs no cross-replica sync under data
  parallelism; BatchNorm remains available (``norm="batch"``) with
  ``axis_name``-synced statistics for strict parity experiments;
* no per-shard locks — stages are pure functions, the hazard the reference's
  ``threading.Lock`` guards (`model_parallel_ResNet50.py:48,112,137`) does
  not exist (SURVEY.md §5 "Race detection");
* an arbitrary ``num_stages`` split (2 reproduces the reference) chosen at
  block granularity so stages balance FLOPs.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

STAGE_SIZES = (3, 4, 6, 3)  # ResNet50 Bottleneck counts per layer group
STAGE_WIDTHS = (64, 128, 256, 512)


def _norm(norm: str, dtype: Any) -> Callable[..., nn.Module]:
    if norm == "group":
        # custom-VJP GroupNorm: autodiff of the flax two-pass stats emits
        # badly-fused backward HLO (~6 ms/step of ResNet50 at batch 128 —
        # measured in scripts/resnet_mfu_sweep.py); the closed-form
        # gradient is two group reductions + elementwise
        from tpudist.ops.group_norm import GroupNorm

        return lambda: GroupNorm(num_groups=32, dtype=dtype, param_dtype=jnp.float32)
    if norm == "group_flax":  # the autodiff baseline, kept for comparison
        return lambda: nn.GroupNorm(num_groups=32, dtype=dtype, param_dtype=jnp.float32)
    if norm == "batch":
        from tpudist.ops.batch_norm import BatchNorm

        return lambda: BatchNorm(
            use_running_average=False, momentum=0.9, dtype=dtype, axis_name="data"
        )
    if norm == "batch_local":  # per-replica statistics, closed-form VJP
        from tpudist.ops.batch_norm import BatchNorm

        return lambda: BatchNorm(
            use_running_average=False, momentum=0.9, dtype=dtype
        )
    if norm == "batch_flax":  # the autodiff baseline, kept for comparison
        return lambda: nn.BatchNorm(
            use_running_average=False, momentum=0.9, dtype=dtype
        )
    if norm == "none":  # ablation/benchmark control: no normalization
        return lambda: (lambda x: x)
    raise ValueError(f"unknown norm {norm!r}")


class Bottleneck(nn.Module):
    """1x1 reduce → 3x3 → 1x1 expand (×4), with projection shortcut when
    shape changes (`model_parallel_ResNet50.py:64-76` equivalent).

    Norm placement is the standard post-norm bottleneck; ``norm="group"``
    routes through the closed-form custom-VJP GroupNorm
    (:mod:`tpudist.ops.group_norm`).  NOTE (measured, round 3): the
    slab-resident Pallas GN kernels were tried here and made training
    2.3× SLOWER — XLA fuses the forward GN into the conv epilogues for
    free, and the kernel boundary destroyed that fusion (9.5 ms fwd vs
    1.24 ms).  Keep norms as XLA-fusible jnp ops in this model."""

    features: int
    strides: int = 1
    norm: str = "group"
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        mk_norm = _norm(self.norm, self.compute_dtype)

        def norm_relu(y):
            return nn.relu(mk_norm()(y))

        out_c = self.features * 4
        needs_proj = x.shape[-1] != out_c or self.strides != 1
        y = nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.compute_dtype)(x)
        y = norm_relu(y)
        y = nn.Conv(
            self.features, (3, 3), strides=(self.strides, self.strides),
            padding="SAME", use_bias=False, dtype=self.compute_dtype,
        )(y)
        y = norm_relu(y)
        y = nn.Conv(out_c, (1, 1), use_bias=False, dtype=self.compute_dtype)(y)
        # norm instantiation order (y-branch norm BEFORE the projection
        # norm) is load-bearing: flax auto-names follow call order, and
        # swapping them would silently cross-load same-shaped checkpoint
        # leaves between the two norms
        y = mk_norm()(y)
        residual = x
        if needs_proj:
            residual = nn.Conv(
                out_c, (1, 1), strides=(self.strides, self.strides),
                use_bias=False, dtype=self.compute_dtype,
            )(residual)
            residual = mk_norm()(residual)
        return nn.relu(residual + y)


class ResNetStem(nn.Module):
    """conv7x7/s2 + norm + relu + 3x3 maxpool/s2 (`model_parallel_ResNet50.py:90-95`)."""

    norm: str = "group"
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.astype(self.compute_dtype)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.compute_dtype)(x)
        x = nn.relu(_norm(self.norm, self.compute_dtype)()(x))
        return nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))


class ResNetHead(nn.Module):
    """Global avgpool + fc(2048→num_classes) (`model_parallel_ResNet50.py:127-130`)."""

    num_classes: int = 1000
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=self.compute_dtype)(x).astype(jnp.float32)


@dataclasses.dataclass(frozen=True)
class _BlockSpecEntry:
    features: int
    strides: int


def _block_plan() -> list[_BlockSpecEntry]:
    plan = []
    for group, (count, width) in enumerate(zip(STAGE_SIZES, STAGE_WIDTHS)):
        for i in range(count):
            stride = 2 if (i == 0 and group > 0) else 1
            plan.append(_BlockSpecEntry(width, stride))
    return plan


class ResNetSegment(nn.Module):
    """A contiguous run of Bottleneck blocks; optionally carries the stem
    (first segment) and the head (last segment)."""

    blocks: Sequence[_BlockSpecEntry]
    with_stem: bool = False
    with_head: bool = False
    num_classes: int = 1000
    norm: str = "group"
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        if self.with_stem:
            x = ResNetStem(self.norm, self.compute_dtype)(x)
        x = x.astype(self.compute_dtype)
        for b in self.blocks:
            x = Bottleneck(b.features, b.strides, self.norm, self.compute_dtype)(x)
        if self.with_head:
            x = ResNetHead(self.num_classes, self.compute_dtype)(x)
        return x


def resnet50_stages(
    num_stages: int = 2,
    num_classes: int = 1000,
    norm: str = "group",
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> list[ResNetSegment]:
    """Split ResNet50's 16 Bottleneck blocks into ``num_stages`` contiguous
    segments.  ``num_stages=2`` reproduces the reference split (blocks 0-6 =
    layer1+layer2 with the stem; blocks 7-15 = layer3+layer4 with the head,
    `model_parallel_ResNet50.py:96-100,125-126`)."""
    plan = _block_plan()
    if num_stages == 2:
        cuts = [7]  # after layer2, the reference's split point
    else:
        per = -(-len(plan) // num_stages)
        cuts = [per * i for i in range(1, num_stages)]
    bounds = [0, *cuts, len(plan)]
    segs = []
    for s in range(num_stages):
        segs.append(
            ResNetSegment(
                blocks=tuple(plan[bounds[s] : bounds[s + 1]]),
                with_stem=(s == 0),
                with_head=(s == num_stages - 1),
                num_classes=num_classes,
                norm=norm,
                compute_dtype=compute_dtype,
            )
        )
    return segs


class ResNet50(nn.Module):
    """The whole network as one module (for single-device / pure-DP runs)."""

    num_classes: int = 1000
    norm: str = "group"
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for seg in resnet50_stages(1, self.num_classes, self.norm, self.compute_dtype):
            x = seg(x)
        return x
