"""Continuous-batching serving loop — request-level scheduling over the
compiled decode step.

The round-3 verdict: the kernels and sharded rollouts existed, the
REQUEST layer didn't — fixed-batch rollouts make every sequence in the
batch start and stop together, so a mixed workload pays the longest
request's schedule.  This module adds the vLLM-style iteration-level
scheduler, shaped for TPU/XLA rather than for a GPU runtime:

* ``num_slots`` fixed decode lanes, each owning one row of the KV cache;
  the cache's ``cache_index`` leaves are VECTORS ``[B]`` — every slot
  decodes at its own length through the per-row cache path
  (``CausalSelfAttention._serve_attend``; the flash kernel takes per-row
  lengths) — one compiled step, no padding to a common position;
* ONE compiled SEGMENT (``lax.scan`` of ``steps_per_sync`` single-token
  steps) between host syncs: per-token host round trips would be
  RTT-bound, so admission/completion happen at segment granularity (a
  slot finishing mid-segment idles ≤ ``steps_per_sync`` ticks — the
  standard iteration-level-scheduling trade);
* admission PREFILLS the prompt through the scalar-index path into a
  side cache of batch 1 (chunked — the same ``_prefill`` the rollouts
  use, prompts right-padded to a chunk multiple so compile count is
  bounded by ``max_seq_len / prefill_chunk`` distinct shapes), then one
  compiled INSERT scatters the row into the freed slot and stamps its
  true length;
* per-request ``max_new_tokens`` and stop tokens: budgets ride the
  compiled segment as an ``[B]`` countdown (a stopped/funded-out slot
  freezes inside the segment), the host finalizes completions and reuses
  the slot.

The bench criterion (``bench.py: serve_loop``): tokens/s/slot at 8k
context with MIXED prompt lengths within ~15% of the fixed-batch
rollout, which is the cost of the request layer — the decode step is the
same kernels either way.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import time
from collections import deque
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpudist import obs
from tpudist.runtime import faults
from tpudist.models.generate import (
    _blank_cache,
    _make_select,
    _prefill,
    _stop_array,
    serving_layout,
)
from tpudist.models.kv_pages import BlockPool, PrefixCache, chain_hashes
from tpudist.models.kv_tier import HostTier, tier_budget_from_env
from tpudist.models.speculative import (
    AdaptiveDraftPolicy,
    _accept_and_next,
    _filtered_probs,
    _set_cache_index,
)
from tpudist.models.transformer import TransformerConfig, TransformerLM

# placeholder page row for the dense layout's admit signature (the insert
# walk never reaches a paged node there)
_NO_PAGES = np.zeros((0,), np.int32)


def _park_hash(rid: str, i: int) -> int:
    """Synthetic host-tier key for a parked (preempted) slot's i-th KV
    block: a 63-bit blake2b digest of ``(rid, i)`` — int-typed as the
    tier requires, and disjoint from prefix chain hashes with
    overwhelming probability."""
    d = hashlib.blake2b(f"park:{rid}:{i}".encode(), digest_size=8)
    return int.from_bytes(d.digest(), "big") >> 1


@dataclasses.dataclass
class Request:
    """One serving request: a prompt and its generation budget.

    ``deadline_s`` is an ABSOLUTE wall-clock deadline (``time.time()``
    epoch seconds, ``None`` = no deadline).  A request whose deadline
    passes while queued completes with ``reason="timeout"`` and no
    tokens; one that expires mid-decode is killed at the next segment
    boundary, completes with the tokens generated so far, and refunds
    its KV block reservation — a stuck client can never pin pool
    capacity forever.

    ``priority`` ranks requests for overload degradation: 0 (default) is
    best-effort, higher values are more important.  Under pressure the
    loop degrades best-effort traffic FIRST — clamps its
    ``max_new_tokens`` past the soft watermark, sheds it first at the
    hard bound — so paid/interactive traffic keeps full service until
    best-effort is exhausted.

    ``trace`` is the distributed-tracing context
    (:class:`tpudist.obs.events.TraceContext`, ``None`` for untraced
    local runs): minted by the router at submit, it rides the fleet
    wire format and keys every lifecycle event this loop records —
    admit, segments, degrade clamps, timeouts, finalize — to the one
    fleet-wide id that survives a SIGKILL + redispatch.

    ``prefix_hash`` is an opaque client-stamped hash of the prompt's
    shared prefix (:func:`tpudist.models.kv_pages.request_prefix_hash`
    over e.g. a tenant's system prompt; ``None`` = no known prefix).
    The serve loop records recently admitted hashes while prefix
    sharing is on (:meth:`ServeLoop.prefix_summary`), replicas publish
    them, and the router steers same-hash requests to a replica whose
    prefix cache is already warm — fleet-level hit rate survives
    scale-out without any process agreeing on block sizes."""

    prompt: np.ndarray            # [L] int32 tokens, L >= 1
    max_new_tokens: int
    rid: Any = None               # caller's correlation id
    deadline_s: float | None = None
    priority: int = 0             # 0 = best-effort; higher = keep longer
    trace: Any = None             # TraceContext | None (fleet tracing)
    prefix_hash: int | None = None  # router prefix-affinity key
    # disaggregated serving: a KV-migration payload from a prefill
    # replica (see tpudist.runtime.disagg).  A decode-role loop ADOPTS
    # the migrated pages instead of prefilling; None (or a payload that
    # fails verification) means ordinary admission — the re-prefill
    # fallback that keeps a lost handoff exact.
    kv_handoff: Any = None
    # fleet-global prefix cache (pull mode): an opaque KVTransport ref
    # to a peer-exported prefix payload.  The replica worker resolves
    # it and installs the pages as cached-idle blocks BEFORE admission,
    # so the admission below hits locally; a missing/corrupt/stale ref
    # installs nothing and the ordinary prefill is the exact fallback.
    prefix_ref: str | None = None


@dataclasses.dataclass
class Completion:
    rid: Any
    prompt: np.ndarray
    tokens: np.ndarray            # the generated tokens (stop included)
    # "stop" | "length" — the normal endings; "rejected" (load-shed at a
    # full admission queue), "timeout" (deadline_s passed), "invalid"
    # (service-mode request failed validation), "shed" (router-side SLO
    # admission refused it before any replica paid a prefill — see
    # tpudist.runtime.router), "handoff" (a prefill-role loop finished
    # the prompt and exported its KV; `handoff` carries the migration
    # payload and the DECODE stage produces the tokens)
    reason: str
    handoff: Any = None           # KV-migration payload (prefill role)


def _index_leaves(cache: Any) -> tuple[jnp.ndarray, jnp.ndarray | None]:
    """(cache_index [B], side_index scalar | None), matched BY NAME:
    every layer carries the same values, so the first of each suffices."""
    main = side = None

    def walk(node):
        nonlocal main, side
        if not isinstance(node, dict):
            return
        for k, v in node.items():
            if k == "cache_index" and main is None:
                main = v
            elif k == "side_index" and side is None:
                side = v
            else:
                walk(v)

    walk(cache)
    if main is None:
        raise ValueError("cache holds no index leaves")
    return main, side


def _shift_index_leaves(cache: Any, delta, names) -> Any:
    """Subtract ``delta`` from every index leaf named in ``names`` — the
    speculative ROLLBACK: the verify chunk optimistically wrote K+1
    tokens' K/V, and the accepted prefix kept only ``m + 1`` of them, so
    the write cursor backs up by ``K - m`` and the next round's chunk
    overwrites the rejected slots."""
    def walk(node):
        if not isinstance(node, dict):
            return node
        return {k: (v - delta if k in names else walk(v))
                for k, v in node.items()}
    return walk(cache)


class ServeLoop:
    """Continuous-batching server over one model.

    Args:
      cfg / params: the model (scanned checkpoints are normalized via
        :func:`serving_layout`).
      num_slots: decode lanes (the B of the slot cache).  Pick the
        fixed-batch size that saturates the chip; the request layer keeps
        those lanes full across requests of different lengths.
      steps_per_sync: decode ticks per compiled segment (the admission
        latency / dispatch-amortization trade; ≥ the tunnel RTT in ticks).
      decode_attention: "flash" (per-row kernel) or "dense".
      prefill_chunk: admission prefill chunk; prompts are right-padded to
        a multiple of it, so it also bounds the number of distinct
        prefill executables.
      stop_tokens / pad_token: EOS semantics as in ``greedy_generate``.
      temperature / top_k / top_p: sampling controls (0 = greedy).
      cache_layout: "dense" (per-slot ``[B, S]`` KV buffers) or "paged"
        (a shared block pool per layer + per-slot page tables —
        PagedAttention).  Paged serving's KV HBM scales with the tokens
        requests actually reserve, not ``num_slots × max_seq_len``; see
        :mod:`tpudist.models.kv_pages`.  Admission gains a capacity
        check against free blocks (requests QUEUE when the pool is
        full, FIFO), and dispatch grows every live slot's page coverage
        by ``steps_per_sync`` before each segment.
      kv_block_size: tokens per KV block (paged only); a positive
        multiple of 8.  Small blocks waste less memory on the last
        partial block per request (~block_size/2 tokens × slots), large
        blocks mean fewer grid steps and page-table entries.
      kv_num_blocks: pool capacity (paged only).  Default ``None``
        sizes the pool to full dense capacity
        (``num_slots × ceil(max_seq_len / block_size)``); the HBM win
        comes from passing the capacity the workload actually needs.
      pipeline_depth: compiled segments in flight before the host blocks
        on a fetch.  2 (the default) dispatches segment ``k+1`` as soon
        as ``k`` returns — the carry chains on device — and then fetches
        ``k``'s emits (whose device→host copy was started async at
        dispatch time) overlapped with ``k+1``'s compute, so the device
        never waits on the host round trip in steady state.  The cost is
        BOUNDED STALENESS: the host learns stop/budget events one
        segment later, so admissions and finalizations shift one segment
        — the same trade the segment design already accepts at
        ``steps_per_sync`` granularity — while the drain path stays
        token-identical (frozen rows emit pads in-graph; stale columns
        are dropped by the same rules as the synchronous loop).  1
        restores the fully synchronous loop.
      max_queue: bound on WAITING requests (excluding the ones already
        in slots).  ``None`` (default) keeps the queue unbounded; with a
        bound, overflow requests are load-shed — lowest ``priority``
        class first, newest-first within a class — completing
        immediately with ``reason="rejected"`` and ticking the
        ``serve/rejected`` counter, which a router reads to back off a
        saturated replica instead of piling more work on it.
      degrade_queue: soft overload watermark (defaults to
        ``max_queue // 2`` when ``max_queue`` is set).  While the queue
        sits above it the loop is DEGRADED (``serve/degraded`` gauge = 1)
        and newly admitted best-effort requests (``priority == 0``) get
        ``max_new_tokens`` clamped to ``degrade_max_new`` — shorter
        answers for everyone beats no answer for the tail, and the clamp
        engages BEFORE any request is rejected outright.
      degrade_max_new: the degraded-mode ``max_new_tokens`` clamp for
        best-effort traffic (default 32).
      decode_mode: "plain" (one model step per generated token) or
        "speculative" — the fused segment runs draft-K proposal +
        one-chunk target verification per round
        (:mod:`tpudist.models.speculative` folded into the serve loop),
        emitting up to K+1 tokens per target forward.  Output follows
        the TARGET's distribution exactly (greedy: exact-match against
        plain decode); weight hot-swaps rebind the target only — the
        draft may lag a version, which costs acceptance, never
        exactness.
      draft_cfg / draft_params: the proposal model (speculative only).
        ``vocab_size`` must match the target and ``max_seq_len`` must
        cover the target's (the draft cache mirrors each lane's
        position); it is normalized via :func:`serving_layout` like the
        target.  The draft always decodes DENSE per-row (its cache is
        small by construction; paging it would buy nothing).
      num_draft: draft tokens per verify round — a fixed int, or
        "adaptive" (default) to let :class:`AdaptiveDraftPolicy` pick
        from ``spec_ladder`` using the observed acceptance rate and
        measured per-round costs (each ladder K compiles once).
      spec_ladder: candidate K values for the adaptive policy.
      chunked_prefill: interleave admission prefill with decode
        (plain decode mode only; speculative keeps one-shot admission).
        Instead of one fused prefill+insert dispatch, admission
        dispatches ONE ``prefill_chunk``-wide slice per host-loop
        iteration between fused decode segments, so a 10k-token prompt
        can no longer stall every in-flight request's inter-token
        latency for its whole prefill.  The chunk partition is the SAME
        grid the one-shot path uses, so output stays token-identical.
      prefix_sharing: copy-on-write prefix page sharing (paged layout +
        chunked prefill only; silently off otherwise).  A host-side
        :class:`~tpudist.models.kv_pages.PrefixCache` maps rolling
        token-hash chains to pool blocks; an admission whose prompt
        prefix is cached ALIASES those blocks (refcounted, read-only)
        and prefills only the suffix — a full-prompt hit recomputes
        one position through a COW split of the last shared block.
        The cache is flushed at every weight hot-swap (cached KV is
        stale the moment params change).
      role: ``"both"`` (default — the unified loop), ``"prefill"``, or
        ``"decode"`` — the disaggregated fleet split
        (:mod:`tpudist.runtime.disagg`).  A PREFILL loop runs chunked
        prefill to completion, exports the slot's KV pages plus the
        first sampled token as a migration payload
        (``Completion(reason="handoff", handoff=payload)``), frees the
        slot, and never dispatches a decode segment — its lanes turn
        over at prompt cadence.  A DECODE loop admits requests whose
        ``Request.kv_handoff`` carries such a payload by ADOPTING the
        pages into its own pool (no prefill) and decoding from the
        migrated state; a missing or unverifiable payload falls back
        to an ordinary prefill of the same prompt, which greedy
        decoding over identical weights makes byte-identical.
        ``"prefill"`` requires the paged layout + chunked prefill
        (plain decode); ``"decode"`` requires the paged layout.
    """

    def __init__(
        self,
        cfg: TransformerConfig,
        params: Any,
        num_slots: int,
        *,
        steps_per_sync: int = 32,
        decode_attention: str = "flash",
        prefill_chunk: int = 512,
        stop_tokens: Sequence[int] | None = None,
        pad_token: int = 0,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        key: jax.Array | None = None,
        auto_unstack: bool = True,
        pipeline_depth: int = 2,
        cache_layout: str = "dense",
        kv_block_size: int = 128,
        kv_num_blocks: int | None = None,
        max_queue: int | None = None,
        degrade_queue: int | None = None,
        degrade_max_new: int = 32,
        decode_mode: str = "plain",
        draft_cfg: TransformerConfig | None = None,
        draft_params: Any = None,
        num_draft: int | str = "adaptive",
        spec_ladder: Sequence[int] = (2, 4, 8),
        chunked_prefill: bool = True,
        prefix_sharing: bool = True,
        role: str = "both",
        preempt: str = "degrade",
    ) -> None:
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if max_queue is not None and max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got {max_queue}")
        if steps_per_sync < 1:
            raise ValueError(
                f"steps_per_sync must be >= 1, got {steps_per_sync}")
        if pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {pipeline_depth}")
        if auto_unstack:
            cfg, params = serving_layout(cfg, params)
        if cfg.scan_layers:
            raise ValueError(
                "ServeLoop needs the unrolled layout; pass the scanned "
                "checkpoint with auto_unstack=True (the default)")
        if cache_layout not in ("dense", "paged"):
            raise ValueError(
                f"cache_layout must be 'dense' or 'paged', got "
                f"{cache_layout!r}")
        if cache_layout == "paged" and cfg.attention_window is not None:
            raise ValueError(
                "cache_layout='paged' has no sliding-window trim yet; "
                "serve windowed models with the dense layout")
        if role not in ("both", "prefill", "decode"):
            raise ValueError(
                f"role must be 'both', 'prefill', or 'decode', got "
                f"{role!r}")
        if role == "prefill" and not (cache_layout == "paged"
                                      and chunked_prefill
                                      and decode_mode == "plain"):
            raise ValueError(
                "role='prefill' needs cache_layout='paged' with chunked "
                "prefill under plain decode: the handoff exports pool "
                "pages at the chunked-admission finish")
        if role == "decode" and cache_layout != "paged":
            raise ValueError(
                "role='decode' needs cache_layout='paged': handoff "
                "adoption scatters migrated pages into the block pool")
        if preempt not in ("degrade", "migrate"):
            raise ValueError(
                f"preempt must be 'degrade' or 'migrate', got "
                f"{preempt!r}")
        if preempt == "migrate" and cache_layout != "paged":
            raise ValueError(
                "preempt='migrate' needs cache_layout='paged': "
                "preemption exports the victim slot's pool pages")
        # pressure policy: 'degrade' clamps best-effort budgets under a
        # degrade_queue breach (the PR 10 ladder); 'migrate' PAUSES them
        # instead — the victim slot's KV pages export to the host tier
        # (or a dict park) and re-adopt when pressure clears, so
        # best-effort output is byte-identical to the undisturbed run.
        # 'migrate' also makes admission priority-first and lets the
        # worker evacuate in-flight work at drain/swap time.
        self.preempt = preempt
        self.role = role
        self.cfg = cfg
        self.params = params
        self.B = num_slots
        self.steps = steps_per_sync
        # mutable on purpose: benches flip the SAME instance between
        # synchronous (1) and pipelined runs, so both share executables
        self.pipeline_depth = pipeline_depth
        self.prefill_chunk = prefill_chunk
        self.pad_token = int(pad_token)
        self._stop = _stop_array(stop_tokens)
        self._stop_set = (set(np.asarray(self._stop).tolist())
                          if self._stop is not None else set())
        if decode_attention == "flash" and cfg.attention_window is not None:
            import warnings

            warnings.warn(
                "ServeLoop with a sliding-window model falls back to "
                "DENSE per-row attention (the per-row flash kernel has "
                "no window trim yet): every decode step streams the "
                "whole cache instead of ~window positions",
                stacklevel=2)
        self._select = _make_select(temperature, top_k, top_p)
        self._temperature = float(temperature)
        self._top_k, self._top_p = top_k, top_p
        self._key = key if key is not None else jax.random.key(0)
        if decode_mode not in ("plain", "speculative"):
            raise ValueError(
                f"decode_mode must be 'plain' or 'speculative', got "
                f"{decode_mode!r}")
        self.decode_mode = decode_mode
        if decode_mode == "speculative":
            if draft_cfg is None or draft_params is None:
                raise ValueError(
                    "decode_mode='speculative' needs draft_cfg and "
                    "draft_params")
            if auto_unstack:
                draft_cfg, draft_params = serving_layout(
                    draft_cfg, draft_params)
            if draft_cfg.scan_layers:
                raise ValueError(
                    "the draft needs the unrolled layout; pass the "
                    "scanned checkpoint with auto_unstack=True")
            if draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_cfg.vocab_size} != target "
                    f"vocab {cfg.vocab_size}")
            if draft_cfg.max_seq_len < cfg.max_seq_len:
                raise ValueError(
                    f"draft max_seq_len {draft_cfg.max_seq_len} < target "
                    f"{cfg.max_seq_len}: the draft cache mirrors each "
                    "lane's position, so it needs the same coverage")
            if isinstance(num_draft, int):
                if num_draft < 1:
                    raise ValueError(
                        f"num_draft must be >= 1, got {num_draft}")
                self._spec_ladder = (int(num_draft),)
            elif num_draft == "adaptive":
                self._spec_ladder = tuple(sorted(
                    int(x) for x in spec_ladder))
                if not self._spec_ladder or self._spec_ladder[0] < 1:
                    raise ValueError(
                        f"spec_ladder must hold K >= 1, got {spec_ladder}")
            else:
                raise ValueError(
                    f"num_draft must be an int or 'adaptive', got "
                    f"{num_draft!r}")
            self._k_max = self._spec_ladder[-1]
        else:
            self._spec_ladder = ()
            self._k_max = 0
        # SIDE-BUFFER mode (flash, no window): steps write a segment-
        # local buffer at a SCALAR index (XLA keeps those in place;
        # per-row-indexed main-cache writes measured +0.35 ms/step on the
        # 8-layer 8k model) and one per-segment merge scatters side ->
        # main.  Other configurations use the direct per-row writes.
        # the paged layout is sided UNCONDITIONALLY: the pool is frozen
        # within a segment (growth happens at dispatch boundaries), so
        # every in-segment token must stage in the side buffer.
        # Speculative mode needs K_max extra slots: the last round before
        # the emit count reaches steps_per_sync can still write a full
        # K+1-token verify chunk past the steps_per_sync-1 already kept.
        self.side = (steps_per_sync + self._k_max
                     if (decode_attention == "flash"
                         and cfg.attention_window is None)
                     or cache_layout == "paged" else 0)
        self.cache_layout = cache_layout
        if cache_layout == "paged":
            bs_ = int(kv_block_size)
            nb = (num_slots * -(-cfg.max_seq_len // bs_)
                  if kv_num_blocks is None else int(kv_num_blocks))
            self.kv_block_size, self.kv_num_blocks = bs_, nb
            # the host half: free list, per-slot block lists, and the
            # page table the compiled carry consumes (stamped at dispatch)
            self.pool = BlockPool(nb, bs_, num_slots, cfg.max_seq_len)
        else:
            self.kv_block_size = self.kv_num_blocks = 0
            self.pool = None
        # chunked-interleaved prefill: plain decode only (the
        # speculative admit fuses a draft prefill into the same dispatch
        # and keeps the one-shot path); prefix sharing additionally
        # needs the paged layout — shared blocks live in the pool
        self.chunked = bool(chunked_prefill) and decode_mode == "plain"
        self._prefix_cache = (
            PrefixCache(self.pool)
            if prefix_sharing and self.chunked and self.pool is not None
            else None)
        # the weights version the loop's CURRENT params correspond to;
        # stamps tier entries and pull-mode exports so KV computed
        # under one version can never be adopted under another (the
        # swap-point flush is the front door, the stamp the backstop)
        self.weights_version = 0
        # host-RAM spill tier (tier 2 of the KV hierarchy): prefix-
        # cache evictions land here instead of vanishing, keyed by the
        # same chain hashes — see tpudist.models.kv_tier.  Budgeted by
        # TPUDIST_KV_HOST_TIER_BYTES (0 disables).
        self._tier: HostTier | None = None
        if self._prefix_cache is not None:
            budget = tier_budget_from_env()
            if budget > 0:
                self._tier = HostTier(budget)
                self._prefix_cache.spill_hook = self._spill_block
        # recently admitted request prefix hashes (wire-opaque ints from
        # Request.prefix_hash), LRU-bounded — the replica's published
        # affinity summary (see prefix_summary)
        self._affinity_recent: dict[int, None] = {}
        # cumulative host-side tallies benches read as deltas (obs
        # counters also tick; this avoids registry round trips)
        self.prefix_stats = {"requests": 0, "hits": 0, "hit_tokens": 0,
                             "prompt_tokens": 0, "prefill_tokens": 0}
        # per-run (gap_seconds_per_token, tokens) samples, one per
        # drained decode segment — benches compute p99 inter-token
        # latency from these (reset at every run())
        self.intertoken_samples: list[tuple[float, int]] = []
        self._last_drain_t: float | None = None
        self.model = TransformerLM(cfg, decode=True,
                                   decode_attention=decode_attention,
                                   serve_side_slots=self.side,
                                   cache_layout=cache_layout,
                                   kv_num_blocks=self.kv_num_blocks,
                                   kv_block_size=self.kv_block_size)
        # admission prefill ALWAYS runs dense: it fills a fresh batch-1
        # scalar-index cache (contiguous chunked writes) and the insert
        # scatters that row into pages — prefilling straight into the
        # pool would need per-chunk page-table plumbing for zero gain
        # (the batch-1 cache is transient)
        self._prefill_model = (
            TransformerLM(cfg, decode=True,
                          decode_attention=decode_attention,
                          serve_side_slots=self.side)
            if cache_layout == "paged" else self.model)
        # the slot cache: blank, with VECTOR index leaves (one position
        # per slot) — this is what routes attention through the per-row
        # cache path — and, in sided mode, the side buffers materialized
        # EAGERLY (a lax.scan carry's structure cannot grow mid-scan)
        blank = _blank_cache(self.model, num_slots)
        self.cache = jax.tree.map(
            lambda leaf: (jnp.zeros((num_slots,), jnp.int32)
                          if leaf.ndim == 0 else leaf), blank)
        if self.side:
            self.cache = self._with_side_buffers(self.cache)
        self._blank1 = _blank_cache(self._prefill_model, 1)  # prefill cache
        if decode_mode == "speculative":
            self.draft_cfg = draft_cfg
            self.draft_params = draft_params
            # the draft decodes DENSE per-row: verify chunks and single
            # steps both go through the banded-mask path, so the CPU
            # bench pays ONE masked matmul per draft step, and its cache
            # is num_slots x draft_seq_len — small by construction
            self.draft_model = TransformerLM(draft_cfg, decode=True,
                                             decode_attention="dense")
            d_blank = _blank_cache(self.draft_model, num_slots)
            self.draft_cache = jax.tree.map(
                lambda leaf: (jnp.zeros((num_slots,), jnp.int32)
                              if leaf.ndim == 0 else leaf), d_blank)
            self._draft_blank1 = _blank_cache(self.draft_model, 1)
            self._spec_policy = (
                AdaptiveDraftPolicy(self._spec_ladder)
                if num_draft == "adaptive" else None)
            # per-K dispatch counts: the first dispatch at each K carries
            # its compile, so its timing is excluded from the cost model
            self._spec_uses: dict[int, int] = {}
        self._tok = jnp.full((num_slots,), self.pad_token, jnp.int32)
        self._active = jnp.zeros((num_slots,), bool)
        self._remaining = jnp.zeros((num_slots,), jnp.int32)
        # deferred first-from-prefill tokens, one lane per slot: admission
        # stamps it on device; the next segment's emits carry it to the
        # host as column 0 — so resolving a first token costs ZERO extra
        # transfers (a per-slot int() fetch measured one full tunnel RTT
        # per admission, ~0.1 s each on the dev tunnel)
        self._first = jnp.full((num_slots,), self.pad_token, jnp.int32)
        # obs handles cached once; recording on the serve loop is host
        # ints/floats only, never a device fetch
        self.max_queue = None if max_queue is None else int(max_queue)
        if degrade_queue is None and max_queue is not None:
            degrade_queue = max(1, max_queue // 2)
        if degrade_queue is not None and degrade_queue < 0:
            raise ValueError(
                f"degrade_queue must be >= 0, got {degrade_queue}")
        if degrade_max_new < 1:
            raise ValueError(
                f"degrade_max_new must be >= 1, got {degrade_max_new}")
        self.degrade_queue = (None if degrade_queue is None
                              else int(degrade_queue))
        self.degrade_max_new = int(degrade_max_new)
        self._degraded = False
        # deadline clock, swappable by tests (deterministic expiry
        # without real sleeps); production uses wall time because
        # Request.deadline_s crosses process boundaries via the router
        self._clock = time.time
        # drain-gated weight hot-swap (see request_swap): set by
        # request_swap, consumed by run() once the loop is fully drained
        self._pending_swap: dict | None = None
        self._obs_requests = obs.counter("serve/requests", unit="reqs")
        self._obs_tokens = obs.counter("serve/tokens", unit="tokens")
        # prefix-sharing accounting: prompt_tokens is every admitted
        # prompt position, prefill_tokens only the positions actually
        # recomputed (the suffix past the cached prefix) — their ratio
        # is the prefill work the cache saved
        self._obs_prompt_tokens = obs.counter("serve/prompt_tokens",
                                              unit="tokens")
        self._obs_prefill_tokens = obs.counter("serve/prefill_tokens",
                                               unit="tokens")
        self._obs_rejected = obs.counter("serve/rejected", unit="reqs")
        self._obs_timeouts = obs.counter("serve/timeouts", unit="reqs")
        # data-plane integrity: lanes the in-graph NaN/inf logit guard
        # froze, plus host-side token-range failures — either way the
        # request finishes reason="corrupt_segment" (the router's cue
        # to redispatch and strike this replica) instead of emitting
        # garbage as if it were output
        self._obs_corrupt = obs.counter("serve/corrupt_segments",
                                        unit="segments")
        # lifetime tokens drained to the host: the trip point for the
        # TPUDIST_FAULT_NAN_AFTER_TOKENS injection
        self._served_tokens = 0
        self._obs_segments = obs.counter("serve/segments", unit="segments")
        self._obs_queue = obs.gauge("serve/queue_depth", unit="reqs")
        self._obs_degraded = obs.gauge("serve/degraded", unit="bool")
        self._obs_degrade_clamped = obs.counter("serve/degrade_clamped",
                                                unit="reqs")
        self._obs_latency = obs.histogram("serve/request_latency", unit="s")
        # enqueue -> admit: how long requests sit behind busy lanes (and,
        # paged, behind a full block pool).  Sliding-window so the SLO
        # gate and the autoscaler react to the LAST minute, not the
        # process lifetime; <= 0 disables the window.
        wait_window = float(
            os.environ.get("TPUDIST_SERVE_WAIT_WINDOW_S", "60"))
        self._obs_queue_wait = obs.histogram(
            "serve/queue_wait_s", unit="s",
            window_s=wait_window if wait_window > 0 else None)
        # host_wait = time run() actually BLOCKS on a segment fetch (the
        # np.asarray tail not hidden by later segments' compute); depth
        # is the live in-flight segment count
        self._obs_host_wait = obs.histogram("serve/host_wait", unit="s")
        self._obs_depth = obs.gauge("serve/pipeline_depth", unit="segments")
        self._obs_swaps = obs.counter("serve/swaps", unit="swaps")
        self._obs_weights_version = obs.gauge("serve/weights_version",
                                              unit="version")
        # RTT-amortization observability: dispatches counts host round
        # trips, steps_per_dispatch is the tokens the last drained
        # dispatch generated — their ratio is the amortization factor
        # the router merges per replica
        self._obs_dispatches = obs.counter("serve/dispatches",
                                           unit="dispatches")
        self._obs_steps_per_dispatch = obs.gauge("serve/steps_per_dispatch",
                                                 unit="tokens")
        self._obs_spec_k = obs.gauge("serve/spec_k", unit="tokens")
        self._obs_spec_accept = obs.gauge("serve/spec_accept_rate",
                                          unit="ratio")
        # EMA of measured seconds per generated token (dispatch -> drain
        # wall time / tokens; an OVERestimate under pipelining, which
        # only clamps harder) — feeds the deadline-aware segment-length
        # clamp in _plan_steps.  Published as a gauge (and stamped into
        # segment events) so the offline fleet simulator and postmortem
        # bundles can read REAL service rates from recorded traces.
        self._step_ema: float | None = None
        self._obs_spt = obs.gauge(
            "serve/seconds_per_token", unit="s",
            help="EMA of realized seconds per generated token "
                 "(dispatch->drain wall / tokens; the replica's "
                 "service rate)")
        # donate every rebound carry: cache, tok, active, remaining, key
        # (argnums 2-4 and 6) mirror _admit_dev — their inputs are dead
        # the moment the segment returns replacements.  `first` (argnum 5)
        # is NOT donated: self._first persists across segments.
        self._segment = jax.jit(self._segment_impl,
                                donate_argnums=(1, 2, 3, 4, 6))
        # params is a jit ARGUMENT (a closure capture would lower the
        # whole parameter tree into the traced program — the HTTP-413 /
        # duplicated-constants hazard bench.py documents — and would pin
        # first-trace weights if self.params is ever rebound)
        self._admit_dev = jax.jit(self._admit_dev_impl,
                                  donate_argnums=(1, 2, 3, 4, 5),
                                  static_argnames=("true_chunk",))
        # standalone prefill, used by benchmarks to price admission's
        # device work without touching live state
        self._prefill_one = jax.jit(self._prefill_impl,
                                    static_argnames=("true_chunk",))
        if cache_layout == "paged":
            # disaggregated handoff adoption: one dispatch scatters the
            # migrated KV blocks into this pool's pages and stamps the
            # lane (the decode-side mirror of _admit_finish, minus any
            # prefill).  Compiled per distinct used-block count, which
            # max_blocks_per_slot bounds.
            self._adopt_dev = jax.jit(self._adopt_dev_impl,
                                      donate_argnums=(0, 1, 2, 3, 4))
            # tiered-KV install: scatter re-admitted (host-tier or
            # pull-mode) blocks into pool pages — the page-write half
            # of adoption with NO lane stamps, because the blocks land
            # as cached-idle prefix entries rather than a live slot.
            # Compiled per distinct block count, like _adopt_dev.
            self._install_dev = jax.jit(self._install_dev_impl,
                                        donate_argnums=(0,))
        # disaggregation accounting: adoptions took the migrated-KV
        # path; fallbacks re-prefilled because the payload was missing
        # or failed verification (both exact by construction — the
        # counters tell which path a request took)
        self._obs_adoptions = obs.counter("serve/adoptions", unit="reqs")
        self._obs_handoff_fallbacks = obs.counter(
            "serve/handoff_fallbacks", unit="reqs")
        # live-migration accounting (PR 19): preempted/resumed count the
        # LOCAL park/unpark cycle (priority preemption), migrated_out
        # counts reason="migrate" exports handed to the router
        # (rebalance + fast drain) — the fleet-level mirror lives on
        # router/migrations
        self._obs_preempted = obs.counter("serve/preempted", unit="reqs")
        self._obs_resumed = obs.counter("serve/resumed", unit="reqs")
        self._obs_migrated_out = obs.counter("serve/migrated_out",
                                             unit="reqs")
        # rid -> parked entry: the request, its original enqueue time,
        # and the exported payload — either whole ("payload") or with
        # its page bytes spilled per-block into the host tier ("keys",
        # "meta"); loss anywhere falls back to re-prefill, byte-exact
        self._parked: dict[str, dict] = {}
        # router-initiated migration intents, consumed by the run loop:
        # request keys to migrate out (rebalance) / evacuate-everything
        # (fast drain, fast swap)
        self._migrate_rids: set[str] = set()
        self._evacuate = False
        if self.chunked:
            # chunked admission's three dispatches: (a) gather a shared
            # prefix's pool blocks into the dense batch-1 prefill cache
            # (reads self.cache without donating — the segment chain
            # donates it later, which is fine sequentially), (b) ONE
            # prompt chunk per host-loop iteration (cache1 is NOT
            # donated: the first chunk may receive the shared _blank1
            # template), (c) the finish: insert + lane stamps, donating
            # the live carry exactly like _admit_dev
            self._gather_prefix = jax.jit(self._gather_prefix_impl)
            self._prefill_chunk = jax.jit(self._prefill_chunk_impl,
                                          static_argnames=("chunk",))
            self._admit_finish = jax.jit(self._admit_finish_impl,
                                         donate_argnums=(0, 1, 2, 3, 4))
        if decode_mode == "speculative":
            # num_draft is STATIC (the draft scan's length is a shape);
            # each ladder K compiles once.  first (argnum 7) is NOT
            # donated, as in the plain segment.
            self._segment_spec = jax.jit(
                self._segment_spec_impl,
                donate_argnums=(2, 3, 4, 5, 6, 8),
                static_argnames=("num_draft",))
            self._admit_dev_spec = jax.jit(
                self._admit_dev_spec_impl,
                donate_argnums=(2, 3, 4, 5, 6, 7),
                static_argnames=("true_chunk",))

    def _with_side_buffers(self, cache):
        def walk(node):
            if not isinstance(node, dict):
                return node
            out = {k: walk(v) for k, v in node.items()}
            if "cached_key" in out:
                # the cache (and therefore the side buffers) is PACKED
                # [B, S, Hkv*D] — see CausalSelfAttention._cached_attend
                b, _, flat = out["cached_key"].shape
                out["side_key"] = jnp.zeros(
                    (b, self.side, flat), out["cached_key"].dtype)
                out["side_value"] = jnp.zeros(
                    (b, self.side, flat), out["cached_value"].dtype)
                out["side_index"] = jnp.zeros((), jnp.int32)
            elif "paged_key" in out:
                # paged pool is [num_blocks, block, Hkv*D]; side buffers
                # are per-SLOT, so their batch is self.B, not the pool's
                flat = out["paged_key"].shape[2]
                out["side_key"] = jnp.zeros(
                    (self.B, self.side, flat), out["paged_key"].dtype)
                out["side_value"] = jnp.zeros(
                    (self.B, self.side, flat), out["paged_value"].dtype)
                out["side_index"] = jnp.zeros((), jnp.int32)
            return out
        return walk(cache)

    def _stamp_table(self) -> None:
        """Push the host allocator's page table into the device carry.
        Each layer gets a FRESH device array: the segment donates the
        whole cache, and one buffer shared across every layer's
        ``page_table`` leaf would be donated more than once."""
        tbl = self.pool.table

        def walk(node):
            if not isinstance(node, dict):
                return node
            out = {k: walk(v) for k, v in node.items()}
            if "page_table" in out:
                out["page_table"] = jnp.asarray(tbl)
            return out

        self.cache = walk(self.cache)

    # -- compiled pieces ---------------------------------------------------

    def _segment_impl(self, params, cache, tok, active, remaining, first,
                      key, n_steps, poison):
        """One fused multi-token segment: a ``lax.while_loop`` of up to
        ``n_steps`` decode ticks (``n_steps`` is a DYNAMIC arg — the
        deadline clamp in :meth:`_plan_steps` shortens segments without
        recompiling) that EXITS EARLY once every lane is frozen, so an
        almost-idle batch never pays full-length segments.  The emit
        buffer is fixed at ``steps_per_sync`` columns (pad-filled past
        ``n_steps``); the host slices to the dispatched length.

        ``poison`` (dynamic bool, normally False) NaN-floods the step's
        logits — the TPUDIST_FAULT_NAN_AFTER_TOKENS injection point,
        kept as a dynamic arg so fault runs reuse the clean executable.
        The integrity guard below it is always on: a lane whose logits
        go NaN/inf is frozen IN-GRAPH before its garbage token reaches
        the emit buffer, and reported in the per-lane ``corrupt``
        output so the host can finalize it ``corrupt_segment``."""
        stop_arr = self._stop
        pad = jnp.int32(self.pad_token)
        S = self.cfg.max_seq_len

        def cond(carry):
            return (carry[0] < n_steps) & jnp.any(carry[3])

        def step(carry):
            i, cache, tok, active, remaining, lived, corrupt, key, E = carry
            main_idx, side_idx = _index_leaves(cache)
            pos = main_idx if side_idx is None else main_idx + side_idx
            pos = jnp.minimum(pos, S - 1)
            # a row active at step ENTRY writes a real token's K/V this
            # step — the merge later scatters exactly these side slots
            lived = lived + active.astype(jnp.int32)
            logits, mut = self.model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                positions=pos[:, None], mutable=["cache"])
            last = logits[:, -1]
            last = jnp.where(poison, jnp.full_like(last, jnp.nan), last)
            # integrity guard: freeze (not emit) lanes whose logits are
            # no longer finite — overflowed accumulator, scrambled KV
            # page, injected fault — so corruption surfaces as a
            # verdict instead of as plausible-looking tokens
            bad = active & ~jnp.all(jnp.isfinite(last), axis=-1)
            corrupt = corrupt | bad
            active = active & ~bad
            key, sk = jax.random.split(key)
            nxt = self._select(last, sk).astype(jnp.int32)
            emit = jnp.where(active, nxt, pad)
            E = lax.dynamic_update_slice(E, emit[:, None], (0, i))
            remaining = remaining - active.astype(jnp.int32)
            hit_stop = (jnp.isin(nxt, stop_arr)
                        if stop_arr is not None
                        else jnp.zeros_like(active))
            active = active & ~hit_stop & (remaining > 0)
            tok = jnp.where(active, nxt, pad)
            return (i + 1, mut["cache"], tok, active, remaining, lived,
                    corrupt, key, E)

        lived0 = jnp.zeros((self.B,), jnp.int32)
        corrupt0 = jnp.zeros((self.B,), bool)
        E0 = jnp.full((self.B, self.steps), pad, jnp.int32)
        (_, cache, tok, active, remaining, lived, corrupt, key,
         E) = lax.while_loop(
            cond, step,
            (jnp.int32(0), cache, tok, active, remaining, lived0,
             corrupt0, key, E0))
        if self.side:
            # side -> main merge INSIDE the segment executable: one
            # dispatch per wave instead of two (each dispatch costs
            # multiple ms through the dev tunnel), and XLA can overlap
            # the merge with the tail of the loop
            cache = self._merge_impl(cache, lived)
        # column 0 carries the admission-deferred first tokens so ONE
        # host fetch resolves them together with the segment's emits
        emits = jnp.concatenate([first[:, None], E], axis=1)
        return cache, tok, active, remaining, key, emits, corrupt

    def _prefill_impl(self, params, prompt_padded, true_len, key,
                      *, true_chunk):
        """Chunked prefill of ONE prompt into a fresh batch-1 cache;
        returns the cache (index stamped to the TRUE length — padded
        positions hold garbage that masking hides and decode overwrites)
        and the first generated token."""
        cache, logits = _prefill(self._prefill_model, params, self._blank1,
                                 prompt_padded, true_chunk)
        cache = _set_cache_index(cache, true_len)
        last = logits[0, true_len - 1 - (prompt_padded.shape[1]
                                         - logits.shape[1])]
        first = self._select(last[None, :], key)[0].astype(jnp.int32)
        return cache, first

    def _insert_impl(self, cache, cache1, slot, true_len, pages,
                     write_block=0):
        """Scatter the prefilled batch-1 cache into slot ``slot`` —
        matched BY NAME because the slot cache carries side buffers the
        prefill cache does not (they are left untouched: side_index is 0
        between segments and stale side rows are masked).  Paged nodes
        are intercepted whole: the prefill cache is always DENSE and its
        row is re-blocked into the slot's pages.  ``write_block`` skips
        the scatter below that block index — a shared-prefix admission
        must not rewrite blocks other slots alias (its page row still
        maps them; only the suffix's private blocks take writes)."""
        def walk(big, small):
            if not isinstance(big, dict):
                if big.ndim == 1:      # cache_index vector <- true length
                    return big.at[slot].set(true_len)
                return big.at[slot].set(small[0])
            if "paged_key" in big:
                return self._insert_paged_node(
                    big, small, slot, true_len, pages, write_block)
            return {k: (walk(v, small[k]) if k in small else v)
                    for k, v in big.items()}
        return walk(cache, cache1)

    def _insert_paged_node(self, big, small, slot, true_len, pages,
                           write_block=0):
        """Scatter one layer's dense batch-1 prefill row into the block
        pool through the slot's page row: the ``[S, F]`` row reshapes to
        ``[M, block, F]`` blocks and lands at pool indices ``pages``;
        blocks past the prompt's coverage — and below ``write_block``
        (shared-prefix blocks owned by the cache) — target the
        (out-of-range) index ``num_blocks`` and are DROPPED — only this
        admission's own allocated pages are written, so no live or
        cached block of another owner can be hit."""
        out = dict(big)
        bs = self.kv_block_size
        m = pages.shape[0]
        n_pool = big["paged_key"].shape[0]
        covered = ((jnp.arange(m) * bs < true_len)
                   & (jnp.arange(m) >= write_block))
        tgt = jnp.where(covered, pages, n_pool)
        for name, src in (("paged_key", "cached_key"),
                          ("paged_value", "cached_value")):
            row = small[src][0]                       # dense [S, F]
            pad = m * bs - row.shape[0]
            blocks = jnp.pad(row, ((0, pad), (0, 0))).reshape(m, bs, -1)
            out[name] = big[name].at[tgt].set(
                blocks.astype(big[name].dtype), mode="drop")
        out["page_table"] = big["page_table"].at[slot].set(pages)
        out["cache_index"] = big["cache_index"].at[slot].set(true_len)
        return out

    def _admit_dev_impl(self, params, cache, tok, active, remaining,
                        first_buf, prompt_padded, true_len, slot, max_new,
                        pages, key, *, true_chunk):
        """The WHOLE of admission's device work — chunked prefill of the
        prompt into a fresh batch-1 cache, insertion into the freed slot,
        and the slot's token/active/budget lane stamps (plus the
        deferred-first lane the next segment's emits carry home) — in
        ONE dispatch with no host sync.  The first token's stop check
        runs on device too: the host learns the token's value at the
        NEXT segment sync, by which time the prefill has long finished
        (chunked-prefill overlap: admission stalls the decode cadence by
        dispatch time only, not the prefill's round trip)."""
        cache1, first = self._prefill_impl(
            params, prompt_padded, true_len, key, true_chunk=true_chunk)
        cache = self._insert_impl(cache, cache1, slot, true_len, pages)
        tok = tok.at[slot].set(first)
        act = max_new > 1
        if self._stop is not None:
            act = act & ~jnp.isin(first, self._stop)
        active = active.at[slot].set(act)
        remaining = remaining.at[slot].set(max_new - 1)
        first_buf = first_buf.at[slot].set(first)
        return cache, tok, active, remaining, first_buf

    # -- chunked-interleaved admission (see chunked_prefill) ---------------

    def _gather_prefix_impl(self, cache, blank1, pages):
        """Build a fresh batch-1 dense prefill cache whose leading rows
        hold a shared prefix's KV gathered from pool blocks ``pages``
        (the slot's full padded page row).  Rows past the prefix carry
        whatever lives in the referenced blocks — suffix chunks
        overwrite the covered span and attention never reads past the
        write cursor, so the garbage is unreachable.  KV bytes come
        straight from the original admission's prefill, which is what
        makes a cache-hit admission bitwise-identical to recomputing."""
        def walk(big, small):
            if not isinstance(small, dict):
                return small
            if "cached_key" in small and "paged_key" in big:
                out = dict(small)
                for pname, dname in (("paged_key", "cached_key"),
                                     ("paged_value", "cached_value")):
                    rows = big[pname][pages]          # [M, bs, F]
                    flat = rows.reshape(-1, rows.shape[-1])
                    S = small[dname].shape[1]
                    flat = flat[:S]
                    if flat.shape[0] < S:
                        flat = jnp.pad(
                            flat, ((0, S - flat.shape[0]), (0, 0)))
                    out[dname] = flat[None].astype(small[dname].dtype)
                return out
            return {k: (walk(big[k], v) if k in big else v)
                    for k, v in small.items()}
        return walk(cache, blank1)

    def _prefill_chunk_impl(self, params, cache1, toks, off, *, chunk):
        """ONE prompt chunk through the scalar-index prefill path:
        write cursor forced to ``off`` (dynamic — every chunk of a given
        width shares one executable), positions ``off + [0, chunk)``.
        The chunk grid matches :func:`_prefill`'s exactly (same widths
        at the same offsets), so the per-chunk dispatches produce
        bitwise the same cache and logits as the fused one-shot path —
        chunking changes WHEN prefill work runs, never its result."""
        cache1 = _set_cache_index(cache1, off)
        logits, mut = self._prefill_model.apply(
            {"params": params, "cache": cache1}, toks,
            positions=off + jnp.arange(chunk)[None, :], mutable=["cache"])
        return mut["cache"], logits

    def _admit_finish_impl(self, cache, tok, active, remaining, first_buf,
                           cache1, logits, off, true_len, slot, max_new,
                           pages, write_block, key):
        """The tail of a chunked admission, one dispatch: insert the
        prefilled batch-1 cache into the slot (skipping shared blocks
        below ``write_block``), sample the deferred first token from the
        LAST chunk's logits (position ``true_len - 1`` lives at row
        ``true_len - 1 - off`` of that chunk), stamp the lane."""
        cache1 = _set_cache_index(cache1, true_len)
        cache = self._insert_impl(cache, cache1, slot, true_len, pages,
                                  write_block=write_block)
        last = lax.dynamic_index_in_dim(
            logits[0], true_len - 1 - off, keepdims=False)
        first = self._select(last[None, :], key)[0].astype(jnp.int32)
        tok = tok.at[slot].set(first)
        act = max_new > 1
        if self._stop is not None:
            act = act & ~jnp.isin(first, self._stop)
        active = active.at[slot].set(act)
        remaining = remaining.at[slot].set(max_new - 1)
        first_buf = first_buf.at[slot].set(first)
        return cache, tok, active, remaining, first_buf

    def _adopt_dev_impl(self, cache, tok, active, remaining, first_buf,
                        kv, pages_used, full_row, true_len, slot,
                        max_new, first):
        """Adopt a MIGRATED prefill into ``slot``, one dispatch: the
        handoff's per-layer KV blocks scatter into this pool's freshly
        allocated pages and the lane stamps mirror
        :meth:`_admit_finish_impl`'s tail exactly — except ``first`` is
        the token the EXPORTER sampled (carried in the payload), not a
        local selection, so no prefill runs here at all.  ``kv`` walks
        the cache's paged nodes in natural dict order, the SAME order
        :meth:`_paged_nodes` exported them in: every replica builds an
        identical cache structure from the same model code, so index
        ``i`` here names the layer index ``i`` named there."""
        i = 0

        def walk(node):
            nonlocal i
            if not isinstance(node, dict):
                return node
            if "paged_key" in node:
                k, v = kv[i]
                i += 1
                out = dict(node)
                out["paged_key"] = node["paged_key"].at[pages_used].set(
                    k.astype(node["paged_key"].dtype))
                out["paged_value"] = (
                    node["paged_value"].at[pages_used].set(
                        v.astype(node["paged_value"].dtype)))
                out["page_table"] = (
                    node["page_table"].at[slot].set(full_row))
                out["cache_index"] = (
                    node["cache_index"].at[slot].set(true_len))
                return out
            return {key: walk(val) for key, val in node.items()}

        cache = walk(cache)
        tok = tok.at[slot].set(first)
        act = max_new > 1
        if self._stop is not None:
            act = act & ~jnp.isin(first, self._stop)
        active = active.at[slot].set(act)
        remaining = remaining.at[slot].set(max_new - 1)
        first_buf = first_buf.at[slot].set(first)
        return cache, tok, active, remaining, first_buf

    def _install_dev_impl(self, cache, kv, pages):
        """Scatter re-admitted KV blocks into pool pages ``pages`` —
        the page-write half of :meth:`_adopt_dev_impl` only: no page
        table, no cache index, no lane stamps.  The blocks become
        cached-idle prefix-cache entries (pinned, refcount 0); the
        admission that matches them aliases them in via the ordinary
        ``share`` path, which is what makes a tier re-admit or a peer
        pull byte-identical to having kept the pages in HBM all
        along."""
        i = 0

        def walk(node):
            nonlocal i
            if not isinstance(node, dict):
                return node
            if "paged_key" in node:
                k, v = kv[i]
                i += 1
                out = dict(node)
                out["paged_key"] = node["paged_key"].at[pages].set(
                    k.astype(node["paged_key"].dtype))
                out["paged_value"] = (
                    node["paged_value"].at[pages].set(
                        v.astype(node["paged_value"].dtype)))
                return out
            return {key: walk(val) for key, val in node.items()}

        return walk(cache)

    def _admit_dev_spec_impl(self, params, draft_params, cache, d_cache,
                             tok, active, remaining, first_buf,
                             prompt_padded, true_len, slot, max_new, pages,
                             key, *, true_chunk):
        """Speculative admission: the target's admit (prefill + insert +
        lane stamps) plus a DRAFT prefill of the same prompt inserted
        into the draft cache's matching slot — both in the same dispatch,
        still no host sync."""
        cache, tok, active, remaining, first_buf = self._admit_dev_impl(
            params, cache, tok, active, remaining, first_buf,
            prompt_padded, true_len, slot, max_new, pages, key,
            true_chunk=true_chunk)
        d1, _ = _prefill(self.draft_model, draft_params,
                         self._draft_blank1, prompt_padded, true_chunk)
        d1 = _set_cache_index(d1, true_len)
        d_cache = self._insert_impl(d_cache, d1, slot, true_len, _NO_PAGES)
        return cache, d_cache, tok, active, remaining, first_buf

    def _segment_spec_impl(self, params, draft_params, cache, d_cache,
                           tok, active, remaining, first, key, n_steps,
                           *, num_draft):
        """The speculative fused segment: rounds of draft-K proposal +
        one-chunk target verification (``lax.while_loop``) until at
        least ``n_steps`` tokens are emitted or every lane freezes.

        Each round mirrors :func:`speculative_generate.round_body`, made
        lane-aware:

        * the draft runs K+1 per-row single-token steps (the last writes
          d_K's K/V); the target verifies ``[tok, d_1..d_K]`` as ONE
          s=K+1 chunk through the per-row cache path;
        * frozen lanes are masked ALL-ACCEPT in ``_accept_and_next`` so
          they never drag the batch-min prefix down — their garbage
          emits are padded out and their K/V writes are dropped by the
          ``lived``-masked merge exactly as in the plain segment;
        * both caches ROLL BACK by ``K - m`` after the verify (the side
          counter in sided/paged layouts, per-row ``cache_index`` in the
          dense non-sided layout) so resident K/V tracks emitted tokens
          — the invariant the segment-boundary merge and the host's
          page-growth accounting both rely on;
        * a lane that hits a stop or exhausts its budget mid-round
          contributes ``min(stop_pos + 1, remaining)`` REAL tokens (the
          same count the host's drain rules will consume) to ``lived``
          and freezes.

        ``stats`` returns ``[emitted, rounds, accepted_sum,
        active_row_rounds]`` — the host feeds them to the adaptive-K
        policy and the ``serve/spec_*`` gauges."""
        stop_arr = self._stop
        pad = jnp.int32(self.pad_token)
        S = self.cfg.max_seq_len
        Sd = self.draft_cfg.max_seq_len
        k = num_draft
        # the last round can start at emitted == n_steps - 1 and still
        # append a full K+1 window, so the emit buffer needs K extra
        # columns past steps_per_sync
        cap_out = self.steps + k
        t_idx = jnp.arange(k + 1)

        def cond(carry):
            return (carry[0] < n_steps) & jnp.any(carry[5])

        def round_body(carry):
            (com, cache, d_cache, tok, active, remaining, lived, key, E,
             rounds, acc_sum, act_rounds) = carry
            main_idx, side_idx = _index_leaves(cache)
            n_pos = main_idx if side_idx is None else main_idx + side_idx
            key, dk, vk = jax.random.split(key, 3)

            # DRAFT: K single-token proposals with their distributions;
            # K+1 steps so the last writes d_K's K/V (the all-accepted
            # case needs it resident), its sampled output discarded
            def chain(chain_carry, step_key):
                d_cache, d_tok = chain_carry
                d_idx, _ = _index_leaves(d_cache)
                logits, mut = self.draft_model.apply(
                    {"params": draft_params, "cache": d_cache},
                    d_tok[:, None],
                    positions=jnp.minimum(d_idx, Sd - 1)[:, None],
                    mutable=["cache"])
                q_probs = _filtered_probs(
                    logits[:, -1], self._temperature, self._top_k,
                    self._top_p)
                nxt = self._select(logits[:, -1],
                                   step_key).astype(jnp.int32)
                return (mut["cache"], nxt), (nxt, q_probs)

            (d_cache2, _), (drafts_t, q_t) = lax.scan(
                chain, (d_cache, tok), jax.random.split(dk, k + 1))
            drafts = drafts_t[:k].T                            # [B, K]
            q = jnp.moveaxis(q_t[:k], 0, 1)                    # [B, K, V]

            # VERIFY: one target chunk over [tok, d_1..d_K] per lane
            verify = jnp.concatenate([tok[:, None], drafts], axis=1)
            positions = jnp.minimum(
                n_pos[:, None] + t_idx[None, :], S - 1)
            t_logits, mut = self.model.apply(
                {"params": params, "cache": cache}, verify,
                positions=positions, mutable=["cache"])
            p = _filtered_probs(t_logits, self._temperature, self._top_k,
                                self._top_p)
            m, emit, accepted = _accept_and_next(p, q, drafts, vk,
                                                 active=active)
            names = {"side_index"} if self.side else {"cache_index"}
            cache2 = _shift_index_leaves(mut["cache"], k - m, names)
            d_cache2 = _shift_index_leaves(d_cache2, k - m,
                                           {"cache_index"})

            # the round's emit window: accepted drafts then the verify
            # token at column m (columns past m are garbage the host's
            # slice/stop rules never consume)
            e_buf = jnp.concatenate([drafts, emit[:, None]], axis=1)
            e_buf = lax.dynamic_update_slice(e_buf, emit[:, None], (0, m))
            hit = (jnp.isin(e_buf, stop_arr) if stop_arr is not None
                   else jnp.zeros(e_buf.shape, bool))
            stop_pos = jnp.min(
                jnp.where(hit & (t_idx[None, :] <= m), t_idx[None, :],
                          m + 1), axis=1)                      # [B]
            no_stop = stop_pos >= m + 1
            # REAL tokens this round = what the host's drain will
            # consume: up to the stop (inclusive), capped by budget and
            # the batch-min window — identical to plain-mode `lived`
            real = jnp.where(
                active,
                jnp.minimum(jnp.minimum(stop_pos + 1, remaining), m + 1),
                0)
            lived = lived + real
            remaining = remaining - real
            active2 = active & no_stop & (remaining > 0)
            cols = jnp.where(t_idx <= m, com + t_idx, cap_out)
            E = E.at[:, cols].set(
                jnp.where(active[:, None], e_buf, pad), mode="drop")
            tok2 = jnp.where(active2, emit, pad)
            return (com + m + 1, cache2, d_cache2, tok2, active2,
                    remaining, lived, key, E, rounds + 1,
                    acc_sum + jnp.sum(jnp.where(active, accepted, 0)),
                    act_rounds + jnp.sum(active.astype(jnp.int32)))

        lived0 = jnp.zeros((self.B,), jnp.int32)
        E0 = jnp.full((self.B, cap_out), pad, jnp.int32)
        (com, cache, d_cache, tok, active, remaining, lived, key, E,
         rounds, acc_sum, act_rounds) = lax.while_loop(
            cond, round_body,
            (jnp.int32(0), cache, d_cache, tok, active, remaining,
             lived0, key, E0, jnp.int32(0), jnp.int32(0), jnp.int32(0)))
        if self.side:
            cache = self._merge_impl(cache, lived)
        emits = jnp.concatenate([first[:, None], E], axis=1)
        stats = jnp.stack([com, rounds, acc_sum, act_rounds])
        return cache, d_cache, tok, active, remaining, key, emits, stats

    def _merge_impl(self, cache, lived):
        """End-of-segment: scatter each layer's side buffer into the main
        cache at every row's own offset (per-row-index writes, but ONCE
        per segment instead of once per step), advance the per-row
        lengths, reset the side counter.

        ``lived`` is the per-row count of REAL side tokens (steps the row
        entered active); the merge is masked to exactly those slots and
        the length advance uses it too, so a frozen row's garbage side
        writes never land in the main cache and its length never drifts —
        local correctness, not a host-loop invariant.  Near the cache end
        the cap-aligned write window shifts below ``idx[r]``; the side
        row is re-aligned by ``sh`` so live token ``t`` still lands at
        global position ``idx[r] + t`` and everything below ``idx[r]``
        rewrites the main cache's own (sliced-out) values."""
        B = self.B

        def walk(node):
            if not isinstance(node, dict):
                return node
            out = {k: walk(v) for k, v in node.items()}
            if "paged_key" in out:
                return self._merge_paged_node(out, lived)
            if "side_key" in out:
                idx = out["cache_index"]
                S = out["cached_key"].shape[1]
                cap = out["side_key"].shape[1]
                p = jnp.arange(cap)
                for name, side_name in (("cached_key", "side_key"),
                                        ("cached_value", "side_value")):
                    main = out[name]                 # packed [B, S, F]
                    side = out[side_name]            # packed [B, cap, F]
                    for r in range(B):
                        start = jnp.minimum(idx[r], S - cap)
                        sh = idx[r] - start          # 0 unless near S
                        src = p - sh
                        cur = jax.lax.dynamic_slice(
                            main, (r, start, 0), (1, cap, main.shape[2]))
                        live = ((src >= 0) & (src < lived[r]))[
                            None, :, None]
                        shifted = side[r][jnp.clip(src, 0, cap - 1)][None]
                        merged = jnp.where(
                            live, shifted.astype(main.dtype), cur)
                        main = jax.lax.dynamic_update_slice(
                            main, merged, (r, start, 0))
                    out[name] = main
                out["cache_index"] = jnp.minimum(idx + lived, S)
                out["side_index"] = jnp.zeros((), jnp.int32)
            return out
        return walk(cache)

    def _merge_paged_node(self, out, lived):
        """End-of-segment side -> POOL merge for one paged layer: row
        ``r``'s live side token ``t`` lands at logical position
        ``idx[r] + t``, i.e. pool block ``table[r, pos // block]`` offset
        ``pos % block`` — a single two-axis scatter per buffer (unlike
        the dense merge there is no contiguous window to dynamic-slice;
        pages are scattered by construction).  Dead entries (frozen rows
        past ``lived``, positions past ``max_seq_len``) are redirected to
        the out-of-range pool index and DROPPED, so a frozen row's
        garbage side writes never reach a block — including blocks that
        the host has freed and re-admitted to another slot while this
        segment was in flight (the pipelined-staleness hazard)."""
        idx = out["cache_index"]                   # [B] main lengths
        tbl = out["page_table"]                    # [B, M]
        bs = self.kv_block_size
        S = self.cfg.max_seq_len
        n_pool = out["paged_key"].shape[0]
        cap = out["side_key"].shape[1]
        m = tbl.shape[1]
        t = jnp.arange(cap)[None, :]               # [1, cap]
        pos = idx[:, None] + t                     # [B, cap] logical
        live = (t < lived[:, None]) & (pos < S)
        blk = jnp.minimum(pos // bs, m - 1)
        page = jnp.take_along_axis(tbl, blk, axis=1)
        page = jnp.where(live, page, n_pool).reshape(-1)
        off = (pos % bs).reshape(-1)
        for name, side_name in (("paged_key", "side_key"),
                                ("paged_value", "side_value")):
            vals = out[side_name].astype(out[name].dtype)
            out[name] = out[name].at[page, off].set(
                vals.reshape(-1, vals.shape[2]), mode="drop")
        out["cache_index"] = jnp.minimum(idx + lived, S)
        out["side_index"] = jnp.zeros((), jnp.int32)
        return out

    # -- the host loop -----------------------------------------------------

    def _validate(self, req: Request) -> None:
        prompt = np.asarray(req.prompt)
        if prompt.ndim != 1 or prompt.size < 1:
            raise ValueError("request prompt must be a non-empty 1-D "
                             "token array")
        if not np.issubdtype(prompt.dtype, np.integer):
            raise ValueError(
                f"request prompt must be integer token ids, got dtype "
                f"{prompt.dtype} (_admit's int32 cast would silently "
                "truncate float values)")
        if req.max_new_tokens < 1:
            raise ValueError("request max_new_tokens must be >= 1")
        if prompt.size + req.max_new_tokens > self.cfg.max_seq_len:
            raise ValueError(
                f"request needs {prompt.size + req.max_new_tokens} cache "
                f"slots > max_seq_len {self.cfg.max_seq_len}")
        if (self.decode_mode == "speculative"
                and prompt.size + req.max_new_tokens + self._k_max - 1
                > self.cfg.max_seq_len):
            raise ValueError(
                f"speculative serving needs prompt + max_new + "
                f"num_draft - 1 <= max_seq_len "
                f"({prompt.size + req.max_new_tokens + self._k_max - 1} "
                f"> {self.cfg.max_seq_len}): the verify chunk writes up "
                "to num_draft slots past the last emitted token")
        if self.pool is not None:
            need = self.pool.request_blocks(prompt.size, req.max_new_tokens)
            if need > self.pool.num_blocks:
                raise ValueError(
                    f"request reserves {need} KV blocks > pool capacity "
                    f"{self.pool.num_blocks}; it could never be admitted "
                    "(raise kv_num_blocks or shrink the request)")

    def _prefix_plan(self, prompt: np.ndarray,
                     L: int) -> tuple[list[int], int, bool]:
        """Match ``prompt`` against the prefix cache: returns
        ``(shared_blocks, suffix_start, cow)``.  ``suffix_start`` is the
        first position prefill must actually compute; a FULL-prompt hit
        still recomputes position ``L - 1`` (the first output logit has
        to come from somewhere) and that write lands in the last shared
        block — the ``cow`` split.

        With a host tier, the chain walk CONTINUES past the HBM-resident
        run: spilled blocks extending the match are re-admitted (host ->
        HBM scatter into freshly pinned cached-idle pages) and aliased
        exactly like blocks that never left."""
        blocks = self._prefix_cache.match(prompt)
        if self._tier is not None and len(self._tier):
            chain = chain_hashes(prompt, self.kv_block_size)
            blocks = blocks + self._readmit_tiered(chain, len(blocks))
        if not blocks:
            return [], 0, False
        matched = len(blocks) * self.kv_block_size
        if matched >= L:
            return blocks, L - 1, True
        return blocks, matched, False

    def prefix_summary(self, limit: int = 64) -> list[int]:
        """Most recently admitted ``Request.prefix_hash`` values while
        prefix sharing is on — the replica's published affinity summary
        (the router steers matching requests here).  Empty when sharing
        is off: never advertise affinity this loop cannot honor."""
        return list(self._affinity_recent)[-limit:]

    def flush_prefix_cache(self) -> None:
        """Drop every cached prefix (idle blocks return to the free
        list) AND every host-tier entry.  Called automatically at
        weight hot-swaps — spilled KV is exactly as stale as resident
        KV — and by benches before asserting fully drained pool and
        tier."""
        if self._prefix_cache is not None:
            self._prefix_cache.flush()
        if self._tier is not None:
            self._tier.flush()
        self._affinity_recent.clear()

    # -- tiered KV memory (see tpudist.models.kv_tier) ---------------------

    def _spill_block(self, h: int, blk: int, parent: int | None) -> None:
        """PrefixCache spill hook: copy an evicted idle block's page
        bytes to the host tier before its pin (and page) drop.  The
        block is refcount-0 and still pinned here, so the bytes are
        stable; the ``np.asarray`` gather syncs the device — an
        eviction is already a capacity-pressure event, so the stall
        buys keeping a prefix instead of losing it."""
        layers = [{"k": np.asarray(node["paged_key"][blk]),
                   "v": np.asarray(node["paged_value"][blk])}
                  for node in self._paged_nodes(self.cache)]
        self._tier.put(h, layers, parent=parent,
                       version=self.weights_version)

    def _readmit_tiered(self, chain: list[int], start: int) -> list[int]:
        """Re-admit the longest run of tiered blocks extending a local
        chain match at index ``start``: take each entry (version-
        checked), land it in a freshly pinned cached-idle page, and
        index it back into the prefix cache.  Allocation never evicts —
        paging one cached block in must not page another out — so when
        only reclaimable-cached capacity is left the walk stops and the
        suffix re-prefills.  Returns the installed pool blocks, in
        chain order."""
        taken: list[tuple[int, int | None, int, list]] = []
        j = start
        while j < len(chain):
            if not self._tier.has(chain[j], version=self.weights_version):
                break
            blk = self.pool.alloc_cached_block()
            if blk is None:
                break
            layers = self._tier.take(chain[j],
                                     version=self.weights_version)
            if layers is None:   # unreachable after has(); stay safe
                self.pool.cache_unpin(blk)
                break
            taken.append((chain[j], chain[j - 1] if j else None,
                          blk, layers))
            j += 1
        self._scatter_install(taken)
        return [t[2] for t in taken]

    def _scatter_install(self,
                         taken: list[tuple[int, int | None, int, list]]
                         ) -> int:
        """One ``_install_dev`` dispatch landing ``taken``'s block
        bytes (``(hash, parent, pool_block, layers)`` each) into their
        pages, then the cache-index installs — host-ordered AFTER the
        scatter, so any later match's gather reads the written pages
        (the same ordering argument as register-after-insert)."""
        if not taken:
            return 0
        nodes = self._paged_nodes(self.cache)
        kv = tuple(
            (jnp.asarray(np.stack([np.asarray(t[3][li]["k"])
                                   for t in taken])),
             jnp.asarray(np.stack([np.asarray(t[3][li]["v"])
                                   for t in taken])))
            for li in range(len(nodes)))
        pages = jnp.asarray(
            np.asarray([t[2] for t in taken], np.int32))
        self.cache = self._install_dev(self.cache, kv, pages)
        for h, parent, blk, _ in taken:
            self._prefix_cache.install(h, blk, parent)
        return len(taken)

    def prefix_residency(self, limit: int = 256) -> dict:
        """Resident prefix chain hashes for the fleet directory:
        ``{"chains": [...], "tiered": [...]}`` — HBM prefix-cache
        entries plus host-tier entries (``tiered`` is the subset that
        lives in the tier), most-recently-used last, bounded."""
        if self._prefix_cache is None:
            return {"chains": [], "tiered": []}
        hbm = list(self._prefix_cache._entries)
        tiered = self._tier.hashes() if self._tier is not None else []
        chains = (hbm + tiered)[-int(limit):]
        tset = set(tiered)
        return {"chains": chains,
                "tiered": [h for h in chains if h in tset]}

    def export_prefix(self, chain: Sequence[int]) -> dict | None:
        """Pull-mode owner half: serialize the longest leading run of
        ``chain`` resident here — HBM prefix-cache pages gathered from
        the device, host-tier entries read in place (no removal: the
        export is a COPY, local hits keep working) — as a migration-
        style payload a peer installs via :meth:`install_prefix`.
        ``None`` when the leading link is not resident (the directory
        was stale; the requester just re-prefills)."""
        if self._prefix_cache is None or self.pool is None:
            return None
        chain = [int(h) for h in chain]
        hbm_blocks: list[int] = []
        for h in chain:
            blk = self._prefix_cache._entries.get(h)
            if blk is None:
                break
            hbm_blocks.append(blk)
        tier_layers: list[list] = []
        if self._tier is not None:
            while len(hbm_blocks) + len(tier_layers) < len(chain):
                layers = self._tier.peek_layers(
                    chain[len(hbm_blocks) + len(tier_layers)],
                    version=self.weights_version)
                if layers is None:
                    break
                tier_layers.append(layers)
        n = len(hbm_blocks) + len(tier_layers)
        if not n:
            return None
        nodes = self._paged_nodes(self.cache)
        pages = np.asarray(hbm_blocks, np.int32)
        layers_out = []
        for li, node in enumerate(nodes):
            ks, vs = [], []
            if hbm_blocks:
                ks.append(np.asarray(node["paged_key"][pages]))
                vs.append(np.asarray(node["paged_value"][pages]))
            for tl in tier_layers:
                ks.append(np.asarray(tl[li]["k"])[None])
                vs.append(np.asarray(tl[li]["v"])[None])
            layers_out.append({"k": np.concatenate(ks, axis=0),
                               "v": np.concatenate(vs, axis=0)})
        return {
            "key": None,      # stamped by the worker at publish
            "rid": None,
            "prompt": [],     # pull payloads carry no fallback prompt:
                              # the REQUESTER holds the real request
            "chain": chain[:n],
            "block_size": int(self.kv_block_size),
            "version": int(self.weights_version),
            "published_at": time.time(),
            "layers": layers_out,
        }

    def install_prefix(self, prompt, payload: dict) -> int:
        """Pull-mode requester half: verify a peer-exported prefix
        payload against ``prompt``'s OWN chain (recomputed locally —
        the peer is never trusted), the loop's block size, and the
        CURRENT weights version, then land its blocks as cached-idle
        prefix entries so the admission that follows hits locally and
        prefills only the suffix.  Any gate failing installs nothing
        and returns 0 — the ordinary prefill is the byte-identical
        fallback.  Returns the number of blocks installed."""
        if self._prefix_cache is None or self.pool is None:
            return 0
        try:
            bs = int(payload["block_size"])
            version = int(payload.get("version", -1))
            chain = [int(h) for h in payload["chain"]]
            layers = payload["layers"]
        except (KeyError, TypeError, ValueError):
            return 0
        nodes = self._paged_nodes(self.cache)
        prompt = np.asarray(prompt, np.int32)
        want = chain_hashes(prompt, self.kv_block_size)
        n = len(chain)
        if (bs != self.kv_block_size
                or version != self.weights_version
                or not n or n > len(want) or chain != want[:n]
                or not isinstance(layers, (list, tuple))
                or len(layers) != len(nodes)):
            return 0
        arrs = []
        for l in layers:
            try:
                k = np.asarray(l["k"])
                v = np.asarray(l["v"])
            except (KeyError, TypeError, ValueError):
                return 0
            if (k.ndim != 3 or k.shape[0] != n or k.shape[1] != bs
                    or v.shape != k.shape):
                return 0
            arrs.append((k, v))
        taken: list[tuple[int, int | None, int, list]] = []
        try:
            for j in range(n):
                if chain[j] in self._prefix_cache._entries:
                    continue   # local copy wins (first-wins install)
                blk = self.pool.alloc_cached_block()
                if blk is None:
                    break
                taken.append((chain[j],
                              want[j - 1] if j else None, blk,
                              [{"k": arrs[li][0][j], "v": arrs[li][1][j]}
                               for li in range(len(nodes))]))
            installed = self._scatter_install(taken)
            if self._tier is not None:
                # a pulled link that was ALSO spilled locally is now
                # HBM-resident: drop the tier copy (disjointness rule)
                for h, _, _, _ in taken:
                    self._tier.discard(h)
            return installed
        except Exception:
            # a half-taken install must not leak pinned pages: undo the
            # allocations that never reached the cache index
            for _, _, blk, _ in taken:
                if blk not in self._prefix_cache._entries.values():
                    self.pool.cache_unpin(blk)
            raise

    def tier_drained(self) -> bool | None:
        """Tier invariants + emptiness — the exit-report / bench drain
        gate (``None`` when no tier exists).  Runs the cross-structure
        check: no hash simultaneously tiered and HBM-resident."""
        if self._tier is None:
            return None
        resident = (self._prefix_cache._entries.keys()
                    if self._prefix_cache is not None else ())
        self._tier.check(resident)
        return len(self._tier) == 0

    def _admit(self, slot: int, req: Request) -> dict:
        """Admit ``req`` into ``slot`` WITHOUT a host sync: the prefill
        and the state stamp are dispatched; the first token stays a
        device scalar until the next segment sync resolves it (by which
        point the decode segment has already hidden the prefill).

        With ``chunked_prefill`` the prefill is NOT dispatched here:
        admission allocates (and prefix-aliases) pool blocks, stages a
        batch-1 prefill cache, and returns a slot state carrying a
        ``prefill`` phase — the run loop dispatches one prompt chunk
        per iteration between decode segments and finishes with the
        insert + lane stamps (see ``advance_admissions``)."""
        self._validate(req)
        prompt = np.asarray(req.prompt, np.int32)
        L = int(prompt.size)
        self.prefix_stats["requests"] += 1
        self.prefix_stats["prompt_tokens"] += L
        self._obs_prompt_tokens.inc(L)
        if req.prefix_hash is not None and self._prefix_cache is not None:
            self._affinity_recent.pop(int(req.prefix_hash), None)
            self._affinity_recent[int(req.prefix_hash)] = None
            while len(self._affinity_recent) > 128:
                self._affinity_recent.pop(
                    next(iter(self._affinity_recent)))
        if (req.kv_handoff is not None and self.pool is not None
                and self.role != "prefill"):
            # disaggregated decode stage: adopt the migrated pages —
            # zero prefill compute — unless the payload fails
            # verification, in which case fall THROUGH to an ordinary
            # admission of the same prompt (greedy + fleet-identical
            # weights make the re-prefill output byte-identical, so the
            # fallback trades only latency)
            st = self._admit_adopt(slot, req, prompt, L)
            if st is not None:
                return st
            self._obs_handoff_fallbacks.inc()
        if self.chunked:
            return self._admit_start(slot, req, prompt, L)
        self.prefix_stats["prefill_tokens"] += L
        self._obs_prefill_tokens.inc(L)
        if self.pool is not None:
            # allocate-on-admit: pages covering the prompt now, the rest
            # of the worst-case footprint RESERVED (growth at dispatch
            # boundaries draws on the reservation and can never fail —
            # required: the pipelined host learns stops a segment late
            # and keeps growing blindly until the finalize lands)
            self.pool.admit(slot, L, int(req.max_new_tokens))
            pages = jnp.asarray(self.pool.table[slot])
        else:
            pages = _NO_PAGES
        chunk = min(self.prefill_chunk, self.cfg.max_seq_len)
        # pad to a chunk multiple, CAPPED at the cache size: an uncapped
        # pad past max_seq_len would make the final chunk's
        # dynamic_update_slice clamp backwards and overwrite real prompt
        # positions (observed: silently corrupted completions)
        Lp = min(-(-L // chunk) * chunk, self.cfg.max_seq_len)
        padded = np.full((1, Lp), self.pad_token, np.int32)
        padded[0, :L] = prompt
        self._key, pk = jax.random.split(self._key)
        if self.decode_mode == "speculative":
            (self.cache, self.draft_cache, self._tok, self._active,
             self._remaining, self._first) = self._admit_dev_spec(
                self.params, self.draft_params, self.cache,
                self.draft_cache, self._tok, self._active,
                self._remaining, self._first, padded, np.int32(L),
                np.int32(slot), np.int32(req.max_new_tokens), pages, pk,
                true_chunk=chunk)
        else:
            (self.cache, self._tok, self._active, self._remaining,
             self._first) = self._admit_dev(
                self.params, self.cache, self._tok, self._active,
                self._remaining, self._first, padded, np.int32(L),
                np.int32(slot), np.int32(req.max_new_tokens), pages, pk,
                true_chunk=chunk)
        return {"req": req, "tokens": [], "pending_first": True}

    def _admit_start(self, slot: int, req: Request, prompt: np.ndarray,
                     L: int) -> dict:
        """Phase A of a chunked admission — all host bookkeeping, at
        most one device dispatch (the shared-prefix gather):

        * pool admit, with cached prefix blocks ALIASED in via
          ``shared=`` and the full-prompt-hit COW split applied (the
          split block's content is rewritten whole by the finish insert,
          which IS the copy);
        * the newly prefilled prefix registered into the cache
          (first-wins; an already-cached hash keeps its block);
        * the chunk worklist: the SAME ``prefill_chunk`` grid the
          one-shot path uses (full-width chunks plus one remainder —
          identical executables, bitwise-identical output), starting at
          the chunk containing ``suffix_start`` so a cache hit skips
          the covered prefix entirely (positions below ``suffix_start``
          inside the first chunk are recomputed to identical bytes).

        The run loop pops one ``(off, width)`` per iteration."""
        max_new = int(req.max_new_tokens)
        suffix_start = 0
        write_block = 0
        shared_n = 0
        if self._prefix_cache is not None:
            blocks, suffix_start, cow = self._prefix_plan(prompt, L)
            shared_n = len(blocks)
            self.pool.admit(slot, L, max_new, shared=blocks)
            if cow:
                self.pool.cow_write(slot, len(blocks) - 1)
            # registration is DEFERRED to the finish dispatch: the
            # prompt's KV only lands in these blocks at the finish
            # insert, and registering now would let a concurrent
            # admission match and gather blocks not yet written
            write_block = suffix_start // self.kv_block_size
            if shared_n:
                self.prefix_stats["hits"] += 1
                self.prefix_stats["hit_tokens"] += (
                    shared_n * self.kv_block_size)
        elif self.pool is not None:
            self.pool.admit(slot, L, max_new)
        self.prefix_stats["prefill_tokens"] += L - suffix_start
        self._obs_prefill_tokens.inc(L - suffix_start)
        if self.pool is not None:
            pages = jnp.asarray(self.pool.table[slot])
            cache1 = (self._gather_prefix(self.cache, self._blank1, pages)
                      if suffix_start else self._blank1)
        else:
            pages = _NO_PAGES
            cache1 = self._blank1
        C = min(self.prefill_chunk, self.cfg.max_seq_len)
        Lp = min(-(-L // C) * C, self.cfg.max_seq_len)
        padded = np.full((1, Lp), self.pad_token, np.int32)
        padded[0, :L] = prompt
        chunks = []
        off = (suffix_start // C) * C
        while off < Lp:
            w = min(C, Lp - off)
            chunks.append((off, w))
            off += w
        return {"req": req, "tokens": [], "pending_first": True,
                "prefill": {"cache1": cache1, "padded": padded,
                            "chunks": chunks, "logits": None,
                            "off_last": 0, "L": L, "max_new": max_new,
                            "pages": pages, "write_block": write_block}}

    # -- disaggregated handoff (see tpudist.runtime.disagg) ----------------

    def _paged_nodes(self, cache) -> list:
        """The cache's paged layer nodes in natural dict order — the
        canonical layer order for KV migration payloads.  Export and
        adoption both walk this order (see ``_adopt_dev_impl``), which
        is stable fleet-wide because every replica instantiates the
        same model structure."""
        out = []

        def walk(node):
            if not isinstance(node, dict):
                return
            if "paged_key" in node:
                out.append(node)
                return
            for v in node.values():
                walk(v)

        walk(cache)
        return out

    def _build_handoff(self, slot: int, req: Request, pf: dict) -> dict:
        """Serialize ``slot``'s finished prefill as a migration payload
        (see :mod:`tpudist.runtime.disagg` for the schema) and free the
        slot.  The page gather syncs the device — acceptable on a
        prefill-only replica, where no decode cadence exists to stall —
        and the export freeze guarantees the pages it reads are this
        slot's (``check()`` would catch a mutation mid-copy)."""
        manifest = self.pool.export_slot(slot)
        pages = np.asarray(manifest["blocks"], np.int32)
        layers = [{"k": np.asarray(node["paged_key"][pages]),
                   "v": np.asarray(node["paged_value"][pages])}
                  for node in self._paged_nodes(self.cache)]
        prompt = np.asarray(req.prompt, np.int32)
        payload = {
            "key": None,   # stamped by the worker at publish
            "rid": req.rid,
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(req.max_new_tokens),
            # the exporter's sampled first token rides along: the decode
            # side emits it verbatim instead of re-running the prompt's
            # last logit
            "first": int(self._first[slot]),
            "true_len": int(pf["L"]),
            "block_size": int(self.kv_block_size),
            "chain": chain_hashes(prompt, self.kv_block_size),
            "published_at": time.time(),
            "layers": layers,
        }
        self.pool.complete_export(slot)
        return payload

    def _build_migration(self, slot: int, st: dict) -> dict:
        """Serialize an IN-FLIGHT decode slot as a migration payload
        and free the slot — the mid-decode sibling of
        :meth:`_build_handoff`, used by priority preemption (local
        park), hot/cold rebalancing, and fast drain.

        The caller must have resolved every in-flight segment first
        (the host token list is final, no stale merge can touch the
        exported pages) and frozen the lane on device.  The payload's
        ``generated`` rider carries every emitted token but the last;
        the last emitted token travels as ``first`` (the adopter's
        deferred-first lane stamp re-emits it), so the resumed output
        concatenates to exactly the uninterrupted sequence.  The
        ``version`` stamp keeps a roll in flight from mixing KV across
        weight versions — a mismatched adopter re-prefills instead."""
        req = st["req"]
        tokens = st["tokens"]
        prompt = np.asarray(req.prompt, np.int32)
        if st.get("pending_first") or not tokens:
            # the deferred first token is still device-side (fresh
            # admission — or a re-exported ADOPTION, whose seeded
            # tokens are already page-covered and ride ``generated``)
            first = int(self._first[slot])
            generated = [int(t) for t in tokens]
        else:
            first = int(tokens[-1])
            generated = [int(t) for t in tokens[:-1]]
        prompt_eff = (np.concatenate(
            [prompt, np.asarray(generated, np.int32)])
            if generated else prompt)
        true_len = int(prompt.size) + len(generated)
        manifest = self.pool.export_slot(slot)
        # the pool grows lanes a segment ahead of the watermark; the
        # adopter allocates exactly ceil(true_len / bs), so trim the
        # gather to the pages real KV occupies
        n_used = -(-true_len // self.kv_block_size)
        pages = np.asarray(manifest["blocks"], np.int32)[:n_used]
        layers = [{"k": np.asarray(node["paged_key"][pages]),
                   "v": np.asarray(node["paged_value"][pages])}
                  for node in self._paged_nodes(self.cache)]
        payload = {
            "key": None,   # stamped by the worker at publish
            "rid": req.rid,
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(req.max_new_tokens),
            "first": first,
            "generated": generated,
            "true_len": true_len,
            "block_size": int(self.kv_block_size),
            "chain": chain_hashes(prompt_eff, self.kv_block_size),
            "published_at": time.time(),
            "version": int(self.weights_version),
            "layers": layers,
        }
        self.pool.complete_export(slot)
        return payload

    def _admit_adopt(self, slot: int, req: Request, prompt: np.ndarray,
                     L: int) -> dict | None:
        """Admit ``req`` by ADOPTING its migrated KV payload — zero
        prefill compute.  Returns ``None`` when the payload fails any
        verification gate (structure, lengths, block size, prefix-hash
        chain, layer count/shape, weights version): the caller falls
        back to an ordinary re-prefill of the carried prompt, which
        greedy decoding over fleet-identical weights makes
        byte-identical.

        A MID-DECODE payload (preemption / rebalance / drain) carries
        ``generated`` — tokens the exporter already emitted, excluding
        the ``first`` rider.  The pages then cover prompt+generated,
        the chain is recomputed over that effective prompt, the slot's
        output list is SEEDED with the generated tokens, and the
        remaining budget shrinks by their count — so the terminal
        completion carries the full byte-identical token sequence and
        the original request (deadline, trace, priority) rides along
        untouched."""
        payload = req.kv_handoff
        try:
            first = int(payload["first"])
            true_len = int(payload["true_len"])
            bs = int(payload["block_size"])
            chain = [int(h) for h in payload["chain"]]
            generated = [int(t) for t in payload.get("generated", ())]
            layers = payload["layers"]
        except (KeyError, TypeError, ValueError):
            return None
        if "version" in payload:
            # KV computed under one weights version must never continue
            # under another (a roll in flight): refuse and re-prefill
            # under THIS replica's weights instead
            try:
                if int(payload["version"]) != self.weights_version:
                    return None
            except (TypeError, ValueError):
                return None
        if generated:
            prompt_eff = np.concatenate(
                [prompt, np.asarray(generated, np.int32)])
        else:
            prompt_eff = prompt
        L_eff = L + len(generated)
        max_new_eff = int(req.max_new_tokens) - len(generated)
        nodes = self._paged_nodes(self.cache)
        if (true_len != L_eff or bs != self.kv_block_size
                or max_new_eff < 1
                or chain != chain_hashes(prompt_eff, self.kv_block_size)
                or len(layers) != len(nodes)):
            return None
        max_new = max_new_eff
        blocks = self.pool.adopt_blocks(slot, L_eff, max_new)
        m_used = len(blocks)
        kv = []
        for l in layers:
            try:
                k = jnp.asarray(l["k"])
                v = jnp.asarray(l["v"])
            except (KeyError, TypeError, ValueError):
                k = v = None
            if (k is None or k.ndim != 3 or k.shape[0] != m_used
                    or k.shape[1] != bs or v.shape != k.shape):
                # shape lies past the chain check: un-admit and let the
                # fallback prefill take the slot instead
                self.pool.free_slot(slot)
                return None
            kv.append((k, v))
        pages_used = jnp.asarray(np.asarray(blocks, np.int32))
        full_row = jnp.asarray(self.pool.table[slot])
        (self.cache, self._tok, self._active, self._remaining,
         self._first) = self._adopt_dev(
            self.cache, self._tok, self._active, self._remaining,
            self._first, tuple(kv), pages_used, full_row,
            np.int32(true_len), np.int32(slot), np.int32(max_new),
            np.int32(first))
        self._obs_adoptions.inc()
        obs.recorder.record("serve_adopt", slot=slot, prompt_len=L,
                            blocks=m_used, generated=len(generated))
        # seed the output with the exporter's already-emitted tokens:
        # the terminal completion replaces the exporter's partial state
        # wholesale, so the router never assembles tokens across hops
        return {"req": req, "tokens": list(generated),
                "pending_first": True}

    def _plan_steps(self, slot_state) -> int:
        """Per-dispatch segment length: ``steps_per_sync``, CLAMPED
        against the tightest live in-flight deadline so a timeout is
        detected within ~one token of expiry instead of up to a full
        fixed-length segment late.  Uses the measured per-token EMA
        (``None`` until the first dispatch drains — the first segment
        runs full-length, which matches the old behavior)."""
        if self._step_ema is None or self._step_ema <= 0:
            return self.steps
        tightest = None
        for st in slot_state:
            if st is None or st.get("zombie"):
                continue
            dl = st["req"].deadline_s
            if dl is not None and (tightest is None or dl < tightest):
                tightest = dl
        if tightest is None:
            return self.steps
        slack = tightest - self._clock()
        if slack <= self._step_ema:
            return 1
        return max(1, min(self.steps, int(slack / self._step_ema)))

    def _spec_k(self, live: int) -> int:
        """The round's draft length: the fixed ``num_draft`` or the
        adaptive policy's pick at the current live-lane count
        (``allow_plain=False`` — inside the fused segment a K=0 round
        does not exist; the break-even fallback is choosing the smallest
        ladder K)."""
        if self._spec_policy is None:
            return self._spec_ladder[0]
        return int(self._spec_policy.best_k(batch=max(live, 1),
                                            allow_plain=False))

    def request_swap(self, params_fn, *, version: int | None = None,
                     on_swapped=None) -> None:
        """Schedule a DRAIN-GATED weight hot-swap: admission pauses,
        every lane already decoding runs to completion on the OLD
        weights, every in-flight segment drains, and only then is
        ``params_fn()`` called and its tree rebound as ``self.params``
        before admission resumes — no request ever straddles two weight
        versions, so greedy output stays exact-match against whichever
        single-version reference admitted it.  Because ``params`` is a
        jit ARGUMENT (not a closure capture), a same-shape/dtype tree
        swaps in with ZERO recompilation.

        ``params_fn`` returning ``None`` (e.g. a missing snapshot)
        aborts the rebind — old weights stay, the version gauge does
        not move — but the swap still COMPLETES: ``on_swapped()`` fires
        either way, so a rolling-upgrade chain (``runtime/router.py``'s
        ticket protocol) can never stall on one replica's failed
        restore.  ``version`` (when given and applied) lands on the
        ``serve/weights_version`` gauge the router and ``wait_swapped``
        poll.  Callable between :meth:`run` calls or during one from
        the ``source()``/``sink`` callbacks — the loop is single-
        threaded, so no locking; the latest request wins if one is
        already pending."""
        self._pending_swap = {"fn": params_fn, "version": version,
                              "on_swapped": on_swapped}

    def request_migrate(self, rids) -> None:
        """Ask the loop to migrate the named requests OUT (hot/cold
        rebalancing): at the next safe point each named request —
        queued, parked, or in-flight — leaves as a
        ``reason="migrate"`` completion carrying its exported KV
        payload (in-flight) or nothing (queued: a ref-less requeue the
        router redispatches as a fresh, byte-identical prefill).
        Unknown rids are ignored — the request finished first, and its
        normal terminal wins.  Callable from the ``source()`` callback;
        the loop is single-threaded, so no locking."""
        self._migrate_rids.update(str(r) for r in rids)

    def request_evacuate(self) -> None:
        """Ask the loop to migrate EVERYTHING out — queued, parked, and
        in-flight (fast drain): the worker calls this when its replica
        is marked draining, collapsing drain time from "longest
        remaining decode" to roughly one handoff RTT.  Idempotent; the
        flag clears after one evacuation pass, so a draining worker
        re-arms it every poll to bounce late arrivals too."""
        self._evacuate = True

    def run(self, requests: Sequence[Request] = (), *,
            source=None, sink=None,
            idle_wait_s: float = 0.005) -> list[Completion]:
        """Serve every request to completion; returns completions in
        FINISH order (slot events), each with its generated tokens.

        The loop keeps up to ``pipeline_depth`` compiled segments in
        flight: each dispatch chains the device carry immediately and
        starts an async device→host copy of its emits; the host fetch
        (and the admission/finalization decisions it feeds) happens
        while the NEXT segment computes.  A per-slot ``seq`` stamp — the
        index of the first segment whose emits can carry the slot's
        tokens — gates draining, so a lane re-admitted while an older
        segment's emits are still in flight never has stale rows
        misread as the new request's output.  The drain itself applies
        the same stop/budget rules as the synchronous loop, so output
        is token-identical at any depth (greedy selection ignores the
        RNG key; sampled runs see a shifted key chain across depths).

        SERVICE MODE — ``source`` / ``sink`` turn the batch runner into
        a long-lived replica worker (the router tier's unit):

        * ``source()`` is polled once per outer-loop iteration and
          returns an iterable of new :class:`Request`\\ s (``[]`` =
          open but idle; the loop sleeps ``idle_wait_s`` when there is
          nothing to do), or ``None`` to CLOSE intake — the loop then
          drains everything in flight and returns.  Service-mode
          requests that fail validation complete with
          ``reason="invalid"`` instead of raising (one malformed
          request must not take the replica down).
        * ``sink(completion)`` fires at every finalize — including
          rejections and timeouts — so completions stream out while the
          loop runs; the full list is still returned.

        Deadline kills and the paged layout interact with pipelining:
        segments already in flight at kill time carry the PRE-KILL
        active mask and page table, so the killed lane's blocks cannot
        be refunded (and re-allocated) until every one of those
        segments has drained — a freed-then-recycled block would be
        written by a stale merge.  The lane is parked as a ZOMBIE
        (finalized for the caller, un-admittable, blocks held) and the
        refund happens when the drain index passes the kill point; the
        in-graph freeze (``active=False``) guarantees segments
        dispatched AFTER the kill never write it."""
        for req in requests:  # fail BEFORE any slot is touched, not mid-run
            self._validate(req)
        self.intertoken_samples = []
        self._last_drain_t = None
        pending: deque[tuple[Request, float]] = deque()
        slot_state: list[dict | None] = [None] * self.B
        done: list[Completion] = []
        # (seq, emits, stats|None, n_steps, k, t_dispatch)
        inflight: deque[tuple] = deque()
        seq = 0   # segments dispatched so far == index of the next one
        closed = source is None
        swap_pause_logged = False   # one swap_pause event per barrier

        def emit(comp: Completion) -> None:
            done.append(comp)
            if sink is not None:
                sink(comp)

        def tev(kind: str, req: Request, **fields) -> None:
            """One request-lifecycle event into the tracing ring —
            only for TRACED requests (fleet traffic); untraced local
            runs stay out of the ring entirely."""
            tc = getattr(req, "trace", None)
            if tc is not None:
                obs.events.record(kind, trace=tc.trace_id, **fields)

        def complete_unadmitted(req: Request, reason: str) -> None:
            """Finalize a request that never reached a slot (shed,
            expired in queue, or invalid): no tokens, no lane state."""
            if reason == "rejected":
                self._obs_rejected.inc()
            elif reason == "timeout":
                self._obs_timeouts.inc()
            tev(reason, req, stage="queue")
            emit(Completion(
                rid=req.rid, prompt=np.asarray(req.prompt),
                tokens=np.zeros((0,), np.int32), reason=reason))

        def intake(batch, strict: bool) -> None:
            """Enqueue new requests; service mode (strict=False) turns
            validation failures into ``reason="invalid"`` completions."""
            for req in batch:
                if not strict:
                    try:
                        self._validate(req)
                    except ValueError:
                        complete_unadmitted(req, "invalid")
                        continue
                pending.append((req, time.perf_counter()))

        def shed() -> None:
            """Overload ladder.  Past the soft ``degrade_queue``
            watermark the loop goes DEGRADED (admissions clamp
            best-effort budgets — see admit_free).  Past the hard
            ``max_queue`` bound it sheds: lowest ``priority`` class
            first, newest-first within a class, so earlier arrivals keep
            their FIFO place and important traffic is the LAST to be
            rejected."""
            self._degraded = (self.degrade_queue is not None
                              and len(pending) > self.degrade_queue)
            self._obs_degraded.set(1.0 if self._degraded else 0.0)
            while (self.max_queue is not None
                   and len(pending) > self.max_queue):
                lowest = min(r.priority for r, _ in pending)
                victim = max(i for i, (r, _) in enumerate(pending)
                             if r.priority == lowest)
                req, _ = pending[victim]
                del pending[victim]
                complete_unadmitted(req, "rejected")
            self._obs_queue.set(len(pending))

        def finalize(slot: int, reason: str, *,
                     free_pool: bool = True) -> None:
            st = slot_state[slot]
            tev("finalize", st["req"], slot=slot, reason=reason,
                tokens=len(st["tokens"]))
            emit(Completion(
                rid=st["req"].rid, prompt=np.asarray(st["req"].prompt),
                tokens=np.asarray(st["tokens"], np.int32), reason=reason))
            self._obs_tokens.inc(len(st["tokens"]))
            if "t_admit" in st:
                self._obs_latency.record(time.perf_counter() - st["t_admit"])
            slot_state[slot] = None
            if self.pool is not None and free_pool:
                # free-on-finalize: blocks AND the unused reservation
                # return to the pool now.  Safe against in-flight
                # segments that still map this slot to these blocks: the
                # lane froze in-graph at the stop token, and the merge is
                # masked by `lived`, so a frozen row never writes a page
                # (its reads of recycled pages feed discarded pad emits).
                self.pool.free_slot(slot)

        def expire_inflight() -> None:
            """Kill lanes whose deadline passed: freeze the row on
            device, finalize with the tokens drained so far.  Dense
            lanes free immediately (the seq stamp already gates stale
            emits); paged lanes with segments in flight become zombies
            until the pre-kill segments drain (see the docstring)."""
            now = None
            for slot in range(self.B):
                st = slot_state[slot]
                if (st is None or st.get("zombie")
                        or st["req"].deadline_s is None):
                    continue
                if now is None:
                    now = self._clock()
                if now <= st["req"].deadline_s:
                    continue
                self._active = self._active.at[slot].set(False)
                self._obs_timeouts.inc()
                obs.recorder.record("serve_timeout", slot=slot, seq=seq,
                                    tokens=len(st["tokens"]))
                tev("timeout", st["req"], stage="decode", slot=slot,
                    tokens=len(st["tokens"]))
                if "prefill" in st:
                    # mid-prefill kill: the lane was never stamped
                    # active, so in-flight segments have lived=0 for it
                    # (merges masked) and its chunk dispatches touched
                    # only the transient batch-1 cache — the pool refund
                    # is safe immediately, no zombie needed
                    finalize(slot, "timeout")
                elif self.pool is not None and inflight:
                    finalize(slot, "timeout", free_pool=False)
                    slot_state[slot] = {"zombie": True, "free_at": seq}
                else:
                    finalize(slot, "timeout")

        def admit_free() -> None:
            """Expire queued deadlines, then fill free lanes from the
            queue; a new admission's tokens first surface in the NEXT
            dispatched segment (index ``seq``), so its drain is gated
            on that stamp."""
            nonlocal pending, swap_pause_logged
            if pending:
                now = None
                kept: deque[tuple[Request, float]] = deque()
                for req, t_q in pending:
                    if req.deadline_s is not None:
                        if now is None:
                            now = self._clock()
                        if now > req.deadline_s:
                            complete_unadmitted(req, "timeout")
                            continue
                    kept.append((req, t_q))
                pending = kept
            if self._pending_swap is not None:
                # swap barrier: no new admissions until the rebind lands
                # (queued-deadline expiry above still runs — a request
                # cannot outlive its deadline waiting on a swap)
                if not swap_pause_logged:
                    swap_pause_logged = True
                    for req, _ in pending:
                        tev("swap_pause", req, queued=len(pending),
                            version=self._pending_swap.get("version"))
                self._obs_queue.set(len(pending))
                return
            for slot in range(self.B):
                if slot_state[slot] is None and pending:
                    if self.preempt == "migrate":
                        # priority-first admission: the best waiting
                        # class jumps the queue (FIFO within a class);
                        # a blocked high-priority head is what arms
                        # maybe_preempt rather than starving behind
                        # best-effort arrivals
                        sel = max(range(len(pending)),
                                  key=lambda i: (pending[i][0].priority,
                                                 -i))
                    else:
                        sel = 0
                    req, t_q = pending[sel]
                    if self.pool is not None:
                        L_q = int(np.asarray(req.prompt).size)
                        if self._prefix_cache is not None:
                            # count the aliased prefix against nothing:
                            # shared blocks cost no allocation, but a
                            # full-prompt hit draws one COW block
                            n_sh = self._prefix_cache.peek(req.prompt)
                            cow = int(
                                n_sh * self.kv_block_size >= L_q)
                            ok = self.pool.can_admit(
                                L_q, int(req.max_new_tokens),
                                shared=n_sh, cow=cow)
                        else:
                            ok = self.pool.can_admit(
                                L_q, int(req.max_new_tokens))
                        if not ok:
                            # capacity gate: QUEUE instead of OOMing the
                            # pool.  FIFO — the head waits for blocks
                            # rather than being jumped by a smaller
                            # request behind it, which would starve
                            # long prompts
                            break
                    del pending[sel]
                    if (self.preempt != "migrate" and self._degraded
                            and req.priority <= 0
                            and req.max_new_tokens > self.degrade_max_new):
                        # degraded mode: best-effort traffic gets a short
                        # answer instead of (later) no answer.  A copy —
                        # the caller's Request is never mutated.
                        req = dataclasses.replace(
                            req, max_new_tokens=self.degrade_max_new)
                        self._obs_degrade_clamped.inc()
                        tev("degrade_clamp", req, stage="replica",
                            max_new=self.degrade_max_new)
                    self._obs_queue_wait.record(time.perf_counter() - t_q)
                    with obs.span("serve/admit", slot=slot):
                        slot_state[slot] = self._admit(slot, req)
                    # stamped here, not in _admit: benches wrap
                    # loop._admit, and latency must cover the wrapper.
                    # A chunked admission gets its seq stamp at the
                    # FINISH dispatch (advance_admissions) — its tokens
                    # cannot surface before that segment.
                    slot_state[slot]["t_admit"] = time.perf_counter()
                    if "prefill" not in slot_state[slot]:
                        slot_state[slot]["seq"] = seq
                    self._obs_requests.inc()
                    obs.recorder.record(
                        "serve_admit", slot=slot, seq=seq,
                        prompt_len=int(np.asarray(req.prompt).size),
                        max_new=req.max_new_tokens)
                    tev("admit", req, slot=slot, seq=seq,
                        prompt_len=int(np.asarray(req.prompt).size),
                        max_new=req.max_new_tokens)
            self._obs_queue.set(len(pending))

        def drain(slot: int, emit_row) -> None:
            """Feed a slot's newly visible tokens (column 0 = the
            admission-deferred first token, then the segment's emits)
            through the stop/budget rules; the first hit finalizes
            BEFORE any frozen-row pad could be consumed, mirroring the
            compiled freeze rule token for token."""
            st = slot_state[slot]
            row = [int(t) for t in emit_row]
            if st["pending_first"]:
                st["pending_first"] = False
            else:
                row = row[1:]               # column 0 is a stale first
            vocab = self.cfg.vocab_size
            for t in row:
                if not 0 <= t < vocab:
                    # host-side range net: an id outside the vocab can
                    # only come from scrambled device memory or a bad
                    # transfer (the sampler indexes [0, vocab)).  Covers
                    # the speculative path, which has no in-graph guard.
                    self._obs_corrupt.inc()
                    obs.recorder.record(
                        "serve_corrupt_segment", slot=slot,
                        token=t, tokens=len(st["tokens"]))
                    tev("corrupt_segment", st["req"], slot=slot,
                        token=t, tokens=len(st["tokens"]))
                    self._active = self._active.at[slot].set(False)
                    if self.pool is not None and inflight:
                        # host-side kill, like timeout: pre-kill
                        # segments may still write this lane's pages,
                        # so the refund waits for them to drain
                        finalize(slot, "corrupt_segment",
                                 free_pool=False)
                        slot_state[slot] = {"zombie": True,
                                            "free_at": seq}
                    else:
                        finalize(slot, "corrupt_segment")
                    return
                st["tokens"].append(t)
                self._served_tokens += 1
                if t in self._stop_set:
                    finalize(slot, "stop")
                    return
                if len(st["tokens"]) >= st["req"].max_new_tokens:
                    finalize(slot, "length")
                    return

        def advance_admissions() -> None:
            """Chunked prefill: advance every prefilling lane by ONE
            prompt chunk per outer-loop iteration, interleaved with the
            decode segments ``dispatch()`` chains — a 10k-token prompt
            spreads its prefill across many iterations instead of
            stalling every in-flight request behind one long dense
            pass.  Each chunk is an async dispatch into the lane's
            transient batch-1 cache (same chunk grid as the one-shot
            ``_prefill``, so the KV and logits are bitwise identical).
            When the worklist empties, the FINISH dispatch scatters the
            batch-1 cache into the paged table (suffix blocks only —
            shared prefix blocks are read in place), selects the first
            token from the final chunk's logits, stamps the lane
            active, and the slot joins decode with its drain gated on
            the NEXT segment."""
            freed_by_handoff: list[int] = []
            for slot in range(self.B):
                st = slot_state[slot]
                if st is None or "prefill" not in st:
                    continue
                pf = st["prefill"]
                if pf["chunks"]:
                    off, w = pf["chunks"].pop(0)
                    toks = pf["padded"][:, off:off + w]
                    with obs.span("serve/prefill_chunk", slot=slot,
                                  off=off, width=w):
                        pf["cache1"], pf["logits"] = self._prefill_chunk(
                            self.params, pf["cache1"], toks,
                            np.int32(off), chunk=w)
                    pf["off_last"] = off
                    tev("prefill_chunk", st["req"], slot=slot,
                        off=off, width=w, left=len(pf["chunks"]))
                    continue
                self._key, pk = jax.random.split(self._key)
                with obs.span("serve/admit_finish", slot=slot):
                    (self.cache, self._tok, self._active,
                     self._remaining, self._first) = self._admit_finish(
                        self.cache, self._tok, self._active,
                        self._remaining, self._first, pf["cache1"],
                        pf["logits"], np.int32(pf["off_last"]),
                        np.int32(pf["L"]), np.int32(slot),
                        np.int32(pf["max_new"]), pf["pages"],
                        np.int32(pf["write_block"]), pk)
                if self._prefix_cache is not None:
                    # register AFTER the insert dispatch: any later
                    # match's gather is host-ordered behind the write
                    # (first-wins — a hash cached meanwhile keeps its
                    # original block)
                    self._prefix_cache.register(
                        pf["padded"][0, :pf["L"]],
                        self.pool._slot_blocks[slot])
                    if self._tier is not None and len(self._tier):
                        # every full block of this prompt is now
                        # HBM-resident (first-wins or fresh): drop any
                        # surviving tier copy — e.g. a re-admit that
                        # stopped at pool exhaustion left deep links
                        # spilled, and the full prefill just recomputed
                        # them.  Tiered/resident must stay disjoint.
                        for h in chain_hashes(pf["padded"][0, :pf["L"]],
                                              self.kv_block_size):
                            self._tier.discard(h)
                tev("prefill_done", st["req"], slot=slot, seq=seq,
                    prompt_len=pf["L"])
                if self.role == "prefill":
                    # disaggregated handoff: this loop's job ENDS at
                    # prefill_done.  Undo the finish dispatch's active
                    # stamp (no decode segment may advance this lane),
                    # export the slot's pages + first token as the
                    # migration payload, and emit a reason="handoff"
                    # completion the router turns into a decode-stage
                    # dispatch.  complete_export (inside _build_handoff)
                    # frees the slot, so the lane recycles immediately —
                    # the structural TTFT win of a prefill-only replica.
                    self._active = self._active.at[slot].set(False)
                    payload = self._build_handoff(slot, st["req"], pf)
                    tev("handoff_export", st["req"], slot=slot, seq=seq,
                        prompt_len=pf["L"],
                        blocks=-(-pf["L"] // self.kv_block_size))
                    emit(Completion(
                        rid=st["req"].rid,
                        prompt=np.asarray(st["req"].prompt),
                        tokens=np.zeros((0,), np.int32),
                        reason="handoff", handoff=payload))
                    if "t_admit" in st:
                        self._obs_latency.record(
                            time.perf_counter() - st["t_admit"])
                    del st["prefill"]
                    slot_state[slot] = None
                    freed_by_handoff.append(slot)
                    continue
                del st["prefill"]
                # tokens first surface in the NEXT dispatched segment
                st["seq"] = seq
            if freed_by_handoff:
                # a prefill-role loop has no decode dispatches, so
                # nothing else would refill a lane freed by export —
                # pull from the queue NOW or an idle source starves the
                # loop with work still pending
                admit_free()
                shed()

        def busy_decode() -> bool:
            """Lanes a decode segment could advance — zombie and
            PREFILL-phase slots excluded: a prefilling lane is inactive
            on device until its finish dispatch lands, so segments
            dispatched for it alone would run empty."""
            return any(st is not None and not st.get("zombie")
                       and "prefill" not in st for st in slot_state)

        def can_work() -> bool:
            """Is there decode work a dispatch could advance?  A pending
            swap gates QUEUED requests out (the admission barrier means
            they cannot reach a slot, so dispatching for them would spin
            empty segments forever); lanes already decoding still count
            — they must run to completion before the swap lands.
            ``pending`` alone also counts: queued requests can be
            blocked on pool blocks held by ZOMBIE lanes, whose refund
            only lands when segments drain past the kill point.  A
            prefill-role loop NEVER decodes: its lanes hand off at
            prefill_done, so decode segments would only spin empty."""
            if self.role == "prefill":
                return False
            return busy_decode() or (bool(pending)
                                     and self._pending_swap is None)

        def maybe_swap() -> None:
            """Apply a pending weight swap once the loop is fully
            drained: no in-flight segments (their emits were computed
            under the old weights and must finalize against them) and
            no occupied lanes (zombies included — their pool blocks are
            refunded by the drain that just ran)."""
            nonlocal swap_pause_logged
            if (self._pending_swap is None or inflight
                    or any(st is not None for st in slot_state)):
                return
            swap, self._pending_swap = self._pending_swap, None
            swap_pause_logged = False   # barrier is down; next swap re-logs
            with obs.span("serve/swap", version=swap["version"]):
                tree = swap["fn"]()
                if tree is not None:
                    self.params = jax.tree.map(jnp.asarray, tree)
                    self._obs_swaps.inc()
                    if swap["version"] is not None:
                        self._obs_weights_version.set(int(swap["version"]))
                        # the version stamp every subsequent tier spill
                        # and pull-mode export carries: KV computed
                        # before this line can never pass the
                        # version gate after it
                        self.weights_version = int(swap["version"])
                    # cached prefix KV was computed under the OLD
                    # weights — serving it to a post-swap admission
                    # would break exactness.  The loop is drained here,
                    # so every refcount is zero and the flush returns
                    # every cached block to the free list (and empties
                    # the host tier, whose spilled KV is exactly as
                    # stale).
                    self.flush_prefix_cache()
            obs.recorder.record("serve_swap", seq=seq,
                                version=swap["version"],
                                applied=tree is not None)
            if swap["on_swapped"] is not None:
                swap["on_swapped"]()
            admit_free()   # the barrier is down; refill lanes now
            shed()

        def dispatch() -> None:
            """Chain one more segment on device and start its emits'
            async device→host copy — no host block."""
            nonlocal seq
            n = self._plan_steps(slot_state)
            live = sum(1 for st in slot_state
                       if st is not None and not st.get("zombie"))
            k = (self._spec_k(live)
                 if self.decode_mode == "speculative" else 0)
            if self.pool is not None:
                # grow-on-decode-boundary: advance every live lane's page
                # coverage by the segment's worst case (drawn from its
                # admit-time reservation, so this cannot fail), then
                # stamp the fresh table into the carry this segment
                # consumes.  Speculative segments can emit up to n + k
                # tokens (the last round's full K+1 window).  Lanes
                # already frozen on device (host hasn't drained the stop
                # yet) grow harmlessly within their reservation and
                # refund it at finalize.  Zombie lanes are dead (their
                # reservation was dropped at finalize); their held
                # blocks just wait for the refund.
                for slot in range(self.B):
                    st = slot_state[slot]
                    if (st is not None and not st.get("zombie")
                            and "prefill" not in st):
                        # prefill-phase lanes don't grow: nothing
                        # decodes there yet, and their prompt coverage
                        # was allocated at admit
                        self.pool.grow(slot, n + k)
                self._stamp_table()
            # the segment splits per-step keys and returns the advanced
            # key — no per-wave host-side split dispatch needed
            t_disp = time.perf_counter()
            with obs.span("serve/segment", steps=n, seq=seq):
                if self.decode_mode == "speculative":
                    # the speculative segment has no in-graph guard;
                    # the host-side token-range check in drain() is the
                    # integrity net for this path
                    corrupt = None
                    (self.cache, self.draft_cache, self._tok,
                     self._active, self._remaining, self._key, emits,
                     stats) = self._segment_spec(
                        self.params, self.draft_params, self.cache,
                        self.draft_cache, self._tok, self._active,
                        self._remaining, self._first, self._key,
                        jnp.int32(n), num_draft=k)
                    self._obs_spec_k.set(k)
                else:
                    stats = None
                    poison = faults.poison_logits(self._served_tokens)
                    (self.cache, self._tok, self._active,
                     self._remaining, self._key, emits,
                     corrupt) = self._segment(
                        self.params, self.cache, self._tok, self._active,
                        self._remaining, self._first, self._key,
                        jnp.int32(n), jnp.bool_(poison))
            self._obs_segments.inc()
            self._obs_dispatches.inc()
            for slot in range(self.B):
                st = slot_state[slot]
                if (st is not None and not st.get("zombie")
                        and "prefill" not in st):
                    tev("segment", st["req"], slot=slot, seq=seq,
                        steps=n, tokens=len(st["tokens"]),
                        spt=(round(self._step_ema, 6)
                             if self._step_ema is not None else None))
            try:
                emits.copy_to_host_async()
            except AttributeError:  # non-jax array (test doubles)
                pass
            inflight.append((seq, emits, corrupt, stats, n, k, t_disp))
            seq += 1
            self._obs_depth.set(len(inflight))
            # fault harness: a configured kill-after-K-segments SIGKILLs
            # here — mid-decode, with segments in flight
            faults.on_segment()

        def drain_oldest() -> None:
            """Resolve the oldest in-flight segment: block on its fetch
            (usually already landed — the copy overlapped later compute),
            then feed every lane whose stamp says this segment carries
            its tokens.  Plain segments carry exactly ``n`` emit columns
            past the deferred-first column; speculative ones carry
            ``stats[0]`` (the emitted count) — either way the drain
            slices to the real width so pad columns past a short segment
            are never consumed."""
            (s_idx, emits_dev, corrupt_dev, stats_dev, n_disp, k_disp,
             t_disp) = inflight.popleft()
            self._obs_depth.set(len(inflight))
            if any(st is not None and not st.get("zombie")
                   and "seq" in st and st["seq"] <= s_idx
                   for st in slot_state):
                t0 = time.perf_counter()
                emits = np.asarray(emits_dev)
                stats = (np.asarray(stats_dev)
                         if stats_dev is not None else None)
                self._obs_host_wait.record(time.perf_counter() - t0)
                n_tok = n_disp if stats is None else int(stats[0])
                # inter-token latency sample: wall gap between
                # consecutive decode-segment drains, per token of this
                # segment.  A one-shot long-prompt admission lands
                # between two segments and shows up here as one huge
                # gap — exactly the stall chunked prefill removes.
                now_t = time.perf_counter()
                if self._last_drain_t is not None and n_tok > 0:
                    self.intertoken_samples.append(
                        ((now_t - self._last_drain_t) / n_tok, n_tok))
                self._last_drain_t = now_t
                dt = time.perf_counter() - t_disp
                if n_tok > 0:
                    # dispatch->drain wall time per token; under
                    # pipelining this spans overlapped segments, so it
                    # OVERestimates — which only makes the deadline
                    # clamp more conservative
                    per = dt / n_tok
                    self._step_ema = (
                        per if self._step_ema is None
                        else 0.7 * self._step_ema + 0.3 * per)
                    self._obs_spt.set(self._step_ema)
                self._obs_steps_per_dispatch.set(n_tok)
                if stats is not None:
                    rounds = int(stats[1])
                    act_rounds = int(stats[3])
                    if act_rounds > 0 and k_disp > 0:
                        self._obs_spec_accept.set(
                            float(stats[2]) / (act_rounds * k_disp))
                        if self._spec_policy is not None:
                            self._spec_policy.update(
                                {"rounds": act_rounds,
                                 "draft_accepted": int(stats[2])},
                                batch=1, num_draft=k_disp)
                    if self._spec_policy is not None and rounds > 0:
                        # skip each K's first dispatch: its wall time is
                        # compile-polluted and would poison the measured
                        # cost model
                        if self._spec_uses.get(k_disp, 0) >= 1:
                            self._spec_policy.observe_round_cost(
                                k_disp, dt / rounds)
                        self._spec_uses[k_disp] = (
                            self._spec_uses.get(k_disp, 0) + 1)
                corrupt = (np.asarray(corrupt_dev)
                           if corrupt_dev is not None else None)
                for slot in range(self.B):
                    st = slot_state[slot]
                    if (st is not None and not st.get("zombie")
                            and "seq" in st and st["seq"] <= s_idx):
                        if corrupt is not None and bool(corrupt[slot]):
                            # the in-graph guard froze this lane before
                            # emitting anything from the bad step, but
                            # this segment's earlier columns are from
                            # the same poisoned state — discard them
                            # all and surface the verdict.  free_pool
                            # is safe for the same reason stop-finalize
                            # is: the lane is frozen in-graph, so later
                            # in-flight segments never write its pages.
                            self._obs_corrupt.inc()
                            obs.recorder.record(
                                "serve_corrupt_segment", slot=slot,
                                seq=s_idx, tokens=len(st["tokens"]))
                            tev("corrupt_segment", st["req"], slot=slot,
                                seq=s_idx, tokens=len(st["tokens"]))
                            finalize(slot, "corrupt_segment")
                        else:
                            drain(slot, emits[slot, :1 + n_tok])
            # zombie refund: every segment dispatched before the kill
            # (index < free_at) has drained once s_idx reaches
            # free_at - 1 — no stale merge can touch the blocks now
            for slot in range(self.B):
                st = slot_state[slot]
                if (st is not None and st.get("zombie")
                        and s_idx >= st["free_at"] - 1):
                    self.pool.free_slot(slot)
                    slot_state[slot] = None

        # -- live KV migration (preempt / rebalance / fast drain) ----------

        def quiesce() -> None:
            """Resolve EVERY in-flight segment: after this, each lane's
            host token list is final and no stale merge can touch pages
            an export is about to read — the precondition of
            ``_build_migration``."""
            while inflight:
                drain_oldest()

        def export_slot_payload(slot: int) -> dict:
            """Freeze ``slot`` on device, serialize it as a migration
            payload, and release the lane (no Completion — the caller
            decides whether the request parks locally or leaves as a
            ``reason="migrate"`` commit)."""
            st = slot_state[slot]
            self._active = self._active.at[slot].set(False)
            payload = self._build_migration(slot, st)
            slot_state[slot] = None
            return payload

        def park(slot: int) -> None:
            """Export ``slot`` and park it LOCALLY: payload metadata in
            the host dict, page bytes spilled per-block into the host
            tier when one exists (budget-accounted; eviction of any
            parked block downgrades the resume to a byte-identical
            re-prefill)."""
            st = slot_state[slot]
            req = st["req"]
            n_gen = len(st["tokens"])
            payload = export_slot_payload(slot)
            entry: dict = {"req": req, "t_q": time.perf_counter()}
            if self._tier is not None and payload["layers"]:
                n_blk = int(np.asarray(
                    payload["layers"][0]["k"]).shape[0])
                keys: list[int] = []
                parent = None
                ok = True
                for i in range(n_blk):
                    h = _park_hash(req.rid, i)
                    blk = [{"k": np.asarray(l["k"][i]),
                            "v": np.asarray(l["v"][i])}
                           for l in payload["layers"]]
                    if not self._tier.put(h, blk, parent=parent,
                                          version=self.weights_version):
                        ok = False
                        break
                    keys.append(h)
                    parent = h
                if ok:
                    entry["meta"] = {k: v for k, v in payload.items()
                                     if k != "layers"}
                    entry["keys"] = keys
                else:
                    # tier refused (budget): keep the payload whole in
                    # host RAM rather than losing the pages outright
                    for h in keys:
                        self._tier.discard(h)
                    entry["payload"] = payload
            else:
                entry["payload"] = payload
            self._parked[req.rid] = entry
            self._obs_preempted.inc()
            obs.recorder.record("serve_preempt", slot=slot,
                                tokens=n_gen, parked=len(self._parked))
            tev("preempt", req, stage="replica", slot=slot,
                tokens=n_gen, parked=len(self._parked))

        def unpark(entry: dict) -> dict | None:
            """Rebuild a parked payload; ``None`` when any tier block
            was evicted or version-flushed — the resume falls back to a
            re-prefill of the original request (byte-identical)."""
            if "payload" in entry:
                return entry["payload"]
            blocks = []
            for h in entry["keys"]:
                blk = self._tier.take(h, version=self.weights_version)
                if blk is None:
                    drop_parked(entry)
                    return None
                blocks.append(blk)
            n_lay = len(blocks[0]) if blocks else 0
            layers = [{"k": np.stack([b[li]["k"] for b in blocks]),
                       "v": np.stack([b[li]["v"] for b in blocks])}
                      for li in range(n_lay)]
            return {**entry["meta"], "layers": layers}

        def drop_parked(entry: dict) -> None:
            for h in entry.get("keys", ()):
                self._tier.discard(h)
            entry.pop("keys", None)
            entry.pop("payload", None)

        def migrate_out(req: Request, payload: dict | None,
                        stage: str) -> None:
            """Hand one request back to the router as a
            ``reason="migrate"`` completion — with its exported KV
            (in-flight) or ref-less (queued/prefill-phase: the
            redispatch re-prefills, byte-identical)."""
            self._obs_migrated_out.inc()
            tev("migrate_export", req, stage=stage,
                tokens=(len(payload.get("generated", ()))
                        + 1 if payload else 0),
                refless=payload is None)
            emit(Completion(
                rid=req.rid, prompt=np.asarray(req.prompt),
                tokens=np.zeros((0,), np.int32),
                reason="migrate", handoff=payload))

        def do_migrates() -> bool:
            """Router-initiated migration: evacuate everything (fast
            drain / fast swap) or the named requests (hot/cold
            rebalance).  Unknown rids mean the request finished first —
            its normal terminal wins and the intent is dropped."""
            nonlocal pending
            if not (self._evacuate or self._migrate_rids):
                return False
            if sink is None and source is None:
                # batch mode has no router to resume a migrated
                # request — the intents are meaningless here
                self._migrate_rids.clear()
                self._evacuate = False
                return False
            evac = self._evacuate
            wanted = set(self._migrate_rids)
            moved = False
            if pending:
                kept: deque[tuple[Request, float]] = deque()
                for req, t_q in pending:
                    if evac or req.rid in wanted:
                        migrate_out(req, None, "queue")
                        moved = True
                    else:
                        kept.append((req, t_q))
                pending = kept
            for rid in list(self._parked):
                if evac or rid in wanted:
                    entry = self._parked.pop(rid)
                    payload = unpark(entry)
                    migrate_out(entry["req"], payload, "parked")
                    moved = True
            if self.pool is not None and any(
                    st is not None and not st.get("zombie")
                    and (evac or st["req"].rid in wanted)
                    for st in slot_state):
                quiesce()
                for slot in range(self.B):
                    st = slot_state[slot]
                    if (st is None or st.get("zombie")
                            or not (evac or st["req"].rid in wanted)):
                        continue
                    req = st["req"]
                    if "prefill" in st:
                        # mid-chunked-prefill: the pages are not a
                        # finished prefix yet — requeue ref-less, the
                        # target re-prefills to identical bytes
                        self.pool.free_slot(slot)
                        slot_state[slot] = None
                        migrate_out(req, None, "prefill")
                    else:
                        migrate_out(req, export_slot_payload(slot),
                                    "decode")
                    moved = True
            self._migrate_rids.clear()
            self._evacuate = False
            return moved

        def maybe_preempt() -> bool:
            """Priority preemption (``preempt='migrate'``): under
            pressure — the degrade watermark breached, or the
            best-priority waiting request blocked on a lane/pool a
            strictly-lower-priority decode holds — quiesce and PARK the
            lowest-priority in-flight slot instead of degrade-clamping
            it.  Paused, never killed or truncated."""
            if (self.preempt != "migrate" or self.pool is None
                    or self.role == "prefill" or not pending):
                return False

            def victims() -> list[tuple[int, int, int]]:
                top = max(r.priority for r, _ in pending)
                return sorted(
                    (st["req"].priority, -len(st["tokens"]), slot)
                    for slot, st in enumerate(slot_state)
                    if st is not None and not st.get("zombie")
                    and "prefill" not in st
                    and st["req"].priority < top)
            if not victims():
                return False
            if not self._degraded:
                top_req = max(
                    (r for r, _ in pending), key=lambda r: r.priority)
                blocked = not any(s is None for s in slot_state)
                if not blocked:
                    blocked = not self.pool.can_admit(
                        int(np.asarray(top_req.prompt).size),
                        int(top_req.max_new_tokens))
                if not blocked:
                    return False
            quiesce()   # drains may finalize lanes: re-pick after
            vs = victims()
            if not vs:
                return False
            park(vs[0][2])
            return True

        def maybe_resume() -> bool:
            """Resume the oldest parked request once pressure clears
            (or unconditionally once intake is closed): its payload
            re-enters through the adopt path at the FRONT of the queue,
            original deadline/trace/priority intact."""
            if not self._parked or self._pending_swap is not None:
                return False
            if not closed and self._degraded:
                return False
            rid = next(iter(self._parked))
            entry = self._parked[rid]
            req = entry["req"]
            if (req.deadline_s is not None
                    and self._clock() > req.deadline_s):
                drop_parked(entry)
                del self._parked[rid]
                complete_unadmitted(req, "timeout")
                return True
            if not any(s is None for s in slot_state):
                return False
            if not self.pool.can_admit(
                    int(np.asarray(req.prompt).size),
                    int(req.max_new_tokens)):
                return False
            payload = unpark(entry)
            del self._parked[rid]
            resumed = (dataclasses.replace(req, kv_handoff=payload)
                       if payload is not None else req)
            pending.appendleft((resumed, entry["t_q"]))
            self._obs_resumed.inc()
            obs.recorder.record("serve_resume",
                                fallback=payload is None,
                                parked=len(self._parked))
            tev("resume", req, stage="replica",
                fallback=payload is None, parked=len(self._parked))
            return True

        # an unhandled exception mid-serve dumps the flight-recorder
        # bundle (admission ring, final snapshot) before propagating
        with obs.recorder.guard("serve_loop", num_slots=self.B,
                                requests=len(requests),
                                pipeline_depth=self.pipeline_depth):
            intake(requests, strict=True)
            admit_free()
            shed()
            while True:
                if not closed:
                    batch = source()
                    if batch is None:
                        closed = True
                    elif batch:
                        intake(batch, strict=False)
                        admit_free()
                        shed()
                expire_inflight()
                if (self.preempt == "migrate"
                        and self._pending_swap is not None
                        and source is not None):
                    # fast swap: evacuate in-flight work to peers so
                    # the swap barrier drains in ~one handoff RTT
                    # instead of the longest remaining decode
                    self._evacuate = True
                if do_migrates() | maybe_preempt() | maybe_resume():
                    admit_free()
                    shed()
                advance_admissions()
                if can_work():
                    dispatch()
                # fetch when the pipeline is full — or when there is
                # nothing left to dispatch and only fetches remain
                while inflight and (
                        len(inflight) >= self.pipeline_depth
                        or not can_work()):
                    drain_oldest()
                    admit_free()
                maybe_swap()
                if not (pending or inflight or self._parked or any(
                        st is not None for st in slot_state)):
                    if closed:
                        break
                    time.sleep(idle_wait_s)
            # the queue drained on the way out: an idle loop must not
            # keep advertising DEGRADED to the router
            self._degraded = False
            self._obs_degraded.set(0.0)
        return done
