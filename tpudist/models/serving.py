"""Continuous-batching serving loop — request-level scheduling over the
compiled decode step.

The round-3 verdict: the kernels and sharded rollouts existed, the
REQUEST layer didn't — fixed-batch rollouts make every sequence in the
batch start and stop together, so a mixed workload pays the longest
request's schedule.  This module adds the vLLM-style iteration-level
scheduler, shaped for TPU/XLA rather than for a GPU runtime:

* ``num_slots`` fixed decode lanes, each owning one row of the KV cache;
  the cache's ``cache_index`` leaves are VECTORS ``[B]`` — every slot
  decodes at its own length through the per-row cache path
  (``CausalSelfAttention._serve_attend``; the flash kernel takes per-row
  lengths) — one compiled step, no padding to a common position;
* ONE compiled SEGMENT (``lax.scan`` of ``steps_per_sync`` single-token
  steps) between host syncs: per-token host round trips would be
  RTT-bound, so admission/completion happen at segment granularity (a
  slot finishing mid-segment idles ≤ ``steps_per_sync`` ticks — the
  standard iteration-level-scheduling trade);
* admission PREFILLS the prompt through the scalar-index path into a
  side cache of batch 1 (chunked — the same ``_prefill`` the rollouts
  use, prompts right-padded to a chunk multiple so compile count is
  bounded by ``max_seq_len / prefill_chunk`` distinct shapes), then one
  compiled INSERT scatters the row into the freed slot and stamps its
  true length;
* per-request ``max_new_tokens`` and stop tokens: budgets ride the
  compiled segment as an ``[B]`` countdown (a stopped/funded-out slot
  freezes inside the segment), the host finalizes completions and reuses
  the slot.

The bench criterion (``bench.py: serve_loop``): tokens/s/slot at 8k
context with MIXED prompt lengths within ~15% of the fixed-batch
rollout, which is the cost of the request layer — the decode step is the
same kernels either way.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpudist.models.generate import (
    _blank_cache,
    _make_select,
    _prefill,
    _stop_array,
    serving_layout,
)
from tpudist.models.speculative import _set_cache_index
from tpudist.models.transformer import TransformerConfig, TransformerLM


@dataclasses.dataclass
class Request:
    """One serving request: a prompt and its generation budget."""

    prompt: np.ndarray            # [L] int32 tokens, L >= 1
    max_new_tokens: int
    rid: Any = None               # caller's correlation id


@dataclasses.dataclass
class Completion:
    rid: Any
    prompt: np.ndarray
    tokens: np.ndarray            # the generated tokens (stop included)
    reason: str                   # "stop" | "length"


def _index_leaves(cache: Any) -> tuple[jnp.ndarray, jnp.ndarray | None]:
    """(cache_index [B], side_index scalar | None), matched BY NAME:
    every layer carries the same values, so the first of each suffices."""
    main = side = None

    def walk(node):
        nonlocal main, side
        if not isinstance(node, dict):
            return
        for k, v in node.items():
            if k == "cache_index" and main is None:
                main = v
            elif k == "side_index" and side is None:
                side = v
            else:
                walk(v)

    walk(cache)
    if main is None:
        raise ValueError("cache holds no index leaves")
    return main, side


class ServeLoop:
    """Continuous-batching server over one model.

    Args:
      cfg / params: the model (scanned checkpoints are normalized via
        :func:`serving_layout`).
      num_slots: decode lanes (the B of the slot cache).  Pick the
        fixed-batch size that saturates the chip; the request layer keeps
        those lanes full across requests of different lengths.
      steps_per_sync: decode ticks per compiled segment (the admission
        latency / dispatch-amortization trade; ≥ the tunnel RTT in ticks).
      decode_attention: "flash" (per-row kernel) or "dense".
      prefill_chunk: admission prefill chunk; prompts are right-padded to
        a multiple of it, so it also bounds the number of distinct
        prefill executables.
      stop_tokens / pad_token: EOS semantics as in ``greedy_generate``.
      temperature / top_k / top_p: sampling controls (0 = greedy).
    """

    def __init__(
        self,
        cfg: TransformerConfig,
        params: Any,
        num_slots: int,
        *,
        steps_per_sync: int = 32,
        decode_attention: str = "flash",
        prefill_chunk: int = 512,
        stop_tokens: Sequence[int] | None = None,
        pad_token: int = 0,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        key: jax.Array | None = None,
        auto_unstack: bool = True,
    ) -> None:
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if steps_per_sync < 1:
            raise ValueError(
                f"steps_per_sync must be >= 1, got {steps_per_sync}")
        if auto_unstack:
            cfg, params = serving_layout(cfg, params)
        if cfg.scan_layers:
            raise ValueError(
                "ServeLoop needs the unrolled layout; pass the scanned "
                "checkpoint with auto_unstack=True (the default)")
        self.cfg = cfg
        self.params = params
        self.B = num_slots
        self.steps = steps_per_sync
        self.prefill_chunk = prefill_chunk
        self.pad_token = int(pad_token)
        self._stop = _stop_array(stop_tokens)
        self._stop_set = (set(np.asarray(self._stop).tolist())
                          if self._stop is not None else set())
        if decode_attention == "flash" and cfg.attention_window is not None:
            import warnings

            warnings.warn(
                "ServeLoop with a sliding-window model falls back to "
                "DENSE per-row attention (the per-row flash kernel has "
                "no window trim yet): every decode step streams the "
                "whole cache instead of ~window positions",
                stacklevel=2)
        self._select = _make_select(temperature, top_k, top_p)
        self._key = key if key is not None else jax.random.key(0)
        # SIDE-BUFFER mode (flash, no window): steps write a segment-
        # local buffer at a SCALAR index (XLA keeps those in place;
        # per-row-indexed main-cache writes measured +0.35 ms/step on the
        # 8-layer 8k model) and one per-segment merge scatters side ->
        # main.  Other configurations use the direct per-row writes.
        self.side = (steps_per_sync
                     if decode_attention == "flash"
                     and cfg.attention_window is None else 0)
        self.model = TransformerLM(cfg, decode=True,
                                   decode_attention=decode_attention,
                                   serve_side_slots=self.side)
        # the slot cache: blank, with VECTOR index leaves (one position
        # per slot) — this is what routes attention through the per-row
        # cache path — and, in sided mode, the side buffers materialized
        # EAGERLY (a lax.scan carry's structure cannot grow mid-scan)
        blank = _blank_cache(self.model, num_slots)
        self.cache = jax.tree.map(
            lambda leaf: (jnp.zeros((num_slots,), jnp.int32)
                          if leaf.ndim == 0 else leaf), blank)
        if self.side:
            self.cache = self._with_side_buffers(self.cache)
        self._blank1 = _blank_cache(self.model, 1)  # prefill side cache
        self._tok = jnp.full((num_slots,), self.pad_token, jnp.int32)
        self._active = jnp.zeros((num_slots,), bool)
        self._remaining = jnp.zeros((num_slots,), jnp.int32)
        self._segment = jax.jit(self._segment_impl)
        self._insert = jax.jit(self._insert_impl, donate_argnums=(0,))
        self._merge = jax.jit(self._merge_impl, donate_argnums=(0,))
        self._prefill_one = jax.jit(self._prefill_impl,
                                    static_argnames=("true_chunk",))

    def _with_side_buffers(self, cache):
        def walk(node):
            if not isinstance(node, dict):
                return node
            out = {k: walk(v) for k, v in node.items()}
            if "cached_key" in out:
                b, _, h_kv, d = out["cached_key"].shape
                out["side_key"] = jnp.zeros(
                    (b, self.side, h_kv, d), out["cached_key"].dtype)
                out["side_value"] = jnp.zeros(
                    (b, self.side, h_kv, d), out["cached_value"].dtype)
                out["side_index"] = jnp.zeros((), jnp.int32)
            return out
        return walk(cache)

    # -- compiled pieces ---------------------------------------------------

    def _segment_impl(self, params, cache, tok, active, remaining, key):
        stop_arr = self._stop
        pad = jnp.int32(self.pad_token)
        S = self.cfg.max_seq_len

        def step(carry, _):
            cache, tok, active, remaining, key = carry
            main_idx, side_idx = _index_leaves(cache)
            pos = main_idx if side_idx is None else main_idx + side_idx
            pos = jnp.minimum(pos, S - 1)
            logits, mut = self.model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                positions=pos[:, None], mutable=["cache"])
            key, sk = jax.random.split(key)
            nxt = self._select(logits[:, -1], sk).astype(jnp.int32)
            emit = jnp.where(active, nxt, pad)
            remaining = remaining - active.astype(jnp.int32)
            hit_stop = (jnp.isin(nxt, stop_arr)
                        if stop_arr is not None
                        else jnp.zeros_like(active))
            active = active & ~hit_stop & (remaining > 0)
            tok = jnp.where(active, nxt, pad)
            return (mut["cache"], tok, active, remaining, key), emit

        (cache, tok, active, remaining, key), emits = lax.scan(
            step, (cache, tok, active, remaining, key), None,
            length=self.steps)
        return cache, tok, active, remaining, key, emits.T  # [B, steps]

    def _prefill_impl(self, params, prompt_padded, true_len, key,
                      *, true_chunk):
        """Chunked prefill of ONE prompt into a fresh batch-1 cache;
        returns the cache (index stamped to the TRUE length — padded
        positions hold garbage that masking hides and decode overwrites)
        and the first generated token."""
        cache, logits = _prefill(self.model, params, self._blank1,
                                 prompt_padded, true_chunk)
        cache = _set_cache_index(cache, true_len)
        last = logits[0, true_len - 1 - (prompt_padded.shape[1]
                                         - logits.shape[1])]
        first = self._select(last[None, :], key)[0].astype(jnp.int32)
        return cache, first

    def _insert_impl(self, cache, cache1, slot, true_len):
        """Scatter the prefilled batch-1 cache into slot ``slot`` —
        matched BY NAME because the slot cache carries side buffers the
        prefill cache does not (they are left untouched: side_index is 0
        between segments and stale side rows are masked)."""
        def walk(big, small):
            if not isinstance(big, dict):
                if big.ndim == 1:      # cache_index vector <- true length
                    return big.at[slot].set(true_len)
                return big.at[slot].set(small[0])
            return {k: (walk(v, small[k]) if k in small else v)
                    for k, v in big.items()}
        return walk(cache, cache1)

    def _merge_impl(self, cache):
        """End-of-segment: scatter each layer's side buffer into the main
        cache at every row's own offset (per-row-index writes, but ONCE
        per segment instead of once per step), advance the per-row
        lengths by the segment's token count, reset the side counter."""
        B = self.B

        def walk(node):
            if not isinstance(node, dict):
                return node
            out = {k: walk(v) for k, v in node.items()}
            if "side_key" in out:
                used = out["side_index"]
                idx = out["cache_index"]
                S = out["cached_key"].shape[1]
                cap = out["side_key"].shape[1]
                for name, side_name in (("cached_key", "side_key"),
                                        ("cached_value", "side_value")):
                    main = out[name]
                    side = out[side_name]
                    for r in range(B):
                        start = jnp.minimum(idx[r], S - cap)
                        main = jax.lax.dynamic_update_slice(
                            main, side[r:r + 1].astype(main.dtype),
                            (r, start, 0, 0))
                    out[name] = main
                out["cache_index"] = jnp.minimum(idx + used, S)
                out["side_index"] = jnp.zeros((), jnp.int32)
            return out
        return walk(cache)

    # -- the host loop -----------------------------------------------------

    def _validate(self, req: Request) -> None:
        prompt = np.asarray(req.prompt)
        if prompt.ndim != 1 or prompt.size < 1:
            raise ValueError("request prompt must be a non-empty 1-D "
                             "token array")
        if req.max_new_tokens < 1:
            raise ValueError("request max_new_tokens must be >= 1")
        if prompt.size + req.max_new_tokens > self.cfg.max_seq_len:
            raise ValueError(
                f"request needs {prompt.size + req.max_new_tokens} cache "
                f"slots > max_seq_len {self.cfg.max_seq_len}")

    def _admit(self, slot: int, req: Request) -> dict:
        self._validate(req)
        prompt = np.asarray(req.prompt, np.int32)
        L = int(prompt.size)
        chunk = min(self.prefill_chunk, self.cfg.max_seq_len)
        # pad to a chunk multiple, CAPPED at the cache size: an uncapped
        # pad past max_seq_len would make the final chunk's
        # dynamic_update_slice clamp backwards and overwrite real prompt
        # positions (observed: silently corrupted completions)
        Lp = min(-(-L // chunk) * chunk, self.cfg.max_seq_len)
        padded = np.full((1, Lp), self.pad_token, np.int32)
        padded[0, :L] = prompt
        self._key, pk = jax.random.split(self._key)
        cache1, first = self._prefill_one(
            self.params, jnp.asarray(padded), jnp.int32(L), pk,
            true_chunk=chunk)
        self.cache = self._insert(self.cache, cache1, jnp.int32(slot),
                                  jnp.int32(L))
        first = int(first)
        state = {"req": req, "tokens": [first], "done": None}
        if first in self._stop_set:
            state["done"] = "stop"
        elif req.max_new_tokens == 1:
            state["done"] = "length"
        self._tok = self._tok.at[slot].set(first)
        self._active = self._active.at[slot].set(state["done"] is None)
        self._remaining = self._remaining.at[slot].set(
            req.max_new_tokens - 1)
        return state

    def run(self, requests: Sequence[Request]) -> list[Completion]:
        """Serve every request to completion; returns completions in
        FINISH order (slot events), each with its generated tokens."""
        for req in requests:  # fail BEFORE any slot is touched, not mid-run
            self._validate(req)
        pending = deque(requests)
        slot_state: list[dict | None] = [None] * self.B
        done: list[Completion] = []

        def finalize(slot: int, reason: str) -> None:
            st = slot_state[slot]
            done.append(Completion(
                rid=st["req"].rid, prompt=np.asarray(st["req"].prompt),
                tokens=np.asarray(st["tokens"], np.int32), reason=reason))
            slot_state[slot] = None

        while pending or any(s is not None for s in slot_state):
            for slot in range(self.B):
                if slot_state[slot] is None and pending:
                    st = self._admit(slot, pending.popleft())
                    if st["done"] is not None:   # finished at prefill
                        slot_state[slot] = st
                        finalize(slot, st["done"])
                    else:
                        slot_state[slot] = st
            if not any(s is not None for s in slot_state):
                continue
            self._key, sk = jax.random.split(self._key)
            (self.cache, self._tok, self._active, self._remaining,
             _, emits) = self._segment(
                self.params, self.cache, self._tok, self._active,
                self._remaining, sk)
            if self.side:
                self.cache = self._merge(self.cache)
            emits = np.asarray(emits)
            for slot in range(self.B):
                st = slot_state[slot]
                if st is None:
                    continue
                # the device emits real tokens exactly while the row is
                # active; the first stop/budget hit below breaks BEFORE
                # any frozen-row pad could be consumed, mirroring the
                # compiled freeze rule token for token
                for t in emits[slot]:
                    t = int(t)
                    st["tokens"].append(t)
                    if t in self._stop_set:
                        finalize(slot, "stop")
                        break
                    if len(st["tokens"]) >= st["req"].max_new_tokens:
                        finalize(slot, "length")
                        break
        return done
