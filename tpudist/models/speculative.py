"""Speculative decoding: a draft model proposes, the target verifies.

Decode at long context is HBM-bandwidth-bound — each generated token
streams the whole KV cache once (measured 668-739 GB/s, 82-90% of the
v5e's spec, in `tpudist/ops/flash_decode.py`).  Speculative decoding
attacks exactly that bound: a cheap DRAFT model autoregressively
proposes ``num_draft`` tokens, then the TARGET model scores all of them
in ONE chunked forward (its cache is streamed once per *round*, not once
per token).  Accepted prefixes keep the target's exact output
distribution — greedy speculative decoding emits the target's own greedy
tokens (bit-identical whenever the verify-chunk and per-token decode
paths produce identical logits, as in f32; in bf16 a near-tie argmax can
flip across the two attention kernels), and sampled speculative decoding
emits tokens whose distribution is exactly the target's, by the standard
accept/resample argument (accept draft token x with probability
min(1, p(x)/q(x)); on rejection resample from norm(max(p-q, 0))).

TPU-shaped design decisions:

* The whole loop is ONE compiled ``lax.while_loop`` — fixed-shape draft
  scans, fixed-shape verify chunks, a fixed-capacity output buffer
  written with ``dynamic_update_slice``.  No per-token host round trips.
* Cache rollback is O(1): the flax cache masks by its scalar
  ``cache_index`` and every write lands at an explicit index, so
  rejecting draft tokens = resetting the index (stale slots are masked
  now and overwritten later).  No cache copies.
* Batched rollouts stay in LOCKSTEP: every row advances by the same
  ``m + 1`` tokens per round, where ``m`` is the BATCH-MIN accepted
  prefix length.  Rows that accepted more simply re-draft from the
  shorter prefix next round — per-row output distributions are
  unchanged (a prefix of an accepted prefix is accepted), and uniform
  advancement keeps the scalar cache index / static output offsets.
  Acceptance-rate throughput therefore degrades with batch; batch 1-8
  with a well-matched draft is the intended regime.

Reference scope note: the reference suite is training-only
(SURVEY.md §2 — no inference path anywhere); this module extends the
serving story that `tpudist/models/generate.py` opens.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from tpudist.models.generate import (
    _blank_cache,
    _filtered_logits,
    _is_stop,
    _make_select,
    _prefill,
    _stop_array,
    apply_cache_constraint,
    sequence_lengths,
)
from tpudist.models.transformer import TransformerConfig, TransformerLM


def _filtered_probs(logits: jnp.ndarray, temperature: float,
                    top_k: Optional[int], top_p: Optional[float]):
    """The (possibly filtered) categorical the rollout samples from, as
    PROBABILITIES — the quantity the accept/resample rule needs on both
    the draft and target sides.  Exactness requires this to be the SAME
    distribution ``_make_select`` samples, so the filtering pipeline is
    the shared :func:`tpudist.models.generate._filtered_logits`.
    ``temperature == 0`` returns the argmax one-hot (greedy is the
    zero-temperature limit of the same rule)."""
    if temperature == 0.0:
        logits = logits.astype(jnp.float32)
        return jax.nn.one_hot(
            jnp.argmax(logits, axis=-1), logits.shape[-1], dtype=jnp.float32)
    return jax.nn.softmax(
        _filtered_logits(logits, temperature, top_k, top_p), axis=-1)


def _accept_and_next(p: jnp.ndarray, q: jnp.ndarray, draft: jnp.ndarray,
                     key: jax.Array, active: Optional[jnp.ndarray] = None):
    """The speculative accept/advance rule for one round, batched.

    Args:
      p: ``[B, K+1, V]`` target probabilities — ``p[:, j]`` is the
        target's next-token distribution AFTER draft token j (``p[:, 0]``
        conditions on the round's input token only; ``p[:, K]`` is the
        bonus position after all K drafts).
      q: ``[B, K, V]`` draft probabilities — ``q[:, j]`` is the
        distribution draft token ``draft[:, j]`` was sampled from.
      draft: ``[B, K]`` proposed tokens.
      key: randomness for accept tests and residual resampling.
      active: optional ``[B]`` bool — FROZEN rows (finished lanes inside
        a fused serve segment) count as all-accept so they never drag the
        batch-min ``m`` down for live rows; their emit is discarded by
        the caller's lane masks.

    Returns ``(m, emit, accepted)``: the batch-min accepted prefix
    length ``m`` (scalar int32, 0..K), the ``[B]`` token to emit at
    position ``m + 1`` (accepted draft for rows whose acceptance reached
    past ``m``, a residual/bonus resample otherwise), and the ``[B]``
    per-row accepted counts (for telemetry).

    Output-distribution exactness is the standard argument, applied at
    position ``m + 1``: rows with ``accepted > m`` passed the accept
    test for draft ``m+1`` (keep it); rows with ``accepted == m``
    rejected there (resample from ``norm(max(p - q, 0))``); when
    ``m == K`` every row accepted everything and the emit is a pure
    sample of ``p[:, K]`` — which is the ``q = 0`` degenerate case of
    the same residual formula, so one code path serves both.
    """
    b, k = draft.shape
    u_key, r_key = jax.random.split(key)
    p_at_draft = jnp.take_along_axis(
        p[:, :k], draft[..., None], axis=-1)[..., 0]         # [B, K]
    q_at_draft = jnp.take_along_axis(
        q, draft[..., None], axis=-1)[..., 0]                # [B, K]
    u = jax.random.uniform(u_key, (b, k))
    # Greedy (one-hot p/q) reduces to: accept iff the draft token IS the
    # target argmax — p_at_draft is 1 or 0 and u < 1 almost surely.
    ok = u * jnp.maximum(q_at_draft, 1e-20) < p_at_draft     # [B, K]
    if active is not None:
        ok = ok | ~active[:, None]
    cum_ok = jnp.cumprod(ok.astype(jnp.int32), axis=1)
    accepted = jnp.sum(cum_ok, axis=1)                       # [B] in 0..K
    m = jnp.min(accepted)

    # q padded with a zero row at index K: the all-accepted bonus position
    # resamples from norm(max(p - 0, 0)) = p itself.
    q_pad = jnp.concatenate([q, jnp.zeros_like(q[:, :1])], axis=1)
    p_m = lax.dynamic_index_in_dim(p, m, axis=1, keepdims=False)
    q_m = lax.dynamic_index_in_dim(q_pad, m, axis=1, keepdims=False)
    residual = jnp.maximum(p_m - q_m, 0.0)
    # all-zero residual can only arise when p == q (any draft sample is
    # accepted with probability 1, so rejection there has probability 0);
    # guard anyway so the categorical never sees -inf everywhere
    residual = jnp.where(
        jnp.sum(residual, axis=-1, keepdims=True) > 0, residual, p_m)
    resampled = jax.random.categorical(
        r_key, jnp.log(jnp.maximum(residual, 1e-38)), axis=-1)

    # rows whose acceptance reached PAST m keep draft token m+1 (only
    # possible when m < K; at m == K the gather index clamps but the
    # take-branch is all-False)
    took_next = accepted > m
    next_draft = lax.dynamic_index_in_dim(
        draft, jnp.minimum(m, k - 1), axis=1, keepdims=False)
    emit = jnp.where(took_next, next_draft, resampled).astype(jnp.int32)
    return m, emit, accepted


def _set_cache_index(cache: Any, idx: jnp.ndarray) -> Any:
    """Roll the cache to ``idx`` tokens: every ``cache_index`` leaf is
    reset (K/V buffers are left as-is — slots past the index are masked
    by every cached-attention path and overwritten on the next write at
    that position).  Index leaves are 0-D scalars in the unrolled layout
    and [num_layers] vectors under ``cfg.scan_layers``; K/V buffers are
    always >= 3-D (packed [B, S, Hkv·D]), so dimensionality separates
    them."""
    return jax.tree.map(
        lambda leaf: (jnp.full_like(leaf, idx) if leaf.ndim <= 1 else leaf),
        cache)


def speculative_generate(
    target_cfg: TransformerConfig,
    target_params: Any,
    draft_cfg: TransformerConfig,
    draft_params: Any,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    *,
    num_draft: int = 4,
    key: jax.Array | None = None,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    decode_attention: str = "dense",
    draft_decode_attention: str = "dense",
    prefill_chunk: int | None = None,
    stop_tokens: Sequence[int] | None = None,
    pad_token: int = 0,
    return_stats: bool = False,
    decode_shard: Any = None,
    cache_constraint: Any = None,
    draft_cache_constraint: Any = None,
    auto_unstack: bool = True,
):
    """Generate ``max_new_tokens`` past ``prompt`` with draft/verify
    speculative decoding.

    Args:
      target_cfg / target_params: the model whose output distribution the
        result follows exactly.
      draft_cfg / draft_params: the proposal model.  Only
        ``vocab_size`` must match the target; it may be arbitrarily
        smaller/shallower and may use sliding-window attention
        (``attention_window``) so its own cache streaming stays cheap at
        long context.
      num_draft: draft tokens proposed per verify round (the classic K).
      temperature / top_k / top_p: sampling controls, applied to BOTH
        models' distributions (temperature 0 = greedy: output matches
        :func:`tpudist.models.generate.greedy_generate` of the target —
        bit-identical when both paths' logits agree bitwise; bf16
        near-tie argmaxes can flip between the chunked-verify and
        single-token attention kernels).
      decode_attention / draft_decode_attention: cached-attention
        implementation per model ("dense" or "flash"); the target only
        ever runs chunk forwards (prefill path), the draft runs
        single-token steps.
      prefill_chunk: bound prompt-prefill memory, as in ``_rollout``.
      stop_tokens / pad_token: EOS semantics as elsewhere — positions
        past a sequence's first stop token freeze to ``pad_token`` and
        per-sequence lengths are returned.
      return_stats: also return ``{"rounds", "draft_accepted"}``
        (scalars; ``draft_accepted`` counts ACCEPTED draft tokens summed
        over rounds AND batch rows — acceptance rate =
        draft_accepted / (rounds · K · batch) — guard the division:
        ``rounds`` is 0 when ``max_new_tokens == 1`` (the prefill's
        own next token satisfies the request before any draft/verify
        round runs), so compute it as
        ``draft_accepted / max(rounds, 1) / (K · batch)``.  Note the
        lockstep rollout only ADVANCES by the batch-min accepted prefix
        each round, so emitted tokens can trail acceptance for
        batch > 1; emitted tokens additionally include one verify token
        per round).
      auto_unstack: normalize a scanned-trained DRAFT to the unrolled
        layout (its single-token steps pay ~4× through the stacked
        cache); the target's layout is preserved either way — scanned
        targets keep their depth-independent compile size and verify
        chunks amortize the slicing.
      decode_shard / cache_constraint / draft_cache_constraint: the
        sharded-serving hooks (same contracts as in
        :mod:`tpudist.models.generate`): ``decode_shard`` routes the
        TARGET's attention kernels through per-shard ``shard_map``
        islands, and the constraints (leaf -> sharding or None) pin the
        two cache layouts under GSPMD.  :func:`tp_speculative_generate`
        wires them for the Megatron layout.

    Returns ``[B, prompt_len + max_new_tokens]`` tokens, with
    ``(tokens, lengths)`` when ``stop_tokens`` is given, and the stats
    dict appended when ``return_stats`` is set.
    """
    if auto_unstack:
        # Serve a scanned-trained DRAFT through the unrolled layout by
        # default (generate.serving_layout): the draft runs single-token
        # steps, where the stacked layout costs ~4×.  The TARGET's layout
        # is PRESERVED: it only ever runs chunk verifies, which amortize
        # the stacked-cache slicing, so a scanned target keeps its
        # depth-independent compile size at ~no step-time cost — the
        # configuration bench.py relies on (the unrolled 8-layer rollout
        # exceeds the remote-compile request limit).  The sharded entry
        # points normalize BOTH unconditionally (their sharding rules
        # need per-layer names).
        from tpudist.models.generate import serving_layout

        draft_cfg, draft_params = serving_layout(draft_cfg, draft_params)
    if target_cfg.vocab_size != draft_cfg.vocab_size:
        raise ValueError(
            f"draft vocab {draft_cfg.vocab_size} != target vocab "
            f"{target_cfg.vocab_size}")
    if num_draft < 1:
        raise ValueError(f"num_draft must be >= 1, got {num_draft}")
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}")
    b, prompt_len = prompt.shape
    if prompt_len < 1:
        raise ValueError("prompt must hold at least one token")
    k = num_draft
    # the verify chunk writes up to K cache slots past the last emitted
    # token, so both caches need headroom beyond prompt+max_new
    need = prompt_len + max_new_tokens + k - 1
    for name, cfg in (("target", target_cfg), ("draft", draft_cfg)):
        if need > cfg.max_seq_len:
            raise ValueError(
                f"{name} max_seq_len {cfg.max_seq_len} < prompt_len + "
                f"max_new_tokens + num_draft - 1 = {need}")
    stop_arr = _stop_array(stop_tokens)
    select = _make_select(temperature, top_k, top_p)
    if key is None:
        key = jax.random.key(0)

    target = TransformerLM(target_cfg, decode=True,
                           decode_attention=decode_attention,
                           decode_shard=decode_shard)
    draft = TransformerLM(draft_cfg, decode=True,
                          decode_attention=draft_decode_attention)

    # PREFILL both models on the prompt (the shared serving split)
    t_cache, t_logits = _prefill(
        target, target_params,
        apply_cache_constraint(_blank_cache(target, b), cache_constraint),
        prompt, prefill_chunk)
    d_cache, _ = _prefill(
        draft, draft_params,
        apply_cache_constraint(_blank_cache(draft, b),
                               draft_cache_constraint),
        prompt, prefill_chunk)
    key, k0 = jax.random.split(key)
    first = select(t_logits[:, -1], k0).astype(jnp.int32)

    cap = max_new_tokens + k + 1
    out0 = jnp.zeros((b, cap), jnp.int32)
    out0 = lax.dynamic_update_slice(out0, first[:, None], (0, 0))

    def round_body(carry):
        t_cache, d_cache, x, emitted, out, key, rounds, acc_total = carry
        n_cache = prompt_len + emitted - 1  # tokens resident in caches
        key, dk, vk = jax.random.split(key, 3)

        # DRAFT: K single-token proposals with their distributions.  The
        # scan runs K+1 steps so the LAST iteration writes d_K into the
        # draft cache (needed for the all-accepted case); its sampled
        # output is discarded — one copy of the draft-step body.
        def chain(carry, inp):
            j, step_key = inp
            cache, tok = carry
            logits, mut = draft.apply(
                {"params": draft_params, "cache": cache}, tok[:, None],
                positions=jnp.full((b, 1), n_cache + j, jnp.int32),
                mutable=["cache"])
            q_probs = _filtered_probs(
                logits[:, -1], temperature, top_k, top_p)
            nxt = select(logits[:, -1], step_key).astype(jnp.int32)
            return (mut["cache"], nxt), (nxt, q_probs)

        d_keys = jax.random.split(dk, k + 1)
        (d_cache2, _), (drafts_t, q_t) = lax.scan(
            chain, (d_cache, x), (jnp.arange(k + 1), d_keys))
        drafts = drafts_t[:k].T                               # [B, K]
        q = jnp.moveaxis(q_t[:k], 0, 1)                       # [B, K, V]

        # VERIFY: one target forward over [x, d_1..d_K]
        verify = jnp.concatenate([x[:, None], drafts], axis=1)  # [B, K+1]
        positions = (n_cache + jnp.arange(k + 1))[None, :]
        t_logits, mut = target.apply(
            {"params": target_params, "cache": t_cache}, verify,
            positions=positions, mutable=["cache"])
        t_cache2 = mut["cache"]
        p = _filtered_probs(t_logits, temperature, top_k, top_p)

        m, emit, accepted = _accept_and_next(p, q, drafts, vk)

        # emit e_1..e_{m+1}: the accepted drafts then the verify token —
        # written as a full K+1 window (positions past m+1 are garbage,
        # overwritten next round or trimmed at the end)
        e_buf = jnp.concatenate([drafts, emit[:, None]], axis=1)
        e_buf = lax.dynamic_update_slice(e_buf, emit[:, None], (0, m))
        out = lax.dynamic_update_slice(out, e_buf, (0, emitted))

        new_len = n_cache + m + 1
        return (_set_cache_index(t_cache2, new_len),
                _set_cache_index(d_cache2, new_len),
                emit, emitted + m + 1, out, key,
                rounds + 1, acc_total + jnp.sum(accepted))

    def cond(carry):
        return carry[3] < max_new_tokens

    carry = (t_cache, d_cache, first, jnp.int32(1), out0, key,
             jnp.int32(0), jnp.int32(0))
    if max_new_tokens > 1:
        carry = lax.while_loop(cond, round_body, carry)
    _, _, _, _, out, _, rounds, acc_total = carry
    generated = out[:, :max_new_tokens]

    if stop_arr is not None:
        # EOS semantics as in _rollout: keep each row's first stop token,
        # freeze everything after it to pad_token
        hit = _is_stop(generated, stop_arr)
        after_stop = (jnp.cumsum(hit, axis=1) - hit) > 0
        generated = jnp.where(after_stop, jnp.int32(pad_token), generated)
    tokens = jnp.concatenate([prompt, generated], axis=1)

    result = (tokens,) if stop_arr is None else (
        tokens, sequence_lengths(generated, stop_arr, prompt_len))
    if return_stats:
        result = result + ({"rounds": rounds, "draft_accepted": acc_total},)
    return result[0] if len(result) == 1 else result


def _sharded_speculative(
    target_cfg, target_params, draft_cfg, draft_params, prompt,
    max_new_tokens, mesh, *, cache_spec, decode_shard, decode_attention,
    num_draft, key, temperature, top_k, top_p, prefill_chunk,
    stop_tokens, pad_token, return_stats):
    """Common tail of the sharded speculative entry points (tp / sp) —
    one copy of the serving-layout normalization, cache-constraint
    closures, key default, and kwarg plumbing, mirroring
    ``generate._sharded_generate`` so the layouts can never drift."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    # (cfgs, params) arrive NORMALIZED: every public sharded entry point
    # runs serving_layout on target AND draft before its shardings

    def cache_constraint(leaf):
        if leaf.ndim == 3:  # PACKED [B, S, Hkv*D] K/V buffers
            return NamedSharding(mesh, cache_spec)
        return NamedSharding(mesh, P())

    def draft_cache_constraint(leaf):
        return NamedSharding(mesh, P())

    def run(tp_params, dp_params, t):
        return speculative_generate(
            target_cfg, tp_params, draft_cfg, dp_params, t,
            max_new_tokens, num_draft=num_draft,
            key=key if key is not None else jax.random.key(0),
            temperature=temperature, top_k=top_k, top_p=top_p,
            decode_attention=decode_attention,
            draft_decode_attention="dense",
            prefill_chunk=prefill_chunk, stop_tokens=stop_tokens,
            pad_token=pad_token, return_stats=return_stats,
            decode_shard=decode_shard,
            cache_constraint=cache_constraint,
            draft_cache_constraint=draft_cache_constraint,
            auto_unstack=False)

    with mesh:
        return jax.jit(run)(target_params, draft_params, prompt)


def tp_speculative_generate(
    target_cfg: TransformerConfig,
    target_params: Any,
    draft_cfg: TransformerConfig,
    draft_params: Any,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    mesh,
    axis: str = "model",
    rules=None,
    *,
    num_draft: int = 4,
    key: jax.Array | None = None,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    decode_attention: str = "dense",
    prefill_chunk: int | None = 512,
    stop_tokens: Sequence[int] | None = None,
    pad_token: int = 0,
    return_stats: bool = False,
):
    """Tensor-parallel speculative decoding: the TARGET runs in the
    Megatron layout (weights + KV cache sharded over ``axis``, memory
    1/tp per chip — the :func:`tpudist.models.generate.tp_generate`
    layout) while the tiny DRAFT stays replicated, so every chip drafts
    locally and the verify rounds are the only sharded compute.  One
    GSPMD program; ``decode_attention="flash"`` routes the target's
    prefill/verify kernels through per-shard ``shard_map`` islands.

    Requires ``target_cfg.kv_heads % tp == 0``.  Same output contract
    as :func:`speculative_generate`.
    """
    from jax.sharding import PartitionSpec as P

    from tpudist.parallel.tensor_parallel import (
        shard_tree,
        spec_tree_from_rules,
        transformer_tp_rules,
    )

    tp = mesh.shape[axis]
    if target_cfg.kv_heads % tp:
        raise ValueError(
            f"target kv_heads {target_cfg.kv_heads} not divisible by "
            f"{axis!r} size {tp}")

    from tpudist.models.generate import serving_layout

    # normalize BEFORE the spec computation: the TP rules regex-match
    # per-layer kernel names, which a stacked checkpoint doesn't have
    target_cfg, target_params = serving_layout(target_cfg, target_params)
    draft_cfg, draft_params = serving_layout(draft_cfg, draft_params)
    specs = spec_tree_from_rules(
        target_params, rules or transformer_tp_rules(axis))
    return _sharded_speculative(
        target_cfg, shard_tree(target_params, mesh, specs), draft_cfg,
        draft_params, prompt, max_new_tokens, mesh,
        cache_spec=P(None, None, axis),
        decode_shard=((mesh, axis) if decode_attention == "flash"
                      else None),
        decode_attention=decode_attention, num_draft=num_draft, key=key,
        temperature=temperature, top_k=top_k, top_p=top_p,
        prefill_chunk=prefill_chunk, stop_tokens=stop_tokens,
        pad_token=pad_token, return_stats=return_stats)


def tp_sp_speculative_generate(
    target_cfg: TransformerConfig,
    target_params: Any,
    draft_cfg: TransformerConfig,
    draft_params: Any,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    mesh,
    axis: str = "model",
    seq_axis: str = "seq",
    rules=None,
    *,
    num_draft: int = 4,
    key: jax.Array | None = None,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    prefill_chunk: int | None = 512,
    stop_tokens: Sequence[int] | None = None,
    pad_token: int = 0,
    return_stats: bool = False,
):
    """2-D sharded speculative decoding — the full distributed-serving
    layout with a draft: the TARGET's weights are Megatron-sharded over
    ``axis`` and its KV cache sharded over heads (``axis``) AND sequence
    (``seq_axis``), so per-chip target cache memory is 1/(tp·sp) (the
    :func:`tpudist.models.generate.tp_sp_generate` layout); the tiny
    DRAFT stays replicated.  Verify chunks run on the GSPMD-partitioned
    dense path.  Same output contract as :func:`speculative_generate`.
    """
    from jax.sharding import PartitionSpec as P

    from tpudist.parallel.tensor_parallel import (
        shard_tree,
        spec_tree_from_rules,
        transformer_tp_rules,
    )

    tp, sp = mesh.shape[axis], mesh.shape[seq_axis]
    if target_cfg.kv_heads % tp:
        raise ValueError(
            f"target kv_heads {target_cfg.kv_heads} not divisible by "
            f"{axis!r} size {tp}")
    if target_cfg.max_seq_len % sp:
        raise ValueError(
            f"target max_seq_len {target_cfg.max_seq_len} not divisible "
            f"by {seq_axis!r} size {sp}")

    from tpudist.models.generate import serving_layout

    # normalize BEFORE the spec computation: the TP rules regex-match
    # per-layer kernel names, which a stacked checkpoint doesn't have
    target_cfg, target_params = serving_layout(target_cfg, target_params)
    draft_cfg, draft_params = serving_layout(draft_cfg, draft_params)
    specs = spec_tree_from_rules(
        target_params, rules or transformer_tp_rules(axis))
    return _sharded_speculative(
        target_cfg, shard_tree(target_params, mesh, specs), draft_cfg,
        draft_params, prompt, max_new_tokens, mesh,
        cache_spec=P(None, seq_axis, axis),
        decode_shard=None, decode_attention="dense",
        num_draft=num_draft, key=key, temperature=temperature,
        top_k=top_k, top_p=top_p, prefill_chunk=prefill_chunk,
        stop_tokens=stop_tokens, pad_token=pad_token,
        return_stats=return_stats)


def sp_speculative_generate(
    target_cfg: TransformerConfig,
    target_params: Any,
    draft_cfg: TransformerConfig,
    draft_params: Any,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    mesh,
    axis: str = "seq",
    *,
    num_draft: int = 4,
    key: jax.Array | None = None,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    prefill_chunk: int | None = 512,
    stop_tokens: Sequence[int] | None = None,
    pad_token: int = 0,
    return_stats: bool = False,
):
    """Sequence-sharded speculative decoding: the TARGET's KV cache is
    sharded over ``axis`` on its SEQUENCE dimension (per-chip target
    cache memory 1/n — the :func:`tpudist.models.generate.sp_generate`
    layout for contexts beyond one chip's HBM) with params replicated;
    the tiny DRAFT stays fully replicated.  The target's verify chunks
    run on the dense partitioned attention path (GSPMD turns them into
    per-shard partial attention + reductions; the sequence-sharded
    prefill never gathers the cache), so no ``decode_shard`` islands are
    needed.  Same output contract as :func:`speculative_generate`.
    """
    from jax.sharding import PartitionSpec as P

    from tpudist.models.generate import serving_layout

    target_cfg, target_params = serving_layout(target_cfg, target_params)
    draft_cfg, draft_params = serving_layout(draft_cfg, draft_params)
    sp = mesh.shape[axis]
    if target_cfg.max_seq_len % sp:
        raise ValueError(
            f"target max_seq_len {target_cfg.max_seq_len} not divisible "
            f"by {axis!r} size {sp}")

    return _sharded_speculative(
        target_cfg, target_params, draft_cfg, draft_params, prompt,
        max_new_tokens, mesh,
        cache_spec=P(None, axis, None),
        decode_shard=None, decode_attention="dense",
        num_draft=num_draft, key=key, temperature=temperature,
        top_k=top_k, top_p=top_p, prefill_chunk=prefill_chunk,
        stop_tokens=stop_tokens, pad_token=pad_token,
        return_stats=return_stats)


class AdaptiveDraftPolicy:
    """Acceptance-driven choice of ``num_draft`` (the round-3 verdict's
    adaptive-K ask): low acceptance makes long draft chunks WORSE than
    plain decode — the target still streams its cache/weights once per
    round, but the round only advances by the batch-min accepted prefix
    plus one — so K must shrink with measured acceptance, not be tuned
    for the perfect-draft ceiling.

    Model (the standard speculative-throughput algebra, batch-aware):
    with per-token acceptance probability ``a`` (i.i.d. across rows and
    positions), a batch-B round advances by::

        E[tokens/round] = 1 + sum_{j=1..K} a^(B*j)

    (each term is P(every row's accepted prefix reaches j) — the
    batch-min lockstep documented in this module's header), while the
    round costs ``K * c_draft + c_verify``.  :meth:`best_k` maximizes
    tokens/cost over the candidate ladder; ``a`` itself is recovered from
    observed per-row acceptance (``draft_accepted / (rounds * B)`` =
    ``sum_{j=1..K} a^j``) by bisection, because the reported accept rate
    is a K-truncated mean, not ``a``.

    The policy is HOST-side state adapting ACROSS compiled rollouts —
    inside one rollout K is a static shape (a lax.while_loop cannot
    reshape its draft scan), so adaptation happens at segment boundaries
    (:func:`adaptive_speculative_generate`), each ladder K reusing its
    own jit-cached executable.

    COSTS ARE MEASURED, NOT MODELED (round-4 verdict #2): the analytic
    ``K·r + 1`` round-cost shape mispredicts on real hardware — the
    verify chunk is cache-stream-bound (nearly K-independent) with a
    large fixed cost, so long chunks stay cheap even at modest
    acceptance.  Feed the policy realized per-round seconds via
    :meth:`observe_round_cost` (the adaptive driver does this
    automatically, skipping each K's first — compile-polluted — segment)
    and, optionally, the plain-decode per-token cost via
    :meth:`set_plain_cost`; :meth:`best_k` then maximizes MEASURED
    tokens/second over the ladder, interpolating a linear fit for
    not-yet-probed Ks, and — the break-even gate — returns ``0``
    ("use plain decode") whenever even the best ladder K's predicted
    rate loses to the plain rollout.  Until any cost is observed the
    analytic shape with ``draft_cost_ratio`` is the prior (and the gate
    stays off: unit-less analytic costs cannot be compared to plain
    seconds).

    Args:
      ladder: candidate K values (each gets its own compiled rollout).
      draft_cost_ratio: PRIOR c_draft / c_verify used only before any
        measured cost arrives.
      ema: smoothing for the acceptance estimate AND the cost estimates
        across updates.
    """

    def __init__(self, ladder: Sequence[int] = (4, 8, 16),
                 draft_cost_ratio: float = 0.1, ema: float = 0.5,
                 initial_acceptance: float = 0.8) -> None:
        if not ladder or any(k < 1 for k in ladder):
            raise ValueError(f"ladder must hold K >= 1, got {ladder}")
        if not 0 < draft_cost_ratio:
            raise ValueError("draft_cost_ratio must be > 0")
        self.ladder = tuple(sorted(ladder))
        self.r = float(draft_cost_ratio)
        self.ema = float(ema)
        self.acceptance = float(initial_acceptance)
        self.rounds_seen = 0
        self._round_cost: dict[int, float] = {}   # K -> seconds/round
        self._plain_tok_s: float | None = None    # seconds/token, plain

    # -- the algebra -------------------------------------------------------

    @staticmethod
    def _per_row_mean(a: float, k: int) -> float:
        """E[accepted prefix] / 1 for one row at per-token prob a."""
        return sum(a ** j for j in range(1, k + 1))

    @classmethod
    def infer_acceptance(cls, accept_rate: float, k: int) -> float:
        """Per-token acceptance probability ``a`` from the K-truncated
        mean accept fraction (``draft_accepted / (rounds*K*B)``)."""
        accept_rate = min(max(accept_rate, 0.0), 1.0)
        target = accept_rate * k
        lo, hi = 0.0, 1.0
        for _ in range(50):
            mid = (lo + hi) / 2
            if cls._per_row_mean(mid, k) < target:
                lo = mid
            else:
                hi = mid
        return (lo + hi) / 2

    def expected_tokens_per_round(self, a: float, k: int,
                                  batch: int) -> float:
        return 1.0 + sum(a ** (batch * j) for j in range(1, k + 1))

    # -- measured costs ----------------------------------------------------

    def observe_round_cost(self, k: int, seconds_per_round: float) -> None:
        """Fold one measured draft+verify round cost at chunk ``k`` into
        the cost model (EMA-smoothed per K)."""
        if seconds_per_round <= 0:
            return
        prev = self._round_cost.get(k)
        self._round_cost[k] = (
            seconds_per_round if prev is None
            else self.ema * seconds_per_round + (1 - self.ema) * prev)

    def set_plain_cost(self, seconds_per_token: float) -> None:
        """Arm the break-even gate with the measured plain-decode cost
        (EMA-smoothed once armed, like the per-K round costs — one noisy
        timing must not flip the gate wholesale)."""
        if seconds_per_token <= 0:
            return
        prev = self._plain_tok_s
        self._plain_tok_s = (
            float(seconds_per_token) if prev is None
            else self.ema * float(seconds_per_token) + (1 - self.ema) * prev)

    @property
    def calibrated(self) -> bool:
        return bool(self._round_cost)

    def round_cost(self, k: int) -> float:
        """Seconds (measured mode) or c_verify units (analytic prior)
        for one draft+verify round at chunk ``k``: exact where observed;
        a least-squares linear-in-K fit where ≥ 2 Ks were observed; the
        one observed point scaled by the analytic shape at 1; the pure
        analytic shape at 0."""
        if k in self._round_cost:
            return self._round_cost[k]
        pts = sorted(self._round_cost.items())
        if len(pts) >= 2:
            xs = [p[0] for p in pts]
            ys = [p[1] for p in pts]
            n = len(pts)
            mx = sum(xs) / n
            my = sum(ys) / n
            den = sum((x - mx) ** 2 for x in xs)
            slope = (sum((x - mx) * (y - my) for x, y in pts) / den
                     if den else 0.0)
            return max(my + slope * (k - mx), 1e-9)
        if len(pts) == 1:
            k0, c0 = pts[0]
            return c0 * (k * self.r + 1.0) / (k0 * self.r + 1.0)
        return k * self.r + 1.0

    def best_k(self, a: float | None = None, batch: int = 1,
               allow_plain: bool = True) -> int:
        """The ladder K maximizing expected tokens per unit cost at
        acceptance ``a`` (default: the policy's running estimate) —
        or ``0``, meaning "fall back to plain decode", when the break-
        even gate is armed (measured costs + plain cost known) and even
        the best K's predicted tokens/sec loses to the plain rollout.
        ``allow_plain=False`` bypasses the gate (the adaptive driver's
        periodic re-probe: a plain-locked policy would otherwise never
        see acceptance recover)."""
        a = self.acceptance if a is None else a

        def rate(k):
            return (self.expected_tokens_per_round(a, k, batch)
                    / self.round_cost(k))

        k_star = max(self.ladder, key=rate)
        if allow_plain and self.calibrated and self._plain_tok_s is not None:
            if rate(k_star) <= 1.0 / self._plain_tok_s:
                return 0
        return k_star

    # -- the feedback loop -------------------------------------------------

    @property
    def num_draft(self) -> int:
        return self.best_k()

    def update(self, stats: dict, batch: int, num_draft: int) -> None:
        """Fold one rollout's ``return_stats`` dict into the acceptance
        estimate (guarding the documented ``rounds == 0`` case)."""
        rounds = int(stats["rounds"])
        if rounds == 0:
            return
        rate = float(stats["draft_accepted"]) / (rounds * num_draft * batch)
        a = self.infer_acceptance(rate, num_draft)
        w = self.ema if self.rounds_seen else 1.0
        self.acceptance = w * a + (1.0 - w) * self.acceptance
        self.rounds_seen += rounds


def adaptive_speculative_generate(
    target_cfg: TransformerConfig,
    target_params: Any,
    draft_cfg: TransformerConfig,
    draft_params: Any,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    policy: AdaptiveDraftPolicy,
    *,
    segment_tokens: int = 128,
    key: jax.Array | None = None,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    decode_attention: str = "dense",
    draft_decode_attention: str = "dense",
    prefill_chunk: int | None = None,
    return_stats: bool = False,
    auto_unstack: bool = True,
    probe_plain: bool = True,
    reprobe_every: int = 4,
):
    """Speculative decoding with ``num_draft`` ADAPTED to measured
    acceptance, in segments.

    Each segment is one compiled :func:`speculative_generate` rollout at
    the policy's current K; its stats update the policy before the next
    segment.  Output distribution stays EXACT: a greedy (or sampled, with
    fresh per-segment keys) continuation of an exact prefix is an exact
    sample of the whole — K only changes the schedule, never the accept
    rule.  The cost is one compile per (segment boundary, ladder K) pair;
    a serving deployment amortizes the grid across requests (segment
    lengths and the ladder are static), and the common case converges to
    ONE K after the first segment.

    ``stop_tokens`` is deliberately unsupported here: per-row early stop
    interacts with segment boundaries (a stopped row would keep paying
    rollout segments); serve bounded-length requests through the
    continuous-batching loop instead.

    Segment wall times feed the policy's MEASURED cost model (each K's
    first segment is skipped — it contains the compile), so the K choice
    adapts to realized hardware costs, not the analytic prior.  With
    ``probe_plain`` (default), segment 2 runs the PLAIN rollout as a
    probe — its first call carries the compile, and a same-input re-run
    of the compiled executable supplies the clean timing that arms the
    policy's break-even gate — after which any segment where even the
    best K's predicted rate loses to plain decode runs the plain rollout
    instead (the "never worse than plain" guarantee costs ~two plain
    segments' device time once; pass ``probe_plain=False`` to skip the
    probe and arm the gate manually via ``policy.set_plain_cost``).
    Exactness is untouched either way: both continuations are exact
    samples.

    Returns tokens ``[B, prompt_len + max_new_tokens]`` (and, with
    ``return_stats``, a dict with per-segment ``ks`` (0 = plain
    fallback), acceptance estimates, and summed rounds/accepted)."""
    import time as _time

    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if segment_tokens < 1:
        raise ValueError(
            f"segment_tokens must be >= 1, got {segment_tokens}")
    if key is None:
        key = jax.random.key(0)
    batch = prompt.shape[0]
    toks = prompt
    remaining = max_new_tokens
    seg_stats: dict = {"ks": [], "acceptance": [], "rounds": 0,
                       "draft_accepted": 0}
    # compile-pollution guard keyed by (K, n): jit executables are per
    # SEGMENT LENGTH too, so a shorter final segment would otherwise feed
    # its compile time into the measured cost model
    uses: dict[tuple[int, int], int] = {}
    seg_i = 0
    plain_streak = 0
    while remaining > 0:
        n = min(segment_tokens, remaining)
        k_seg = policy.best_k(batch=batch)
        if probe_plain and policy._plain_tok_s is None and seg_i == 1:
            k_seg = 0   # the plain probe segment (arms the gate below)
        elif (k_seg == 0 and reprobe_every > 0
                and plain_streak >= reprobe_every):
            # plain segments observe no acceptance, so a gate-locked
            # policy would never notice the draft recovering — re-probe
            # speculation periodically (one spec segment per
            # ``reprobe_every`` plain ones, bounded cost)
            k_seg = policy.best_k(batch=batch, allow_plain=False)
        plain_streak = plain_streak + 1 if k_seg == 0 else 0
        key, seg_key = jax.random.split(key)
        t0 = _time.perf_counter()
        if k_seg == 0:
            # break-even fallback: plain rollout for this segment
            from tpudist.models.generate import (
                greedy_generate, sample_generate,
            )

            def plain_call(t):
                if temperature > 0:
                    return sample_generate(
                        target_cfg, target_params, t, n, key=seg_key,
                        temperature=temperature, top_k=top_k, top_p=top_p,
                        decode_attention=decode_attention,
                        prefill_chunk=prefill_chunk,
                        auto_unstack=auto_unstack)
                return greedy_generate(
                    target_cfg, target_params, t, n,
                    decode_attention=decode_attention,
                    prefill_chunk=prefill_chunk,
                    auto_unstack=auto_unstack)

            toks_in = toks
            toks = plain_call(toks_in)
            jax.block_until_ready(toks)
            dt = _time.perf_counter() - t0
            if uses.get((0, n), 0) >= 1:   # first call holds the compile
                policy.set_plain_cost(dt / n)
            elif policy._plain_tok_s is None:
                # first plain call at this length carried the compile —
                # re-run the now-compiled executable on the SAME input
                # (output discarded) so the gate arms in ONE probe
                # segment regardless of segment-length truncation
                t1 = _time.perf_counter()
                jax.block_until_ready(plain_call(toks_in))
                policy.set_plain_cost(
                    (_time.perf_counter() - t1) / n)
            stats = {"rounds": 0, "draft_accepted": 0}
        else:
            toks, stats = speculative_generate(
                target_cfg, target_params, draft_cfg, draft_params, toks,
                n, num_draft=k_seg, key=seg_key, temperature=temperature,
                top_k=top_k, top_p=top_p,
                decode_attention=decode_attention,
                draft_decode_attention=draft_decode_attention,
                prefill_chunk=prefill_chunk, return_stats=True,
                auto_unstack=auto_unstack)
            jax.block_until_ready(toks)
            dt = _time.perf_counter() - t0
            rounds = int(stats["rounds"])
            if rounds > 0 and uses.get((k_seg, n), 0) >= 1:
                policy.observe_round_cost(k_seg, dt / rounds)
            policy.update(stats, batch, k_seg)
        uses[(k_seg, n)] = uses.get((k_seg, n), 0) + 1
        seg_stats["ks"].append(k_seg)
        seg_stats["acceptance"].append(policy.acceptance)
        seg_stats["rounds"] += int(stats["rounds"])
        seg_stats["draft_accepted"] += int(stats["draft_accepted"])
        remaining -= n
        seg_i += 1
    return (toks, seg_stats) if return_stats else toks
