"""Decoder-only transformer LM — the long-context workload of the zoo.

The reference suite has no attention model anywhere (SURVEY.md §2.3: the
sequence-parallel family is absent; largest model is ResNet50,
`model_parallel_ResNet50.py:43-139`).  tpudist adds one deliberately: it is
the workload that exercises tensor parallelism
(:mod:`tpudist.parallel.tensor_parallel`), sequence/context parallelism and
ring attention (:mod:`tpudist.parallel.ring_attention`), and the pallas
flash-attention kernel (:mod:`tpudist.ops.flash_attention`) — the
capabilities a modern user of the reference's *mechanisms* (RPC model
parallelism, DDP) actually scales with on TPU.

Design notes (TPU-first):

* every projection width is a multiple of 128 (MXU lane width); compute in
  bfloat16 with float32 params via ``compute_dtype``;
* attention is **pluggable**: any ``AttentionFn`` with the
  ``(q, k, v, *, causal) -> out`` contract on ``[batch, seq, heads, hd]``
  arrays can be swapped in — the default is plain softmax attention, ring
  attention and the pallas kernel provide drop-in replacements;
* static shapes everywhere; the layer stack is a Python loop (unrolled at
  trace time), causality is a static flag.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

# (q, k, v, *, causal, window=None) on [batch, seq, heads, head_dim]
# arrays -> out shaped like q.  ``window`` is the sliding-window width
# (None = full causal attention); implementations may reject it.
AttentionFn = Callable[..., jnp.ndarray]


def _masked_attend(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    mask: Optional[jnp.ndarray],
) -> jnp.ndarray:
    """The one copy of the attention numerics every path shares: scaled
    f32-accumulated QKᵀ, finfo-min mask fill, f32 softmax, cast back.
    ``mask`` is boolean, broadcastable to [B, H, Sq, Sk] (True = attend)."""
    dtype = q.dtype
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def repeat_kv(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray):
    """Expand grouped K/V heads to match Q's head count (GQA → MHA view).

    KV head ``j`` serves query heads ``[j·g, (j+1)·g)`` — the convention
    the pallas kernels implement natively via index maps (no expansion)."""
    group = q.shape[2] // k.shape[2]
    if group > 1:
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    return k, v


def sdpa(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    window: int | None = None,
) -> jnp.ndarray:
    """Plain scaled-dot-product attention on [B, S, H, D] arrays; K/V may
    carry fewer (grouped) heads (GQA), and ``window`` restricts each query
    to the last ``window`` positions (sliding-window attention).

    The reference semantics all pluggable attention implementations (ring,
    pallas flash) must match.  Softmax statistics in float32 regardless of
    the compute dtype — bfloat16 logits lose too much for long sequences.
    """
    k, v = repeat_kv(q, k, v)
    mask = None
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((s_q, s_k), jnp.bool_), k=s_k - s_q)
        if window is not None:
            pos_q = jnp.arange(s_q)[:, None] + (s_k - s_q)
            mask = mask & (pos_q - jnp.arange(s_k)[None, :] < window)
    elif window is not None:
        raise ValueError("window requires causal=True")
    return _masked_attend(q, k, v, mask)


def _shard_kind(decode_shard) -> str:
    return decode_shard[2] if len(decode_shard) > 2 else "heads"


def _head_sharded(decode_shard, fn, q, k, v, scalar):
    """Run ``fn(q, k, v, scalar)`` per shard over the HEAD dim of q/k/v
    (``scalar`` replicated) — the shard_map island that lets Pallas
    attention kernels compose with a GSPMD rollout (GSPMD cannot
    partition a pallas_call; heads are embarrassingly parallel)."""
    from jax.sharding import PartitionSpec as P

    mesh, ax = decode_shard[0], decode_shard[1]
    spec = P(None, None, ax, None)
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec, P()),
        out_specs=spec, check_vma=False)(q, k, v, scalar)


def _seq_sharded_decode(decode_shard, q, k_all, v_all, n, window, h_kv):
    """Sequence-sharded kernelized decode over the PACKED cache: cache
    slices stay put, each shard runs flash_decode with global masking,
    partial softmaxes merge by log-sum-exp (one [B, H] all-gather + one
    psum — no cache movement).  With the 2-D ``"heads_seq"`` kind the
    axis pair ``(head_axis, seq_axis)`` shards heads AND sequence: each
    shard kernels its own (head slice × cache slice) — the packed minor
    dim shards by whole KV heads, contiguous per head — and the merge
    runs over the sequence axis only."""
    from jax.sharding import PartitionSpec as P

    from tpudist.ops.flash_decode import sp_flash_decode

    mesh, ax = decode_shard[0], decode_shard[1]
    if isinstance(ax, tuple):
        hax, sax = ax
    else:
        hax, sax = None, ax
    n_h = mesh.shape[hax] if hax else 1
    if h_kv % n_h:
        raise ValueError(
            f"kv heads {h_kv} not divisible by {hax!r} axis size {n_h}")
    local_kv = h_kv // n_h
    q_spec = P(None, None, hax, None)
    kv_spec = P(None, sax, hax)
    return jax.shard_map(
        lambda qs, ks, vs, nn_: sp_flash_decode(
            qs, ks, vs, nn_, sax, window=window,
            packed_kv_heads=local_kv),
        mesh=mesh, in_specs=(q_spec, kv_spec, kv_spec, P()),
        out_specs=q_spec, check_vma=False)(q, k_all, v_all, n)


def _head_sharded_packed(decode_shard, q, k_all, v_all, n, window, h_kv):
    """Head-sharded flash decode over the PACKED cache: each shard owns
    whole KV-head chunks of the packed minor dim and runs the kernel on
    its slice — the TP layout, no collectives at all."""
    from jax.sharding import PartitionSpec as P

    from tpudist.ops.flash_decode import flash_decode

    mesh, ax = decode_shard[0], decode_shard[1]
    if h_kv % mesh.shape[ax]:
        raise ValueError(
            f"kv heads {h_kv} not divisible by {ax!r} axis size "
            f"{mesh.shape[ax]}")
    local_kv = h_kv // mesh.shape[ax]
    q_spec = P(None, None, ax, None)
    kv_spec = P(None, None, ax)
    return jax.shard_map(
        lambda qs, ks, vs, nn_: flash_decode(
            qs, ks, vs, nn_, window=window, packed_kv_heads=local_kv),
        mesh=mesh, in_specs=(q_spec, kv_spec, kv_spec, P()),
        out_specs=q_spec, check_vma=False)(q, k_all, v_all, n)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 256
    num_layers: int = 2
    num_heads: int = 4
    embed_dim: int = 128
    mlp_ratio: int = 4
    max_seq_len: int = 512
    compute_dtype: jnp.dtype = jnp.float32
    # Grouped-query attention: K/V heads (None = num_heads, plain MHA).
    # Shrinks the decode KV cache by num_heads/num_kv_heads.
    num_kv_heads: int | None = None
    # Sliding-window attention width (None = full causal attention).
    # The single source of truth: the training path passes it to the
    # attention_fn and the decode cache mask applies the same band.
    attention_window: int | None = None
    # Compile the layer stack as ONE lax.scan over stacked parameters
    # instead of a Python loop (the maxtext-style "scan over layers").
    # The traced program holds one block body regardless of depth, so
    # HLO size and compile time stop scaling with num_layers — which is
    # what keeps deep-model rollouts under remote-compile size limits.
    # Param layout changes from block{i}/... to blocks/block/... with a
    # leading layer axis; convert with stack_layer_params /
    # unstack_layer_params.  Lives on the config so every cache-decode
    # rollout (generate / speculative) builds the matching model.
    # NOTE: `transformer_tp_rules` targets the UNROLLED layout — its
    # 2-D PartitionSpecs would land on the wrong axes of the stacked
    # [L, in, out] kernels, so tp_generate/TP training take the
    # unrolled layout (serving rollouts convert with
    # unstack_layer_params if needed).  Single-token DECODE is ~4×
    # slower scanned (measured): every scan step dynamic-slices its
    # layer's cache from the stacked buffer and writes it back, ~3×
    # extra HBM traffic per token — prefer the unrolled layout for
    # plain decode latency; chunked verify forwards (speculative)
    # amortize the cost and keep the compile-size win.
    scan_layers: bool = False

    @property
    def head_dim(self) -> int:
        assert self.embed_dim % self.num_heads == 0
        return self.embed_dim // self.num_heads

    @property
    def kv_heads(self) -> int:
        kv = self.num_kv_heads or self.num_heads
        assert self.num_heads % kv == 0, (self.num_heads, kv)
        return kv


class CausalSelfAttention(nn.Module):
    cfg: TransformerConfig
    attention_fn: AttentionFn = sdpa
    decode: bool = False
    # "dense": masked softmax over the whole cache buffer; "flash": the
    # Pallas flash-decode kernel (tpudist.ops.flash_decode) — same numerics,
    # one cache read per KV head, the long-context serving path.
    decode_attention: str = "dense"
    # (mesh, axis) or (mesh, axis, kind): run the flash kernels PER SHARD
    # via shard_map — GSPMD cannot partition a Pallas call.  kind="heads"
    # (default, the TP layout): heads are embarrassingly parallel, each
    # shard owns whole KV-head groups; both prefill and decode kernelize.
    # kind="seq" (the SP layout, cache sequence-sharded): each shard runs
    # flash_decode on its cache slice with GLOBAL masking and partial
    # softmaxes merge by log-sum-exp (tpudist.ops.flash_decode.
    # sp_flash_decode); prefill stays on the dense GSPMD path (queries
    # must attend across every shard's slice).
    # kind="heads_seq" (the 2-D TP×SP layout): axis is the PAIR
    # (head_axis, seq_axis) — each shard kernels its own (head-group ×
    # cache-slice) block and the merge runs over seq_axis only.
    decode_shard: Any = None
    # continuous-batching side-buffer capacity (tokens per segment); > 0
    # selects the sided serve step — see _serve_attend_sided
    serve_side_slots: int = 0
    # "dense": per-slot [B, S, Hkv*D] cache buffers; "paged": ONE shared
    # block pool [kv_num_blocks, kv_block_size, Hkv*D] per layer plus a
    # [B, max_blocks] page table (PagedAttention) — HBM scales with
    # allocated tokens, not B x S.  Paged serving requires the side-
    # buffer step (the pool is frozen within a segment; the ServeLoop's
    # per-segment merge scatters side tokens through the page table).
    cache_layout: str = "dense"
    kv_num_blocks: int = 0
    kv_block_size: int = 0

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, causal: bool = True) -> jnp.ndarray:
        cfg = self.cfg
        b, s, _ = x.shape
        if cfg.kv_heads == cfg.num_heads:
            qkv = nn.Dense(3 * cfg.embed_dim, use_bias=False,
                           dtype=cfg.compute_dtype, name="qkv")(x)
            qkv = qkv.reshape(b, s, 3, cfg.num_heads, cfg.head_dim)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        else:  # GQA: separate projections, K/V at the grouped head count
            q = nn.Dense(cfg.embed_dim, use_bias=False,
                         dtype=cfg.compute_dtype, name="q")(x)
            q = q.reshape(b, s, cfg.num_heads, cfg.head_dim)
            kv = nn.Dense(2 * cfg.kv_heads * cfg.head_dim, use_bias=False,
                          dtype=cfg.compute_dtype, name="kv")(x)
            kv = kv.reshape(b, s, 2, cfg.kv_heads, cfg.head_dim)
            k, v = kv[:, :, 0], kv[:, :, 1]
        # cfg is the single source of truth for the sliding window: a
        # factory built with its OWN window (flash_attention_fn(window=W))
        # that disagrees is rejected — in BOTH branches, since the decode
        # cache masks from cfg alone and would otherwise silently discard
        # the factory's window.
        fw = getattr(self.attention_fn, "factory_window", None)
        if fw is not None and fw != cfg.attention_window:
            raise ValueError(
                f"attention_fn was built with window={fw} but "
                f"cfg.attention_window={cfg.attention_window}; set the "
                "window on TransformerConfig (the single source of "
                "truth) or make the two agree")
        if self.decode:
            out = self._cached_attend(q, k, v)
        else:
            # window passed unconditionally (None = full causal) so the
            # training path can never diverge from the decode cache mask,
            # and a fn that doesn't accept the kwarg fails loudly instead
            # of training full-attention against a windowed decode cache.
            out = self.attention_fn(q, k, v, causal=causal,
                                    window=cfg.attention_window)
        out = out.reshape(b, s, cfg.embed_dim)
        return nn.Dense(cfg.embed_dim, use_bias=False,
                        dtype=cfg.compute_dtype, name="proj")(out)

    def _cached_attend(self, q, k, v):
        """Decoding against a KV cache of ``max_seq_len`` slots (the
        standard flax ``cache`` collection pattern): fixed-shape buffers +
        ``dynamic_update_slice`` keep the whole autoregressive loop
        jittable as a ``lax.scan``.

        ``s == 1`` is the per-token decode step; ``s > 1`` is PREFILL —
        the whole prompt chunk lands in the cache in one call and attends
        causally within itself + everything cached before it (the serving
        split: one batched forward for the prompt, then one-token steps).
        """
        cfg = self.cfg
        b, s, _, d = q.shape
        h_kv = k.shape[2]  # the GQA cache-memory win: Hkv slots, not H
        if self.cache_layout == "paged":
            # the paged layout never materializes the dense buffers —
            # that absence IS the capacity win, so branch before the
            # cached_key/cached_value variables exist
            return self._paged_attend(q, k, v)
        if self.cache_layout != "dense":
            raise ValueError(
                f"cache_layout must be 'dense' or 'paged', got "
                f"{self.cache_layout!r}")
        # The cache is stored PACKED [B, S, Hkv*D]: with the per-head
        # 4-D shape and narrow heads (e.g. [B, S, 2, 64]), XLA lays the
        # carry out S-minor and inserts TWO full-cache layout-conversion
        # copies per decode step feeding the pallas kernel (measured ~2x
        # step time at 8k context; see flash_decode's packed mode).  A
        # lane-multiple minor dim keeps every consumer relayout-free;
        # per-head views are reshaped where semantics need them.
        flat = h_kv * d
        cached_k = self.variable(
            "cache", "cached_key", jnp.zeros,
            (b, cfg.max_seq_len, flat), cfg.compute_dtype)
        cached_v = self.variable(
            "cache", "cached_value", jnp.zeros,
            (b, cfg.max_seq_len, flat), cfg.compute_dtype)
        idx_var = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32))
        idx = idx_var.value
        if idx.ndim == 1:
            # PER-ROW cache positions (vector cache_index [B]) — the
            # continuous-batching serve mode: every slot decodes at its
            # own length (tpudist.models.serving swaps the scalar index
            # leaves for vectors when building the slot cache).  s == 1
            # is the decode step, s > 1 the speculative verify chunk;
            # prefill runs per-slot through a scalar-index side cache
            # and is INSERTED (serving._insert).
            return self._serve_attend(
                q, k, v, cached_k, cached_v, idx_var)
        k_all = jax.lax.dynamic_update_slice(
            cached_k.value,
            k.reshape(b, s, flat).astype(cached_k.value.dtype),
            (0, idx, 0))
        v_all = jax.lax.dynamic_update_slice(
            cached_v.value,
            v.reshape(b, s, flat).astype(cached_v.value.dtype),
            (0, idx, 0))
        cached_k.value, cached_v.value = k_all, v_all
        idx_var.value = idx + s

        def view4(x):
            return x.reshape(b, cfg.max_seq_len, h_kv, d)

        if s > 1:
            return self._prefill_attend(q, view4(k_all), view4(v_all), idx)
        if self.decode_attention == "flash":
            from tpudist.ops.flash_decode import flash_decode

            if self.decode_shard is not None:
                if _shard_kind(self.decode_shard) in ("seq", "heads_seq"):
                    return _seq_sharded_decode(
                        self.decode_shard, q, k_all, v_all, idx + 1,
                        cfg.attention_window, h_kv)
                return _head_sharded_packed(
                    self.decode_shard, q, k_all, v_all, idx + 1,
                    cfg.attention_window, h_kv)
            return flash_decode(q, k_all, v_all, idx + 1,
                                window=cfg.attention_window,
                                packed_kv_heads=h_kv)
        mask = jnp.arange(cfg.max_seq_len) <= idx            # causal: ≤ self
        if cfg.attention_window is not None:  # sliding window: last W only
            mask = mask & (
                idx - jnp.arange(cfg.max_seq_len) < cfg.attention_window)
        k4, v4 = repeat_kv(q, view4(k_all), view4(v_all))
        return _masked_attend(q, k4, v4, mask[None, None, None, :])

    def _serve_attend(self, q, k, v, cached_k, cached_v, idx_var):
        """One decode step with PER-ROW cache positions: row ``r``'s K/V
        logically lives at its own ``idx[r]`` and it attends over its own
        first ``idx[r] + 1`` slots.

        With ``serve_side_slots > 0`` (the ServeLoop configuration) the
        write goes to a SEGMENT-LOCAL side buffer at a SCALAR in-segment
        index — XLA keeps scalar dynamic_update_slice chains in place,
        while per-row-indexed main-cache writes measured +0.35 ms/step on
        the 8-layer 8k bench model (neither batched scatters nor
        per-row-index DUS chains stay in place inside the full segment
        graph).  Attention then runs as ONE fused flash-decode call over
        the frozen main cache (per-row lengths) plus the side buffer's
        live positions (:meth:`_serve_attend_sided`); the ServeLoop
        scatters side → main once per segment (amortized to ~nothing).
        ``serve_side_slots == 0`` keeps the direct per-row-write path
        (simple, correct, slower).

        ``s > 1`` is the speculative VERIFY CHUNK inside a fused serve
        segment: row ``r``'s ``s`` tokens land at its own
        ``idx[r]..idx[r]+s-1`` and query ``j`` attends causally over the
        row's first ``idx[r] + j + 1`` positions.  The ServeLoop rolls
        the index back to the accepted prefix afterwards."""
        cfg = self.cfg
        b, s = q.shape[0], q.shape[1]
        idx = idx_var.value
        if self.decode_shard is not None:
            raise NotImplementedError(
                "sharded decode with per-row cache positions is not "
                "wired yet; serve through the replicated path")

        if self.serve_side_slots > 0:
            return self._serve_attend_sided(
                q, k, v, cached_k, cached_v, idx_var)

        h_kv, d = k.shape[2], k.shape[3]
        flat = h_kv * d
        at = jnp.minimum(idx, cfg.max_seq_len - s)
        k_all, v_all = cached_k.value, cached_v.value
        kf = k.reshape(b, s, flat)
        vf = v.reshape(b, s, flat)
        for r in range(b):
            k_all = jax.lax.dynamic_update_slice(
                k_all, kf[r:r + 1].astype(k_all.dtype), (r, at[r], 0))
            v_all = jax.lax.dynamic_update_slice(
                v_all, vf[r:r + 1].astype(v_all.dtype), (r, at[r], 0))
        cached_k.value, cached_v.value = k_all, v_all
        idx_var.value = idx + s

        n = idx + 1  # [B] valid lengths including the current token
        if (s == 1 and self.decode_attention == "flash"
                and cfg.attention_window is None):
            from tpudist.ops.flash_decode import flash_decode

            return flash_decode(q, k_all, v_all, n,
                                packed_kv_heads=h_kv)
        # NOTE: flash + attention_window falls back to the dense masked
        # path here (the per-row kernel has no per-row window trim yet) —
        # ServeLoop warns about the bandwidth cost at construction.
        # Multi-query chunks (s > 1) are dense banded too: the chunk was
        # just written to the main cache, so one banded mask covers main
        # history and the in-chunk causal structure together (the flash
        # s>1 wrapper exists for the sided/frozen-main-cache layout).
        positions = jnp.arange(cfg.max_seq_len)[None, None, :]  # [1,1,S]
        q_pos = idx[:, None] + jnp.arange(s)[None, :]           # [B, s]
        mask = positions < (q_pos + 1)[:, :, None]              # [B,s,S]
        if cfg.attention_window is not None:
            mask = mask & (q_pos[:, :, None] - positions
                           < cfg.attention_window)
        k4 = k_all.reshape(b, cfg.max_seq_len, h_kv, d)
        v4 = v_all.reshape(b, cfg.max_seq_len, h_kv, d)
        k_rep, v_rep = repeat_kv(q, k4, v4)
        return _masked_attend(q, k_rep, v_rep, mask[:, None])

    def _serve_attend_sided(self, q, k, v, cached_k, cached_v, idx_var):
        """The side-buffer serve step (see :meth:`_serve_attend`).

        ``cache_index`` stays the MAIN-cache per-row length for the whole
        segment; the side buffer's own scalar counter tracks in-segment
        tokens (every row writes the same side slot each step — admission
        only happens at segment boundaries, so side occupancy is uniform
        across rows; frozen rows write garbage that their discarded
        outputs never expose and the merge-time mask drops).

        Attention runs as ONE fused kernel call: the flash-decode kernel
        streams the frozen main cache at each row's own length and then
        attends the side buffer's live positions as a trailing grid step
        of the SAME online softmax (``flash_decode(side_k=...)``) — the
        separate dense side attend + explicit log-sum-exp merge this
        method used through round 4 measured +0.15–0.2 ms/step on the
        8-layer 8k bench model."""
        cfg = self.cfg
        b, s = q.shape[0], q.shape[1]
        cap = self.serve_side_slots
        h_kv, d = k.shape[2], k.shape[3]
        flat = h_kv * d
        side_k = self.variable(
            "cache", "side_key", jnp.zeros, (b, cap, flat),
            cfg.compute_dtype)
        side_v = self.variable(
            "cache", "side_value", jnp.zeros, (b, cap, flat),
            cfg.compute_dtype)
        side_idx = self.variable(
            "cache", "side_index", lambda: jnp.zeros((), jnp.int32))
        # s > 1 writes a verify chunk (speculative decode); the chunk
        # lands contiguously and flash_decode's multi-query wrapper gives
        # query j visibility over side positions [0, side_idx + j].
        s_at = jnp.minimum(side_idx.value, cap - s)
        side_k.value = jax.lax.dynamic_update_slice(
            side_k.value, k.reshape(b, s, flat).astype(side_k.value.dtype),
            (0, s_at, 0))
        side_v.value = jax.lax.dynamic_update_slice(
            side_v.value, v.reshape(b, s, flat).astype(side_v.value.dtype),
            (0, s_at, 0))
        side_idx.value = side_idx.value + s

        from tpudist.ops.flash_decode import flash_decode

        return flash_decode(
            q, cached_k.value, cached_v.value, idx_var.value,
            side_k=side_k.value, side_v=side_v.value,
            side_len=side_idx.value, packed_kv_heads=h_kv)

    def _paged_attend(self, q, k, v):
        """One decode step against the PAGED cache: K/V live in a shared
        block pool (``paged_key``/``paged_value``,
        ``[kv_num_blocks, kv_block_size, Hkv*D]``) and each slot reaches
        its logical positions through ``page_table``
        (``[B, max_blocks_per_slot]`` int32 pool indices) — slot ``b``'s
        position ``p`` is ``pool[table[b, p // bs], p % bs]``.

        Within a compiled segment the pool is FROZEN (like the dense
        sided path's main cache): the current token's K/V goes to the
        segment-local side buffer at a scalar index, and the ServeLoop's
        per-segment merge scatters side -> pool through the page table.
        That makes the side-buffer step mandatory here — there is no
        per-step paged write path (a per-row scatter through the table
        every step would re-materialize exactly the indexed-write cost
        the sided design measured and removed).

        Attention: ``decode_attention="flash"`` runs
        :func:`tpudist.ops.flash_decode.paged_flash_decode` (the dense
        kernel's online softmax with a page-table-driven K/V index map);
        ``"dense"`` gathers the slot's pages into a contiguous view and
        masks — the CPU/test fallback."""
        cfg = self.cfg
        b, s = q.shape[0], q.shape[1]
        h_kv, d = k.shape[2], k.shape[3]
        flat = h_kv * d
        bs_, nb = self.kv_block_size, self.kv_num_blocks
        if bs_ < 1 or nb < 1:
            raise ValueError(
                "cache_layout='paged' needs kv_block_size and "
                f"kv_num_blocks > 0 (got {bs_}, {nb})")
        m_blocks = -(-cfg.max_seq_len // bs_)
        paged_k = self.variable(
            "cache", "paged_key", jnp.zeros, (nb, bs_, flat),
            cfg.compute_dtype)
        paged_v = self.variable(
            "cache", "paged_value", jnp.zeros, (nb, bs_, flat),
            cfg.compute_dtype)
        table = self.variable(
            "cache", "page_table", jnp.zeros, (b, m_blocks), jnp.int32)
        idx_var = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32))
        idx = idx_var.value
        if idx.ndim == 0:
            if self.is_initializing():
                # init only creates the cache variables' shapes; the
                # serve loop swaps cache_index to the per-row vector
                # before any real apply
                return jnp.zeros_like(q)
            raise ValueError(
                "the paged cache decodes through per-row vector "
                "cache_index only (ServeLoop with cache_layout='paged'); "
                "scalar-index rollouts use the dense layout")
        # s > 1 is the speculative verify chunk (staged in the side
        # buffer like single steps; prefill still goes through a dense
        # batch-1 side cache and serving._insert scatters it into pages)
        if self.decode_shard is not None:
            raise NotImplementedError(
                "sharded decode over the paged cache is not wired yet; "
                "serve paged through the replicated path")
        if cfg.attention_window is not None:
            raise ValueError(
                "the paged cache has no sliding-window trim yet; use "
                "cache_layout='dense' for windowed models")
        if self.serve_side_slots <= 0:
            raise ValueError(
                "cache_layout='paged' requires serve_side_slots > 0 "
                "(the pool is frozen within a segment; tokens stage in "
                "the side buffer)")
        cap = self.serve_side_slots
        side_k = self.variable(
            "cache", "side_key", jnp.zeros, (b, cap, flat),
            cfg.compute_dtype)
        side_v = self.variable(
            "cache", "side_value", jnp.zeros, (b, cap, flat),
            cfg.compute_dtype)
        side_idx = self.variable(
            "cache", "side_index", lambda: jnp.zeros((), jnp.int32))
        s_base = side_idx.value
        s_at = jnp.minimum(s_base, cap - s)
        side_k.value = jax.lax.dynamic_update_slice(
            side_k.value,
            k.reshape(b, s, flat).astype(side_k.value.dtype), (0, s_at, 0))
        side_v.value = jax.lax.dynamic_update_slice(
            side_v.value,
            v.reshape(b, s, flat).astype(side_v.value.dtype), (0, s_at, 0))
        side_idx.value = s_base + s

        if self.decode_attention == "flash":
            from tpudist.ops.flash_decode import paged_flash_decode

            return paged_flash_decode(
                q, paged_k.value, paged_v.value, table.value, idx,
                packed_kv_heads=h_kv, side_k=side_k.value,
                side_v=side_v.value, side_len=side_idx.value)
        # dense fallback: gather the slot's pages into a contiguous view
        # (one full-logical-cache copy per step — fine on CPU, the reason
        # the kernel exists on TPU) and mask main + side positions;
        # chunk query j (s > 1, speculative verify) sees side positions
        # [0, s_base + j] — causal within the chunk it just wrote
        from tpudist.ops.flash_decode import paged_gather_kv

        k_main = paged_gather_kv(paged_k.value, table.value)
        v_main = paged_gather_kv(paged_v.value, table.value)
        s_all = k_main.shape[1]
        mask_main = jnp.broadcast_to(
            (jnp.arange(s_all)[None, :] < idx[:, None])[:, None],
            (b, s, s_all))                                     # [B, s, S']
        mask_side = jnp.broadcast_to(
            jnp.arange(cap)[None, None, :]
            < s_base + jnp.arange(s)[None, :, None] + 1,
            (b, s, cap))                                       # [B, s, cap]
        mask = jnp.concatenate([mask_main, mask_side], axis=2)
        k_all = jnp.concatenate([k_main, side_k.value], axis=1)
        v_all = jnp.concatenate([v_main, side_v.value], axis=1)
        k4 = k_all.reshape(b, s_all + cap, h_kv, d)
        v4 = v_all.reshape(b, s_all + cap, h_kv, d)
        k_rep, v_rep = repeat_kv(q, k4, v4)
        return _masked_attend(q, k_rep, v_rep, mask[:, None])

    def _prefill_attend(self, q, k_all, v_all, idx):
        """Chunk prefill: queries at global positions [idx, idx+s) attend
        over the cache's first idx+s slots, causally.  The flash path
        reuses the forward kernel at ``q_offset=idx`` (its causal mask
        also silences the garbage in not-yet-written slots; dead tiles are
        pruned); the dense path builds the banded mask explicitly."""
        cfg = self.cfg
        s = q.shape[1]
        seq_sharded = (self.decode_shard is not None
                       and _shard_kind(self.decode_shard)
                       in ("seq", "heads_seq"))
        # seq-sharded prefill stays on the dense GSPMD path below: the
        # queries attend across every shard's cache slice, which GSPMD
        # partitions into per-shard partial attention + reductions
        # (measured HLO: no cache all-gather), while a Pallas call cannot
        # be partitioned at all
        if self.decode_attention == "flash" and not seq_sharded:
            from tpudist.ops.flash_attention import (
                _auto_block, _flash_forward,
            )

            # Pad the query-row count so _auto_block lands on a Mosaic-
            # lowerable block: the LSE output's [1, 1, block_q] block
            # needs block_q % 128 == 0 or block_q == s_pad, and q/out
            # need the 8-row sublane tile.  Short chunks round up to a
            # power of two (block = whole chunk); long ones to a multiple
            # of 1024 so block_q is the measured-optimal 1024 (a prompt
            # like 7928 = 8·991 would otherwise get block_q = 8, which
            # real-TPU lowering rejects).  Padded rows are causally
            # garbage but independent of the real rows; sliced off below.
            if s <= 1024:
                s_pad = max(8, 1 << (s - 1).bit_length())
            else:
                s_pad = -(-s // 1024) * 1024
            q_in = q if s_pad == s else jnp.pad(
                q, ((0, 0), (0, s_pad - s), (0, 0), (0, 0)))
            block_k = _auto_block(cfg.max_seq_len)
            if block_k < 8:  # the K side has the same sublane floor
                raise ValueError(
                    f"decode_attention='flash' needs a power-of-two factor "
                    f">= 8 in max_seq_len (got {cfg.max_seq_len}); round "
                    f"max_seq_len up to a multiple of 8")
            interp = jax.default_backend() == "cpu"
            bq = _auto_block(s_pad)
            if self.decode_shard is not None:
                def local(qs, ks, vs, off):
                    out, _ = _flash_forward(
                        qs, ks, vs, True, bq, block_k, interp,
                        q_offset=off, window=cfg.attention_window)
                    return out

                out = _head_sharded(self.decode_shard, local,
                                    q_in, k_all, v_all, idx)
                return out[:, :s]
            out, _ = _flash_forward(
                q_in, k_all, v_all, True, bq, block_k, interp,
                q_offset=idx, window=cfg.attention_window)
            return out[:, :s]
        q_pos = idx + jnp.arange(s)[:, None]                  # [s, 1]
        k_pos = jnp.arange(cfg.max_seq_len)[None, :]          # [1, S]
        mask = k_pos <= q_pos
        if cfg.attention_window is not None:
            mask = mask & (q_pos - k_pos < cfg.attention_window)
        k_all, v_all = repeat_kv(q, k_all, v_all)
        return _masked_attend(q, k_all, v_all, mask[None, None])


class MLPBlock(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.cfg
        h = nn.Dense(cfg.mlp_ratio * cfg.embed_dim, use_bias=False,
                     dtype=cfg.compute_dtype, name="up")(x)
        h = nn.gelu(h)
        return nn.Dense(cfg.embed_dim, use_bias=False,
                        dtype=cfg.compute_dtype, name="down")(h)


class DecoderBlock(nn.Module):
    cfg: TransformerConfig
    attention_fn: AttentionFn = sdpa
    decode: bool = False
    decode_attention: str = "dense"
    decode_shard: Any = None
    serve_side_slots: int = 0
    cache_layout: str = "dense"
    kv_num_blocks: int = 0
    kv_block_size: int = 0

    @nn.compact
    def __call__(self, x: jnp.ndarray, causal: bool = True) -> jnp.ndarray:
        # NOTE: ``causal`` is positional (arg 2) so nn.remat can mark it
        # static (static_argnums) — keyword args would be traced.
        h = nn.LayerNorm(dtype=self.cfg.compute_dtype, name="ln1")(x)
        x = x + CausalSelfAttention(self.cfg, self.attention_fn,
                                    decode=self.decode,
                                    decode_attention=self.decode_attention,
                                    decode_shard=self.decode_shard,
                                    serve_side_slots=self.serve_side_slots,
                                    cache_layout=self.cache_layout,
                                    kv_num_blocks=self.kv_num_blocks,
                                    kv_block_size=self.kv_block_size,
                                    name="attn")(h, causal=causal)
        h = nn.LayerNorm(dtype=self.cfg.compute_dtype, name="ln2")(x)
        return x + MLPBlock(self.cfg, name="mlp")(h)


class _ScanBody(nn.Module):
    """One scanned step of the layer stack: wraps :class:`DecoderBlock`
    with the ``(carry, x) -> (carry, y)`` signature ``nn.scan`` expects.
    ``causal`` rides as a static attribute (it must not be traced)."""

    cfg: TransformerConfig
    attention_fn: AttentionFn
    decode: bool
    decode_attention: str
    decode_shard: Any
    causal: bool
    remat: bool

    @nn.compact
    def __call__(self, x, _):
        blk = (nn.remat(DecoderBlock, static_argnums=(2,)) if self.remat
               else DecoderBlock)
        x = blk(self.cfg, self.attention_fn, decode=self.decode,
                decode_attention=self.decode_attention,
                decode_shard=self.decode_shard,
                name="block")(x, self.causal)
        return x, None


def stack_layer_params(params, num_layers: int):
    """Convert unrolled-layout params (``block{i}/...``) to the
    ``scan_layers`` layout (``blocks/block/...`` with a leading layer
    axis) — e.g. to serve a model trained unrolled through a scanned
    rollout.  Non-block leaves pass through unchanged."""
    out = {k: v for k, v in params.items() if not k.startswith("block")}
    blocks = [params[f"block{i}"] for i in range(num_layers)]
    out["blocks"] = {
        "block": jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)}
    return out


def unstack_layer_params(params, num_layers: int):
    """Inverse of :func:`stack_layer_params`."""
    out = {k: v for k, v in params.items() if k != "blocks"}
    stacked = params["blocks"]["block"]
    for i in range(num_layers):
        out[f"block{i}"] = jax.tree.map(lambda x: x[i], stacked)
    return out


class TransformerLM(nn.Module):
    """Decoder-only LM: tokens [B, S] int32 -> logits [B, S, vocab] f32.

    With ``decode=True`` the attention layers keep a KV cache in the flax
    ``cache`` collection and expect one token per call — see
    :func:`tpudist.models.generate.greedy_generate`.
    """

    cfg: TransformerConfig
    attention_fn: AttentionFn = sdpa
    decode: bool = False
    remat: bool = False
    decode_attention: str = "dense"
    decode_shard: Any = None
    serve_side_slots: int = 0
    cache_layout: str = "dense"
    kv_num_blocks: int = 0
    kv_block_size: int = 0

    @nn.compact
    def __call__(
        self,
        tokens: jnp.ndarray,
        *,
        causal: bool = True,
        positions: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        cfg = self.cfg
        if positions is None:
            positions = jnp.arange(tokens.shape[1])[None, :]
        x = nn.Embed(cfg.vocab_size, cfg.embed_dim,
                     dtype=cfg.compute_dtype, name="tok_embed")(tokens)
        x = x + nn.Embed(cfg.max_seq_len, cfg.embed_dim,
                         dtype=cfg.compute_dtype, name="pos_embed")(positions)
        # remat: recompute each block's activations in backward instead of
        # storing them — the jax.checkpoint memory/FLOPs trade that makes
        # long-context training fit in HBM.  Default prevent_cse=True:
        # under plain jit XLA could otherwise CSE the recomputation back
        # into the stored forward and silently undo the memory savings.
        if cfg.scan_layers:
            if self.serve_side_slots:
                raise ValueError(
                    "serve_side_slots requires the unrolled layout "
                    "(scan_layers=False); serving normalizes via "
                    "serving_layout / auto_unstack")
            if self.cache_layout != "dense":
                raise ValueError(
                    "cache_layout='paged' requires the unrolled layout "
                    "(scan_layers=False), same as serve_side_slots")
            scanned = nn.scan(
                _ScanBody,
                variable_axes={"params": 0, "cache": 0},
                split_rngs={"params": True},
                length=cfg.num_layers,
            )
            x, _ = scanned(cfg, self.attention_fn, self.decode,
                           self.decode_attention, self.decode_shard,
                           causal, self.remat, name="blocks")(x, None)
        else:
            block_cls = (nn.remat(DecoderBlock, static_argnums=(2,))
                         if self.remat else DecoderBlock)
            for i in range(cfg.num_layers):
                x = block_cls(cfg, self.attention_fn, decode=self.decode,
                              decode_attention=self.decode_attention,
                              decode_shard=self.decode_shard,
                              serve_side_slots=self.serve_side_slots,
                              cache_layout=self.cache_layout,
                              kv_num_blocks=self.kv_num_blocks,
                              kv_block_size=self.kv_block_size,
                              name=f"block{i}")(x, causal)
        x = nn.LayerNorm(dtype=cfg.compute_dtype, name="ln_f")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False,
                          dtype=cfg.compute_dtype, name="lm_head")(x)
        return logits.astype(jnp.float32)
