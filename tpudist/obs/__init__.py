"""tpudist.obs — distributed observability: metrics, spans, aggregation,
exporters.

The subsystem every layer reports through (see docs/OBSERVABILITY.md):

* :mod:`tpudist.obs.registry` — counters / gauges / log-bucket histograms
  with MetricLogger-style lazy device accumulation (recording never
  syncs; one batched ``device_get`` per snapshot).
* :mod:`tpudist.obs.spans` — ``with obs.span("train_step"):`` Chrome-trace
  timelines, optional ``jax.effects_barrier()`` fencing, composes with
  the XProf trace from :func:`tpudist.utils.metrics.maybe_profile`.
* :mod:`tpudist.obs.aggregate` — workers publish snapshots through the
  coord KV store; rank 0 merges them into a cluster view.
* :mod:`tpudist.obs.export` — bench-schema JSONL, Prometheus text, and a
  stdlib-only HTTP ``/metrics`` endpoint.

Module-level conveniences bind to one process-global registry and tracer,
so library code can just ``from tpudist import obs; obs.counter(...)``.
Env knobs (parsed by :func:`tpudist.utils.config.env_flag`, so ``=0`` and
``=false`` really mean off): ``TPUDIST_OBS_FENCE`` fences spans with
``jax.effects_barrier()``.
"""

from __future__ import annotations

from tpudist.obs.aggregate import (
    MetricsPublisher,
    collect,
    collect_and_merge,
    merge_snapshots,
)
from tpudist.obs.export import (
    MetricsServer,
    jsonl_line,
    snapshot_to_jsonl,
    to_prometheus,
)
from tpudist.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricRegistry,
    hist_quantile,
    summarize,
)
from tpudist.obs.spans import SpanTracer

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricRegistry",
    "MetricsPublisher",
    "MetricsServer",
    "SpanTracer",
    "collect",
    "collect_and_merge",
    "counter",
    "gauge",
    "histogram",
    "hist_quantile",
    "jsonl_line",
    "merge_snapshots",
    "registry",
    "snapshot",
    "snapshot_to_jsonl",
    "span",
    "summarize",
    "to_prometheus",
    "tracer",
]

# process-global registry + tracer: instrumentation all over the stack
# reports here, snapshot()/tracer.dump() read it out
registry = MetricRegistry()
tracer = SpanTracer()

counter = registry.counter
gauge = registry.gauge
histogram = registry.histogram
snapshot = registry.snapshot
span = tracer.span
