"""tpudist.obs — distributed observability: metrics, spans, aggregation,
exporters, and the health plane.

The subsystem every layer reports through (see docs/OBSERVABILITY.md):

* :mod:`tpudist.obs.registry` — counters / gauges / log-bucket histograms
  with MetricLogger-style lazy device accumulation (recording never
  syncs; one batched ``device_get`` per snapshot).
* :mod:`tpudist.obs.spans` — ``with obs.span("train_step"):`` Chrome-trace
  timelines, optional ``jax.effects_barrier()`` fencing, composes with
  the XProf trace from :func:`tpudist.utils.metrics.maybe_profile`.
* :mod:`tpudist.obs.aggregate` — workers publish snapshots through the
  coord KV store; rank 0 merges them into a cluster view.
* :mod:`tpudist.obs.export` — bench-schema JSONL, Prometheus text, and a
  stdlib-only HTTP ``/metrics`` + ``/healthz`` endpoint.
* :mod:`tpudist.obs.health` — rank-0 straggler/staleness classification
  over the published snapshots, with hysteresis.
* :mod:`tpudist.obs.recorder` — bounded flight-recorder ring and crash
  post-mortem bundles (``with obs.recorder.guard("trainer"): ...``).
* :mod:`tpudist.obs.events` — per-request distributed tracing: trace
  contexts riding the serve fleet's wire format, the request-event
  ring each process records lifecycle transitions into, fleet-wide
  timeline merge (``python -m tpudist.obs.timeline`` renders it), and
  SLO burn-rate accounting (:class:`SLOTracker`).
* :mod:`tpudist.obs.xla` — XLA compile/memory/cost telemetry: compile
  counts and durations, per-device HBM gauges, live MFU.
* :mod:`tpudist.obs.tsdb` — bounded in-memory time-series store scraped
  from the registry/merged snapshots on a cadence, with
  rate/delta/quantile_over_time queries (:class:`TSDB`,
  :class:`FleetScraper`).
* :mod:`tpudist.obs.alerts` — declarative alert rules (query +
  predicate + hold) with a pending->firing->resolved lifecycle; the
  sim's scenario matrix regression-tests the shipped defaults.
* :mod:`tpudist.obs.console` — ``python -m tpudist.obs.console``: live
  terminal dashboard (topology, sparklines, firing alerts, recent
  trace terminals); ``--once`` renders a single frame for CI.

Module-level conveniences bind to one process-global registry, tracer and
flight recorder, so library code can just ``from tpudist import obs;
obs.counter(...)``.  Env knobs (parsed by
:func:`tpudist.utils.config.env_flag`, so ``=0`` and ``=false`` really
mean off): ``TPUDIST_OBS_FENCE`` fences spans with
``jax.effects_barrier()``; ``TPUDIST_POSTMORTEM_DIR`` picks where crash
bundles land.
"""

from __future__ import annotations

from tpudist.obs.aggregate import (
    MetricsPublisher,
    collect,
    collect_and_merge,
    merge_snapshots,
)
from tpudist.obs.alerts import (
    AlertManager,
    AlertRule,
    autoscale_rules,
    default_rules,
    load_rules,
    rules_hash,
)
from tpudist.obs.events import (
    EventPublisher,
    RequestEventLog,
    SLOTracker,
    TraceContext,
    collect_events,
    group_timelines,
    is_complete,
    merge_events,
    timeline_for_rid,
)
from tpudist.obs.export import (
    MetricsServer,
    jsonl_line,
    snapshot_to_jsonl,
    to_prometheus,
)
from tpudist.obs.health import HealthMonitor, HealthWatcher
from tpudist.obs.recorder import POSTMORTEM_SCHEMA, FlightRecorder
from tpudist.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricRegistry,
    hist_quantile,
    summarize,
)
from tpudist.obs.spans import SpanTracer, atomic_write_json
from tpudist.obs.tsdb import TSDB, FleetScraper
from tpudist.obs.xla import (
    install_compile_telemetry,
    mfu,
    note_compile,
    note_step,
    peak_tflops,
    update_memory_gauges,
)

__all__ = [
    "AlertManager",
    "AlertRule",
    "Counter",
    "EventPublisher",
    "FleetScraper",
    "FlightRecorder",
    "Gauge",
    "HealthMonitor",
    "HealthWatcher",
    "Histogram",
    "MetricRegistry",
    "MetricsPublisher",
    "MetricsServer",
    "POSTMORTEM_SCHEMA",
    "RequestEventLog",
    "SLOTracker",
    "SpanTracer",
    "TSDB",
    "TraceContext",
    "atomic_write_json",
    "autoscale_rules",
    "collect",
    "collect_and_merge",
    "collect_events",
    "counter",
    "default_rules",
    "events",
    "gauge",
    "group_timelines",
    "histogram",
    "hist_quantile",
    "install_compile_telemetry",
    "is_complete",
    "jsonl_line",
    "load_rules",
    "merge_events",
    "merge_snapshots",
    "mfu",
    "note_compile",
    "note_step",
    "peak_tflops",
    "recorder",
    "registry",
    "rules_hash",
    "slo",
    "snapshot",
    "snapshot_to_jsonl",
    "span",
    "summarize",
    "timeline_for_rid",
    "to_prometheus",
    "tracer",
    "update_memory_gauges",
]

# process-global registry + tracer + event ring + SLO tracker + flight
# recorder: instrumentation all over the stack reports here;
# snapshot()/tracer.dump()/events.snapshot()/recorder.dump() read it out
registry = MetricRegistry()
tracer = SpanTracer()
events = RequestEventLog()
slo = SLOTracker(registry=registry)
recorder = FlightRecorder(registry=registry, tracer=tracer,
                          request_events=events)

counter = registry.counter
gauge = registry.gauge
histogram = registry.histogram
snapshot = registry.snapshot
span = tracer.span
