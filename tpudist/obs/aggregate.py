"""Cross-host aggregation over the coord KV service.

Each worker periodically publishes its registry snapshot as JSON under
``{namespace}/{rank}`` through :class:`tpudist.runtime.coord.CoordClient`
(set is last-write-wins, so a slow worker's stale snapshot is simply
replaced by its next publish).  Rank 0 — or any observer with a client —
collects every published snapshot and merges them into one cluster view:

* counters: sum across workers;
* gauges: sum across workers (queue depths and world sizes add; a
  consumer that wants one worker's value reads ``per_worker``);
* histograms: bucket-by-bucket count merge (the whole point of the
  log-bucket design — quantiles of the merged histogram are computed
  from merged counts, never averaged from per-worker quantiles).

Every merged entry carries ``per_worker`` ({rank: value/count}) so the
cluster view keeps per-host attribution for debugging skew.

This is pull-based pub/sub on a plain KV store: no new coord verbs, and
a worker that dies mid-round just stops refreshing its key.
"""

from __future__ import annotations

import json
import threading
import time

from tpudist.runtime import faults

__all__ = ["MetricsPublisher", "collect", "merge_snapshots"]

DEFAULT_NAMESPACE = "obs/metrics"


class MetricsPublisher:
    """Publishes ``registry.snapshot()`` to the coord store, either on
    demand (:meth:`publish`) or on a background daemon thread every
    ``interval_s`` (:meth:`start` / :meth:`stop`).  The background thread
    takes its own client clone — CoordClient sockets are not shared
    across threads."""

    def __init__(self, client, rank: int, registry,
                 namespace: str = DEFAULT_NAMESPACE,
                 interval_s: float = 5.0) -> None:
        self._client = client
        self._rank = rank
        self._registry = registry
        self._namespace = namespace
        self._interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def key(self) -> str:
        return f"{self._namespace}/{self._rank}"

    def publish(self, client=None) -> dict:
        snap = self._registry.snapshot()
        snap["rank"] = self._rank
        # the staleness stamp: collect() ages each snapshot off this, so
        # consumers can drop (or the health plane can flag) leftovers
        # from ranks that died in a previous elastic round
        snap["published_at"] = time.time()
        # fault harness (TPUDIST_FAULT_PUBLISH_DROP): swallow the store
        # write while heartbeats keep flowing — the end-to-end shape of
        # a wedged obs plane, which the health monitor must classify
        # `stale` (not `lost`) and a router must NOT treat as a death
        if faults.drop_publish():
            return snap
        (client or self._client).set(
            self.key, json.dumps(snap).encode("utf-8"))
        return snap

    def start(self) -> None:
        if self._thread is not None:
            return

        def loop() -> None:
            client = self._client.clone()
            try:
                while not self._stop.wait(self._interval_s):
                    try:
                        self.publish(client)
                    except Exception:
                        # the store may be tearing down; keep trying
                        # until stop() — observability must never take
                        # the worker down
                        pass
            finally:
                client.close()

        self._thread = threading.Thread(
            target=loop, name=f"obs-publish-r{self._rank}", daemon=True)
        self._thread.start()

    def stop(self, final_publish: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final_publish:
            try:
                self.publish()
            except Exception:
                pass


def collect(client, namespace: str = DEFAULT_NAMESPACE,
            max_age_s: float | None = None,
            members: "set[int] | None" = None) -> dict[int, dict]:
    """Fetch every published snapshot: {rank: snapshot}.  Keys listed but
    deleted between list and get (a departing worker) are skipped.

    Each returned snapshot carries ``age_s`` — seconds since its
    ``published_at`` stamp (None for pre-stamp publishers).  With
    ``max_age_s``, snapshots older than that are DROPPED: a rank that
    died in a previous elastic round leaves its last snapshot in the KV
    store forever, and merging it would silently distort the cluster
    view.  The health plane collects WITHOUT a cutoff and classifies the
    stale ranks instead.

    ``members`` is the stronger, membership-based cutoff: when given
    (ranks currently registered in ``{ns}/replica/*``), snapshots from
    any OTHER rank are dropped regardless of age.  A publisher that
    departs mid-histogram-window otherwise leaves its last generation
    pinned in merged quantiles until ``max_age_s`` — up to an entire
    collection window of a dead replica's queue waits steering the
    autoscaler.  ``None`` means "no membership information", not "no
    members": collection stays age-based only."""
    out: dict[int, dict] = {}
    prefix = namespace + "/"
    now = time.time()
    for key in client.keys(prefix):
        if members is not None:
            try:
                if int(key[len(prefix):]) not in members:
                    continue
            except ValueError:
                continue
        raw = client.get(key)
        if raw is None:
            continue
        snap = json.loads(raw.decode("utf-8"))
        published = snap.get("published_at")
        age = (now - published) if published is not None else None
        snap["age_s"] = age
        if max_age_s is not None and age is not None and age > max_age_s:
            continue
        out[int(key[len(prefix):])] = snap
    return out


def _merge_hist(merged: dict, hist: dict, rank: int) -> None:
    if hist["growth"] != merged["growth"]:
        raise ValueError(
            f"cannot merge histograms with growth {hist['growth']} into "
            f"{merged['growth']}: bucket indices are incompatible")
    merged["count"] += hist["count"]
    merged["sum"] += hist["sum"]
    merged["zero"] += hist.get("zero", 0)
    for bound in ("min", "max"):
        vals = [v for v in (merged[bound], hist[bound]) if v is not None]
        merged[bound] = (min(vals) if bound == "min" else max(vals)) \
            if vals else None
    for idx, n in hist["buckets"].items():
        merged["buckets"][idx] = merged["buckets"].get(idx, 0) + n
    merged["per_worker"][str(rank)] = hist["count"]


def merge_snapshots(snapshots: dict[int, dict]) -> dict:
    """Merge per-worker snapshots into the cluster view (sum counters and
    gauges, merge histogram buckets), keeping ``per_worker`` attribution
    on every metric."""
    merged: dict = {"workers": sorted(snapshots),
                    "counters": {}, "gauges": {}, "histograms": {}}
    ages = {str(rank): snapshots[rank].get("age_s")
            for rank in sorted(snapshots)
            if "age_s" in snapshots[rank]}
    if ages:
        merged["ages"] = ages
    for rank in sorted(snapshots):
        snap = snapshots[rank]
        for kind in ("counters", "gauges"):
            for name, m in snap.get(kind, {}).items():
                slot = merged[kind].setdefault(
                    name, {"value": 0.0, "unit": m.get("unit", ""),
                           "per_worker": {}})
                if m["value"] is not None:
                    slot["value"] += m["value"]
                slot["per_worker"][str(rank)] = m["value"]
                if m.get("help") and "help" not in slot:
                    slot["help"] = m["help"]
        for name, h in snap.get("histograms", {}).items():
            slot = merged["histograms"].get(name)
            if slot is None:
                slot = merged["histograms"][name] = {
                    "unit": h.get("unit", ""), "growth": h["growth"],
                    "count": 0, "sum": 0.0, "min": None, "max": None,
                    "zero": 0, "buckets": {}, "per_worker": {}}
            if h.get("help") and "help" not in slot:
                slot["help"] = h["help"]
            _merge_hist(slot, h, rank)
    # canonical bucket order for stable JSON / prometheus output
    for h in merged["histograms"].values():
        h["buckets"] = {str(i): h["buckets"][str(i)]
                        for i in sorted(int(k) for k in h["buckets"])}
    return merged


def collect_and_merge(client, namespace: str = DEFAULT_NAMESPACE,
                      max_age_s: float | None = None) -> dict:
    """Rank 0's one-call cluster view; ``max_age_s`` drops dead ranks'
    leftover snapshots (see :func:`collect`)."""
    merged = merge_snapshots(collect(client, namespace,
                                     max_age_s=max_age_s))
    merged["time"] = time.time()
    return merged
