"""Declarative alert rules over the TSDB.

A rule is a query + predicate + hold duration, declared as data the
same way ``ScenarioSpec`` declares a scenario: a dict (or JSON doc)
with unknown keys rejected, so a typo'd rule fails at load time
instead of silently never firing.

    {"name": "CoordOutage", "metric": "fleet/coord_up",
     "fn": "last", "window_s": 5, "op": "<", "threshold": 0.5,
     "for_s": 1.0, "severity": "page"}

:class:`AlertManager` evaluates a rule set against a :class:`~.tsdb.TSDB`
on each tick and runs the pending -> firing -> resolved lifecycle: a
breach opens a *pending* alert, which *fires* once it has held for
``for_s`` seconds, and *resolves* the first tick the predicate stops
holding.  Alerts are deduplicated by rule name; lifecycle counts are
exported as ``alerts/*`` counters and gauges when a registry is given.

Two rule sets ship with the repo:

* :func:`default_rules` — the fleet operator set (SLO burn, KV/tier
  headroom, coord outage, quarantine, stale publishers,
  handoff-fallback spikes, replica loss).  The sim's builtin scenarios
  regression-test these: each scenario's ``alerts:`` envelope says
  which rules must and must not fire.
* :func:`autoscale_rules` — the Autoscaler's breach predicates,
  expressed as rules over its own private TSDB so scaling decisions
  read fired alerts through the same interface instead of bespoke
  threshold probes.

:func:`rules_hash` gives a stable short hash of a loaded rule set; the
bench stamps it onto every JSONL row so trajectory comparisons detect
silent rule drift.
"""

from __future__ import annotations

import hashlib
import json
import operator
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

from .tsdb import TSDB

__all__ = ["AlertRule", "AlertManager", "default_rules", "autoscale_rules",
           "load_rules", "rules_hash", "ALERT_FNS", "ALERT_OPS",
           "SEVERITIES"]

ALERT_FNS = ("last", "rate", "delta", "avg_over_time", "max_over_time",
             "min_over_time", "quantile_over_time")
ALERT_OPS: dict[str, Callable[[float, float], bool]] = {
    ">": operator.gt, "<": operator.lt, ">=": operator.ge,
    "<=": operator.le, "==": operator.eq, "!=": operator.ne,
}
SEVERITIES = ("info", "warn", "page")

_RULE_KEYS = {"name", "metric", "fn", "window_s", "q", "op", "threshold",
              "for_s", "severity", "labels", "match", "help"}


@dataclass(frozen=True)
class AlertRule:
    """One declarative rule: ``fn(metric, window_s) op threshold``
    holding for ``for_s`` seconds."""

    name: str
    metric: str
    op: str
    threshold: float
    fn: str = "last"
    window_s: float | None = None
    q: float | None = None          # quantile_over_time only
    for_s: float = 0.0
    severity: str = "warn"
    labels: dict = field(default_factory=dict)   # attached to the alert
    match: dict = field(default_factory=dict)    # series label selector
    help: str = ""

    def __post_init__(self):
        if not self.name or not self.metric:
            raise ValueError("alert rule needs name and metric")
        if self.fn not in ALERT_FNS:
            raise ValueError(f"rule {self.name}: unknown fn {self.fn!r} "
                             f"(choose from {ALERT_FNS})")
        if self.op not in ALERT_OPS:
            raise ValueError(f"rule {self.name}: unknown op {self.op!r}")
        if self.severity not in SEVERITIES:
            raise ValueError(f"rule {self.name}: unknown severity "
                             f"{self.severity!r} (choose from {SEVERITIES})")
        if self.fn != "last" and self.window_s is None:
            raise ValueError(f"rule {self.name}: fn {self.fn!r} needs "
                             f"window_s")
        if self.fn == "quantile_over_time" and self.q is None:
            raise ValueError(f"rule {self.name}: quantile_over_time needs q")
        if self.for_s < 0:
            raise ValueError(f"rule {self.name}: for_s must be >= 0")

    @classmethod
    def from_dict(cls, d: dict) -> "AlertRule":
        if not isinstance(d, dict):
            raise TypeError(f"alert rule must be a dict, got {type(d)}")
        unknown = set(d) - _RULE_KEYS
        if unknown:
            raise ValueError(
                f"alert rule {d.get('name', '?')!r}: unknown keys "
                f"{sorted(unknown)} (allowed: {sorted(_RULE_KEYS)})")
        for key in ("name", "metric", "op", "threshold"):
            if key not in d:
                raise ValueError(f"alert rule missing required key {key!r}")
        kw = dict(d)
        kw["threshold"] = float(kw["threshold"])
        if kw.get("window_s") is not None:
            kw["window_s"] = float(kw["window_s"])
        if kw.get("q") is not None:
            kw["q"] = float(kw["q"])
        kw["for_s"] = float(kw.get("for_s", 0.0))
        return cls(**kw)

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"name": self.name, "metric": self.metric,
                             "fn": self.fn, "op": self.op,
                             "threshold": self.threshold,
                             "for_s": self.for_s,
                             "severity": self.severity}
        if self.window_s is not None:
            d["window_s"] = self.window_s
        if self.q is not None:
            d["q"] = self.q
        if self.labels:
            d["labels"] = dict(sorted(self.labels.items()))
        if self.match:
            d["match"] = dict(sorted(self.match.items()))
        if self.help:
            d["help"] = self.help
        return d

    def value(self, tsdb: TSDB, at: float | None = None) -> float | None:
        """Evaluate the query half against the store."""
        m, w, sel = self.metric, self.window_s, (self.match or None)
        if self.fn == "last":
            return tsdb.latest(m, labels=sel, window_s=w, at=at)
        if self.fn == "rate":
            return tsdb.rate(m, w, labels=sel, at=at)
        if self.fn == "delta":
            return tsdb.delta(m, w, labels=sel, at=at)
        if self.fn == "avg_over_time":
            return tsdb.avg_over_time(m, w, labels=sel, at=at)
        if self.fn == "max_over_time":
            return tsdb.max_over_time(m, w, labels=sel, at=at)
        if self.fn == "min_over_time":
            return tsdb.min_over_time(m, w, labels=sel, at=at)
        return tsdb.quantile_over_time(m, self.q, w, labels=sel, at=at)

    def test(self, value: float | None) -> bool:
        """Predicate half; absent (None) never breaches, and NaN
        compares False under every op."""
        if value is None:
            return False
        return ALERT_OPS[self.op](value, self.threshold)


def load_rules(docs: Iterable[dict] | str) -> tuple[AlertRule, ...]:
    """Rules from a list of dicts, a JSON string, or a JSON file path
    (the doc may be a bare list or ``{"rules": [...]}``)."""
    if isinstance(docs, str):
        text = docs
        if not docs.lstrip().startswith(("[", "{")):
            with open(docs, encoding="utf-8") as f:
                text = f.read()
        parsed = json.loads(text)
        docs = parsed.get("rules", []) if isinstance(parsed, dict) else parsed
    rules = tuple(AlertRule.from_dict(d) if isinstance(d, dict) else d
                  for d in docs)
    seen: set[str] = set()
    for r in rules:
        if r.name in seen:
            raise ValueError(f"duplicate alert rule name {r.name!r}")
        seen.add(r.name)
    return rules


def rules_hash(rules: Iterable[AlertRule]) -> str:
    """Stable short hash of a rule set (order-insensitive): bench rows
    carry it so silent rule drift shows up in trajectory diffs."""
    canon = json.dumps(sorted((r.to_dict() for r in rules),
                              key=lambda d: d["name"]),
                       sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()[:12]


def default_rules() -> tuple[AlertRule, ...]:
    """The shipped fleet-operator rule set.  Thresholds are regression
    -tested by the sim scenario matrix (each builtin scenario's
    ``alerts:`` envelope pins which of these must and must not fire)."""
    mk = AlertRule
    return (
        mk(name="CoordOutage", metric="fleet/coord_up", fn="last",
           window_s=5.0, op="<", threshold=0.5, for_s=1.0, severity="page",
           help="metric collection cannot reach the coordinator"),
        mk(name="ReplicaLost", metric="router/replica_deaths", fn="delta",
           window_s=30.0, op=">", threshold=0.0, severity="page",
           help="the router declared a replica dead in the last 30s"),
        mk(name="QuarantineActive", metric="router/quarantines", fn="delta",
           window_s=30.0, op=">", threshold=0.0, severity="warn",
           help="a replica was quarantined for output corruption"),
        mk(name="SLOBurnHigh", metric="slo/burn_rate_60s", fn="last",
           window_s=10.0, op=">", threshold=2.0, for_s=2.0, severity="page",
           help="error budget burning >2x sustainable in the 60s window"),
        mk(name="QueueWaitHigh", metric="serve/queue_wait_s/p90",
           fn="last", window_s=5.0, op=">", threshold=1.0, for_s=2.0,
           severity="warn",
           help="p90 admission wait over 1s across the fleet"),
        mk(name="KVHeadroomLow", metric="fleet/kv_free_frac", fn="last",
           window_s=5.0, op="<", threshold=0.10, for_s=2.0, severity="warn",
           help="fleet KV pool nearly exhausted (<10% free)"),
        mk(name="TierHeadroomLow", metric="fleet/tier_headroom_frac",
           fn="last", window_s=5.0, op="<", threshold=0.10, for_s=2.0,
           severity="warn",
           help="host-RAM spill tier nearly full (<10% headroom)"),
        mk(name="StalePublisher", metric="fleet/max_publish_age_s",
           fn="max_over_time", window_s=10.0, op=">", threshold=15.0,
           severity="warn",
           help="a replica's metrics snapshot is older than 15s"),
        mk(name="HandoffFallbackSpike", metric="serve/handoff_fallbacks",
           fn="delta", window_s=60.0, op=">", threshold=3.0, severity="warn",
           help="disagg prefill->decode handoffs falling back to "
                "re-prefill faster than 3/min"),
        mk(name="MigrationFallbackSpike",
           metric="router/migration_fallbacks",
           fn="delta", window_s=60.0, op=">", threshold=3.0, severity="warn",
           help="live KV migrations losing their payload (ref-less "
                "commit or adopt-side fetch miss -> re-prefill) faster "
                "than 3/min"),
        mk(name="FleetDegraded", metric="serve/degraded", fn="last",
           window_s=5.0, op=">", threshold=0.0, severity="warn",
           help="a replica is advertising degraded service"),
    )


def autoscale_rules(cfg) -> tuple[AlertRule, ...]:
    """The Autoscaler's breach predicates as declarative rules over its
    private per-poll TSDB (``autoscale/*`` series recorded each poll).

    These mirror the historical inline thresholds exactly — fn="last"
    with no staleness window reads the value recorded by the current
    poll (absent signals are recorded as NaN, which no predicate
    matches), and for_s=0 fires in the same evaluation, so the breach
    streak/cooldown hysteresis above stays byte-identical.
    """
    rules = [AlertRule(name="AutoscaleQueueWait", metric="autoscale/wait_q",
                       op=">", threshold=cfg.target_wait_s, severity="info",
                       help="queue wait quantile above target_wait_s")]
    if getattr(cfg, "max_burn_rate", None) is not None:
        rules.append(AlertRule(
            name="AutoscaleBurnRate", metric="autoscale/burn_rate",
            op=">", threshold=cfg.max_burn_rate, severity="info",
            help="SLO burn above max_burn_rate"))
    if getattr(cfg, "min_kv_free_frac", None) is not None:
        rules.append(AlertRule(
            name="AutoscaleKVStarved", metric="autoscale/kv_free_frac",
            op="<", threshold=cfg.min_kv_free_frac, severity="info",
            help="KV free fraction under min_kv_free_frac"))
    if getattr(cfg, "min_tier_headroom_frac", None) is not None:
        rules.append(AlertRule(
            name="AutoscaleTierPressure",
            metric="autoscale/tier_headroom_frac",
            op="<", threshold=cfg.min_tier_headroom_frac, severity="info",
            help="spill-tier headroom under min_tier_headroom_frac"))
    return tuple(rules)


class AlertManager:
    """Evaluates a rule set against a TSDB and tracks alert lifecycle.

    One alert instance per rule name (dedup); states are *pending*
    (breaching, hold not yet met) and *firing*.  ``fired_names`` keeps
    every rule that ever reached firing during this manager's lifetime
    — the sim's ``alerts:`` envelope checks against it.
    """

    def __init__(self, tsdb: TSDB, rules: Iterable[AlertRule] | None = None,
                 *, registry=None,
                 clock: Callable[[], float] = time.monotonic,
                 history: int = 256) -> None:
        self.tsdb = tsdb
        self.rules: tuple[AlertRule, ...] = \
            load_rules(rules if rules is not None else default_rules())
        self._clock = clock
        self._states: dict[str, dict] = {}
        self.fired_names: set[str] = set()
        self.resolved: deque = deque(maxlen=history)
        self.evaluations = 0
        self._obs = None
        if registry is not None:
            self._obs = {
                "evals": registry.counter("alerts/evaluations",
                                          unit="evaluations"),
                "fired": registry.counter("alerts/fired", unit="alerts"),
                "resolved": registry.counter("alerts/resolved",
                                             unit="alerts"),
                "firing": registry.gauge("alerts/firing", unit="alerts"),
                "pending": registry.gauge("alerts/pending", unit="alerts"),
            }

    @property
    def rules_hash(self) -> str:
        return rules_hash(self.rules)

    def evaluate(self, now: float | None = None) -> list[dict]:
        """One evaluation pass; returns the lifecycle transitions that
        happened (``{"rule", "event": "pending"|"firing"|"resolved",
        "value", "t"}``)."""
        now = self._clock() if now is None else now
        self.evaluations += 1
        transitions: list[dict] = []
        for rule in self.rules:
            try:
                v = rule.value(self.tsdb, at=now)
            except Exception:  # noqa: BLE001 - a bad rule must not stop others
                v = None
            st = self._states.get(rule.name)
            if rule.test(v):
                if st is None:
                    st = self._states[rule.name] = {
                        "rule": rule.name, "severity": rule.severity,
                        "labels": dict(rule.labels), "state": "pending",
                        "since": now, "value": v,
                    }
                    transitions.append({"rule": rule.name,
                                        "event": "pending",
                                        "value": v, "t": now})
                st["value"] = v
                if (st["state"] == "pending"
                        and now - st["since"] >= rule.for_s):
                    st["state"] = "firing"
                    st["fired_at"] = now
                    self.fired_names.add(rule.name)
                    transitions.append({"rule": rule.name, "event": "firing",
                                        "value": v, "t": now})
                    if self._obs:
                        self._obs["fired"].inc()
            elif st is not None:
                del self._states[rule.name]
                if st["state"] == "firing":
                    st["resolved_at"] = now
                    self.resolved.append(st)
                    transitions.append({"rule": rule.name,
                                        "event": "resolved",
                                        "value": v, "t": now})
                    if self._obs:
                        self._obs["resolved"].inc()
        if self._obs:
            self._obs["evals"].inc()
            firing = sum(1 for s in self._states.values()
                         if s["state"] == "firing")
            self._obs["firing"].set(float(firing))
            self._obs["pending"].set(float(len(self._states) - firing))
        return transitions

    # ------------------------------------------------------------- read

    def active(self) -> list[dict]:
        """Pending + firing alerts, firing first, then by severity."""
        rank = {"page": 0, "warn": 1, "info": 2}
        return sorted((dict(s) for s in self._states.values()),
                      key=lambda s: (s["state"] != "firing",
                                     rank.get(s["severity"], 3), s["rule"]))

    def firing(self, severity: str | None = None) -> list[dict]:
        return [s for s in self.active() if s["state"] == "firing"
                and (severity is None or s["severity"] == severity)]

    def is_firing(self, *names: str) -> bool:
        """The one-call consumer interface: is any of these rules
        currently firing?  (No names = any rule at all.)"""
        firing = {s["rule"] for s in self._states.values()
                  if s["state"] == "firing"}
        return bool(firing if not names else firing & set(names))

    def to_doc(self) -> dict:
        """JSON body of the ``/alerts`` endpoint and the console
        snapshot's ``alerts`` key."""
        return {
            "schema": "tpudist.alerts/1",
            "rules_hash": self.rules_hash,
            "rules": [r.to_dict() for r in self.rules],
            "active": self.active(),
            "resolved": list(self.resolved),
            "fired_ever": sorted(self.fired_names),
            "evaluations": self.evaluations,
        }

