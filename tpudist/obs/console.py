"""``python -m tpudist.obs.console`` — the fleet operations console.

A terminal dashboard over the observability plane this package already
ships: fleet topology from ``{ns}/replica/*`` registrations + live
leases, per-pool queue/KV sparklines from the :class:`~.tsdb.TSDB`,
firing alerts from the :class:`~.alerts.AlertManager`, and the most
recent request trace terminals from the merged event timeline.

Two modes:

* **Live** (default): connect to the coordinator, run a
  :class:`~.tsdb.FleetScraper` + default alert rules in-process, and
  redraw every ``--interval`` seconds.
* **Snapshot** (``--once [--snapshot FILE]``): render ONE frame — from
  a recorded ``tpudist.console/1`` doc (CI smoke: must exit 0 against
  the checked-in fixture) or from a single live scrape — and exit.

Everything renders through :func:`render`, a pure function of the doc,
so tests and CI never need a terminal or a fleet.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from .alerts import AlertManager, default_rules
from .events import TERMINAL_KINDS
from .tsdb import TSDB, FleetScraper

__all__ = ["gather", "render", "main", "CONSOLE_SCHEMA"]

CONSOLE_SCHEMA = "tpudist.console/1"

_SPARK = "▁▂▃▄▅▆▇█"

# the series panel: what an operator triages from, in order.  Only
# series present in the doc render; suffix-matching keeps pool/label
# variants (e.g. serve/queue_wait_s/p90~pool=decode) visible.
_PANEL_SERIES = (
    "serve/queue_depth",
    "serve/queue_wait_s/p90",
    "fleet/kv_free_frac",
    "fleet/tier_headroom_frac",
    "fleet/coord_up",
    "fleet/replicas_publishing",
    "slo/burn_rate_60s",
)


def sparkline(values: list[float], width: int = 32) -> str:
    """Unicode sparkline of the last ``width`` values (empty-safe)."""
    vals = [v for v in values if v == v][-width:]   # drop NaN
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK[0] * len(vals)
    return "".join(_SPARK[min(len(_SPARK) - 1,
                              int((v - lo) / span * (len(_SPARK) - 1)))]
                   for v in vals)


def gather(client, namespace: str, *, tsdb: TSDB | None = None,
           alerts: AlertManager | None = None,
           events: list[dict] | None = None) -> dict:
    """One ``tpudist.console/1`` doc from a live coordinator: replica
    topology, merged metrics, TSDB dump, alert state, recent events."""
    from .aggregate import collect, merge_snapshots

    doc: dict = {"schema": CONSOLE_SCHEMA, "namespace": namespace,
                 "generated_at": time.time(), "replicas": {},
                 "merged": {}, "tsdb": None, "alerts": None, "events": []}
    live = set()
    try:
        mark = f"{namespace}:"
        live = {name[len(mark):] for name in client.live()
                if name.startswith(mark)}
    except Exception:  # noqa: BLE001 - no lease API on this client
        pass
    draining = {k.rsplit("/", 1)[-1]
                for k in client.keys(f"{namespace}/draining/")}
    quarantined = {k.rsplit("/", 1)[-1]
                   for k in client.keys(f"{namespace}/quarantined/")}
    prefix = f"{namespace}/replica/"
    for key in client.keys(prefix):
        raw = client.get(key)
        if raw is None:
            continue
        rid = key[len(prefix):]
        info = json.loads(raw.decode()) if isinstance(raw, bytes) else raw
        doc["replicas"][rid] = {
            "rank": info.get("rank"),
            "role": info.get("role", "both"),
            "live": rid in live,
            "draining": rid in draining,
            "quarantined": rid in quarantined,
        }
    snaps = collect(client, f"{namespace}/metrics", max_age_s=30.0)
    doc["merged"] = merge_snapshots(snaps)
    if tsdb is not None:
        doc["tsdb"] = tsdb.to_doc(window_s=120.0)
    if alerts is not None:
        doc["alerts"] = alerts.to_doc()
    if events is not None:
        doc["events"] = events[-200:]
    else:
        try:
            from .events import collect_events, merge_events
            doc["events"] = merge_events(
                collect_events(client, f"{namespace}/events"))[-200:]
        except Exception:  # noqa: BLE001 - no event ring published
            doc["events"] = []
    return doc


def _fmt_val(v: float | None) -> str:
    if v is None:
        return "-"
    if v != v:
        return "nan"
    if abs(v) >= 1000 or v == int(v):
        return f"{v:.0f}"
    return f"{v:.3f}"


def render(doc: dict, width: int = 96) -> str:
    """One frame of the console, as plain text (pure function)."""
    out: list[str] = []
    ns = doc.get("namespace", "?")
    stamp = doc.get("generated_at")
    when = (time.strftime("%H:%M:%S", time.localtime(stamp))
            if stamp else "?")
    out.append(f"tpudist fleet console — ns={ns} — {when}")
    out.append("=" * min(width, 96))

    replicas = doc.get("replicas") or {}
    n_live = sum(1 for r in replicas.values() if r.get("live"))
    n_drain = sum(1 for r in replicas.values() if r.get("draining"))
    n_quar = sum(1 for r in replicas.values() if r.get("quarantined"))
    out.append(f"REPLICAS  ({n_live} live, {n_drain} draining, "
               f"{n_quar} quarantined)")
    if replicas:
        out.append(f"  {'rid':<10}{'rank':<6}{'role':<10}{'state':<14}")
        for rid in sorted(replicas):
            r = replicas[rid]
            state = ("quarantined" if r.get("quarantined")
                     else "draining" if r.get("draining")
                     else "live" if r.get("live") else "lost")
            out.append(f"  {rid:<10}{str(r.get('rank', '?')):<6}"
                       f"{r.get('role', 'both'):<10}{state:<14}")
    else:
        out.append("  (none registered)")

    alerts = doc.get("alerts") or {}
    active = alerts.get("active") or []
    firing = [a for a in active if a.get("state") == "firing"]
    pending = [a for a in active if a.get("state") == "pending"]
    out.append("")
    out.append(f"ALERTS    ({len(firing)} firing, {len(pending)} pending, "
               f"rules={alerts.get('rules_hash', '-')})")
    if active:
        for a in active:
            out.append(f"  [{a.get('severity', '?').upper():<4}] "
                       f"{a.get('rule'):<24} {a.get('state'):<8} "
                       f"value={_fmt_val(a.get('value'))}")
    else:
        out.append("  (none)")
    fired_ever = alerts.get("fired_ever") or []
    if fired_ever:
        out.append(f"  fired this session: {', '.join(fired_ever)}")

    tsdb = doc.get("tsdb") or {}
    series = tsdb.get("series") or {}
    out.append("")
    stats = tsdb.get("stats") or {}
    out.append(f"SERIES    ({stats.get('series', 0)} series, "
               f"~{stats.get('approx_bytes', 0) // 1024} KiB of "
               f"{stats.get('byte_budget', 0) // 1024} KiB budget)")
    shown = 0
    for want in _PANEL_SERIES:
        for name in sorted(series):
            if name != want and not name.startswith(want + "~"):
                continue
            pts = series[name].get("points") or []
            vals = [p[1] for p in pts]
            last = _fmt_val(vals[-1]) if vals else "-"
            out.append(f"  {name:<34} {sparkline(vals):<32} {last:>8}")
            shown += 1
    if not shown:
        out.append("  (no series scraped yet)")

    events = doc.get("events") or []
    terminals = [e for e in events if e.get("kind") in TERMINAL_KINDS]
    out.append("")
    out.append(f"RECENT TERMINALS  (last {min(len(terminals), 8)} of "
               f"{len(terminals)})")
    for e in terminals[-8:]:
        t = e.get("t")
        hhmm = (time.strftime("%H:%M:%S", time.localtime(t))
                if isinstance(t, (int, float)) else "?")
        trace = str(e.get("trace", ""))[:12]
        req = e.get("rid", e.get("i", "?"))
        out.append(f"  {hhmm}  {e.get('kind', '?'):<8} "
                   f"req={req!s:<12} trace={trace}")
    if not terminals:
        out.append("  (none)")
    out.append("")
    return "\n".join(out)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpudist.obs.console",
        description="tpudist fleet operations console")
    p.add_argument("--coord", default=None,
                   help="coordinator host:port (live mode)")
    p.add_argument("--namespace", default="fleet")
    p.add_argument("--interval", type=float, default=2.0,
                   help="redraw cadence in live mode")
    p.add_argument("--once", action="store_true",
                   help="render one frame and exit 0 (CI mode)")
    p.add_argument("--snapshot", default=None,
                   help="render a recorded tpudist.console/1 doc "
                        "instead of connecting (implies --once)")
    p.add_argument("--width", type=int, default=96)
    args = p.parse_args(argv)

    if args.snapshot is not None:
        with open(args.snapshot, encoding="utf-8") as f:
            doc = json.load(f)
        if doc.get("schema") != CONSOLE_SCHEMA:
            print(f"error: {args.snapshot} is not a {CONSOLE_SCHEMA} doc",
                  file=sys.stderr)
            return 2
        print(render(doc, width=args.width))
        return 0

    if args.coord is None:
        print("error: need --coord host:port (or --snapshot FILE)",
              file=sys.stderr)
        return 2

    from tpudist.runtime.coord import CoordClient

    client = CoordClient(args.coord)
    tsdb = TSDB.from_env()
    alerts = AlertManager(tsdb, default_rules())
    scraper = FleetScraper(tsdb, client=client, namespace=args.namespace,
                           alerts=alerts, interval_s=args.interval)
    try:
        while True:
            scraper.tick()
            doc = gather(client, args.namespace, tsdb=tsdb, alerts=alerts)
            frame = render(doc, width=args.width)
            if args.once:
                print(frame)
                return 0
            # clear + home, then the frame (plain ANSI; no curses dep)
            sys.stdout.write("\x1b[2J\x1b[H" + frame)
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
