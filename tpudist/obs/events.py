"""Per-request distributed tracing: trace contexts, the request-event
ring, fleet-wide timeline merge, and SLO burn-rate accounting.

The aggregate counters (``router/slo_shed``, ``serve/rejected``,
``autoscale/*``) answer "how many" but never "why was THIS request
slow/shed/duplicated" — the first operational question at serving
scale.  This module is the per-request answer:

* :class:`TraceContext` — trace id + parent span + enqueue timestamp,
  minted by the :class:`~tpudist.runtime.router.Router` at submit.  It
  rides the existing request wire encoding into each replica's inbox,
  so one id follows a request across processes — including across a
  SIGKILL + redispatch (the router keys the context to its own request
  entry, which survives the death sweep).
* :class:`RequestEventLog` — a bounded ring (the
  :class:`~tpudist.obs.spans.SpanTracer` discipline: deque + dropped
  counter + lock, host-only appends) of structured lifecycle events:
  enqueue, shed, dispatch, admit, segment, degrade_clamp, swap_pause,
  timeout, reroute, redispatch, done_commit, done.  Every event
  carries the trace id, a wall-clock stamp, and a per-process sequence
  number ``i`` so repeated publishes of the same ring merge without
  duplicates.
* :class:`EventPublisher` — the
  :class:`~tpudist.obs.aggregate.MetricsPublisher` pattern applied to
  the event ring: each replica publishes its ring snapshot under
  ``{namespace}/{rank}``; :func:`collect_events` +
  :func:`merge_events` give rank 0 the fleet-wide, time-ordered
  decision log, and :func:`group_timelines` folds it into one causal
  timeline per trace id.  ``python -m tpudist.obs.timeline`` renders
  those timelines (and exports Chrome-trace JSON).
* :class:`SLOTracker` — multi-window good/bad request accounting over
  the same completion events.  ``burn rate`` is the Google-SRE
  definition: the fraction of the error budget (1 - ``target``) the
  observed bad-request rate consumes — 1.0 burns the budget exactly at
  the window's pace, >>1 pages someone.  Rates are exported as
  registry gauges (``slo/burn_rate_{window}s``) so they flow through
  the existing publisher / Prometheus / ``/healthz`` paths, and the
  autoscaler reads them as scale-up pressure.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import uuid
from collections import deque
from typing import Any, Iterable

from tpudist.runtime import faults

__all__ = [
    "EVENTS_SCHEMA",
    "EventPublisher",
    "RequestEventLog",
    "SLOTracker",
    "SLO_CLASSES",
    "TraceContext",
    "collect_events",
    "group_timelines",
    "is_complete",
    "merge_events",
    "slo_class",
    "timeline_for_rid",
]

EVENTS_SCHEMA = "tpudist.events/1"
DEFAULT_NAMESPACE = "obs/events"

# the terminal router-side kinds: a timeline ending in one of these is
# resolved (the request got exactly one Completion)
TERMINAL_KINDS = ("done", "shed", "timeout", "failed")

# completion reasons that count as GOOD service for SLO accounting;
# everything else (shed / timeout / failed / rejected / invalid) burns
# error budget
GOOD_REASONS = ("stop", "length")


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One request's distributed identity: the trace id every lifecycle
    event is keyed by, the parent span (for callers nesting requests
    under their own traces), and the router-side enqueue wall time."""

    trace_id: str
    parent: str | None = None
    enqueued_at: float | None = None

    @classmethod
    def mint(cls, key: Any = None,
             parent: str | None = None) -> "TraceContext":
        """A fresh context: the router's request key (readable) plus a
        random suffix (unique across router restarts, whose key
        sequences both start at 00000000)."""
        suffix = uuid.uuid4().hex[:12]
        tid = f"{key}-{suffix}" if key is not None else suffix
        return cls(trace_id=tid, parent=parent, enqueued_at=time.time())

    def to_wire(self) -> dict:
        return {"id": self.trace_id, "parent": self.parent,
                "enq": self.enqueued_at}

    @classmethod
    def from_wire(cls, d: dict | None) -> "TraceContext | None":
        if not d or d.get("id") is None:
            return None
        return cls(trace_id=str(d["id"]), parent=d.get("parent"),
                   enqueued_at=d.get("enq"))


class RequestEventLog:
    """Bounded per-process ring of request lifecycle events.

    ``record`` is a lock-guarded host-only append (never a device
    sync); overflow evicts the OLDEST event and counts into
    :attr:`dropped` — the crash-adjacent tail is the valuable part,
    exactly the flight-recorder discipline.  Each event carries a
    per-process monotone ``i`` so a collector that sees the same ring
    published twice merges it without duplicates."""

    def __init__(self, capacity: int = 4096) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.dropped = 0
        self._seq = 0
        self._events: deque[dict] = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def record(self, kind: str, *, trace: str | None = None,
               **fields) -> None:
        """Append one event: ``{"t", "i", "kind", "trace", **fields}``.
        Fields must be JSON-ready host values."""
        with self._lock:
            event = {"t": time.time(), "i": self._seq, "kind": kind,
                     "trace": trace, **fields}
            self._seq += 1
            if len(self._events) == self.capacity:
                self.dropped += 1
            self._events.append(event)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def tail(self, n: int) -> list[dict]:
        with self._lock:
            return list(self._events)[-n:]

    def snapshot(self) -> dict:
        """The JSON wire document :class:`EventPublisher` publishes."""
        return {"schema": EVENTS_SCHEMA, "dropped": self.dropped,
                "events": self.events()}

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0
            self._seq = 0


class EventPublisher:
    """Publishes the event-ring snapshot to the coord store under
    ``{namespace}/{rank}`` — on demand or on a background daemon thread
    (its own client clone; CoordClient sockets are not shared across
    threads).  Last-write-wins: each publish replaces the previous ring
    snapshot, and the per-event ``i`` keys dedup at merge time."""

    def __init__(self, client, rank: int, log: RequestEventLog,
                 namespace: str = DEFAULT_NAMESPACE,
                 interval_s: float = 5.0) -> None:
        self._client = client
        self._rank = rank
        self._log = log
        self._namespace = namespace
        self._interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def key(self) -> str:
        return f"{self._namespace}/{self._rank}"

    def publish(self, client=None) -> dict:
        snap = self._log.snapshot()
        snap["rank"] = self._rank
        snap["published_at"] = time.time()
        # same fault gate as the metrics publisher: a starved obs plane
        # starves the event plane too — they ride the same KV store
        if faults.drop_publish():
            return snap
        (client or self._client).set(
            self.key, json.dumps(snap).encode("utf-8"))
        return snap

    def start(self) -> None:
        if self._thread is not None:
            return

        def loop() -> None:
            client = self._client.clone()
            try:
                while not self._stop.wait(self._interval_s):
                    try:
                        self.publish(client)
                    except Exception:  # noqa: BLE001 - teardown races
                        pass
            finally:
                client.close()

        self._thread = threading.Thread(
            target=loop, name=f"obs-events-r{self._rank}", daemon=True)
        self._thread.start()

    def stop(self, final_publish: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final_publish:
            try:
                self.publish()
            except Exception:  # noqa: BLE001
                pass


def collect_events(client, namespace: str = DEFAULT_NAMESPACE
                   ) -> dict[int, dict]:
    """Fetch every published event-ring snapshot: ``{rank: snapshot}``.
    Keys deleted between list and get (a departing worker) are
    skipped; each snapshot gains ``age_s`` off its publish stamp."""
    out: dict[int, dict] = {}
    prefix = namespace + "/"
    now = time.time()
    for key in client.keys(prefix):
        raw = client.get(key)
        if raw is None:
            continue
        snap = json.loads(raw.decode("utf-8"))
        published = snap.get("published_at")
        snap["age_s"] = (now - published) if published is not None else None
        out[int(key[len(prefix):])] = snap
    return out


def merge_events(collected: dict[int, dict] | None = None,
                 **local: dict) -> dict:
    """Merge per-process event rings into ONE time-ordered fleet log.

    ``collected`` is :func:`collect_events` output (replica rings keyed
    by rank); keyword snapshots add local rings under a named source
    (``merge_events(collected, router=obs.events.snapshot())``).  Each
    merged event gains ``src`` (its origin); duplicates — the same ring
    published more than once — dedup on ``(src, i)``.  The result is
    the ``tpudist.events/1`` document the timeline tool loads."""
    seen: set[tuple] = set()
    events: list[dict] = []
    dropped = 0
    sources: list[str] = []

    def fold(src: str, snap: dict) -> None:
        nonlocal dropped
        sources.append(src)
        dropped += int(snap.get("dropped", 0) or 0)
        for ev in snap.get("events", []):
            dk = (src, ev.get("i"))
            if ev.get("i") is not None and dk in seen:
                continue
            seen.add(dk)
            events.append({**ev, "src": src})

    for rank in sorted(collected or {}):
        fold(f"r{rank}", (collected or {})[rank])
    for name, snap in sorted(local.items()):
        fold(name, snap)
    # wall-clock order; per-source sequence breaks same-millisecond ties
    events.sort(key=lambda e: (e.get("t", 0.0), e.get("src", ""),
                               e.get("i", 0)))
    return {"schema": EVENTS_SCHEMA, "sources": sources,
            "dropped": dropped, "events": events}


def group_timelines(events: Iterable[dict]) -> dict[str, list[dict]]:
    """Per-trace causal timelines: ``{trace_id: [events, time-ordered]}``.
    Trace-less fleet events (swaps, etc.) are grouped under ``None``."""
    out: dict[str, list[dict]] = {}
    for ev in events:
        out.setdefault(ev.get("trace"), []).append(ev)
    for tl in out.values():
        tl.sort(key=lambda e: (e.get("t", 0.0), e.get("src", ""),
                               e.get("i", 0)))
    return out


def timeline_for_rid(timelines: dict[str, list[dict]],
                     rid: Any) -> list[dict] | None:
    """The timeline whose router ``enqueue`` event carries this caller
    rid (the NEWEST enqueue wins when a rid was reused across runs)."""
    best, best_t = None, None
    for tid, tl in timelines.items():
        if tid is None:
            continue
        for ev in tl:
            if ev.get("kind") == "enqueue" and ev.get("rid") == str(rid):
                if best_t is None or ev["t"] > best_t:
                    best, best_t = tl, ev["t"]
                break
    return best


def is_complete(timeline: list[dict] | None) -> bool:
    """Does this timeline tell the whole story — enqueue first, a
    terminal event last, and (for served requests) one fresh dispatch
    per death-redispatch / rejection-reroute, so there is no gap where
    the request was in flight with no recorded owner?"""
    if not timeline:
        return False
    kinds = [e.get("kind") for e in timeline]
    if kinds[0] != "enqueue":
        return False
    term = kinds[-1]
    if term in ("shed", "timeout", "failed"):
        return True    # resolved without (successful) service
    if term != "done":
        return False
    n_dispatch = kinds.count("dispatch")
    n_again = kinds.count("redispatch") + kinds.count("reroute")
    return n_dispatch >= n_again + 1


SLO_CLASSES = ("best_effort", "priority")


def slo_class(priority: int | None) -> str:
    """The SLO accounting class for a request priority: paying traffic
    (``priority > 0``) and best-effort get separate error budgets."""
    return "priority" if priority is not None and priority > 0 \
        else "best_effort"


class SLOTracker:
    """Multi-window good/bad request counts and burn rates, split by
    priority class.

    ``observe(reason, priority=...)`` classifies one completion
    (``stop``/``length`` are good; shed/timeout/failed/rejected/invalid
    burn budget) under its class (:func:`slo_class`), prunes
    observations older than the longest window, and refreshes the
    per-window gauges:

    * ``slo/good`` / ``slo/bad`` — lifetime counters (all classes);
    * ``slo/good~class={cls}`` / ``slo/bad~class={cls}`` — per-class
      lifetime counters (the ``~class=`` suffix renders as a
      ``{class="..."}`` label in the Prometheus exporter);
    * ``slo/burn_rate_{W}s`` — per window W, the bad fraction over the
      last W seconds divided by the error budget ``1 - target``;
    * ``slo/burn_rate_{W}s~class={cls}`` — the same, per class, so
      best-effort sheds under degradation cannot mask (or masquerade
      as) priority-traffic budget burn.

    Registering the gauges on a :class:`~tpudist.obs.registry
    .MetricRegistry` makes the rates ride every existing export path
    (publisher -> merge, Prometheus text, ``/metrics``) for free."""

    def __init__(self, registry=None, *, target: float = 0.99,
                 windows: tuple[float, ...] = (60.0, 300.0),
                 clock=time.time) -> None:
        if not 0.0 < target < 1.0:
            raise ValueError(f"target must be in (0, 1), got {target}")
        if not windows or any(w <= 0 for w in windows):
            raise ValueError(f"windows must be positive, got {windows}")
        self.target = float(target)
        self.windows = tuple(sorted(float(w) for w in windows))
        self._budget = 1.0 - self.target
        self._clock = clock
        self._lock = threading.Lock()
        self._obs: deque[tuple[float, bool, str]] = deque()
        self._registry = registry
        self._good = self._bad = None
        self._cls_counters: dict[tuple[str, bool], Any] = {}
        self._gauges: dict[float, Any] = {}
        self._cls_gauges: dict[tuple[float, str], Any] = {}
        if registry is not None:
            self._good = registry.counter(
                "slo/good", unit="reqs",
                help="Requests completed within SLO (stop/length)")
            self._bad = registry.counter(
                "slo/bad", unit="reqs",
                help="Requests that burned error budget "
                     "(shed/timeout/failed/rejected/invalid)")
            for cls in SLO_CLASSES:
                self._cls_counters[(cls, True)] = registry.counter(
                    f"slo/good~class={cls}", unit="reqs",
                    help=f"In-SLO completions of {cls} traffic")
                self._cls_counters[(cls, False)] = registry.counter(
                    f"slo/bad~class={cls}", unit="reqs",
                    help=f"Budget-burning completions of {cls} traffic")
            for w in self.windows:
                self._gauges[w] = registry.gauge(
                    f"slo/burn_rate_{int(w)}s", unit="ratio",
                    help=f"Error-budget burn rate over the last {int(w)}s "
                         f"(bad fraction / {self._budget:.3g} budget)")
                for cls in SLO_CLASSES:
                    self._cls_gauges[(w, cls)] = registry.gauge(
                        f"slo/burn_rate_{int(w)}s~class={cls}",
                        unit="ratio",
                        help=f"{cls} error-budget burn rate over the "
                             f"last {int(w)}s")

    def observe(self, reason: str | None = None, *,
                good: bool | None = None, priority: int = 0) -> None:
        """Record one completed request (by Completion ``reason``, or
        an explicit ``good=`` override) under its priority class and
        refresh the gauges."""
        if good is None:
            good = reason in GOOD_REASONS
        cls = slo_class(priority)
        now = self._clock()
        with self._lock:
            self._obs.append((now, bool(good), cls))
            horizon = now - self.windows[-1]
            while self._obs and self._obs[0][0] < horizon:
                self._obs.popleft()
        if self._good is not None:
            (self._good if good else self._bad).inc()
            self._cls_counters[(cls, bool(good))].inc()
        self._refresh_gauges()

    def _refresh_gauges(self) -> None:
        """Gauges mirror :meth:`burn_rates`, except a window with zero
        traffic reads ABSENT (``Gauge.clear``), not 0.0: a time-series
        consumer averaging/alerting over the gauge must not dilute real
        burn with idle-window zeros.  The method API keeps returning
        0.0 (no evidence is not a breach for control loops)."""
        for w in self.windows:
            g = self._gauges.get(w)
            if g is not None:
                good, bad = self.counts(w)
                if good + bad:
                    g.set((bad / (good + bad)) / self._budget)
                else:
                    g.clear()
        for c in SLO_CLASSES:
            for w in self.windows:
                g = self._cls_gauges.get((w, c))
                if g is None:
                    continue
                good, bad = self.counts(w, cls=c)
                if good + bad:
                    g.set((bad / (good + bad)) / self._budget)
                else:
                    g.clear()

    def counts(self, window_s: float,
               cls: str | None = None) -> tuple[int, int]:
        """(good, bad) over the trailing ``window_s`` seconds — all
        classes, or one class with ``cls=``."""
        cutoff = self._clock() - window_s
        with self._lock:
            good = sum(1 for t, g, c in self._obs
                       if t >= cutoff and g and (cls is None or c == cls))
            bad = sum(1 for t, g, c in self._obs
                      if t >= cutoff and not g
                      and (cls is None or c == cls))
        return good, bad

    def burn_rates(self, cls: str | None = None) -> dict[float, float]:
        """{window_s: burn rate} — 0.0 for a window with no traffic
        (no evidence is not a breach).  ``cls`` narrows to one
        priority class; default is the aggregate."""
        out: dict[float, float] = {}
        for w in self.windows:
            good, bad = self.counts(w, cls=cls)
            total = good + bad
            out[w] = (bad / total) / self._budget if total else 0.0
        return out

    def clear(self) -> None:
        with self._lock:
            self._obs.clear()
        # burn gauges go back to absent too — a fresh run (each sim
        # scenario clears the tracker) must not scrape the previous
        # run's final burn rate as if it were current
        self._refresh_gauges()
