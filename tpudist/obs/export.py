"""Exporters: bench-schema JSONL, Prometheus text format, HTTP /metrics.

Three renderings of the same registry snapshot:

* :func:`jsonl_line` / :func:`snapshot_to_jsonl` — one JSON object per
  line in the exact ``bench.py`` schema (``{"metric", "value", "unit",
  "vs_baseline", ...}``, insertion order preserved) so BENCH_*.json
  parsers keep working when bench emits through the registry.
* :func:`to_prometheus` — Prometheus text exposition format 0.0.4.
  Log-bucket histograms become classic cumulative ``le`` histograms
  whose upper bounds are the bucket upper edges ``growth**(idx+1)``.
* :class:`MetricsServer` — optional stdlib-only HTTP endpoint serving
  ``/metrics`` (Prometheus text) and ``/metrics.json`` (raw snapshot)
  from a daemon thread; no third-party dependency, safe to leave off.
"""

from __future__ import annotations

import json
import re
import threading

from tpudist.obs.registry import split_labels, summarize

__all__ = ["jsonl_line", "snapshot_to_jsonl", "to_prometheus",
           "MetricsServer"]


# -- JSONL (the bench.py wire schema) ---------------------------------------

def jsonl_line(metric: str, value, unit: str, vs_baseline=None,
               **extra) -> str:
    """One bench-schema line.  Key order is load-bearing: existing
    BENCH_*.json tooling reads these positionally-ish and the recap
    printer re-dumps them verbatim."""
    return json.dumps({"metric": metric, "value": value, "unit": unit,
                       "vs_baseline": vs_baseline, **extra})


def snapshot_to_jsonl(snapshot: dict, **extra) -> list[str]:
    """Render a registry (or merged cluster) snapshot as bench-schema
    lines: counters/gauges one line each, histograms one line per summary
    stat (count/mean/p50/p90/p99/...)."""
    lines: list[str] = []
    for name, m in snapshot.get("counters", {}).items():
        lines.append(jsonl_line(name, m["value"], m["unit"], **extra))
    for name, m in snapshot.get("gauges", {}).items():
        lines.append(jsonl_line(name, m["value"], m["unit"], **extra))
    for name, h in snapshot.get("histograms", {}).items():
        summary = summarize(h)
        for stat in ("count", "mean", "min", "max", "p50", "p90", "p99"):
            unit = "" if stat == "count" else h.get("unit", "")
            lines.append(
                jsonl_line(f"{name}/{stat}", summary[stat], unit, **extra))
    return lines


# -- Prometheus text format -------------------------------------------------

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    out = _NAME_BAD.sub("_", name)
    return "_" + out if out[:1].isdigit() else out


# label parsing lives with the registry now (the TSDB and the name
# validator share it); kept as an alias for older imports.
_split_labels = split_labels


def _prom_num(v) -> str:
    if v is None:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    return repr(float(v))


def to_prometheus(snapshot: dict) -> str:
    """Prometheus text exposition of a registry or merged snapshot.
    Metrics registered with a ``help`` string get a ``# HELP`` line
    ahead of their ``# TYPE``.  Histograms are cumulative: ``le`` edges
    are the log-bucket UPPER
    bounds (``growth**(idx+1)``; the zero bucket folds into the smallest
    edge since its values are <= 0 < every positive edge), closing with
    ``+Inf``, ``_sum`` and ``_count``.  Merged cluster snapshots keep
    their per-host attribution: each counter/gauge additionally emits one
    ``{name}{{worker="r"}}`` sample per rank from its ``per_worker``
    map.  Registry names carrying ``~key=value`` suffixes (the per-class
    SLO series, e.g. ``slo/bad~class=priority``) render as one base
    metric with a real label set (``slo_bad{class="priority"}``)."""
    out: list[str] = []
    typed: set[str] = set()

    def help_line(pname: str, m: dict) -> None:
        h = m.get("help")
        if h:
            # the exposition format's escapes: backslash and newline
            h = h.replace("\\", "\\\\").replace("\n", "\\n")
            out.append(f"# HELP {pname} {h}")

    def label_value(v: str) -> str:
        # exposition-format escapes for label values: backslash, the
        # double quote, and newline (anything else passes through —
        # '/' and '=' are legal inside a quoted label value)
        return (v.replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    def label_str(labels: dict[str, str]) -> str:
        if not labels:
            return ""
        inner = ",".join(f'{k}="{label_value(v)}"'
                         for k, v in sorted(labels.items()))
        return "{" + inner + "}"

    def scalar_lines(pname: str, labels: dict, m: dict) -> None:
        out.append(f"{pname}{label_str(labels)} {_prom_num(m['value'])}")
        for rank in sorted(m.get("per_worker", {}), key=int):
            out.append(
                f"{pname}{label_str({**labels, 'worker': rank})} "
                f"{_prom_num(m['per_worker'][rank])}")

    def type_line(pname: str, kind: str, m: dict) -> None:
        # one HELP/TYPE per base name even when several labeled series
        # share it (the exposition format forbids duplicates)
        if pname in typed:
            return
        typed.add(pname)
        help_line(pname, m)
        out.append(f"# TYPE {pname} {kind}")

    for name, m in snapshot.get("counters", {}).items():
        base, labels = _split_labels(name)
        pname = _prom_name(base)
        type_line(pname, "counter", m)
        scalar_lines(pname, labels, m)
    for name, m in snapshot.get("gauges", {}).items():
        base, labels = _split_labels(name)
        pname = _prom_name(base)
        type_line(pname, "gauge", m)
        scalar_lines(pname, labels, m)
    for name, h in snapshot.get("histograms", {}).items():
        base, labels = split_labels(name)
        pname = _prom_name(base)
        if pname not in typed:
            typed.add(pname)
            help_line(pname, h)
            out.append(f"# TYPE {pname} histogram")
        growth = h["growth"]
        cum = h.get("zero", 0)
        for idx in sorted(int(i) for i in h["buckets"]):
            cum += h["buckets"][str(idx)]
            le = label_str({**labels, "le": _prom_num(growth ** (idx + 1))})
            out.append(f"{pname}_bucket{le} {cum}")
        out.append(
            f'{pname}_bucket{label_str({**labels, "le": "+Inf"})} '
            f'{h["count"]}')
        out.append(f"{pname}_sum{label_str(labels)} {_prom_num(h['sum'])}")
        out.append(f"{pname}_count{label_str(labels)} {h['count']}")
    return "\n".join(out) + "\n"


# -- HTTP /metrics ----------------------------------------------------------

_KNOWN_PATHS = ("/metrics", "/metrics.json", "/healthz", "/alerts",
                "/tsdb")


class MetricsServer:
    """stdlib-only metrics + liveness endpoint.

    ``MetricsServer(registry).port`` binds an ephemeral port; pass
    ``snapshot_fn`` to serve something other than the local registry
    (e.g. rank 0 serving the merged cluster view from
    :func:`tpudist.obs.aggregate.collect_and_merge`).  Pass ``health_fn``
    (conventionally ``HealthMonitor.verdict``) to activate ``/healthz``
    as a container liveness probe: 200 while the verdict is healthy (or
    not yet known), 503 once it is degraded — the role the reference's
    Docker HEALTHCHECK plays, but cluster-aware.  Unknown paths get a
    real 404 with a JSON body listing the endpoints.  Runs in a daemon
    thread; :meth:`close` shuts it down.

    With ``alerts`` (an :class:`tpudist.obs.alerts.AlertManager`) the
    server additionally exposes ``/alerts`` — active/resolved alerts +
    the loaded rule set and its hash; with ``tsdb`` (a
    :class:`tpudist.obs.tsdb.TSDB`) it exposes ``/tsdb`` — per-series
    points and store stats (``?match=substr`` filters series,
    ``?window_s=60`` bounds the lookback)."""

    def __init__(self, registry=None, snapshot_fn=None, host: str = "",
                 port: int = 0, health_fn=None, alerts=None,
                 tsdb=None) -> None:
        if (registry is None) == (snapshot_fn is None):
            raise ValueError("pass exactly one of registry / snapshot_fn")
        snap = snapshot_fn or registry.snapshot
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        from urllib.parse import parse_qs, urlsplit

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 - http.server API
                split = urlsplit(self.path)
                path = split.path
                status = 200
                if path == "/metrics":
                    body = to_prometheus(snap()).encode("utf-8")
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/metrics.json":
                    body = json.dumps(snap()).encode("utf-8")
                    ctype = "application/json"
                elif path == "/healthz":
                    verdict = (health_fn() if health_fn is not None
                               else {"status": "ok"})
                    status = 503 if verdict.get("status") == "degraded" \
                        else 200
                    body = json.dumps(verdict).encode("utf-8")
                    ctype = "application/json"
                elif path == "/alerts" and alerts is not None:
                    body = json.dumps(alerts.to_doc()).encode("utf-8")
                    ctype = "application/json"
                elif path == "/tsdb" and tsdb is not None:
                    q = parse_qs(split.query)
                    window = q.get("window_s", [None])[0]
                    doc = tsdb.to_doc(
                        match=q.get("match", [None])[0],
                        window_s=float(window) if window else None)
                    body = json.dumps(doc).encode("utf-8")
                    ctype = "application/json"
                else:
                    status = 404
                    body = json.dumps(
                        {"error": f"unknown path {path!r}",
                         "paths": list(_KNOWN_PATHS)}).encode("utf-8")
                    ctype = "application/json"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:  # silence per-request noise
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="obs-metrics-http",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)
