"""Health plane — rank classification from the snapshots already flowing.

The reference suite's entire failure-detection story is "a process dies
and torchrun/Horovod restarts it"; tpudist's TTL heartbeats
(:class:`tpudist.runtime.coord.ElasticMonitor`) already see *death*, but
nothing sees the failures that matter most on TPU pods: a straggling
host dragging every synchronous collective, or a rank whose publisher
went quiet while its heartbeat thread stays alive (main thread wedged).

:class:`HealthMonitor` is the rank-0 (or sidecar) consumer of the
per-rank snapshots :class:`~tpudist.obs.aggregate.MetricsPublisher`
already publishes through the coord KV store.  Per observation round it

* derives each rank's recent mean step time from the DELTA of its
  ``train/step_time`` histogram (sum/count since the previous snapshot —
  the live signal, not the job-lifetime average);
* computes cross-host skew as ``rank_mean / median(rank_means)``;
* reads publish staleness from the ``published_at`` stamp
  (:func:`tpudist.obs.aggregate.collect` attaches ``age_s``);
* classifies every rank ``healthy | straggler | stale | lost`` with
  HYSTERESIS — ``confirm_n`` consecutive over-threshold rounds to enter
  ``straggler``, ``recover_n`` consecutive clean rounds to leave — so
  one GC pause or one fast round never flaps the verdict;
* emits the classification as obs gauges/counters (``health/ranks_*``,
  ``health/transitions``) and records every transition into the flight
  recorder ring (:mod:`tpudist.obs.recorder`).

The machine-readable verdict (:meth:`HealthMonitor.verdict`) is what
``/healthz`` (:class:`tpudist.obs.export.MetricsServer`) serves as a
liveness probe and what the elastic launcher
(:mod:`tpudist.runtime.launch`) logs next to its blacklist decisions.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from tpudist.obs.aggregate import DEFAULT_NAMESPACE, collect
from tpudist.utils.logging import get_logger

log = get_logger(__name__)

__all__ = ["HealthMonitor", "HealthWatcher", "STATES"]

# state encoding for the per-rank gauge (machine-readable ordering:
# higher is worse)
STATES = ("healthy", "straggler", "stale", "lost")
_STATE_CODE = {s: i for i, s in enumerate(STATES)}


def _median(values: list[float]) -> float:
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    return (ordered[mid] if n % 2
            else (ordered[mid - 1] + ordered[mid]) / 2.0)


class _RankTrack:
    """Per-rank bookkeeping: last histogram cumulatives + hysteresis
    streaks."""

    __slots__ = ("state", "prev_count", "prev_sum", "bad_streak",
                 "good_streak", "skew", "step_time", "age_s")

    def __init__(self) -> None:
        self.state = "healthy"
        self.prev_count = 0.0
        self.prev_sum = 0.0
        self.bad_streak = 0
        self.good_streak = 0
        self.skew: float | None = None
        self.step_time: float | None = None
        self.age_s: float | None = None


class HealthMonitor:
    """Classify ranks from published snapshots.

    Args:
      client: optional :class:`~tpudist.runtime.coord.CoordClient`; when
        given, :meth:`update` collects the published snapshots itself.
        :meth:`observe` works without any client (tests, replay).
      namespace: the publisher namespace in the KV store.
      signal: histogram name carrying the per-step latency signal.
      skew_threshold: a rank whose recent mean step time exceeds
        ``skew_threshold × median`` is a straggler candidate.
      stale_after_s / lost_after_s: publish-age bounds for the
        ``stale`` / ``lost`` states (``lost`` also covers a rank whose
        key vanished from the store).
      confirm_n / recover_n: hysteresis — consecutive candidate rounds
        required to ENTER ``straggler``, consecutive clean rounds to
        LEAVE a non-healthy state.
      registry: obs registry the classification gauges/counters are
        emitted into (default: the process-global one).
      recorder: flight recorder that receives transition events
        (default: the process-global one).
    """

    def __init__(
        self,
        client: Any = None,
        namespace: str = DEFAULT_NAMESPACE,
        signal: str = "train/step_time",
        skew_threshold: float = 2.0,
        stale_after_s: float = 15.0,
        lost_after_s: float = 60.0,
        confirm_n: int = 2,
        recover_n: int = 2,
        registry: Any = None,
        recorder: Any = None,
    ) -> None:
        if skew_threshold <= 1.0:
            raise ValueError(
                f"skew_threshold must be > 1, got {skew_threshold}")
        if confirm_n < 1 or recover_n < 1:
            raise ValueError("confirm_n and recover_n must be >= 1")
        self.client = client
        self.namespace = namespace
        self.signal = signal
        self.skew_threshold = skew_threshold
        self.stale_after_s = stale_after_s
        self.lost_after_s = lost_after_s
        self.confirm_n = confirm_n
        self.recover_n = recover_n
        if registry is None:
            from tpudist import obs

            registry = obs.registry
        self._registry = registry
        if recorder is None:
            from tpudist import obs

            recorder = obs.recorder
        self._recorder = recorder
        self._tracks: dict[int, _RankTrack] = {}
        self._verdict: dict = {"status": "unknown", "time": None,
                               "rounds": 0, "ranks": {}}
        self._lock = threading.Lock()

    # -- observation -------------------------------------------------------

    def update(self) -> dict:
        """Collect the currently published snapshots and classify.  The
        rank-0 / sidecar entry point; needs ``client``."""
        if self.client is None:
            raise ValueError("HealthMonitor.update() needs a coord client; "
                             "use observe(snapshots) without one")
        return self.observe(collect(self.client, self.namespace))

    def observe(self, snapshots: dict[int, dict],
                now: float | None = None) -> dict:
        """One classification round over ``{rank: snapshot}`` (the
        :func:`~tpudist.obs.aggregate.collect` shape).  Returns the new
        verdict."""
        now = time.time() if now is None else now
        with self._lock:
            return self._observe_locked(snapshots, now)

    def _observe_locked(self, snapshots: dict[int, dict],
                        now: float) -> dict:
        # recent per-rank mean step time from histogram deltas
        deltas: dict[int, float | None] = {}
        for rank, snap in snapshots.items():
            track = self._tracks.setdefault(rank, _RankTrack())
            published = snap.get("published_at", snap.get("time"))
            track.age_s = (snap["age_s"] if snap.get("age_s") is not None
                           else (now - published
                                 if published is not None else None))
            hist = snap.get("histograms", {}).get(self.signal)
            if hist is None:
                deltas[rank] = None
                continue
            dc = hist["count"] - track.prev_count
            ds = hist["sum"] - track.prev_sum
            if dc < 0:  # restarted rank: its registry began again at zero
                dc, ds = hist["count"], hist["sum"]
            track.prev_count, track.prev_sum = hist["count"], hist["sum"]
            deltas[rank] = (ds / dc) if dc > 0 else None
        known = [d for d in deltas.values() if d is not None and d > 0]
        median = _median(known) if known else None

        transitions: list[dict] = []
        candidates: dict[int, str] = {}
        for rank, track in self._tracks.items():
            snap_present = rank in snapshots
            if snap_present:
                track.step_time = deltas.get(rank)
                track.skew = (track.step_time / median
                              if track.step_time is not None
                              and median else None)
            age = track.age_s
            # staleness dominates skew: a rank that stopped publishing has
            # no fresh step-time signal to judge
            if (not snap_present
                    or (age is not None and age > self.lost_after_s)):
                candidate = "lost"
            elif age is not None and age > self.stale_after_s:
                candidate = "stale"
            elif (track.skew is not None
                    and track.skew >= self.skew_threshold):
                candidate = "straggler"
            else:
                candidate = "healthy"
            candidates[rank] = candidate
        # stale-not-lost, fleet-wide: when EVERY tracked rank went bad
        # in the same round, the common cause is the path to the obs
        # plane (a coord brownout), not a simultaneous mass death —
        # demote "lost" to "stale" so consumers steer around the blind
        # spot without triggering a redispatch storm.  One genuinely
        # dead rank among healthy peers is unaffected.
        if (len(candidates) >= 2
                and all(c in ("stale", "lost")
                        for c in candidates.values())):
            candidates = {rank: ("stale" if c == "lost" else c)
                          for rank, c in candidates.items()}
        for rank, candidate in candidates.items():
            self._apply_hysteresis(rank, self._tracks[rank], candidate,
                                   transitions)

        verdict = self._render_verdict(now, transitions)
        self._emit(verdict, transitions)
        self._verdict = verdict
        return verdict

    def _apply_hysteresis(self, rank: int, track: _RankTrack,
                          candidate: str,
                          transitions: list[dict]) -> None:
        """``confirm_n`` consecutive bad rounds to degrade, ``recover_n``
        consecutive clean rounds to return to healthy.  Worsening within
        the degraded states (straggler -> stale -> lost) switches
        immediately — staleness is measured, not inferred."""
        if candidate == "healthy":
            track.bad_streak = 0
            if track.state != "healthy":
                track.good_streak += 1
                if track.good_streak >= self.recover_n:
                    transitions.append(
                        {"rank": rank, "from": track.state, "to": "healthy"})
                    track.state = "healthy"
                    track.good_streak = 0
            return
        track.good_streak = 0
        if candidate == track.state:
            return
        if _STATE_CODE[candidate] > _STATE_CODE.get(track.state, 0) \
                and candidate in ("stale", "lost"):
            # measured staleness: no confirmation rounds needed
            transitions.append(
                {"rank": rank, "from": track.state, "to": candidate})
            track.state = candidate
            track.bad_streak = 0
            return
        track.bad_streak += 1
        if track.bad_streak >= self.confirm_n:
            transitions.append(
                {"rank": rank, "from": track.state, "to": candidate})
            track.state = candidate
            track.bad_streak = 0

    # -- verdict + emission ------------------------------------------------

    def _render_verdict(self, now: float,
                        transitions: list[dict]) -> dict:
        ranks = {
            str(rank): {
                "state": t.state,
                "skew": (round(t.skew, 3)
                         if t.skew is not None else None),
                "step_time": t.step_time,
                "age_s": (round(t.age_s, 3)
                          if t.age_s is not None else None),
            }
            for rank, t in sorted(self._tracks.items())
        }
        degraded = sorted(r for r, v in ranks.items()
                          if v["state"] != "healthy")
        return {
            "status": ("degraded" if degraded
                       else ("healthy" if ranks else "unknown")),
            "time": now,
            "rounds": self._verdict["rounds"] + 1,
            "ranks": ranks,
            "stragglers": [r for r, v in ranks.items()
                           if v["state"] == "straggler"],
            "stale": [r for r, v in ranks.items()
                      if v["state"] == "stale"],
            "lost": [r for r, v in ranks.items()
                     if v["state"] == "lost"],
            "transitions": transitions,
        }

    def _emit(self, verdict: dict, transitions: list[dict]) -> None:
        reg = self._registry
        counts = {s: 0 for s in STATES}
        for v in verdict["ranks"].values():
            counts[v["state"]] += 1
        for s in STATES:
            reg.gauge(f"health/ranks_{s}", unit="ranks").set(counts[s])
        reg.gauge("health/degraded").set(
            1 if verdict["status"] == "degraded" else 0)
        if transitions:
            reg.counter("health/transitions").inc(len(transitions))
        for tr in transitions:
            log.warning("health: rank %s %s -> %s", tr["rank"],
                        tr["from"], tr["to"])
            if self._recorder is not None:
                self._recorder.record("health_transition", **tr)

    def verdict(self) -> dict:
        """The most recent verdict (machine-readable; the `/healthz`
        payload)."""
        with self._lock:
            return dict(self._verdict)

    def describe(self) -> str:
        """One-line human summary for launcher/supervisor logs."""
        v = self.verdict()
        if v["status"] == "unknown":
            return "health: no observations yet"
        if v["status"] == "healthy":
            return f"health: {len(v['ranks'])} ranks healthy"
        parts = [f"{k}={v[k]}" for k in ("stragglers", "stale", "lost")
                 if v.get(k)]
        return f"health: degraded ({', '.join(parts)})"


class HealthWatcher:
    """Background health observer — the launcher/sidecar subscription.

    Owns its own :class:`~tpudist.runtime.coord.CoordClient` (coord
    sockets are not shared across threads) and drives
    ``monitor.update()`` every ``interval_s`` on a daemon thread.
    Observation failures are swallowed: health is advisory and must
    never take the supervisor down."""

    def __init__(self, addr: str, interval_s: float = 2.0,
                 **monitor_kwargs) -> None:
        from tpudist.runtime.coord import CoordClient

        host, port = addr.rsplit(":", 1)
        self._client = CoordClient(host, int(port))
        self.monitor = HealthMonitor(client=self._client, **monitor_kwargs)
        self._interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="obs-health-watch", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval_s):
            try:
                self.monitor.update()
            except Exception:  # noqa: BLE001 - advisory plane
                pass

    def verdict(self) -> dict:
        return self.monitor.verdict()

    def describe(self) -> str:
        return self.monitor.describe()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._client.close()
