"""Crash flight recorder — the post-mortem that survives the process.

torch.distributed ships a "flight recorder" that dumps recent collective
state when a job wedges; the reference suite has nothing (a crash's
cause evaporates with the process).  :class:`FlightRecorder` is the
tpudist counterpart: a bounded per-process ring of recent events (step
metric deltas, elastic round changes, serve admissions, health
transitions, compile events) plus a one-call post-mortem dump that
bundles, as ONE JSON document:

* the last-N event ring (and how many older events the ring dropped);
* the exception (type, message, traceback text) when there is one;
* the final registry snapshot (every counter/gauge/histogram as of the
  crash — the one batched device sync is attempted but a dead backend
  must not block the dump, so it degrades to the host-only view);
* the span tail (the last ``span_tail`` completed spans from the
  tracer, crash-adjacent timeline context);
* the request-event tail (the last entries of the per-request tracing
  ring, :mod:`tpudist.obs.events` — which requests this process was
  serving, and what lifecycle decisions it had just made);
* environment and topology: the ``TPUDIST_*``/``JAX_*``/``XLA_FLAGS``
  env surface, pid/host, and the jax device/process layout when a
  backend is up;
* the last compiled HLO text (:attr:`last_hlo` — stashed by
  :class:`tpudist.runtime.ici.IciCollectives` and the trainer's
  cost-analysis probe), the artifact that makes "which program was the
  chip running" answerable after the fact.

``guard()`` is the wiring surface: the trainer, the elastic worker and
the serve loop wrap their run loops in ``with obs.recorder.guard(...)``
so any unhandled exception dumps the bundle before propagating.  Dump
location: ``TPUDIST_POSTMORTEM_DIR`` (default: the current directory).

Schema: ``{"schema": "tpudist.postmortem/1", ...}`` — see
docs/OBSERVABILITY.md for the field-by-field contract.
"""

from __future__ import annotations

import contextlib
import os
import socket
import threading
import time
import traceback
from collections import deque
from typing import Any

from tpudist.obs.spans import atomic_write_json
from tpudist.utils.logging import get_logger

log = get_logger(__name__)

__all__ = ["FlightRecorder", "POSTMORTEM_SCHEMA"]

POSTMORTEM_SCHEMA = "tpudist.postmortem/1"

# the env surface worth preserving in a post-mortem: selection by prefix
# keeps secrets (tokens, credentials) out of the bundle by default
_ENV_PREFIXES = ("TPUDIST_", "JAX_", "XLA_")


def _topology() -> dict:
    """jax process/device layout, degrading to {} without a live
    backend (a post-crash dump must never re-initialize jax)."""
    import sys

    if "jax" not in sys.modules:
        return {}
    try:
        jax = sys.modules["jax"]
        devices = jax.devices()
        return {
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "device_count": len(devices),
            "device_kind": devices[0].device_kind if devices else None,
            "backend": jax.default_backend(),
        }
    except Exception:  # noqa: BLE001 - topology is best-effort context
        return {}


class FlightRecorder:
    """Bounded ring of recent events + post-mortem bundling.

    ``capacity`` bounds the ring; overflow evicts the OLDEST event (the
    crash-adjacent tail is the valuable part) and counts into
    :attr:`dropped`.  Recording is a lock-guarded host-only append —
    never a device sync — so it is safe on hot paths at coarse
    granularity (per log-interval, per round, per admission; not per
    step)."""

    def __init__(self, capacity: int = 512, directory: str | None = None,
                 registry: Any = None, tracer: Any = None,
                 request_events: Any = None) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.directory = directory
        self.dropped = 0
        self.last_hlo: str | None = None
        self.last_dump_path: str | None = None
        self._registry = registry
        self._tracer = tracer
        # the per-request tracing ring (tpudist.obs.events): its tail
        # joins the bundle so a crash dump carries the fleet's recent
        # request-lifecycle decisions next to the metric/span state
        self._request_events = request_events
        self._events: deque[dict] = deque(maxlen=capacity)
        self._lock = threading.Lock()

    # -- recording ---------------------------------------------------------

    def record(self, kind: str, **fields) -> None:
        """Append one event: ``{"t": now, "kind": kind, **fields}``.
        Fields must be JSON-ready (host ints/floats/strings)."""
        event = {"t": time.time(), "kind": kind, **fields}
        with self._lock:
            if len(self._events) == self.capacity:
                self.dropped += 1
            self._events.append(event)

    def note_hlo(self, text: str | None) -> None:
        """Stash the most recently compiled program's HLO text (called at
        compile sites; cheap — the text was already rendered)."""
        if text:
            self.last_hlo = text

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    # -- post-mortem -------------------------------------------------------

    def bundle(self, exc: BaseException | None = None,
               context: dict | None = None, span_tail: int = 50) -> dict:
        """Assemble the post-mortem document (no file I/O)."""
        snapshot = None
        if self._registry is not None:
            try:
                snapshot = self._registry.snapshot()
            except Exception as e:  # noqa: BLE001 - dead backend
                # fall back to the host-only view: fold nothing, just
                # read what already folded (a crash dump must not block
                # on a device sync against a torn-down backend)
                try:
                    snapshot = {
                        "degraded": str(e)[:200],
                        "counters": {n: m._snap() for n, m in
                                     self._registry.metrics().items()
                                     if type(m).__name__ == "Counter"},
                    }
                except Exception:  # noqa: BLE001
                    snapshot = {"degraded": str(e)[:200]}
        spans = None
        if self._tracer is not None:
            try:
                spans = self._tracer.events()[-span_tail:]
            except Exception:  # noqa: BLE001
                spans = None
        request_events = request_events_dropped = None
        if self._request_events is not None:
            try:
                request_events = self._request_events.tail(span_tail * 4)
                request_events_dropped = self._request_events.dropped
            except Exception:  # noqa: BLE001
                request_events = None
        exc_doc = None
        if exc is not None:
            exc_doc = {
                "type": type(exc).__name__,
                "message": str(exc)[:2000],
                "traceback": "".join(traceback.format_exception(
                    type(exc), exc, exc.__traceback__))[-8000:],
            }
        return {
            "schema": POSTMORTEM_SCHEMA,
            "time": time.time(),
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "context": context or {},
            "exception": exc_doc,
            "env": {k: v for k, v in sorted(os.environ.items())
                    if k.startswith(_ENV_PREFIXES)},
            "topology": _topology(),
            "events": self.events(),
            "events_dropped": self.dropped,
            "snapshot": snapshot,
            "spans": spans,
            "request_events": request_events,
            "request_events_dropped": request_events_dropped,
            "last_hlo": self.last_hlo,
        }

    def dump(self, exc: BaseException | None = None, path: str | None = None,
             context: dict | None = None, span_tail: int = 50) -> str:
        """Write the post-mortem bundle and return its path.

        Default location: ``TPUDIST_POSTMORTEM_DIR`` > the recorder's
        ``directory`` > the current directory; the filename carries pid +
        timestamp so concurrent workers never clobber each other."""
        doc = self.bundle(exc=exc, context=context, span_tail=span_tail)
        if path is None:
            directory = (os.environ.get("TPUDIST_POSTMORTEM_DIR")
                         or self.directory or ".")
            os.makedirs(directory, exist_ok=True)
            path = os.path.join(
                directory,
                f"postmortem-{os.getpid()}-{int(doc['time'] * 1000)}.json")
        atomic_write_json(path, doc, indent=1)
        self.last_dump_path = path
        return path

    @contextlib.contextmanager
    def guard(self, component: str, **context):
        """Dump a post-mortem on any unhandled exception, then re-raise.

        The wiring surface for run loops::

            with obs.recorder.guard("trainer", epochs=cfg.total_epochs):
                ...

        Never masks the original exception: a failing dump is logged and
        swallowed; the exception always propagates unchanged."""
        try:
            yield self
        except BaseException as e:  # noqa: BLE001 - re-raised below
            # KeyboardInterrupt/SystemExit also deserve a bundle (a
            # supervisor SIGTERM mid-hang is exactly the wedged case)
            try:
                path = self.dump(
                    exc=e, context={"component": component, **context})
                log.error("%s crashed (%s: %s); post-mortem bundle: %s",
                          component, type(e).__name__, str(e)[:200], path)
            except Exception as dump_err:  # noqa: BLE001 - never mask
                log.warning("post-mortem dump failed: %s", dump_err)
            raise
