"""Process-local metric registry: counters, gauges, log-bucketed histograms.

The reference suite's only perf signal is an end-to-end ``time.time()``
delta (`mnist_ddp_elastic.py:210-213`, `model_parallel_ResNet50.py:258-262`);
production TPU stacks treat per-step latency histograms and per-host
counters as core infrastructure (Horovod's timeline, torch.distributed's
flight recorder).  This module is the recording half of that layer:

* :class:`Counter` — monotonically increasing sum (steps, tokens, bytes).
* :class:`Gauge` — last-written value (queue depth, world size, loss).
* :class:`Histogram` — log-bucketed distribution with p50/p90/p99
  summaries, mergeable across hosts bucket-by-bucket
  (:mod:`tpudist.obs.aggregate`).

The load-bearing property is LAZY accumulation, the same contract as
:class:`tpudist.utils.metrics.MetricLogger`: recorded values may be device
arrays and are appended un-synced, so recording on the step hot path never
blocks the async dispatch queue (no ``float()`` / ``device_get`` per
record).  The one batched host sync happens at :meth:`MetricRegistry
.snapshot`, which folds every metric's pending values in a single
``jax.device_get`` — and skips jax entirely when only plain Python numbers
were recorded, so the registry stays importable/usable without a backend.

Snapshots are plain JSON-ready dicts (string bucket keys), the wire format
the aggregator publishes through the coordination store and the exporters
render to JSONL / Prometheus text.
"""

from __future__ import annotations

import math
import threading
import time

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricRegistry",
    "hist_quantile",
    "split_labels",
    "summarize",
    "validate_metric_name",
    "values_to_hist",
]

_QUANTILES = (("p50", 0.50), ("p90", 0.90), ("p99", 0.99))

# characters a label key/value may not contain: "~" would re-split on
# the wire, "=" in a value would mis-parse the pair, and quote/backslash
# /newline would need escaping in the Prometheus exposition format.
_LABEL_BANNED = ("~", "=", '"', "\\", "\n")


def split_labels(name: str) -> tuple[str, dict[str, str]]:
    """Split ``base~key=value~k2=v2`` into (base, labels).

    Lenient by design — this is the READ path used by exporters and the
    TSDB on names that may predate validation: a ``~`` part without
    ``=`` is folded back into the base name instead of being dropped.
    The WRITE path (:func:`validate_metric_name`, enforced by
    :class:`MetricRegistry`) rejects such names outright, so new
    metrics round-trip exactly."""
    if "~" not in name:
        return name, {}
    base, *parts = name.split("~")
    labels: dict[str, str] = {}
    for part in parts:
        key, sep, value = part.partition("=")
        if sep and key:
            labels[key] = value
        else:
            base = f"{base}~{part}"     # not a k=v tag; keep it literal
    return base, labels


def validate_metric_name(name: str) -> None:
    """Reject metric names whose ``~key=value`` suffixes would not
    round-trip through the snapshot wire format and the Prometheus
    exporter: every ``~`` part must be ``key=value``, keys must be
    identifier-ish, and values may not contain ``~ = " \\`` or
    newlines (a value like ``a=b`` or ``x~y`` would silently mis-split
    on read — reject at registration instead)."""
    if not name:
        raise ValueError("metric name must be non-empty")
    if "~" not in name:
        return
    base, *parts = name.split("~")
    if not base:
        raise ValueError(f"metric {name!r}: empty base name before '~'")
    for part in parts:
        key, sep, value = part.partition("=")
        if not sep or not key:
            raise ValueError(
                f"metric {name!r}: label part {part!r} is not key=value "
                f"(a '~' in a metric name starts a label tag)")
        if not key.replace("_", "").isalnum():
            raise ValueError(
                f"metric {name!r}: label key {key!r} must be "
                f"alphanumeric/underscore")
        bad = [c for c in _LABEL_BANNED if c in value]
        if bad:
            raise ValueError(
                f"metric {name!r}: label value {value!r} contains "
                f"{bad!r} which cannot round-trip the wire format "
                f"(escape or drop these characters at the call site)")


def _is_plain(v) -> bool:
    return isinstance(v, (int, float))


def _sync_pending(pending: dict[str, list]) -> dict[str, list]:
    """ONE batched device->host transfer for every metric's pending list
    (the MetricLogger discipline); pure-host recordings skip jax."""
    if all(_is_plain(v) for vs in pending.values() for v in vs):
        return pending
    import jax

    return jax.device_get(pending)


class Counter:
    """Monotonic sum.  ``inc`` accepts device scalars (or small arrays,
    summed elementwise at fold time) and never syncs."""

    def __init__(self, name: str, unit: str = "", help: str = "") -> None:  # noqa: A002
        self.name = name
        self.unit = unit
        self.help = help
        self._total = 0.0
        self._pending: list = []

    def inc(self, n=1) -> None:
        self._pending.append(n)

    def _take_pending(self) -> list:
        out, self._pending = self._pending, []
        return out

    def _fold(self, host_values: list) -> None:
        import numpy as np

        for v in host_values:
            self._total += float(np.sum(np.asarray(v, dtype=np.float64)))

    def value(self) -> float:
        """Current total (syncs this counter's own pending only)."""
        self._fold(_sync_pending({"v": self._take_pending()})["v"])
        return self._total

    def _snap(self) -> dict:
        snap = {"value": self._total, "unit": self.unit}
        if self.help:
            snap["help"] = self.help
        return snap


class Gauge:
    """Last-written value.  ``set`` keeps the raw (possibly device) value;
    a stacked array (the fused train loop's [n]-step metrics) folds to its
    last element."""

    def __init__(self, name: str, unit: str = "", help: str = "") -> None:  # noqa: A002
        self.name = name
        self.unit = unit
        self.help = help
        self._value: float | None = None
        self._pending: list = []

    def set(self, v) -> None:
        # keep only the latest raw value; older unsynced writes are dead
        self._pending = [v]

    def _take_pending(self) -> list:
        out, self._pending = self._pending, []
        return out

    def _fold(self, host_values: list) -> None:
        import numpy as np

        for v in host_values:
            flat = np.asarray(v, dtype=np.float64).reshape(-1)
            if flat.size:
                self._value = float(flat[-1])

    def value(self) -> float | None:
        self._fold(_sync_pending({"v": self._take_pending()})["v"])
        return self._value

    def clear(self) -> None:
        """Back to absent: the next snapshot reports ``value: null``
        (aggregation skips it, the TSDB records nothing).  Lets a
        conditional signal — e.g. an SLO burn rate with zero traffic in
        its window — read as "no data" instead of a literal 0.0."""
        self._pending = []
        self._value = None

    def _snap(self) -> dict:
        snap = {"value": self._value, "unit": self.unit}
        if self.help:
            snap["help"] = self.help
        return snap


class Histogram:
    """Log-bucketed distribution: value ``v > 0`` lands in bucket
    ``floor(log(v)/log(growth))`` whose lower bound is ``growth**index``
    (so recorded values that are exact powers of ``growth`` report EXACT
    quantiles); ``v <= 0`` lands in a dedicated zero bucket.  Buckets are
    a sparse ``{index: count}`` map, mergeable across hosts by summing
    counts (:func:`tpudist.obs.aggregate.merge_snapshots`).

    ``record`` accepts scalars or arrays (host or device) and never syncs;
    arrays count one observation per element (the fused train loop's
    stacked [n]-step metrics weigh every step).

    With ``window_s`` set the histogram is SLIDING-WINDOW: observations
    expire so control loops (SLO admission, the autoscaler) react to the
    last ``window_s`` seconds instead of the process lifetime — hours-old
    queue-wait samples can neither mask a fresh spike nor pin the fleet
    scaled-up after it passes.  Implementation is two rotating half-window
    generations: folds land in the newest, snapshots merge the live ones,
    and a generation older than the window is dropped wholesale — so a
    snapshot always covers between ``window_s/2`` and ``window_s`` of
    history with O(1) rotation cost and no per-observation timestamps.
    Expiry happens at fold/snapshot time (lazy, like accumulation)."""

    def __init__(self, name: str, unit: str = "", help: str = "",  # noqa: A002
                 growth: float = 2.0, window_s: float | None = None,
                 clock=time.monotonic) -> None:
        if growth <= 1.0:
            raise ValueError(f"histogram growth must be > 1, got {growth}")
        if window_s is not None and window_s <= 0:
            raise ValueError(f"histogram window_s must be > 0, got {window_s}")
        self.name = name
        self.unit = unit
        self.help = help
        self.growth = growth
        self.window_s = window_s
        self._clock = clock
        self._gens: list[dict] = [self._new_gen()]
        self._pending: list = []

    def _new_gen(self) -> dict:
        return {"start": self._clock(), "buckets": {}, "zero": 0,
                "count": 0, "sum": 0.0, "min": None, "max": None}

    def _rotate(self) -> None:
        if self.window_s is None:
            return
        now = self._clock()
        if now - self._gens[-1]["start"] >= self.window_s / 2.0:
            self._gens.append(self._new_gen())
            del self._gens[:-2]
        # after a long quiet gap even the previous generation has expired
        if len(self._gens) == 2 and \
                now - self._gens[0]["start"] >= self.window_s:
            del self._gens[0]

    def record(self, v) -> None:
        self._pending.append(v)

    def _take_pending(self) -> list:
        out, self._pending = self._pending, []
        return out

    def _fold(self, host_values: list) -> None:
        import numpy as np

        self._rotate()
        g = self._gens[-1]
        for v in host_values:
            flat = np.asarray(v, dtype=np.float64).reshape(-1)
            if not flat.size:
                continue
            g["count"] += int(flat.size)
            g["sum"] += float(flat.sum())
            lo, hi = float(flat.min()), float(flat.max())
            g["min"] = lo if g["min"] is None else min(g["min"], lo)
            g["max"] = hi if g["max"] is None else max(g["max"], hi)
            pos = flat[flat > 0]
            g["zero"] += int(flat.size - pos.size)
            if pos.size:
                # +1e-9 absorbs the float error of log-ratio at exact
                # bucket boundaries (log(8)/log(2) may be 2.999...96)
                idx = np.floor(
                    np.log(pos) / math.log(self.growth) + 1e-9).astype(int)
                for i, n in zip(*np.unique(idx, return_counts=True)):
                    g["buckets"][int(i)] = (
                        g["buckets"].get(int(i), 0) + int(n))

    def summary(self) -> dict:
        """p50/p90/p99 + count/sum/mean/min/max (syncs this histogram's
        own pending only)."""
        self._fold(_sync_pending({"v": self._take_pending()})["v"])
        return summarize(self._snap())

    def _snap(self) -> dict:
        self._rotate()
        buckets: dict[int, int] = {}
        zero = count = 0
        total = 0.0
        mn = mx = None
        for g in self._gens:
            count += g["count"]
            total += g["sum"]
            zero += g["zero"]
            if g["min"] is not None:
                mn = g["min"] if mn is None else min(mn, g["min"])
            if g["max"] is not None:
                mx = g["max"] if mx is None else max(mx, g["max"])
            for i, n in g["buckets"].items():
                buckets[i] = buckets.get(i, 0) + n
        snap = {
            "unit": self.unit,
            "growth": self.growth,
            "count": count,
            "sum": total,
            "min": mn,
            "max": mx,
            "zero": zero,
            # string keys: the snapshot is the JSON wire format
            "buckets": {str(i): c for i, c in sorted(buckets.items())},
        }
        if self.window_s is not None:
            snap["window_s"] = self.window_s
        if self.help:
            snap["help"] = self.help
        return snap


def hist_quantile(hist: dict, q: float) -> float:
    """Nearest-rank quantile from a histogram SNAPSHOT dict: the lower
    bound of the bucket holding the ceil(q*count)-th smallest observation
    (0.0 for the zero bucket).  Exact when every recorded value sits on a
    bucket lower bound — e.g. powers of ``growth``."""
    count = hist["count"]
    if count == 0:
        return float("nan")
    k = max(1, math.ceil(q * count))
    cum = hist.get("zero", 0)
    if k <= cum:
        return 0.0
    for idx in sorted(int(i) for i in hist["buckets"]):
        cum += hist["buckets"][str(idx)]
        if k <= cum:
            return float(hist["growth"] ** idx)
    return float(hist["max"]) if hist["max"] is not None else float("nan")


def values_to_hist(values, *, growth: float = 2.0,
                   unit: str = "") -> dict:
    """A histogram SNAPSHOT dict built directly from host values — the
    same wire shape :class:`Histogram` produces, without a registry.
    The offline fleet simulator's fake replicas publish these so the
    REAL router/autoscaler percentile code reads simulated queue waits
    through the same format live ``MetricsPublisher`` snapshots use."""
    if growth <= 1.0:
        raise ValueError(f"histogram growth must be > 1, got {growth}")
    vals = [float(v) for v in values]
    buckets: dict[int, int] = {}
    zero = 0
    for v in vals:
        if v <= 0.0:
            zero += 1
        else:
            idx = int(math.floor(math.log(v) / math.log(growth) + 1e-9))
            buckets[idx] = buckets.get(idx, 0) + 1
    return {
        "unit": unit,
        "growth": growth,
        "count": len(vals),
        "sum": float(sum(vals)),
        "min": min(vals) if vals else None,
        "max": max(vals) if vals else None,
        "zero": zero,
        "buckets": {str(i): c for i, c in sorted(buckets.items())},
    }


def summarize(hist: dict) -> dict:
    """Quantile/mean summary of a histogram snapshot dict (works on both
    per-process and cross-host merged histograms)."""
    count = hist["count"]
    out = {
        "count": count,
        "sum": hist["sum"],
        "mean": hist["sum"] / count if count else float("nan"),
        "min": hist["min"],
        "max": hist["max"],
    }
    for name, q in _QUANTILES:
        out[name] = hist_quantile(hist, q)
    return out


class MetricRegistry:
    """Create-once, look-up-forever registry of named metrics.

    ``counter`` / ``gauge`` / ``histogram`` return the existing metric on
    repeat calls (so instrumentation sites can call them unconditionally);
    re-registering a name as a DIFFERENT kind raises.  :meth:`snapshot`
    folds every metric's pending device values in one batched sync and
    returns the JSON-ready wire dict."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}

    def _get(self, name: str, cls, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                validate_metric_name(name)
                m = self._metrics[name] = cls(name, **kwargs)
            elif type(m) is not cls:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str, unit: str = "", help: str = "") -> Counter:  # noqa: A002
        return self._get(name, Counter, unit=unit, help=help)

    def gauge(self, name: str, unit: str = "", help: str = "") -> Gauge:  # noqa: A002
        return self._get(name, Gauge, unit=unit, help=help)

    def histogram(self, name: str, unit: str = "", help: str = "",  # noqa: A002
                  growth: float = 2.0,
                  window_s: float | None = None) -> Histogram:
        return self._get(name, Histogram, unit=unit, help=help, growth=growth,
                         window_s=window_s)

    def metrics(self) -> dict:
        with self._lock:
            return dict(self._metrics)

    def snapshot(self) -> dict:
        """Fold all pending values (ONE batched device->host sync across
        every metric) and return the JSON-ready snapshot."""
        metrics = self.metrics()
        pending = {name: m._take_pending() for name, m in metrics.items()}
        host = _sync_pending(pending)
        snap: dict = {"time": time.time(), "counters": {}, "gauges": {},
                      "histograms": {}}
        for name, m in metrics.items():
            m._fold(host[name])
            kind = {Counter: "counters", Gauge: "gauges",
                    Histogram: "histograms"}[type(m)]
            snap[kind][name] = m._snap()
        return snap

    def clear(self) -> None:
        """Drop every metric (tests; a long-lived process keeps its
        registry for the life of the job)."""
        with self._lock:
            self._metrics.clear()
