"""Span tracer — Chrome-trace-format timelines for host-side phases.

``with tracer.span("train_step"): ...`` records a complete event per exit
into an in-memory buffer; :meth:`SpanTracer.dump` / :meth:`write` render
the catapult JSON that chrome://tracing and Perfetto load directly:

    {"traceEvents": [{"name", "ph": "X", "ts", "dur", "pid", "tid",
                      "args"}, ...], "displayTimeUnit": "ms"}

with ``ts``/``dur`` in microseconds.  Nesting falls out of the format —
the viewer stacks events on the same tid by containment, and we record a
``depth`` arg from a per-thread stack for programmatic consumers.

Two accelerator-facing hooks:

* ``fence=True`` (or ``TPUDIST_OBS_FENCE=1``) calls
  ``jax.effects_barrier()`` on span exit, so asynchronously dispatched
  device work is attributed to the span that launched it instead of
  whichever span happens to be open when the queue drains.  Off by
  default: fencing serializes dispatch and is a measurement tool, not a
  production default.
* every span is also wrapped in ``jax.profiler.TraceAnnotation`` when a
  profiler trace is active, so spans appear as named regions inside the
  XProf timeline captured by :func:`tpudist.utils.metrics.maybe_profile`.

Spans stay importable and functional without a jax backend: both hooks
degrade to no-ops when jax (or the annotation API) is unavailable.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import tempfile
import threading
import time

from tpudist.utils.config import env_flag

__all__ = ["SpanTracer", "atomic_write_json"]


def atomic_write_json(path: str | os.PathLike, doc,
                      indent: int | None = None) -> str:
    """Write ``doc`` as JSON via temp file + atomic rename, so a crash
    mid-dump (the exact moment traces and post-mortems get written)
    can never leave a truncated/unparseable file at ``path``."""
    path = os.fspath(path)
    fd, tmp = tempfile.mkstemp(
        prefix=".tmp-" + os.path.basename(path) + "-",
        dir=os.path.dirname(path) or ".")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=indent)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def _trace_annotation(name: str):
    try:
        import jax.profiler

        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()


def _effects_barrier() -> None:
    try:
        import jax

        jax.effects_barrier()
    except Exception:
        pass


class SpanTracer:
    """Per-process span recorder.

    ``max_events`` bounds the buffer as a RING (long serving jobs would
    otherwise grow without limit): overflow evicts the OLDEST span and
    increments :attr:`dropped`, keeping the newest spans — the tail a
    post-mortem (:mod:`tpudist.obs.recorder`) actually wants.
    Thread-safe: each thread keeps its own nesting stack, the event
    buffer is lock-guarded.
    """

    def __init__(self, max_events: int = 100_000,
                 fence: bool | None = None) -> None:
        self.max_events = max_events
        # None -> env-controlled so tests/benches can fence without code
        self.fence = env_flag("TPUDIST_OBS_FENCE") if fence is None else fence
        self.dropped = 0
        self._events: collections.deque[dict] = collections.deque(
            maxlen=max_events)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._pid = os.getpid()

    def _depth(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @contextlib.contextmanager
    def span(self, name: str, **args):
        """Record a complete ("ph": "X") event for the enclosed block.
        ``args`` must be JSON-serializable; they land in the event's
        ``args`` field next to the nesting ``depth``."""
        stack = self._depth()
        stack.append(name)
        start = time.perf_counter()
        try:
            with _trace_annotation(name):
                yield self
        finally:
            if self.fence:
                _effects_barrier()
            dur_us = (time.perf_counter() - start) * 1e6
            depth = len(stack) - 1
            stack.pop()
            event = {
                "name": name,
                "ph": "X",
                # perf_counter origin is arbitrary but shared across the
                # process, which is all the viewer needs
                "ts": start * 1e6,
                "dur": dur_us,
                "pid": self._pid,
                "tid": threading.get_ident(),
                "args": {"depth": depth, **args},
            }
            with self._lock:
                if len(self._events) == self.max_events:
                    self.dropped += 1  # deque maxlen evicts the oldest
                self._events.append(event)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def dump(self) -> dict:
        """The Chrome-trace JSON document (catapult "JSON object format")."""
        return {"traceEvents": self.events(), "displayTimeUnit": "ms"}

    def write(self, path: str) -> str:
        return atomic_write_json(path, self.dump())

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0
