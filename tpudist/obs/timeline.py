"""Request-timeline reconstruction: ``python -m tpudist.obs.timeline``.

Loads a fleet event log — the merged ``tpudist.events/1`` document
(:func:`tpudist.obs.events.merge_events` output, e.g. the file the
``serve_fleet`` bench writes), a ``tpudist.postmortem/1`` crash bundle
(whose ``request_events`` tail this tool understands), or a raw
published ring snapshot — and reconstructs each request's causal
history: one time-ordered timeline per trace id, spanning every
process the request touched (router enqueue/dispatch, replica
admit/segments, a SIGKILL's redispatch, the replica-side done-commit,
the router-side done).

Text mode prints each timeline with per-event offsets from its enqueue;
``--chrome OUT`` additionally exports the merged view as Chrome-trace
JSON (chrome://tracing / Perfetto): each trace id becomes one track,
consecutive lifecycle events become the "X" slices between them, so a
request's wait / decode / redispatch phases are visible as bars.

Usage::

    python -m tpudist.obs.timeline events.json                # all traces
    python -m tpudist.obs.timeline events.json --trace ID     # one trace
    python -m tpudist.obs.timeline events.json --rid q3       # by caller rid
    python -m tpudist.obs.timeline events.json --chrome t.json
    python -m tpudist.obs.timeline events.json --summary   # percentiles
    python -m tpudist.obs.timeline events.json --require-complete

``--require-complete`` exits 1 unless every resolved trace passes
:func:`tpudist.obs.events.is_complete` — the CI gate that no completed
request has a gap in its recorded history.
"""

from __future__ import annotations

import argparse
import json
import sys

from tpudist.obs.events import (
    EVENTS_SCHEMA,
    group_timelines,
    is_complete,
    timeline_for_rid,
)
from tpudist.obs.spans import atomic_write_json

__all__ = ["load_events", "render_timeline", "summarize_timelines",
           "render_summary", "to_chrome", "main"]


def load_events(path: str) -> list[dict]:
    """Events from any of the recognized on-disk shapes (see module
    docstring); raises ``ValueError`` on an unrecognizable document."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):
        return doc
    if isinstance(doc, dict):
        if doc.get("schema") == EVENTS_SCHEMA or "events" in doc and \
                not doc.get("schema", "").startswith("tpudist.postmortem"):
            evs = doc.get("events")
            if isinstance(evs, list):
                return evs
        if "request_events" in doc:    # a postmortem bundle's tail
            return doc["request_events"] or []
    raise ValueError(
        f"{path}: not an event log ({EVENTS_SCHEMA}), postmortem "
        f"bundle, or raw event list")


def render_timeline(trace_id: str, timeline: list[dict]) -> list[str]:
    """Human-readable causal history, offsets relative to the first
    event (the router enqueue when the timeline is complete)."""
    if not timeline:
        return [f"trace {trace_id}: (no events)"]
    t0 = timeline[0].get("t", 0.0)
    status = "complete" if is_complete(timeline) else "INCOMPLETE"
    lines = [f"trace {trace_id} [{status}] "
             f"({len(timeline)} events over "
             f"{timeline[-1].get('t', t0) - t0:.3f}s)"]
    for ev in timeline:
        detail = " ".join(
            f"{k}={ev[k]}" for k in sorted(ev)
            if k not in ("t", "i", "kind", "trace", "src")
            and ev[k] is not None)
        lines.append(f"  +{ev.get('t', t0) - t0:9.4f}s "
                     f"{ev.get('src', '?'):>8} {ev.get('kind', '?'):<14}"
                     f" {detail}".rstrip())
    return lines


def _pct(sorted_vals: list[float], q: float) -> float:
    """Linear-interpolated percentile of an already-sorted list."""
    if not sorted_vals:
        return float("nan")
    pos = (len(sorted_vals) - 1) * q
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def summarize_timelines(timelines: dict) -> dict:
    """Per-stage latency samples across every trace: where requests
    actually spend their time, fleet-wide.

    Stages (seconds):

    * ``enqueue_to_admit`` — router submit to the replica slot admit
      (queueing + dispatch + inbox transit: the congestion signal);
    * ``admit_to_first_token`` — admit to the first ``segment`` drain
      (prefill + first decode segment: the TTFT proxy);
    * ``inter_token`` — per-token pace inside decode: each consecutive
      segment gap divided by the later segment's ``steps``;
    * ``enqueue_to_terminal`` — the whole request, submit to its
      terminal router event.

    Plus ``redispatches`` — ``{count: requests}`` over redispatch
    events per trace (the death-recovery tail: anything over 0 means a
    request outlived a replica).
    """
    stages: dict[str, list[float]] = {
        "enqueue_to_admit": [], "admit_to_first_token": [],
        "inter_token": [], "enqueue_to_terminal": []}
    redispatches: dict[int, int] = {}
    n_traces = 0
    for tid, tl in timelines.items():
        if tid is None:
            continue
        n_traces += 1
        t_enq = t_admit = None
        segs: list[dict] = []
        n_re = 0
        t_term = None
        for ev in tl:
            kind, t = ev.get("kind"), ev.get("t")
            if kind == "enqueue" and t_enq is None:
                t_enq = t
            elif kind == "admit" and t_admit is None:
                t_admit = t
            elif kind == "segment":
                segs.append(ev)
            elif kind == "redispatch":
                n_re += 1
            elif kind in ("done", "shed", "timeout", "failed"):
                t_term = t
        redispatches[n_re] = redispatches.get(n_re, 0) + 1
        if t_enq is not None and t_admit is not None:
            stages["enqueue_to_admit"].append(t_admit - t_enq)
        if t_admit is not None and segs:
            stages["admit_to_first_token"].append(
                segs[0]["t"] - t_admit)
        for a, b in zip(segs, segs[1:]):
            steps = int(b.get("steps") or 1)
            if steps > 0 and b["t"] >= a["t"]:
                stages["inter_token"].append((b["t"] - a["t"]) / steps)
        if t_enq is not None and t_term is not None:
            stages["enqueue_to_terminal"].append(t_term - t_enq)
    out: dict = {"traces": n_traces, "redispatches": dict(sorted(
        redispatches.items()))}
    for stage, vals in stages.items():
        vals.sort()
        out[stage] = {
            "n": len(vals),
            "p50": _pct(vals, 0.50), "p90": _pct(vals, 0.90),
            "p99": _pct(vals, 0.99),
            "max": vals[-1] if vals else float("nan")}
    return out


def render_summary(summary: dict) -> list[str]:
    lines = [f"per-stage latency percentiles over "
             f"{summary['traces']} traces:"]
    for stage in ("enqueue_to_admit", "admit_to_first_token",
                  "inter_token", "enqueue_to_terminal"):
        s = summary[stage]
        lines.append(
            f"  {stage:<22} n={s['n']:<6} "
            f"p50={s['p50']:.4f}s p90={s['p90']:.4f}s "
            f"p99={s['p99']:.4f}s max={s['max']:.4f}s"
            if s["n"] else f"  {stage:<22} n=0      (no samples)")
    redis = summary["redispatches"]
    lines.append("redispatches per request: " + (" ".join(
        f"{k}x{v}" for k, v in redis.items()) or "(none)"))
    return lines


def to_chrome(events: list[dict]) -> dict:
    """Chrome-trace JSON of the merged view: one tid per trace id
    (trace-less fleet events land on tid 0), consecutive events drawn
    as the slice between them, terminal events as instants."""
    timelines = group_timelines(events)
    tids = {tid: n for n, tid in enumerate(
        sorted((t for t in timelines if t is not None)), start=1)}
    out: list[dict] = []
    for tid, timeline in sorted(timelines.items(),
                                key=lambda kv: str(kv[0])):
        track = tids.get(tid, 0)
        out.append({"name": "thread_name", "ph": "M", "pid": 0,
                    "tid": track,
                    "args": {"name": f"trace {tid or '(fleet)'}"}})
        for ev, nxt in zip(timeline, timeline[1:]):
            out.append({
                "name": ev.get("kind", "?"), "ph": "X",
                "ts": ev.get("t", 0.0) * 1e6,
                "dur": max(1.0, (nxt.get("t", 0.0) - ev.get("t", 0.0))
                           * 1e6),
                "pid": 0, "tid": track,
                "args": {k: v for k, v in ev.items()
                         if k not in ("t", "kind") and v is not None}})
        if timeline:
            last = timeline[-1]
            out.append({
                "name": last.get("kind", "?"), "ph": "i", "s": "t",
                "ts": last.get("t", 0.0) * 1e6, "pid": 0, "tid": track,
                "args": {k: v for k, v in last.items()
                         if k not in ("t", "kind") and v is not None}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpudist.obs.timeline",
        description="Reconstruct per-request fleet timelines from a "
                    "merged event log (see tpudist.obs.events).")
    ap.add_argument("path", help="event log / postmortem JSON")
    ap.add_argument("--trace", help="show only this trace id")
    ap.add_argument("--rid", help="show only the trace whose enqueue "
                                  "carries this caller rid")
    ap.add_argument("--chrome", metavar="OUT",
                    help="also write Chrome-trace JSON (atomic)")
    ap.add_argument("--summary", action="store_true",
                    help="print per-stage latency percentiles "
                         "(enqueue->admit, admit->first-token, "
                         "inter-token) and the redispatch histogram "
                         "instead of per-trace timelines")
    ap.add_argument("--require-complete", action="store_true",
                    help="exit 1 unless every resolved trace is "
                         "gap-free (CI gate)")
    args = ap.parse_args(argv)

    events = load_events(args.path)
    timelines = group_timelines(events)

    selected = timelines
    if args.trace is not None:
        if args.trace not in timelines:
            print(f"trace {args.trace!r} not in log "
                  f"({len(timelines)} traces)", file=sys.stderr)
            return 2
        selected = {args.trace: timelines[args.trace]}
    elif args.rid is not None:
        tl = timeline_for_rid(timelines, args.rid)
        if tl is None:
            print(f"no trace with enqueue rid={args.rid!r}",
                  file=sys.stderr)
            return 2
        selected = {tl[0].get("trace"): tl}

    if args.summary:
        print("\n".join(render_summary(summarize_timelines(selected))))
    else:
        for tid, timeline in sorted(selected.items(),
                                    key=lambda kv: str(kv[0])):
            if tid is None:
                continue   # trace-less fleet events: chrome export only
            print("\n".join(render_timeline(tid, timeline)))

    if args.chrome:
        atomic_write_json(args.chrome, to_chrome(events))
        print(f"chrome trace: {args.chrome}", file=sys.stderr)

    if args.require_complete:
        bad = [tid for tid, tl in timelines.items()
               if tid is not None
               and any(e.get("kind") in ("done", "shed", "timeout",
                                         "failed") for e in tl)
               and not is_complete(tl)]
        if bad:
            print(f"INCOMPLETE timelines: {bad}", file=sys.stderr)
            return 1
        print(f"all {sum(1 for t in timelines if t is not None)} "
              f"timelines complete", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
