"""Request-timeline reconstruction: ``python -m tpudist.obs.timeline``.

Loads a fleet event log — the merged ``tpudist.events/1`` document
(:func:`tpudist.obs.events.merge_events` output, e.g. the file the
``serve_fleet`` bench writes), a ``tpudist.postmortem/1`` crash bundle
(whose ``request_events`` tail this tool understands), or a raw
published ring snapshot — and reconstructs each request's causal
history: one time-ordered timeline per trace id, spanning every
process the request touched (router enqueue/dispatch, replica
admit/segments, a SIGKILL's redispatch, the replica-side done-commit,
the router-side done).

Text mode prints each timeline with per-event offsets from its enqueue;
``--chrome OUT`` additionally exports the merged view as Chrome-trace
JSON (chrome://tracing / Perfetto): each trace id becomes one track,
consecutive lifecycle events become the "X" slices between them, so a
request's wait / decode / redispatch phases are visible as bars.

Usage::

    python -m tpudist.obs.timeline events.json                # all traces
    python -m tpudist.obs.timeline events.json --trace ID     # one trace
    python -m tpudist.obs.timeline events.json --rid q3       # by caller rid
    python -m tpudist.obs.timeline events.json --chrome t.json
    python -m tpudist.obs.timeline events.json --require-complete

``--require-complete`` exits 1 unless every resolved trace passes
:func:`tpudist.obs.events.is_complete` — the CI gate that no completed
request has a gap in its recorded history.
"""

from __future__ import annotations

import argparse
import json
import sys

from tpudist.obs.events import (
    EVENTS_SCHEMA,
    group_timelines,
    is_complete,
    timeline_for_rid,
)
from tpudist.obs.spans import atomic_write_json

__all__ = ["load_events", "render_timeline", "to_chrome", "main"]


def load_events(path: str) -> list[dict]:
    """Events from any of the recognized on-disk shapes (see module
    docstring); raises ``ValueError`` on an unrecognizable document."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):
        return doc
    if isinstance(doc, dict):
        if doc.get("schema") == EVENTS_SCHEMA or "events" in doc and \
                not doc.get("schema", "").startswith("tpudist.postmortem"):
            evs = doc.get("events")
            if isinstance(evs, list):
                return evs
        if "request_events" in doc:    # a postmortem bundle's tail
            return doc["request_events"] or []
    raise ValueError(
        f"{path}: not an event log ({EVENTS_SCHEMA}), postmortem "
        f"bundle, or raw event list")


def render_timeline(trace_id: str, timeline: list[dict]) -> list[str]:
    """Human-readable causal history, offsets relative to the first
    event (the router enqueue when the timeline is complete)."""
    if not timeline:
        return [f"trace {trace_id}: (no events)"]
    t0 = timeline[0].get("t", 0.0)
    status = "complete" if is_complete(timeline) else "INCOMPLETE"
    lines = [f"trace {trace_id} [{status}] "
             f"({len(timeline)} events over "
             f"{timeline[-1].get('t', t0) - t0:.3f}s)"]
    for ev in timeline:
        detail = " ".join(
            f"{k}={ev[k]}" for k in sorted(ev)
            if k not in ("t", "i", "kind", "trace", "src")
            and ev[k] is not None)
        lines.append(f"  +{ev.get('t', t0) - t0:9.4f}s "
                     f"{ev.get('src', '?'):>8} {ev.get('kind', '?'):<14}"
                     f" {detail}".rstrip())
    return lines


def to_chrome(events: list[dict]) -> dict:
    """Chrome-trace JSON of the merged view: one tid per trace id
    (trace-less fleet events land on tid 0), consecutive events drawn
    as the slice between them, terminal events as instants."""
    timelines = group_timelines(events)
    tids = {tid: n for n, tid in enumerate(
        sorted((t for t in timelines if t is not None)), start=1)}
    out: list[dict] = []
    for tid, timeline in sorted(timelines.items(),
                                key=lambda kv: str(kv[0])):
        track = tids.get(tid, 0)
        out.append({"name": "thread_name", "ph": "M", "pid": 0,
                    "tid": track,
                    "args": {"name": f"trace {tid or '(fleet)'}"}})
        for ev, nxt in zip(timeline, timeline[1:]):
            out.append({
                "name": ev.get("kind", "?"), "ph": "X",
                "ts": ev.get("t", 0.0) * 1e6,
                "dur": max(1.0, (nxt.get("t", 0.0) - ev.get("t", 0.0))
                           * 1e6),
                "pid": 0, "tid": track,
                "args": {k: v for k, v in ev.items()
                         if k not in ("t", "kind") and v is not None}})
        if timeline:
            last = timeline[-1]
            out.append({
                "name": last.get("kind", "?"), "ph": "i", "s": "t",
                "ts": last.get("t", 0.0) * 1e6, "pid": 0, "tid": track,
                "args": {k: v for k, v in last.items()
                         if k not in ("t", "kind") and v is not None}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpudist.obs.timeline",
        description="Reconstruct per-request fleet timelines from a "
                    "merged event log (see tpudist.obs.events).")
    ap.add_argument("path", help="event log / postmortem JSON")
    ap.add_argument("--trace", help="show only this trace id")
    ap.add_argument("--rid", help="show only the trace whose enqueue "
                                  "carries this caller rid")
    ap.add_argument("--chrome", metavar="OUT",
                    help="also write Chrome-trace JSON (atomic)")
    ap.add_argument("--require-complete", action="store_true",
                    help="exit 1 unless every resolved trace is "
                         "gap-free (CI gate)")
    args = ap.parse_args(argv)

    events = load_events(args.path)
    timelines = group_timelines(events)

    selected = timelines
    if args.trace is not None:
        if args.trace not in timelines:
            print(f"trace {args.trace!r} not in log "
                  f"({len(timelines)} traces)", file=sys.stderr)
            return 2
        selected = {args.trace: timelines[args.trace]}
    elif args.rid is not None:
        tl = timeline_for_rid(timelines, args.rid)
        if tl is None:
            print(f"no trace with enqueue rid={args.rid!r}",
                  file=sys.stderr)
            return 2
        selected = {tl[0].get("trace"): tl}

    for tid, timeline in sorted(selected.items(),
                                key=lambda kv: str(kv[0])):
        if tid is None:
            continue   # trace-less fleet events: chrome export only
        print("\n".join(render_timeline(tid, timeline)))

    if args.chrome:
        atomic_write_json(args.chrome, to_chrome(events))
        print(f"chrome trace: {args.chrome}", file=sys.stderr)

    if args.require_complete:
        bad = [tid for tid, tl in timelines.items()
               if tid is not None
               and any(e.get("kind") in ("done", "shed", "timeout",
                                         "failed") for e in tl)
               and not is_complete(tl)]
        if bad:
            print(f"INCOMPLETE timelines: {bad}", file=sys.stderr)
            return 1
        print(f"all {sum(1 for t in timelines if t is not None)} "
              f"timelines complete", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
