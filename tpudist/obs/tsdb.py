"""Bounded in-memory time-series store over registry snapshots.

Every signal the repo emits is a point-in-time snapshot: a registry
``snapshot()`` doc, or the fleet-merged view out of
``collect``/``merge_snapshots``.  This module adds the missing time
axis: :class:`TSDB` scrapes those snapshots on a cadence into
per-series ring buffers and answers the questions an operator (or an
alert rule) actually asks — "what was decode-pool KV headroom over the
last two minutes", "how fast is that counter moving", "what is the
p99 of queue wait over the last window".

Design constraints, in order:

* **Bounded.**  Retention, resolution and an overall byte budget are
  configuration; the store trims itself on every scrape.  Old points
  are folded into coarse (downsampled) buckets before they are dropped
  so a 10-minute view survives a 2 MiB budget.
* **Snapshot-native.**  ``scrape()`` takes the exact doc shape
  ``MetricRegistry.snapshot()`` / ``merge_snapshots()`` produce:
  counters and gauges become points; histograms are expanded into
  derived ``{name}/p50 p90 p99 mean count`` series via ``summarize``.
  ``~key=value`` label suffixes in metric names become series labels.
* **Dependency-free.**  Pure stdlib, injectable clock, usable on the
  sim's VirtualClock and in a live exporter thread alike.

:class:`FleetScraper` is the cadence driver: local registry +
coordinator-collected fleet view -> TSDB -> (optionally) an
``AlertManager.evaluate`` per tick, with membership-aware collection so
departed replicas fall out of the merged view immediately.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque
from typing import Any, Callable

from .registry import split_labels, summarize

__all__ = ["TSDB", "FleetScraper", "Series"]

# honest CPython accounting constants: a raw point is a 2-tuple of
# floats inside a deque (~56B tuple + 2x24B floats + deque slot), a
# coarse bucket is a 5-slot list, and each series carries dict/str
# overhead.  These deliberately round UP so the budget is conservative.
_RAW_POINT_BYTES = 120
_COARSE_POINT_BYTES = 220
_SERIES_BYTES = 900


class Series:
    """One metric stream: a raw ring at scrape resolution plus a
    coarse ring of downsampled buckets for the older window."""

    __slots__ = ("name", "base", "labels", "kind", "unit", "raw", "coarse")

    def __init__(self, name: str, kind: str, unit: str = "") -> None:
        self.name = name
        self.base, self.labels = split_labels(name)
        self.kind = kind            # "counter" | "gauge"
        self.unit = unit
        self.raw: deque = deque()           # (t, value)
        self.coarse: deque = deque()        # [bucket_t, sum, min, max, n]

    def matches(self, labels: dict[str, str] | None) -> bool:
        if not labels:
            return True
        return all(self.labels.get(k) == v for k, v in labels.items())

    def latest(self) -> tuple[float, float] | None:
        if self.raw:
            return self.raw[-1]
        if self.coarse:
            b = self.coarse[-1]
            return (b[0], b[1] / max(b[4], 1))
        return None

    def points(self, t_min: float | None = None) -> list[tuple[float, float]]:
        """Merged (t, value) points, oldest first; coarse buckets
        contribute their average."""
        out: list[tuple[float, float]] = []
        for b in self.coarse:
            if t_min is None or b[0] >= t_min:
                out.append((b[0], b[1] / max(b[4], 1)))
        for t, v in self.raw:
            if t_min is None or t >= t_min:
                out.append((t, v))
        return out

    def weighted_values(self, t_min: float | None = None) \
            -> list[tuple[float, int]]:
        """(value, weight) pairs for quantile queries — coarse buckets
        weigh as many observations as they folded in."""
        out: list[tuple[float, int]] = []
        for b in self.coarse:
            if t_min is None or b[0] >= t_min:
                out.append((b[1] / max(b[4], 1), int(b[4])))
        for t, v in self.raw:
            if t_min is None or t >= t_min:
                out.append((v, 1))
        return out

    def approx_bytes(self) -> int:
        return (_SERIES_BYTES + len(self.raw) * _RAW_POINT_BYTES
                + len(self.coarse) * _COARSE_POINT_BYTES)


def _env_float(environ, key: str, default: float) -> float:
    try:
        return float(environ.get(key, default))
    except (TypeError, ValueError):
        return default


class TSDB:
    """Bounded in-memory time-series database.

    Parameters
    ----------
    retention_s: points older than this are dropped entirely.
    resolution_s: raw ring bucket width — two samples landing in the
        same bucket keep only the latest (last-write-wins), so a
        faster-than-cadence recorder cannot blow the budget.
    downsample_after_s: raw points older than this are folded into
        coarse buckets of ``downsample_resolution_s`` (avg/min/max/n).
    byte_budget: overall cap on the store's approximate footprint;
        enforced after every scrape by trimming oldest points first.
    """

    def __init__(self, *, retention_s: float = 600.0,
                 resolution_s: float = 1.0,
                 downsample_after_s: float = 120.0,
                 downsample_resolution_s: float = 10.0,
                 byte_budget: int = 2 * 1024 * 1024,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if resolution_s <= 0 or downsample_resolution_s <= 0:
            raise ValueError("resolution must be > 0")
        self.retention_s = float(retention_s)
        self.resolution_s = float(resolution_s)
        self.downsample_after_s = float(downsample_after_s)
        self.downsample_resolution_s = float(downsample_resolution_s)
        self.byte_budget = int(byte_budget)
        self._clock = clock
        self._series: dict[str, Series] = {}
        self._lock = threading.Lock()
        self.dropped_points = 0     # budget-trim casualties, observable

    @classmethod
    def from_env(cls, environ=None, **overrides) -> "TSDB":
        """Knobs: ``TPUDIST_TSDB_{RETENTION_S,RESOLUTION_S,
        DOWNSAMPLE_AFTER_S,DOWNSAMPLE_RESOLUTION_S,BYTE_BUDGET}``."""
        env = os.environ if environ is None else environ
        kw: dict[str, Any] = dict(
            retention_s=_env_float(env, "TPUDIST_TSDB_RETENTION_S", 600.0),
            resolution_s=_env_float(env, "TPUDIST_TSDB_RESOLUTION_S", 1.0),
            downsample_after_s=_env_float(
                env, "TPUDIST_TSDB_DOWNSAMPLE_AFTER_S", 120.0),
            downsample_resolution_s=_env_float(
                env, "TPUDIST_TSDB_DOWNSAMPLE_RESOLUTION_S", 10.0),
            byte_budget=int(_env_float(
                env, "TPUDIST_TSDB_BYTE_BUDGET", 2 * 1024 * 1024)),
        )
        kw.update(overrides)
        return cls(**kw)

    # ------------------------------------------------------------- write

    def record(self, name: str, value: float, t: float | None = None,
               kind: str = "gauge", unit: str = "") -> None:
        """Append one sample.  ``None`` is ignored (absent semantics);
        NaN is stored — predicates comparing against NaN are False, so
        a NaN sample reads as "present but undecidable"."""
        if value is None:
            return
        t = self._clock() if t is None else float(t)
        with self._lock:
            s = self._series.get(name)
            if s is None:
                s = self._series[name] = Series(name, kind, unit)
            bucket = math.floor(t / self.resolution_s) * self.resolution_s
            if s.raw and s.raw[-1][0] >= bucket:
                s.raw[-1] = (s.raw[-1][0], float(value))   # last-write-wins
            else:
                s.raw.append((bucket, float(value)))
            self._roll(s, t)

    def scrape(self, snapshot: dict, t: float | None = None) -> int:
        """Ingest one registry/merged snapshot doc; returns the number
        of points written.  Histograms expand into derived
        ``{base}/p50 p90 p99 mean count`` series (labels preserved)."""
        t = self._clock() if t is None else float(t)
        n = 0
        for name, c in (snapshot.get("counters") or {}).items():
            v = c.get("value")
            if v is not None and math.isfinite(v):
                self.record(name, v, t, kind="counter",
                            unit=c.get("unit", ""))
                n += 1
        for name, g in (snapshot.get("gauges") or {}).items():
            v = g.get("value")
            if v is not None and math.isfinite(v):
                self.record(name, v, t, kind="gauge", unit=g.get("unit", ""))
                n += 1
        for name, h in (snapshot.get("histograms") or {}).items():
            base, labels = split_labels(name)
            tail = "".join(f"~{k}={v}" for k, v in sorted(labels.items()))
            stats = summarize(h)
            for stat in ("p50", "p90", "p99", "mean"):
                v = stats.get(stat)
                if v is not None and math.isfinite(v):
                    self.record(f"{base}/{stat}{tail}", v, t,
                                unit=h.get("unit", ""))
                    n += 1
            self.record(f"{base}/count{tail}", float(stats.get("count", 0)),
                        t, unit="observations")
            n += 1
        self._enforce_budget()
        return n

    def _roll(self, s: Series, now: float) -> None:
        """Fold raw points past the downsample horizon into coarse
        buckets; expire coarse buckets past retention.  Lock held."""
        horizon = now - self.downsample_after_s
        res = self.downsample_resolution_s
        while s.raw and s.raw[0][0] < horizon:
            t, v = s.raw.popleft()
            bucket = math.floor(t / res) * res
            if s.coarse and s.coarse[-1][0] == bucket:
                b = s.coarse[-1]
                b[1] += v
                b[2] = min(b[2], v)
                b[3] = max(b[3], v)
                b[4] += 1
                # stored as running sum; points() divides by n
            else:
                s.coarse.append([bucket, v, v, v, 1])
        cutoff = now - self.retention_s
        while s.coarse and s.coarse[0][0] < cutoff:
            s.coarse.popleft()
            self.dropped_points += 1
        while s.raw and s.raw[0][0] < cutoff:
            s.raw.popleft()
            self.dropped_points += 1

    def _enforce_budget(self) -> None:
        """Trim oldest points (coarse first, then raw) proportionally
        across series until the approximate footprint fits the budget.
        If every survivor is at its 2-point floor and the shells still
        overflow (series cardinality blowup), whole series are evicted
        coldest-first — the budget is a hard cap, not a hope."""
        with self._lock:
            total = sum(s.approx_bytes() for s in self._series.values())
            if total <= self.byte_budget:
                return
            # shave the oldest end of every series by the same ratio
            # until we fit; 2 points minimum so rate()/delta() survive
            while total > self.byte_budget:
                shaved = 0
                for s in self._series.values():
                    n = len(s.raw) + len(s.coarse)
                    drop = max(1, n // 8) if n > 2 else 0
                    for _ in range(drop):
                        if s.coarse:
                            s.coarse.popleft()
                        elif len(s.raw) > 2:
                            s.raw.popleft()
                        else:
                            break
                        shaved += 1
                        self.dropped_points += 1
                if not shaved:
                    # every series is at its 2-point floor and the
                    # shells alone exceed the budget: the cap is hard,
                    # so evict whole series, coldest last-write first
                    for key in [k for k, s in self._series.items()
                                if not s.raw and not s.coarse]:
                        del self._series[key]
                    by_age = sorted(
                        self._series,
                        key=lambda k: (self._series[k].raw[-1][0]
                                       if self._series[k].raw else
                                       self._series[k].coarse[-1][0]
                                       if self._series[k].coarse
                                       else float("-inf")))
                    for key in by_age:
                        s = self._series.pop(key)
                        self.dropped_points += len(s.raw) + len(s.coarse)
                        total -= s.approx_bytes()
                        if total <= self.byte_budget:
                            break
                    break
                total = sum(s.approx_bytes() for s in self._series.values())

    # ------------------------------------------------------------- read

    def series_names(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def select(self, name: str, labels: dict[str, str] | None = None) \
            -> list[Series]:
        """Series whose base name matches ``name`` (a full labelled
        name also matches itself) and whose labels are a superset of
        ``labels``."""
        with self._lock:
            out = []
            for s in self._series.values():
                if (s.base == name or s.name == name) and s.matches(labels):
                    out.append(s)
            return out

    def _pooled(self, name, labels, window_s, at) -> list[tuple[float, float]]:
        at = self._clock() if at is None else at
        t_min = None if window_s is None else at - window_s
        pts: list[tuple[float, float]] = []
        for s in self.select(name, labels):
            pts.extend(s.points(t_min))
        pts.sort(key=lambda p: p[0])
        return pts

    def latest(self, name: str, labels: dict[str, str] | None = None,
               window_s: float | None = None,
               at: float | None = None) -> float | None:
        """Most recent sample across matching series; ``window_s``
        bounds staleness (None = any age)."""
        at = self._clock() if at is None else at
        best: tuple[float, float] | None = None
        for s in self.select(name, labels):
            p = s.latest()
            if p is None:
                continue
            if window_s is not None and p[0] < at - window_s:
                continue
            if best is None or p[0] > best[0]:
                best = p
        return None if best is None else best[1]

    def delta(self, name: str, window_s: float,
              labels: dict[str, str] | None = None,
              at: float | None = None) -> float | None:
        """last - first over the window, summed across matching
        series.  None until a series has two points in the window."""
        at = self._clock() if at is None else at
        total = None
        for s in self.select(name, labels):
            pts = s.points(at - window_s)
            if len(pts) < 2:
                continue
            total = (total or 0.0) + (pts[-1][1] - pts[0][1])
        return total

    def rate(self, name: str, window_s: float,
             labels: dict[str, str] | None = None,
             at: float | None = None) -> float | None:
        """Reset-aware per-second rate over the window, summed across
        matching series (counter semantics: a decrease is a restart,
        counted from zero)."""
        at = self._clock() if at is None else at
        total = None
        for s in self.select(name, labels):
            pts = s.points(at - window_s)
            if len(pts) < 2:
                continue
            inc = 0.0
            for (t0, v0), (t1, v1) in zip(pts, pts[1:]):
                inc += (v1 - v0) if v1 >= v0 else v1
            span = pts[-1][0] - pts[0][0]
            if span > 0:
                total = (total or 0.0) + inc / span
        return total

    def _over_time(self, name, window_s, labels, at, fold):
        pts = self._pooled(name, labels, window_s, at)
        if not pts:
            return None
        return fold([v for _, v in pts])

    def avg_over_time(self, name, window_s, labels=None, at=None):
        return self._over_time(name, window_s, labels, at,
                               lambda vs: sum(vs) / len(vs))

    def max_over_time(self, name, window_s, labels=None, at=None):
        return self._over_time(name, window_s, labels, at, max)

    def min_over_time(self, name, window_s, labels=None, at=None):
        return self._over_time(name, window_s, labels, at, min)

    def quantile_over_time(self, name: str, q: float, window_s: float,
                           labels=None, at=None) -> float | None:
        """Nearest-rank quantile over the window's samples; coarse
        buckets weigh as many observations as they folded in."""
        at = self._clock() if at is None else at
        t_min = at - window_s
        weighted: list[tuple[float, int]] = []
        for s in self.select(name, labels):
            weighted.extend(s.weighted_values(t_min))
        if not weighted:
            return None
        weighted.sort(key=lambda p: p[0])
        total = sum(w for _, w in weighted)
        rank = max(1, math.ceil(q * total))
        seen = 0
        for v, w in weighted:
            seen += w
            if seen >= rank:
                return v
        return weighted[-1][0]

    # ------------------------------------------------------------- meta

    def approx_bytes(self) -> int:
        with self._lock:
            return sum(s.approx_bytes() for s in self._series.values())

    def stats(self) -> dict:
        with self._lock:
            return {
                "series": len(self._series),
                "points": sum(len(s.raw) + len(s.coarse)
                              for s in self._series.values()),
                "approx_bytes": sum(s.approx_bytes()
                                    for s in self._series.values()),
                "byte_budget": self.byte_budget,
                "dropped_points": self.dropped_points,
                "retention_s": self.retention_s,
                "resolution_s": self.resolution_s,
            }

    def to_doc(self, match: str | None = None,
               window_s: float | None = None) -> dict:
        """JSON-friendly dump (the ``/tsdb`` endpoint body and the
        console snapshot's ``tsdb`` key): stats + per-series points."""
        at = self._clock()
        t_min = None if window_s is None else at - window_s
        doc: dict[str, Any] = {"schema": "tpudist.tsdb/1",
                               "stats": self.stats(), "series": {}}
        with self._lock:
            items = sorted(self._series.items())
        for name, s in items:
            if match is not None and match not in name:
                continue
            doc["series"][name] = {
                "kind": s.kind, "unit": s.unit, "labels": s.labels,
                "points": [[round(t, 3), v] for t, v in s.points(t_min)],
            }
        return doc


class FleetScraper:
    """Cadence driver: local registry + coordinator fleet view -> TSDB
    -> alert evaluation, one ``tick()`` at a time.

    Membership-aware: ranks are read from ``{ns}/replica/*``
    registrations and passed to ``collect(members=...)`` so a replica
    that left the fleet drops out of the merged view (and its pinned
    histogram window out of merged quantiles) immediately instead of
    lingering until ``max_age_s``.

    Derived series written per tick:

    * ``fleet/coord_up``             1.0 / 0.0 (collect round-trip ok)
    * ``fleet/replicas_publishing``  publishers seen this tick
    * ``fleet/max_publish_age_s``    staleness of the oldest publisher
    * ``fleet/kv_free_frac``         merged kv free/(free+used)
    * ``fleet/tier_headroom_frac``   1 - tier_bytes/tier_budget_bytes
    """

    def __init__(self, tsdb: TSDB, *, client=None, namespace: str = "fleet",
                 registry=None, alerts=None, interval_s: float = 1.0,
                 max_age_s: float | None = 10.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.tsdb = tsdb
        self.client = client
        self.namespace = namespace
        self.registry = registry
        self.alerts = alerts
        self.interval_s = float(interval_s)
        self.max_age_s = max_age_s
        self._clock = clock
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.ticks = 0

    # membership: ranks currently registered under {ns}/replica/*
    def members(self) -> set[int] | None:
        if self.client is None:
            return None
        prefix = f"{self.namespace}/replica/"
        ranks: set[int] = set()
        found = False
        for key in self.client.keys(prefix):
            found = True
            try:
                raw = self.client.get(key)
                if raw is None:
                    continue
                info = (json.loads(raw.decode())
                        if isinstance(raw, (bytes, bytearray)) else raw)
                ranks.add(int(info.get("rank")))
            except (TypeError, ValueError, AttributeError):
                continue
        return ranks if found else None

    def tick(self, now: float | None = None) -> dict:
        """One scrape.  Never raises on coordinator trouble — that is
        itself a signal (``fleet/coord_up`` -> 0)."""
        from .aggregate import collect, merge_snapshots

        now = self._clock() if now is None else now
        self.ticks += 1
        out: dict[str, Any] = {"t": now, "coord_up": None, "publishers": 0}
        if self.registry is not None:
            self.tsdb.scrape(self.registry.snapshot(), t=now)
        if self.client is not None:
            try:
                members = self.members()
                snaps = collect(self.client, f"{self.namespace}/metrics",
                                max_age_s=self.max_age_s, members=members)
                merged = merge_snapshots(snaps)
                self.tsdb.scrape(merged, t=now)
                self._derived(merged, snaps, now)
                out["coord_up"] = True
                out["publishers"] = len(snaps)
            except (ConnectionError, OSError, TimeoutError):
                out["coord_up"] = False
            self.tsdb.record("fleet/coord_up",
                             1.0 if out["coord_up"] else 0.0, t=now)
        if self.alerts is not None:
            out["transitions"] = self.alerts.evaluate(now)
        stats = self.tsdb.stats()
        out["series"] = stats["series"]
        out["approx_bytes"] = stats["approx_bytes"]
        return out

    def _derived(self, merged: dict, snaps: dict, now: float) -> None:
        gauges = merged.get("gauges") or {}

        def g(name):
            e = gauges.get(name)
            return None if e is None else e.get("value")

        self.tsdb.record("fleet/replicas_publishing", float(len(snaps)),
                         t=now)
        ages = [s.get("age_s") for s in snaps.values()
                if s.get("age_s") is not None]
        if ages:
            self.tsdb.record("fleet/max_publish_age_s", max(ages), t=now)
        free, used = g("serve/kv_blocks_free"), g("serve/kv_blocks_used")
        if free is not None and used is not None and free + used > 0:
            self.tsdb.record("fleet/kv_free_frac", free / (free + used),
                             t=now)
        tb, budget = g("serve/tier_bytes"), g("serve/tier_budget_bytes")
        if tb is not None and budget:
            self.tsdb.record("fleet/tier_headroom_frac",
                             max(0.0, 1.0 - tb / budget), t=now)

    # live-mode background loop (the sim calls tick() itself)
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="tpudist-scraper")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - scraping must not die
                pass
