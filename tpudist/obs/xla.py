"""XLA compile / memory / cost telemetry.

ROADMAP's north star ("fast as the hardware allows") is unverifiable
without three signals this module feeds into the obs registry:

* **Compile telemetry** — ``xla/compiles`` counter and
  ``xla/compile_seconds`` histogram.  Two feeds: explicit timing at the
  framework's own lower/compile sites
  (:meth:`tpudist.runtime.ici.IciCollectives._executable`), and — so a
  recompile STORM anywhere (a shape leak re-tracing every step) is
  visible without instrumenting every jit — a process-wide
  ``jax.monitoring`` duration listener on the backend-compile event
  (:func:`install_compile_telemetry`, installed by
  :func:`tpudist.runtime.cache.enable_compilation_cache`).  Every
  compile also lands in the flight-recorder ring.
* **Memory telemetry** — per-device ``memory_stats()`` HBM gauges
  (``xla/mem/bytes_in_use/d{i}``, ``.../peak_bytes_in_use/d{i}``),
  degrading to nothing on backends that report no stats (CPU).
* **Cost/MFU telemetry** — ``cost_analysis()``-derived FLOPs per
  compiled step feeding live ``xla/step_tflops`` and ``xla/mfu`` gauges
  against the chip's known bf16 peak.  ``bench.py`` and
  ``scripts/resnet_mfu_sweep.py`` read :func:`peak_tflops` / :func:`mfu`
  from here instead of keeping their own peak tables.

Everything degrades to a no-op without jax or without a backend — the
obs layer must stay importable everywhere.
"""

from __future__ import annotations

import threading
import time
from typing import Any

__all__ = [
    "PEAK_TFLOPS",
    "compile_watch",
    "cost_flops",
    "install_compile_telemetry",
    "mfu",
    "note_compile",
    "note_step",
    "peak_tflops",
    "update_memory_gauges",
]

# bf16 peak TFLOP/s per chip, by jax device_kind (moved here from
# bench.py so the live MFU gauge and the benches share one table)
PEAK_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,   # v5e
    "TPU v5e": 197.0,
    "TPU v5": 459.0,        # v5p
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,   # Trillium
    "TPU v6e": 918.0,
}

# memory_stats() keys worth exporting (allocator-dependent; TPU reports
# these, CPU reports nothing)
_MEM_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")

_install_lock = threading.Lock()
_installed = False


def _registry(registry: Any = None):
    if registry is not None:
        return registry
    from tpudist import obs

    return obs.registry


def peak_tflops(device: Any = None) -> float | None:
    """The chip's bf16 peak TFLOP/s, or None off-TPU / for unknown
    kinds."""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        return PEAK_TFLOPS.get(device.device_kind)
    except Exception:  # noqa: BLE001 - no backend
        return None


def mfu(tflops: float | None, device: Any = None) -> float | None:
    """Achieved / peak, or None when either side is unknown."""
    peak = peak_tflops(device)
    if peak is None or tflops is None:
        return None
    return round(tflops / peak, 4)


# -- compile telemetry -------------------------------------------------------

def note_compile(seconds: float, registry: Any = None,
                 source: str = "jit") -> None:
    """Record one compilation: count, duration histogram, and a
    flight-recorder event (the recompile-storm breadcrumb)."""
    reg = _registry(registry)
    reg.counter("xla/compiles", unit="compiles").inc()
    reg.histogram("xla/compile_seconds", unit="s").record(float(seconds))
    try:
        from tpudist import obs

        obs.recorder.record("xla_compile", seconds=round(float(seconds), 4),
                            source=source)
    except Exception:  # noqa: BLE001 - recorder is optional context
        pass


def install_compile_telemetry(registry: Any = None) -> bool:
    """Register a process-wide ``jax.monitoring`` listener that feeds
    every backend compile into :func:`note_compile`.  Idempotent; returns
    True when the listener is (already) installed, False when this jax
    has no monitoring hooks."""
    global _installed
    with _install_lock:
        if _installed:
            return True
        try:
            from jax._src import monitoring
        except Exception:  # noqa: BLE001 - jax absent or reshaped
            return False
        reg = _registry(registry)

        def _listener(event: str, duration: float, **_kw) -> None:
            # '/jax/core/compile/backend_compile_duration' on this jax;
            # match loosely so minor renames keep reporting
            if "backend_compile" in event:
                note_compile(duration, registry=reg, source="monitoring")

        try:
            monitoring.register_event_duration_secs_listener(_listener)
        except Exception:  # noqa: BLE001
            return False
        _installed = True
        return True


class compile_watch:
    """``with compile_watch("ici"):`` — explicit timing for the
    framework's own lower/compile sites.  Records under per-site names
    (``xla/compiles_{name}``, ``xla/compile_seconds_{name}``) so the
    process-wide monitoring listener's ``xla/compiles`` totals never
    double-count a compile that was also timed at its call site."""

    def __init__(self, name: str, registry: Any = None) -> None:
        self.name = name
        self._registry = _registry(registry)
        self.seconds = 0.0

    def __enter__(self) -> "compile_watch":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.seconds = time.perf_counter() - self._t0
        if exc[0] is None:
            reg = self._registry
            reg.counter(f"xla/compiles_{self.name}", unit="compiles").inc()
            reg.histogram(f"xla/compile_seconds_{self.name}",
                          unit="s").record(self.seconds)


# -- memory telemetry --------------------------------------------------------

def update_memory_gauges(registry: Any = None) -> dict[str, float]:
    """Refresh per-device HBM gauges from ``device.memory_stats()``;
    returns the values set (empty off-TPU, where the allocator reports
    nothing).  Cheap host-side calls — safe once per epoch/interval, not
    meant for the per-step path."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 - no backend
        return {}
    reg = _registry(registry)
    out: dict[str, float] = {}
    for i, d in enumerate(devices):
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 - backend without stats
            stats = None
        if not stats:
            continue
        for key in _MEM_KEYS:
            if key in stats:
                name = f"xla/mem/{key}/d{i}"
                reg.gauge(name, unit="bytes").set(float(stats[key]))
                out[name] = float(stats[key])
    return out


# -- cost / MFU telemetry ----------------------------------------------------

def cost_flops(stage: Any) -> float | None:
    """Total FLOPs from a ``Lowered``/``Compiled`` stage's
    ``cost_analysis()`` (handles both the flat-dict and the
    list-of-dicts shapes jax has shipped), or None when unavailable."""
    try:
        cost = stage.cost_analysis()
    except Exception:  # noqa: BLE001 - analysis unsupported here
        return None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not isinstance(cost, dict):
        return None
    flops = cost.get("flops")
    if flops is None or flops <= 0:
        return None
    return float(flops)


def note_step(seconds: float, flops: float | None,
              registry: Any = None) -> float | None:
    """Feed one measured step (wall seconds + program FLOPs) into the
    live gauges: ``xla/step_tflops`` always, ``xla/mfu`` when the chip's
    peak is known.  Returns the achieved TFLOP/s."""
    if not flops or seconds <= 0:
        return None
    reg = _registry(registry)
    tflops = flops / seconds / 1e12
    reg.gauge("xla/step_tflops", unit="TFLOP/s").set(tflops)
    frac = mfu(tflops)
    if frac is not None:
        reg.gauge("xla/mfu").set(frac)
    return tflops
