"""Numerical ops: losses, metrics; pallas kernels live in ``ops.kernels``."""

from tpudist.ops.losses import accuracy, cross_entropy, mse_loss, nll_loss

__all__ = ["accuracy", "cross_entropy", "mse_loss", "nll_loss"]
