"""Numerical ops: losses/metrics plus the pallas TPU kernels
(:mod:`tpudist.ops.flash_attention`)."""

from tpudist.ops.flash_attention import flash_attention, flash_attention_fn
from tpudist.ops.flash_decode import (
    flash_decode,
    flash_decode_q8,
    quantize_kv,
    sp_flash_decode,
)
from tpudist.ops.losses import (
    accuracy,
    cross_entropy,
    cross_entropy_per_token,
    mse_loss,
    nll_loss,
)

__all__ = [
    "accuracy",
    "cross_entropy",
    "cross_entropy_per_token",
    "flash_attention",
    "flash_attention_fn",
    "flash_decode",
    "flash_decode_q8",
    "quantize_kv",
    "sp_flash_decode",
    "mse_loss",
    "nll_loss",
]
