"""BatchNorm with a closed-form custom VJP — the same backward fix as
:mod:`tpudist.ops.group_norm`, for the reference's own normalization.

The reference's ResNet50 uses BatchNorm (torchvision Bottleneck,
`rpc/model_parallel_ResNet50.py:43-139`).  Measured on the v5e
(`scripts/resnet_mfu_sweep.py`): flax ``nn.BatchNorm`` trains ResNet50 at
11.2 ms/step vs 6.75 no-norm — like GroupNorm, nearly all of the cost is
autodiff's backward of the stats computation.  The closed-form gradient

    x̂  = (x - μ_c) · rstd_c          (per-channel stats over B, H, W)
    g   = dy · γ
    dx  = rstd · (g - mean_c(g) - x̂ · mean_c(g · x̂))
    dγ  = Σ dy · x̂                    dβ = Σ dy

is two per-channel reductions + elementwise — XLA-fusible passes.

``BatchNorm`` here is parameter- AND collection-compatible with
``flax.linen.BatchNorm`` (params ``scale``/``bias``, batch_stats
``mean``/``var``, same auto-name prefix), so models and checkpoints swap
freely.  Running statistics update with the standard momentum rule and are
treated as non-differentiable exports (stop-gradient semantics), exactly
like flax's.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import linen as nn


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def batch_norm_train(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray,
                     eps: float = 1e-5):
    """Training-mode batch norm over NHWC (stats per channel, f32).

    Returns ``(y, mean, var)``; ``mean``/``var`` are NON-differentiable
    exports for the running-average update (their cotangents are ignored).
    """
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(0, 1, 2))
    var = jnp.mean(jnp.square(x32), axis=(0, 1, 2)) - jnp.square(mean)
    rstd = jax.lax.rsqrt(var + eps)
    y = ((x32 - mean) * rstd * scale.astype(jnp.float32)
         + bias.astype(jnp.float32)).astype(x.dtype)
    return y, mean, var


def _bn_fwd(x, scale, bias, eps):
    y, mean, var = batch_norm_train(x, scale, bias, eps)
    rstd = jax.lax.rsqrt(var + eps)
    return (y, mean, var), (x, scale, mean, rstd)


def _bn_bwd(eps, res, cts):
    dy, _dmean, _dvar = cts  # stats are non-differentiable exports
    x, scale, mean, rstd = res
    n = x.shape[0] * x.shape[1] * x.shape[2]
    x32 = x.astype(jnp.float32)
    dy32 = dy.astype(jnp.float32)
    xhat = (x32 - mean) * rstd
    g = dy32 * scale.astype(jnp.float32)
    m1 = jnp.sum(g, axis=(0, 1, 2)) / n
    m2 = jnp.sum(g * xhat, axis=(0, 1, 2)) / n
    dx = (rstd * (g - m1 - xhat * m2)).astype(x.dtype)
    dscale = jnp.sum(dy32 * xhat, axis=(0, 1, 2)).astype(scale.dtype)
    dbias = jnp.sum(dy32, axis=(0, 1, 2)).astype(scale.dtype)
    return dx, dscale, dbias


batch_norm_train.defvjp(_bn_fwd, _bn_bwd)


class BatchNorm(nn.Module):
    """``flax.linen.BatchNorm`` twin backed by :func:`batch_norm_train`
    (same params, same ``batch_stats`` collection, same auto-name)."""

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: jnp.dtype | None = None
    param_dtype: jnp.dtype = jnp.float32
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,),
                           self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, (c,),
                          self.param_dtype)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((c,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((c,), jnp.float32))
        if self.use_running_average:
            rstd = jax.lax.rsqrt(ra_var.value + self.epsilon)
            y = ((x.astype(jnp.float32) - ra_mean.value) * rstd
                 * scale.astype(jnp.float32)
                 + bias.astype(jnp.float32)).astype(x.dtype)
        elif self.axis_name is not None and not self.is_initializing():
            # cross-replica statistics (the DDP SyncBatchNorm story) stay
            # on plain autodiff: the custom VJP treats exported stats as
            # constants, which would silently freeze the statistics'
            # gradient contribution through the pmean.  During init the
            # axis is unbound (params are created OUTSIDE pmap/shard_map,
            # the flax convention), so init falls through to the local
            # branch below — exactly nn.BatchNorm's behavior.
            x32 = x.astype(jnp.float32)
            mean = jax.lax.pmean(
                jnp.mean(x32, axis=(0, 1, 2)), self.axis_name)
            var = jax.lax.pmean(
                jnp.mean(jnp.square(x32), axis=(0, 1, 2)),
                self.axis_name) - jnp.square(mean)
            rstd = jax.lax.rsqrt(var + self.epsilon)
            y = ((x32 - mean) * rstd * scale.astype(jnp.float32)
                 + bias.astype(jnp.float32)).astype(x.dtype)
            # this branch already requires not is_initializing()
            m = self.momentum
            ra_mean.value = (m * ra_mean.value
                             + (1 - m) * jax.lax.stop_gradient(mean))
            ra_var.value = (m * ra_var.value
                            + (1 - m) * jax.lax.stop_gradient(var))
        else:
            y, mean, var = batch_norm_train(x, scale, bias, self.epsilon)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * jax.lax.stop_gradient(mean)
                ra_var.value = m * ra_var.value + (1 - m) * jax.lax.stop_gradient(var)
        return y.astype(self.dtype) if self.dtype is not None else y
