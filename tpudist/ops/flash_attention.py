"""Pallas TPU flash-attention kernel.

The hot op of the transformer workload, written as a fused Pallas kernel so
the [S, S] score matrix never exists in HBM: inputs are fused to a
[B·H, S, D] layout and per (batch·head, q-block) program, K/V stream
through VMEM in ``block_k`` tiles with the online-softmax recurrence, and
only the [S, D] output (plus the [S] log-sum-exp row statistics for the
backward pass) is written back.  This is the
single-chip counterpart of the cross-chip recurrence in
:func:`tpudist.parallel.ring_attention_fn` — same math, the ring rotates
blocks over ICI while this kernel rotates them through VMEM.

Matmuls hit the MXU with float32 accumulation (``preferred_element_type``);
statistics (row max / row sum) stay in 2-D [block_q, 1] layout to respect
the (8, 128) sublane×lane tiling.  Causal grid steps strictly above the
diagonal are skipped under ``pl.when`` — their K/V tiles are fetched by the
grid pipeline but no FLOPs run.

Training: :func:`flash_attention` carries a ``custom_vjp`` — the forward is
the fused kernel; the backward is two more Pallas kernels (a dQ pass and a
dK/dV pass) that recompute P block-by-block from the saved (q, k, v, lse)
with the standard dS = P ∘ (dO·Vᵀ − rowsum(dO ∘ O)) identities, so the
[S, S] score matrix never exists in HBM in either direction and training
memory stays linear in sequence length.  The per-row
Δ = rowsum(dO ∘ O) is an O(S·D) elementwise reduction left to XLA.

On CPU (tests, CI) the kernel runs in interpreter mode automatically;
numerics match :func:`tpudist.models.sdpa` to float tolerance either way.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -1e30
# Sentinel distinguishing "caller didn't pass window" (factory default
# applies) from an explicit window=None (full causal attention) — so a
# model config's attention_window always overrides the factory's.
_UNSET = object()


def _can_prune(window, causal, q_offset, k_offset) -> bool:
    """Whether the sliding-window band grids may be pruned: static zero
    offsets only (the ring path has traced offsets).  ONE definition so
    forward and backward can never prune differently."""
    return (window is not None and causal
            and isinstance(q_offset, int) and q_offset == 0
            and isinstance(k_offset, int) and k_offset == 0)


def _block_live(qi, kj, block_q: int, block_k: int, causal: bool, q0, k0,
                window: int | None = None):
    """Whether (q-block ``qi``, k-block ``kj``) can contribute: intersects
    the causal lower triangle AND (for sliding-window attention) the band
    ``q_pos - k_pos < window``.  ``True`` when not causal.  ``q0``/``k0``
    are global position offsets (ring attention rotates K/V blocks, so a
    block's global span is offset + local index).  Shared by the forward
    and both backward kernels so a masking change cannot desynchronize
    them."""
    if not causal:
        return True
    live = q0 + (qi + 1) * block_q > k0 + kj * block_k
    if window is not None:
        # k block's last position must reach past the window's left edge
        # of the q block's first position
        live = jnp.logical_and(
            live,
            k0 + (kj + 1) * block_k > q0 + qi * block_q - (window - 1))
    return live


def _causal_mask(s, qi, kj, block_q: int, block_k: int, q0, k0,
                 window: int | None = None):
    """Mask scores above the (global) diagonal — and, with ``window``,
    older than the sliding window — to -inf within a tile."""
    q_pos = q0 + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = k0 + kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    keep = q_pos >= k_pos
    if window is not None:
        keep = jnp.logical_and(keep, q_pos - k_pos < window)
    return jnp.where(keep, s, -jnp.inf)


def _band_k(window: int, block_q: int, block_k: int, num_kb: int):
    """K-block span and per-q-block start for a pruned sliding-window
    grid: q block ``qi`` only visits k blocks overlapping its band
    ``[qi·bq − (window−1), (qi+1)·bq − 1]`` (width bq + window − 1)."""
    span = min(num_kb, (block_q + window - 2) // block_k + 2)

    def start(qi):
        return jnp.clip(
            (qi * block_q - (window - 1)) // block_k, 0, num_kb - span)

    return span, start


def _band_q(window: int, block_q: int, block_k: int, num_qb: int):
    """Q-block span and per-k-block start for the pruned dK/dV grid."""
    span = min(num_qb, (block_k + window - 2) // block_q + 2)

    def start(kj):
        return jnp.clip((kj * block_k) // block_q, 0, num_qb - span)

    return span, start


def _fuse(x):
    """[B, S, H, D] → [B·H, S, D]: every block's minor dims become
    (seq_block, D), the (8, 128)-tileable shape Mosaic requires."""
    b, s, h, d = x.shape
    return x.swapaxes(1, 2).reshape(b * h, s, d)


def _unfuse(x, b: int, h: int):
    """[B·H, S, D] → [B, S, H, D] (inverse of :func:`_fuse`)."""
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).swapaxes(1, 2)


def _flash_kernel(off_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                  m_scr, l_scr, acc_scr,
                  *, scale: float, causal: bool, block_q: int, block_k: int,
                  num_kb: int, window: int | None = None,
                  prune: bool = False, total_kb: int | None = None):
    """One (batch·head, q-block, k-block) grid step on the fused
    [B·H, S, D] layout.

    The K grid dimension is innermost and sequential on TPU, so the VMEM
    scratch accumulators (running max / sum / weighted values) carry the
    online-softmax state across K steps while only one [block_k, D] K/V
    tile is resident at a time.
    """
    qi, j = pl.program_id(1), pl.program_id(2)
    if prune:  # pruned windowed grid: j indexes the band, not all of K
        kj = _band_k(window, block_q, block_k, total_kb)[1](qi) + j
    else:
        kj = j
    q0, k0 = off_ref[0, 0], off_ref[0, 1]

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: q-blocks strictly above the diagonal contribute nothing;
    # with a sliding window, blocks left of the band are dead too.
    @pl.when(_block_live(qi, kj, block_q, block_k, causal, q0, k0, window))
    def _compute():
        # Matmuls run in the input dtype (bf16 hits the MXU at full rate)
        # with float32 accumulation; only the softmax math is f32.
        q, kb, vb = q_ref[0], k_ref[0], v_ref[0]               # [bq|bk, D]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, q0, k0, window)
        m = m_scr[:]                                           # [bq, 1]
        blk_max = jnp.max(s, axis=-1, keepdims=True)
        new_m = jnp.maximum(m, jnp.maximum(blk_max, _NEG_BIG))
        p = jnp.exp(s - new_m)                                 # masked → 0
        corr = jnp.exp(m - new_m)
        m_scr[:] = new_m
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jnp.dot(
            p.astype(vb.dtype), vb, preferred_element_type=jnp.float32)

    @pl.when(j == num_kb - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = (m_scr[:] + jnp.log(l)).T  # [1, bq]


def _offsets_arg(q_offset, k_offset):
    """(1, 2) int32 SMEM operand carrying the global position offsets;
    zeros in the plain (non-ring) path."""
    return jnp.stack(
        [jnp.asarray(q_offset, jnp.int32), jnp.asarray(k_offset, jnp.int32)]
    ).reshape(1, 2)


def _smem_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _flash_forward(q, k, v, causal, block_q, block_k, interpret,
                   q_offset=0, k_offset=0, window=None):
    """[B, S, H, D] in; internally runs on a fused [B·H, S, D] layout so
    every block's minor two dims are (seq_block, D) — the (8, 128)-tileable
    shape Mosaic requires (an [.., S, H, ..] block with a size-1 H slice is
    not lowerable on real TPUs).  ``q_offset``/``k_offset`` shift the causal
    mask to global positions (ring attention)."""
    b, s, h, d = q.shape
    sk, h_kv = k.shape[1], k.shape[2]
    group = h // h_kv
    num_kb = sk // block_k
    q3, k3, v3 = (_fuse(x) for x in (q, k, v))

    def kv_head(g):
        # Grouped-query attention: query head h attends KV head h // group
        # — resolved in the index map, so grouped K/V are never expanded.
        return (g // h) * h_kv + (g % h) // group

    # Sliding window on the plain (non-ring) path: prune the K grid to the
    # band so iterations AND K/V tile traffic scale with S·window, not S².
    prune = _can_prune(window, causal, q_offset, k_offset)
    if prune:
        span_k, k_start = _band_k(window, block_q, block_k, num_kb)
        kv_idx = lambda g, i, j: (kv_head(g), k_start(i) + j, 0)
    else:
        span_k = num_kb
        kv_idx = lambda g, i, j: (kv_head(g), j, 0)

    kernel = functools.partial(
        _flash_kernel, scale=d ** -0.5, causal=causal,
        block_q=block_q, block_k=block_k, num_kb=span_k, window=window,
        prune=prune, total_kb=num_kb)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, s // block_q, span_k),
        in_specs=[
            _smem_spec(),
            pl.BlockSpec((1, block_q, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_idx),
            pl.BlockSpec((1, block_k, d), kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda g, i, j: (g, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, s), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(_offsets_arg(q_offset, k_offset), q3, k3, v3)
    return _unfuse(out, b, h), lse.reshape(b, h, s)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, block_q, block_k, interpret, window):
    out, _ = _flash_forward(q, k, v, causal, block_q, block_k, interpret,
                            window=window)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret, window):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret,
                              window=window)
    return out, (q, k, v, out, lse)


def _bwd_block(q, kb, vb, do, lse_col, delta_col, qi, kj, q0, k0, *,
               scale, causal, block_q, block_k, window=None):
    """Shared per-(q-block, k-block) backward math: recompute P from the
    saved log-sum-exp, then ds = P ∘ (dO·Vᵀ − Δ).  Returns (p, ds) in
    float32; callers contract them onto the MXU in the input dtype."""
    s = jax.lax.dot_general(
        q, kb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if causal:
        s = _causal_mask(s, qi, kj, block_q, block_k, q0, k0, window)
    p = jnp.exp(s - lse_col)                               # masked → 0
    dp = jax.lax.dot_general(
        do, vb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta_col)
    return p, ds


def _flash_bwd_dq_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                         delta_ref, dq_ref, dq_scr, *, scale: float,
                         causal: bool, block_q: int, block_k: int,
                         num_kb: int, window: int | None = None,
                         prune: bool = False, total_kb: int | None = None):
    """Grid (B·H, q-block, k-block); K innermost/sequential accumulates
    dQ = scale · Σ_k dS·K in a VMEM scratch."""
    qi, j = pl.program_id(1), pl.program_id(2)
    if prune:
        kj = _band_k(window, block_q, block_k, total_kb)[1](qi) + j
    else:
        kj = j
    q0, k0 = off_ref[0, 0], off_ref[0, 1]

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(_block_live(qi, kj, block_q, block_k, causal, q0, k0, window))
    def _compute():
        q, kb, vb, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        _, ds = _bwd_block(
            q, kb, vb, do, lse_ref[0].T, delta_ref[0].T, qi, kj, q0, k0,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            window=window)
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(j == num_kb - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dq_fused_kernel(off_ref, q_ref, k_ref, v_ref, do_ref,
                               lse_ref, o_ref, dq_ref, delta_out_ref,
                               dq_scr, delta_scr, *, scale: float,
                               causal: bool, block_q: int, block_k: int,
                               num_kb: int, window: int | None = None,
                               prune: bool = False,
                               total_kb: int | None = None):
    """The dQ pass with the Δ = rowsum(dO ∘ O) prepass FUSED in: at the
    first K step of each q-block, Δ is computed in VMEM from the already-
    resident dO and O tiles (one extra [bq, D] read, amortized over the
    whole K loop) and emitted as a side output for the dK/dV pass — the
    separate XLA elementwise pass over O/dO and its HBM round-trip
    disappear (round-1 verdict #3)."""
    qi, j = pl.program_id(1), pl.program_id(2)
    if prune:
        kj = _band_k(window, block_q, block_k, total_kb)[1](qi) + j
    else:
        kj = j
    q0, k0 = off_ref[0, 0], off_ref[0, 1]

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)
        delta_scr[:] = jnp.sum(
            do_ref[0].astype(jnp.float32) * o_ref[0].astype(jnp.float32),
            axis=-1, keepdims=True)

    @pl.when(_block_live(qi, kj, block_q, block_k, causal, q0, k0, window))
    def _compute():
        q, kb, vb, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        _, ds = _bwd_block(
            q, kb, vb, do, lse_ref[0].T, delta_scr[:], qi, kj, q0, k0,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            window=window)
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(j == num_kb - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)
        delta_out_ref[0] = delta_scr[:].T  # [1, bq] row layout, like lse


def _flash_bwd_dkv_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                          delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                          scale: float, causal: bool, block_q: int,
                          block_k: int, num_q_iters: int, group: int,
                          window: int | None = None, prune: bool = False,
                          total_qb: int | None = None):
    """Grid (B·Hkv, k-block, q-block × group-member); the innermost
    sequential dimension walks every (q-block, query-head-of-the-group)
    pair, accumulating dK = scale · Σ dSᵀ·Q and dV = Σ Pᵀ·dO in VMEM —
    under GQA each KV head's grads sum over its whole query-head group
    here, with no cross-program races and no K/V expansion."""
    kj, t = pl.program_id(1), pl.program_id(2)
    qi = t // group
    if prune:
        qi = _band_q(window, block_q, block_k, total_qb)[1](kj) + qi
    q0, k0 = off_ref[0, 0], off_ref[0, 1]

    @pl.when(t == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(_block_live(qi, kj, block_q, block_k, causal, q0, k0, window))
    def _compute():
        q, kb, vb, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        p, ds = _bwd_block(
            q, kb, vb, do, lse_ref[0].T, delta_ref[0].T, qi, kj, q0, k0,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            window=window)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(t == num_q_iters - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def flash_block_grads(q, k, v, dout, lse, delta, *, causal, block_q,
                      block_k, interpret, q_offset=0, k_offset=0,
                      window=None, out=None):
    """(dQ, dK, dV) of one attention block given the FINAL softmax
    statistics ``lse``/``delta`` (shapes [B, H, S]).

    With ``delta=None`` (the plain, non-ring path) Δ is not precomputed:
    the dQ kernel derives it from ``out``/``dout`` tiles in VMEM and emits
    it for the dK/dV pass — no separate elementwise pass, no Δ HBM write
    from XLA.  The ring backward passes an explicit Δ because its identity
    must come from the FINAL output across all blocks
    (`parallel/ring_attention.py`).

    The flash backward identities hold per K/V block when P is computed
    against the final log-sum-exp, which is what makes the ring backward a
    sum of per-block kernel calls; the plain backward below is the
    single-block case with zero offsets.
    """
    b, s, h, d = q.shape
    sk, h_kv = k.shape[1], k.shape[2]
    group = h // h_kv
    scale = d ** -0.5
    num_qb, num_kb = s // block_q, sk // block_k
    q3, k3, v3, do3 = (_fuse(x) for x in (q, k, v, dout))
    lse3 = lse.reshape(b * h, 1, s)
    fuse_delta = delta is None
    if fuse_delta:
        if out is None:
            raise ValueError("flash_block_grads needs `out` when delta=None")
        o3 = _fuse(out)
    else:
        delta3 = delta.reshape(b * h, 1, s)

    def kv_head(g):
        return (g // h) * h_kv + (g % h) // group

    def q_head(g, t):
        # dK/dV grid runs per KV head; member t % group selects which of
        # its query heads this inner step contracts.
        return (g // h_kv) * h + (g % h_kv) * group + t % group

    prune = _can_prune(window, causal, q_offset, k_offset)
    if prune:
        span_k, k_start = _band_k(window, block_q, block_k, num_kb)
        span_q, q_start = _band_q(window, block_q, block_k, num_qb)
    else:
        span_k, k_start = num_kb, (lambda i: 0)
        span_q, q_start = num_qb, (lambda j: 0)

    def qi_of(j, t):
        return q_start(j) + t // group

    q_spec = pl.BlockSpec((1, block_q, d), lambda g, i, j: (g, i, 0))
    row_spec = pl.BlockSpec((1, 1, block_q), lambda g, i, j: (g, 0, i))
    kv_spec = pl.BlockSpec((1, block_k, d),
                           lambda g, i, j: (kv_head(g), k_start(i) + j, 0))
    # dK/dV pass walks the transposed grid: KV-head programs, k-block
    # major, (q-block × group-member) minor.
    q_spec_t = pl.BlockSpec((1, block_q, d),
                            lambda g, j, t: (q_head(g, t), qi_of(j, t), 0))
    row_spec_t = pl.BlockSpec((1, 1, block_q),
                              lambda g, j, t: (q_head(g, t), 0, qi_of(j, t)))
    kv_spec_t = pl.BlockSpec((1, block_k, d), lambda g, j, t: (g, j, 0))
    semantics = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))

    offs = _offsets_arg(q_offset, k_offset)
    if fuse_delta:
        dq, delta3 = pl.pallas_call(
            functools.partial(
                _flash_bwd_dq_fused_kernel, scale=scale, causal=causal,
                block_q=block_q, block_k=block_k, num_kb=span_k,
                window=window, prune=prune, total_kb=num_kb),
            grid=(b * h, num_qb, span_k),
            in_specs=[_smem_spec(), q_spec, kv_spec, kv_spec, q_spec,
                      row_spec, q_spec],
            out_specs=[q_spec, row_spec],
            out_shape=[
                jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
                jax.ShapeDtypeStruct((b * h, 1, s), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, d), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
            ],
            compiler_params=semantics,
            interpret=interpret,
        )(offs, q3, k3, v3, do3, lse3, o3)
    else:
        dq = pl.pallas_call(
            functools.partial(
                _flash_bwd_dq_kernel, scale=scale, causal=causal,
                block_q=block_q, block_k=block_k, num_kb=span_k,
                window=window, prune=prune, total_kb=num_kb),
            grid=(b * h, num_qb, span_k),
            in_specs=[_smem_spec(), q_spec, kv_spec, kv_spec, q_spec,
                      row_spec, row_spec],
            out_specs=[q_spec],
            out_shape=[jax.ShapeDtypeStruct((b * h, s, d), q.dtype)],
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            compiler_params=semantics,
            interpret=interpret,
        )(offs, q3, k3, v3, do3, lse3, delta3)[0]

    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
            num_q_iters=span_q * group, group=group, window=window,
            prune=prune, total_qb=num_qb),
        grid=(b * h_kv, num_kb, span_q * group),
        in_specs=[_smem_spec(), q_spec_t, kv_spec_t, kv_spec_t, q_spec_t,
                  row_spec_t, row_spec_t],
        out_specs=[kv_spec_t, kv_spec_t],
        out_shape=[
            jax.ShapeDtypeStruct((b * h_kv, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h_kv, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=semantics,
        interpret=interpret,
    )(offs, q3, k3, v3, do3, lse3, delta3)

    return _unfuse(dq, b, h), _unfuse(dk, b, h_kv), _unfuse(dv, b, h_kv)


def flash_delta(out, dout):
    """Δ = rowsum(dO ∘ O) per query position, as [B, H, S] float32."""
    return jnp.sum(
        out.astype(jnp.float32) * dout.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1)


def _flash_bwd(causal, block_q, block_k, interpret, window, res, dout):
    q, k, v, out, lse = res
    return flash_block_grads(
        q, k, v, dout, lse, None, out=out,
        causal=causal, block_q=block_q, block_k=block_k, interpret=interpret,
        window=window)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _auto_block(s: int, cap: int = 1024) -> int:
    """Largest power-of-two ≤ ``cap`` dividing ``s`` (≥ 8 when possible).

    Measured on real TPU at S=2048/8192: 1024-sized blocks run ~1.6× the
    throughput of 128-sized ones (fewer grid steps, larger MXU matmuls),
    so the default block is as big as divisibility allows.
    """
    b = 1
    while b < cap and s % (b * 2) == 0:
        b *= 2
    return b


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    window: int | None = None,
) -> jnp.ndarray:
    """Fused attention on [B, S, H, D] arrays; drop-in for
    :func:`tpudist.models.sdpa` (same ``AttentionFn`` contract),
    differentiable via ``custom_vjp``.  Block sizes default to the largest
    power-of-two divisor of S up to 1024 (the measured sweet spot).

    ``window`` enables sliding-window attention (Mistral-style): each
    query attends only the last ``window`` positions (itself included).
    Requires ``causal=True``; blocks wholly left of the band are skipped,
    so FLOPs scale with S·window instead of S².  K/V may carry fewer
    (grouped) heads — GQA."""
    s = q.shape[1]
    if q.shape[2] % k.shape[2]:
        raise ValueError(
            f"num_heads {q.shape[2]} must be a multiple of kv heads "
            f"{k.shape[2]} (GQA)")
    block_q = _auto_block(s) if block_q is None else min(block_q, s)
    block_k = _auto_block(s) if block_k is None else min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(f"block sizes ({block_q}, {block_k}) must divide "
                         f"seq_len {s}")
    if window is not None and (not causal or window < 1):
        raise ValueError(
            f"window={window} requires causal=True and window >= 1")
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    return _flash(q, k, v, causal, block_q, block_k, interpret, window)


def flash_attention_fn(
    block_q: int | None = None, block_k: int | None = None,
    interpret: bool | None = None, window: int | None = None,
):
    """``AttentionFn`` factory for :class:`tpudist.models.TransformerLM`:
    ``TransformerLM(cfg, attention_fn=flash_attention_fn())``."""
    factory_window = window

    def attend(q, k, v, *, causal: bool = True, window=_UNSET):
        eff = factory_window if window is _UNSET else window
        return flash_attention(q, k, v, causal=causal, block_q=block_q,
                               block_k=block_k, interpret=interpret,
                               window=eff)

    # Discoverable by TransformerLM: a model whose cfg.attention_window
    # disagrees with this must fail loudly instead of silently training
    # full-attention against a windowed decode cache (or vice versa).
    attend.factory_window = factory_window
    return attend
