"""Pallas flash-decode kernel: single-token attention against a KV cache.

The decode hot op.  Training flash attention (`tpudist/ops/flash_attention.py`)
tiles queries in ``block_q`` rows; at decode time there is exactly ONE query
per head, so that layout wastes the (8, 128) tile on padding.  The decode
trick is to put the GQA *query-head group* on the sublane axis instead: with
``g = H / H_kv`` query heads per KV head, the per-(batch, kv-head) work is a
``[g, D] × [S, D]ᵀ`` matmul — queries of the same group share the K/V
stream, so the cache is read ONCE per kv head (the memory-bound quantity at
long context) while the MXU sees a real tile.

Grid: ``(B·H_kv, nb)``, K sequential innermost with the online-softmax
recurrence in VMEM scratch — the same structure as the training kernel's
K loop.  Without ``window``, ``nb = S/block_k`` and blocks past
``cache_len`` skip their FLOPs under ``pl.when`` (the fetch still
streams, bounded by the allocated cache).  With ``window`` the grid is
TRIMMED: a scalar-prefetch ``start_block`` points the block index maps
at the ~``window/block_k`` blocks intersecting the window span, so a
windowed decode streams ~``window`` positions per step instead of the
whole cache — at the bandwidth-bound decode op that is a ~S/window
speedup.  Positions beyond the cache index, or older than the window,
mask to -inf as before.

Measured guideline (BASELINE.md round 3): ``head_dim < 128`` underfills
the 128-lane tile width of the K/V blocks (measured: half DMA
bandwidth).  With EVEN ``h_kv`` both the bf16 AND int8 paths recover
full width by HEAD PAIRING (see ``_flash_decode_impl``; since round 4
the int8 per-(token, head) scales ride the paired tile as one row per
pair member, applied half-wise in the kernel): bf16 kernel-level parity
with d=128 (636 vs 639 GB/s measured), model-level within ~1.37×
(residual per-step packing overhead).  Odd-``h_kv`` narrow-head models
stay unpaired at ~half DMA width — prefer even ``h_kv`` or
head_dim-128 configurations where the model design allows.

Reference scope note: the reference suite is training-only (SURVEY.md §2 —
no inference path anywhere); this kernel + the TP rollout in
:mod:`tpudist.models.generate` are the framework's serving story.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpudist.utils.config import env_flag

_NEG_BIG = -1e30

# benchmarking/debug escape to measure the unpaired narrow-head path
# (normally strictly slower).  Accepted values: 1/true/yes/on disable
# pairing; unset/empty/0/false/no/off keep it (env_flag — the raw
# bool(getenv) this replaced treated "=0" as disable).  Read ONCE at
# import: jit caches are not keyed on env vars, so a mid-process flip
# would silently re-time the cached paired executable.
_DISABLE_PAIRING = env_flag("TPUDIST_DISABLE_HEAD_PAIRING")


def _decode_kernel(meta_ref, q_ref, k_ref, *rest, scale: float,
                   block_k: int, num_kb: int, window: int | None,
                   with_lse: bool, quant: bool,
                   rows_per_batch: int | None = None,
                   paired_q: bool = False,
                   side: bool = False):
    """Online-softmax decode over one (batch·kv-head) row of the cache.

    ``meta_ref`` is the scalar-prefetch vector ``[cache_len, offset,
    start_block]`` — or, with ``rows_per_batch`` set (per-row lengths),
    ``[side_len, offset, start_block, len_0, ..., len_{B-1}]``:
    ``offset`` is this shard's global cache start (sequence-parallel
    decode; 0 for the whole-cache case), and ``start_block`` trims the K
    grid to the sliding window — with ``window`` the grid runs only the
    ~``window/block_k`` blocks that intersect it, so a windowed decode
    STREAMS ~``window`` positions instead of the whole cache (bandwidth
    is the decode bound).

    ``paired_q``: the head-paired layout's block-diagonal query tile is
    built IN VMEM from the two natural [gp, d] halves (a couple of
    concatenates against a zero tile) instead of being scattered into an
    HBM array by XLA every decode step — the per-step packing cost the
    round-4 verdict measured as the d=64 model-level residual.

    ``side``: one extra trailing grid step attends over a small side
    buffer (the continuous-batching segment-local K/V staging) with
    ``meta[0]`` live positions — folding the serve loop's side attention
    and its log-sum-exp merge into this kernel's own online softmax.

    ``quant``: K/V tiles are int8 with per-token scales riding the LANE
    axis ([1, bk] blocks — a [bk, 1] layout would pad every scale to a
    128-lane row and stride the DMA; measured 2× slower).  Scales fold in
    AFTER the matmuls (Σ_d q_d·(k_jd·s_j) = s_j·(q·k_j)), so dequant
    costs [gp, bk] multiplies, not a [bk, D] tile rescale."""
    if quant:
        ks_ref, v_ref, vs_ref = rest[:3]
        rest = rest[3:]
    else:
        v_ref = rest[0]
        rest = rest[1:]
    if side:
        sk_ref, sv_ref = rest[:2]
        rest = rest[2:]
    if paired_q:
        q_scr = rest[-1]
        rest = rest[:-1]
    if with_lse:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    kj = pl.program_id(1)
    if rows_per_batch is None:
        cache_len = meta_ref[0]
    else:
        # per-row lengths (the continuous-batching serve loop: every
        # cache row decodes at its own position): meta carries
        # [side_len, off, start, len_0..len_{B-1}] and grid row g
        # belongs to batch row g // rows_per_batch
        cache_len = meta_ref[3 + pl.program_id(0) // rows_per_batch]
    offset = meta_ref[1]
    kb_idx = meta_ref[2] + kj  # grid step kj streams cache block kb_idx

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)
        if paired_q:
            # block-diagonal [2gp, 2d] from the two [gp, d] members:
            # rows [0, gp) carry member 0's queries in lanes [0, d),
            # rows [gp, 2gp) member 1's in lanes [d, 2d) — the zero
            # half annihilates the other member in the single 2d
            # contraction.  Built ONCE per grid row into scratch: the
            # lane-offset concatenates are not free under Mosaic, and
            # rebuilding them every K step measured ~2x on the whole
            # kernel at B=8
            q0, q1 = q_ref[0, 0], q_ref[0, 1]
            z = jnp.zeros_like(q0)
            q_scr[:] = jnp.concatenate(
                [jnp.concatenate([q0, z], axis=1),
                 jnp.concatenate([z, q1], axis=1)], axis=0)

    def q_tile():
        return q_scr[:] if paired_q else q_ref[0]    # [gp, D]

    def _accum(s, pv_scale, vb):
        """One online-softmax rank update from masked scores ``s`` and
        the value tile ``vb`` (``pv_scale`` folds per-token V scales
        into the probability rows; None for the bf16 path)."""
        m = m_scr[:]
        new_m = jnp.maximum(m, jnp.maximum(
            jnp.max(s, axis=-1, keepdims=True), _NEG_BIG))
        p = jnp.exp(s - new_m)
        corr = jnp.exp(m - new_m)
        m_scr[:] = new_m
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        if pv_scale is not None:
            vs = pv_scale                            # [rows, bk]
            if vs.shape[0] == 2:
                # half m's output lands in member m's lane half (sliced
                # out at unpack), so folding member m's V scale into
                # half-m probability rows is exact
                half = p.shape[0] // 2
                pv32 = (p.reshape(2, half, p.shape[1])
                        * vs[:, None, :]).reshape(p.shape)
            else:
                pv32 = p * vs
            pv = pv32.astype(jnp.bfloat16)
        else:
            pv = p.astype(vb.dtype)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            pv, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(offset + kb_idx * block_k < cache_len)
    def _compute():
        q = q_tile()
        if quant:
            kb = k_ref[0].astype(jnp.bfloat16)       # int8 fits exactly
            s = jax.lax.dot_general(
                q.astype(jnp.bfloat16), kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ks = ks_ref[0] * scale                   # [rows, bk]
            if ks.shape[0] == 2:
                # paired tile: score rows of half m carry pair member
                # m's K only (block-diagonal q), so member m's per-token
                # scale applies to exactly those rows
                half = s.shape[0] // 2
                s = (s.reshape(2, half, s.shape[1])
                     * ks[:, None, :]).reshape(s.shape)
            else:
                s = s * ks                           # [gp, bk]·[1, bk]
        else:
            s = jax.lax.dot_general(
                q, k_ref[0], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
        k_pos = offset + kb_idx * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)                   # GLOBAL positions
        keep = k_pos < cache_len
        if window is not None:
            keep = jnp.logical_and(keep, k_pos >= cache_len - window)
        s = jnp.where(keep, s, -jnp.inf)
        _accum(s, vs_ref[0] if quant else None, (
            v_ref[0].astype(jnp.bfloat16) if quant else v_ref[0]))

    if side:
        # the side buffer rides the LAST main grid step (an extra
        # sequential step measured +17 µs — pipeline bubbles at the
        # boundary of every grid row; folded here it is one more rank
        # update on tiles that are already resident)
        @pl.when(kj == num_kb - 1)
        def _side():
            s = jax.lax.dot_general(
                q_tile(), sk_ref[0], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(pos < meta_ref[0], s, -jnp.inf)
            _accum(s, None, sv_ref[0])

    @pl.when(kj == num_kb - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o = (acc_scr[:] / l).astype(o_ref.dtype)
        if paired_q:
            # UNPACK in kernel: member m's output lives in rows
            # [m·gp, (m+1)·gp) × lanes [m·d, (m+1)·d) of the block-
            # diagonal result — write each member's tile to its own
            # [gp, d] output slot, so XLA sees the natural layout and
            # pays no per-token lane-half slicing/stacking
            half_r = o.shape[0] // 2
            half_d = o.shape[1] // 2
            o_ref[0, 0] = o[:half_r, :half_d]
            o_ref[0, 1] = o[half_r:, half_d:]
        else:
            o_ref[0] = o
        if with_lse:
            # log-sum-exp of this shard's scores: the merge key for
            # sequence-parallel decode (out = Σ out_i·exp(lse_i − LSE))
            lse_ref[0, 0] = (m_scr[:] + jnp.log(l))[:, 0]


def _pick_block_k(s: int, block_k: int) -> int:
    """Largest usable K block: the cap if it divides S, else the largest
    multiple-of-8 divisor (VMEM-safe for arbitrary S), with a one-block
    fast path for small caches whose best divisor is tiny."""
    block_k = min(block_k, s)
    if block_k < s:
        # a PARTIAL block must sit on the 8-row sublane tile (a whole-
        # array block is exempt): a caller-chosen block_k like 12 would
        # otherwise reach Mosaic as an unlowerable block spec
        block_k = max(8, block_k - block_k % 8)
    if s % block_k == 0:
        return block_k
    bk = block_k - block_k % 8
    while bk >= 8 and s % bk:
        bk -= 8
    if bk >= 128 or (bk >= 8 and s > 4096):
        return bk
    if s <= 4096:
        # small cache whose best divisor is tiny (e.g. S = 8·prime):
        # one whole-cache block beats hundreds of sequential 8-row
        # grid steps, and [S, D] tiles at S <= 4096 fit VMEM
        return s
    raise ValueError(
        f"cache length {s} has no block divisor that is a multiple "
        f"of 8 up to {min(block_k, s)}; allocate the cache at a "
        f"multiple of 8 (e.g. {-(-s // 8) * 8})")


def flash_decode(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    cache_len: jnp.ndarray | int,
    *,
    window: int | None = None,
    block_k: int = 1024,
    interpret: bool | None = None,
    pos_offset: jnp.ndarray | int = 0,
    return_lse: bool = False,
    side_k: jnp.ndarray | None = None,
    side_v: jnp.ndarray | None = None,
    side_len: jnp.ndarray | int = 0,
    packed_kv_heads: int | None = None,
) -> jnp.ndarray | tuple[jnp.ndarray, jnp.ndarray]:
    """One decode step of attention.

    Args:
      q: ``[B, 1, H, D]`` — the current token's queries.
      k_cache / v_cache: ``[B, S, H_kv, D]`` fixed-size cache buffers
        (GQA: ``H_kv`` may divide ``H``); slots ``>= cache_len`` are
        ignored.  Alternatively PACKED 3-D buffers ``[B, S, H_kv·D]``
        with ``packed_kv_heads=H_kv`` — the layout the serving cache
        stores (minor dim a lane multiple, so XLA never pays a layout
        conversion at this call; head/pair chunks are selected by the
        kernel's block index maps, not host reshapes).  Measured: the
        4-D ``[B, S, 2, 64]`` cache carry sat in an S-minor layout and
        XLA inserted TWO full-cache copies per decode step feeding this
        kernel (~2× step time at 8k).
      cache_len: number of valid cache positions INCLUDING the current
        token (the flax ``cache_index + 1``); may be traced.  With
        ``pos_offset`` it stays GLOBAL: this buffer's slot ``j`` holds
        global position ``pos_offset + j`` (the sequence-parallel shard
        layout); validity and windowing are evaluated globally.  A
        VECTOR ``[B]`` selects per-row lengths (the continuous-batching
        serve path; row ``r`` attends over its own first ``len_r``
        slots).
      window: sliding-window width (attend to the last ``window``
        positions only), matching :func:`tpudist.models.sdpa` semantics.
      return_lse: also return the per-head log-sum-exp ``[B, H]`` — the
        merge key for combining partial attention across cache shards
        (:func:`sp_flash_decode`).
      side_k / side_v: optional ``[B, cap, H_kv, D]`` side buffers (the
        serve loop's segment-local K/V staging); the first ``side_len``
        positions are attended AFTER the main cache in the same online
        softmax — no separate attend, no log-sum-exp merge.  Requires
        per-row ``cache_len`` and ``window=None``.

    MULTI-QUERY DECODE (``q`` with ``s > 1``): the speculative verify
    chunk of the fused serve segment — ``s`` tokens whose K/V were just
    written to the side buffer (``side_len`` counts them), each query
    ``j`` attending the frozen main cache at the per-row lengths plus
    side positions ``< side_len - (s - 1 - j)`` (causal within the
    chunk).  Requires side buffers: the main cache is frozen during a
    segment, so in-segment queries can only see each other through the
    side staging.  Implemented as ``s`` single-query kernel calls — the
    main cache is streamed once per query, so this is for SHORT chunks
    (draft-k verification), not prefill; ``return_lse`` is single-query
    only.

    Returns ``[B, s, H, D]`` (plus ``[B, H]`` lse when requested).
    """
    s_q = q.shape[1]
    if s_q > 1:
        if side_k is None:
            raise ValueError(
                "multi-query flash_decode needs side buffers (the "
                "in-segment tokens' K/V staging); prefill-style chunks "
                "against the main cache go through the prefill kernel")
        if return_lse:
            raise ValueError(
                "return_lse composes with single-query decode only")
        sl = jnp.asarray(side_len, jnp.int32)
        return jnp.concatenate([
            _flash_decode_impl(
                q[:, j:j + 1], k_cache, None, v_cache, None, cache_len,
                window=window, block_k=block_k, interpret=interpret,
                pos_offset=pos_offset, return_lse=False, side_k=side_k,
                side_v=side_v, side_len=sl - (s_q - 1 - j),
                packed_kv_heads=packed_kv_heads)
            for j in range(s_q)], axis=1)
    return _flash_decode_impl(
        q, k_cache, None, v_cache, None, cache_len, window=window,
        block_k=block_k, interpret=interpret, pos_offset=pos_offset,
        return_lse=return_lse, side_k=side_k, side_v=side_v,
        side_len=side_len, packed_kv_heads=packed_kv_heads)


def _flash_decode_impl(q, k_cache, k_scale, v_cache, v_scale, cache_len,
                       *, window, block_k, interpret, pos_offset,
                       return_lse, side_k=None, side_v=None, side_len=0,
                       packed_kv_heads=None):
    """Shared wrapper for the bf16 and int8 cache paths (``k_scale`` /
    ``v_scale`` None selects bf16)."""
    quant = k_scale is not None
    side = side_k is not None
    packed = k_cache.ndim == 3
    b, s_q, h, d = q.shape
    assert s_q == 1, "flash_decode consumes one query token"
    if packed:
        if packed_kv_heads is None:
            raise ValueError(
                "a 3-D packed cache needs packed_kv_heads=H_kv")
        if quant:
            raise ValueError(
                "packed caches compose with the bf16 path only")
        s, h_kv = k_cache.shape[1], packed_kv_heads
        if k_cache.shape[2] != h_kv * d:
            raise ValueError(
                f"packed cache minor dim {k_cache.shape[2]} != "
                f"H_kv*D = {h_kv * d}")
    else:
        s, h_kv = k_cache.shape[1], k_cache.shape[2]
    if h % h_kv:
        raise ValueError(f"num_heads {h} not a multiple of kv heads {h_kv}")
    g = h // h_kv
    gp = -(-g // 8) * 8  # pad the group to the 8-row sublane tile
    block_k = _pick_block_k(s, block_k)
    num_kb_full = s // block_k
    if interpret is None:
        interpret = jax.default_backend() == "cpu"

    cache_len = jnp.asarray(cache_len, jnp.int32)
    per_row = cache_len.ndim == 1
    if per_row and window is not None:
        raise ValueError(
            "per-row cache lengths compose with window=None only (the "
            "sliding-window grid trim needs one start block per grid)")
    if per_row and cache_len.shape[0] != b:
        raise ValueError(
            f"per-row cache_len has {cache_len.shape[0]} entries for "
            f"batch {b}")
    if side:
        if quant:
            raise ValueError("side buffers compose with the bf16 cache "
                             "path only")
        if not per_row or window is not None:
            raise ValueError(
                "side buffers require per-row cache_len and window=None "
                "(the continuous-batching serve configuration)")
        # pad the side capacity to the 8-row sublane tile; side_len masks
        # the padding rows.  Packed main caches take packed side buffers
        # ([B, cap, Hkv·D]) — same layout contract.
        if side_k.ndim != k_cache.ndim:
            raise ValueError(
                "side buffers must match the cache layout (both packed "
                "3-D or both [B, S, H_kv, D])")
        cap = side_k.shape[1]
        capp = max(8, -(-cap // 8) * 8)
        if capp != cap:
            pad = (((0, 0), (0, capp - cap), (0, 0))
                   if side_k.ndim == 3
                   else ((0, 0), (0, capp - cap), (0, 0), (0, 0)))
            side_k = jnp.pad(side_k, pad)
            side_v = jnp.pad(side_v, pad)
        side_k = side_k.astype(k_cache.dtype)
        side_v = side_v.astype(v_cache.dtype)
    offset = jnp.asarray(pos_offset, jnp.int32)
    if window is None:
        nb = num_kb_full
        start_block = jnp.int32(0)
    else:
        # grid trimming: only blocks intersecting the window's GLOBAL
        # span [cache_len - window, cache_len) are streamed — a windowed
        # decode reads ~window positions, not the whole cache
        nb = min(num_kb_full, -(-window // block_k) + 1)
        start_block = jnp.clip(
            (cache_len - window - offset) // block_k, 0, num_kb_full - nb)
    if per_row:
        meta = jnp.concatenate(
            [jnp.stack([jnp.asarray(side_len, jnp.int32), offset,
                        start_block]), cache_len])
    else:
        meta = jnp.stack([cache_len, offset, start_block])

    # HEAD PAIRING for narrow head_dim: a [block_k, d] K/V tile with
    # d < 128 underfills the 128-lane width and streams at ~half
    # bandwidth (measured: 305 vs 636 GB/s).  When d*2 <= 128 and h_kv
    # is even, ADJACENT KV-head pairs merge into one [*, 2d] tile (a
    # pure reshape of the [B, S, H_kv, D] cache), and the queries go in
    # BLOCK-DIAGONAL: pair rows [q_h0 | 0] and [0 | q_h1] make the
    # single 2d-lane contraction compute each real head's scores
    # exactly (the zero half annihilates the other head), while PV
    # produces each head's output in its own lane half, sliced apart
    # below.  Costs 2x matmul FLOPs on zeros; buys full-width DMA rows
    # at the bandwidth-bound op — measured kernel parity with a d=128
    # layout.  The int8 path pairs too (round-3 verdict #6 — the
    # narrow-head fix and the cache-compression fix now COMPOSE): its
    # per-(token, head) scales ride as [2, block_k] blocks, one row per
    # pair member, and the kernel applies them half-wise — score rows of
    # half m only ever contract member m's K (the zero q half
    # annihilates the other member), and member m's V lands in its own
    # lane half, so folding member m's scale into half-m score/prob rows
    # is exact.
    scale = d ** -0.5
    paired = h_kv % 2 == 0 and d * 2 <= 128 and not _DISABLE_PAIRING
    q4 = q.reshape(b, h_kv, g, d)                    # [B, Hkv, g, d]
    q4 = jnp.pad(q4, ((0, 0), (0, 0), (0, gp - g), (0, 0)))
    if paired:
        # the block-diagonal query tile is built INSIDE the kernel
        # (paired_q) from this natural [·, 2, gp, d] layout — building it
        # here cost an HBM zeros + two scatters EVERY decode step, the
        # measured model-level residual of the paired path (round-4
        # verdict #8); in VMEM it is two concatenates against a zero tile
        n_rows, kv_rows, d_eff = 2 * gp, h_kv // 2, 2 * d
        q3 = q4.reshape(b * kv_rows, 2, gp, d)
        if not packed:
            k3 = k_cache.reshape(
                b, s, kv_rows, d_eff).swapaxes(1, 2).reshape(
                b * kv_rows, s, d_eff)
            v3 = v_cache.reshape(
                b, s, kv_rows, d_eff).swapaxes(1, 2).reshape(
                b * kv_rows, s, d_eff)
            if side:
                side_k = side_k.reshape(
                    b, capp, kv_rows, d_eff).swapaxes(1, 2).reshape(
                    b * kv_rows, capp, d_eff)
                side_v = side_v.reshape(
                    b, capp, kv_rows, d_eff).swapaxes(1, 2).reshape(
                    b * kv_rows, capp, d_eff)
        gp, h_kv, d = n_rows, kv_rows, d_eff
    else:
        q3 = q4.reshape(b * h_kv, gp, d)
        if not packed:
            k3 = k_cache.swapaxes(1, 2).reshape(b * h_kv, s, d)
            v3 = v_cache.swapaxes(1, 2).reshape(b * h_kv, s, d)
            if side:
                side_k = side_k.swapaxes(1, 2).reshape(b * h_kv, capp, d)
                side_v = side_v.swapaxes(1, 2).reshape(b * h_kv, capp, d)

    # index maps see the prefetched meta first: grid step j streams cache
    # block meta[2] + j.  In PACKED mode the cache stays [B, S, Hkv·D]
    # and the grid row's head/pair chunk is picked by the index map's
    # third coordinate — no host reshape ever touches the buffer (a
    # host-side head-major relayout of an S-minor carry measured as two
    # full-cache copies per decode step).
    R = h_kv  # post-pairing rows per batch (pairs when paired)
    if packed:
        k3, v3 = k_cache, v_cache
        kv_spec = pl.BlockSpec(
            (1, block_k, d),
            lambda g_, j, m: (g_ // R, m[2] + j, g_ % R))
    else:
        kv_spec = pl.BlockSpec(
            (1, block_k, d), lambda g_, j, m: (g_, m[2] + j, 0))
    # scales as [B·Hkv, rows, S] (rows = 2 pair members when paired, else
    # 1): the sequence dim rides the LANE axis so a block is a dense
    # [rows, block_k] row set, not a strided column (measured 2× on the
    # whole kernel)
    sc_rows = 2 if paired else 1
    sc_spec = pl.BlockSpec((1, sc_rows, block_k),
                           lambda g_, j, m: (g_, 0, m[2] + j))

    def pack_scale(sc):
        # [B, S, Hkv_orig, 1] -> [B·(Hkv_orig/rows), rows, S]
        flat = sc[..., 0].swapaxes(1, 2)          # [B, Hkv_orig, S]
        return flat.reshape(b * h_kv, sc_rows, s)

    if paired:
        q_spec = pl.BlockSpec((1, 2, gp // 2, d // 2),
                              lambda g_, j, m: (g_, 0, 0, 0))
    else:
        q_spec = pl.BlockSpec((1, gp, d), lambda g_, j, m: (g_, 0, 0))
    args = [meta, q3, k3]
    in_specs = [q_spec, kv_spec]
    if quant:
        args.append(pack_scale(k_scale))
        in_specs.append(sc_spec)
    args.append(v3)
    in_specs.append(kv_spec)
    if quant:
        args.append(pack_scale(v_scale))
        in_specs.append(sc_spec)
    if side:
        if packed:
            side_spec = pl.BlockSpec(
                (1, capp, d), lambda g_, j, m: (g_ // R, 0, g_ % R))
        else:
            side_spec = pl.BlockSpec(
                (1, capp, d), lambda g_, j, m: (g_, 0, 0))
        args += [side_k, side_v]
        in_specs += [side_spec, side_spec]

    if paired:
        out_specs = [pl.BlockSpec((1, 2, gp // 2, d // 2),
                                  lambda g_, j, m: (g_, 0, 0, 0))]
        out_shape = [jax.ShapeDtypeStruct(
            (b * h_kv, 2, gp // 2, d // 2), q.dtype)]
    else:
        out_specs = [pl.BlockSpec((1, gp, d), lambda g_, j, m: (g_, 0, 0))]
        out_shape = [jax.ShapeDtypeStruct((b * h_kv, gp, d), q.dtype)]
    if return_lse:
        out_specs.append(
            pl.BlockSpec((1, 1, gp), lambda g_, j, m: (g_, 0, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((b * h_kv, 1, gp), jnp.float32))
    outs = pl.pallas_call(
        functools.partial(
            _decode_kernel, scale=scale, block_k=block_k,
            num_kb=nb, window=window, with_lse=return_lse,
            quant=quant,
            # h_kv here is POST-pairing: grid row g -> batch g // h_kv
            rows_per_batch=h_kv if per_row else None,
            paired_q=paired, side=side),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * h_kv, nb),
            in_specs=in_specs,
            out_specs=out_specs if return_lse else out_specs[0],
            scratch_shapes=[
                pltpu.VMEM((gp, 1), jnp.float32),
                pltpu.VMEM((gp, 1), jnp.float32),
                pltpu.VMEM((gp, d), jnp.float32),
            ] + ([pltpu.VMEM((gp, d), q.dtype)] if paired else []),
        ),
        out_shape=out_shape if return_lse else out_shape[0],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(*args)
    def unpack_out(out):
        if paired:
            # the kernel already wrote each pair member's [gp, d'] tile
            # to its own output slot — unpacking is a pure reshape +
            # row slice, no lane-half gathers
            d0 = d // 2
            o = out.reshape(b, h_kv * 2, gp // 2, d0)
            return o[:, :, :g].reshape(b, 1, h, d0)
        return out.reshape(b, h_kv, gp, d)[:, :, :g].reshape(b, 1, h, d)

    def unpack_lse(lse):
        if paired:
            return lse.reshape(b, h_kv, 2, gp // 2)[
                :, :, :, :g].reshape(b, h)
        return lse.reshape(b, h_kv, gp)[:, :, :g].reshape(b, h)

    if not return_lse:
        return unpack_out(outs)
    out, lse = outs
    return unpack_out(out), unpack_lse(lse)


def paged_gather_kv(pool: jnp.ndarray, page_table: jnp.ndarray
                    ) -> jnp.ndarray:
    """Gather a paged pool into a contiguous per-slot view: ``[N, bs, F]``
    pool + ``[B, M]`` page table -> ``[B, M*bs, F]`` (slot ``b``'s logical
    position ``p`` lands at row ``p``).  The DENSE-fallback path for
    CPU/test runs and the reference the paged kernel is checked against —
    on TPU it materializes the whole logical cache every step, which is
    exactly the copy :func:`paged_flash_decode` exists to avoid."""
    b, m = page_table.shape
    _, bs, flat = pool.shape
    return pool[page_table.reshape(-1)].reshape(b, m * bs, flat)


def paged_flash_decode(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,
    cache_len: jnp.ndarray,
    *,
    packed_kv_heads: int,
    side_k: jnp.ndarray | None = None,
    side_v: jnp.ndarray | None = None,
    side_len: jnp.ndarray | int = 0,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """One decode step of attention against a PAGED KV cache.

    The continuous-batching capacity lever (PagedAttention): instead of a
    dense ``[B, S, Hkv*D]`` buffer per slot, K/V live in ONE shared block
    pool ``[num_blocks, block_size, Hkv*D]`` and each slot maps its
    logical positions through a page table — slot ``b``'s position ``p``
    is ``pool[page_table[b, p // block_size], p % block_size]``.  HBM
    then scales with tokens actually allocated, not
    ``num_slots x max_seq_len``.

    The kernel is the SAME online-softmax body as :func:`flash_decode`
    (block-diagonal head pairing included): the only paged thing about it
    is the K/V BlockSpec index map, which reads grid step ``j``'s pool
    block id from the scalar-prefetched page table instead of computing
    ``start + j`` — the gather costs nothing on top of the DMA the dense
    kernel already issues per block.  Blocks past a row's length skip
    their FLOPs under ``pl.when`` exactly as before (dead page-table
    entries must hold a VALID pool index, e.g. 0, so the prefetch still
    reads real memory).

    Args:
      q: ``[B, 1, H, D]`` current-token queries.
      k_pool / v_pool: ``[num_blocks, block_size, Hkv*D]`` packed block
        pools (``block_size`` a multiple of 8 — the sublane tile).
      page_table: ``[B, max_blocks_per_slot]`` int32 pool indices.
      cache_len: ``[B]`` per-row valid lengths INCLUDING the current
        token (the serve loop's vector ``cache_index`` + side occupancy
        semantics are the caller's business, as with ``flash_decode``).
      packed_kv_heads: ``H_kv`` of the packed minor dim.
      side_k / side_v / side_len: the serve loop's segment-local staging
        buffers (``[B, cap, Hkv*D]`` packed), attended after the paged
        cache in the same online softmax — as on :func:`flash_decode`.

    MULTI-QUERY DECODE (``q`` with ``s > 1``): the speculative verify
    chunk — the ``page_table`` already covers the segment's pre-reserved
    growth (the ServeLoop grows every lane's coverage at dispatch), the
    in-segment tokens stage in the side buffer, and query ``j`` attends
    the pool at the per-row lengths plus side positions
    ``< side_len - (s - 1 - j)``.  Implemented as ``s`` single-query
    kernel calls (short verify chunks only).

    Returns ``[B, s, H, D]``.
    """
    b, s_q, h, d = q.shape
    if s_q > 1:
        if side_k is None:
            raise ValueError(
                "multi-query paged_flash_decode needs side buffers "
                "(in-segment tokens stage there; the pool is frozen "
                "within a segment)")
        sl = jnp.asarray(side_len, jnp.int32)
        return jnp.concatenate([
            paged_flash_decode(
                q[:, j:j + 1], k_pool, v_pool, page_table, cache_len,
                packed_kv_heads=packed_kv_heads, side_k=side_k,
                side_v=side_v, side_len=sl - (s_q - 1 - j),
                interpret=interpret)
            for j in range(s_q)], axis=1)
    assert s_q == 1, "paged_flash_decode consumes one query token"
    if k_pool.ndim != 3:
        raise ValueError(
            f"paged pools are packed 3-D [N, block, Hkv*D]; got "
            f"{k_pool.shape}")
    n_pool, block, flat = k_pool.shape
    h_kv = packed_kv_heads
    if flat != h_kv * d:
        raise ValueError(
            f"pool minor dim {flat} != H_kv*D = {h_kv * d}")
    if h % h_kv:
        raise ValueError(f"num_heads {h} not a multiple of kv heads {h_kv}")
    if block < 8 or block % 8:
        raise ValueError(
            f"block_size must be a multiple of 8, got {block}")
    g = h // h_kv
    gp = -(-g // 8) * 8
    cache_len = jnp.asarray(cache_len, jnp.int32)
    if cache_len.ndim != 1 or cache_len.shape[0] != b:
        raise ValueError(
            f"paged decode takes per-row cache_len [B={b}]; got "
            f"{cache_len.shape}")
    table = jnp.asarray(page_table, jnp.int32)
    if table.ndim != 2 or table.shape[0] != b:
        raise ValueError(
            f"page_table must be [B={b}, max_blocks]; got {table.shape}")
    m_blocks = table.shape[1]
    side = side_k is not None
    if side:
        if side_k.ndim != 3:
            raise ValueError(
                "side buffers must be packed 3-D [B, cap, Hkv*D]")
        cap = side_k.shape[1]
        capp = max(8, -(-cap // 8) * 8)
        if capp != cap:
            pad = ((0, 0), (0, capp - cap), (0, 0))
            side_k = jnp.pad(side_k, pad)
            side_v = jnp.pad(side_v, pad)
        side_k = side_k.astype(k_pool.dtype)
        side_v = side_v.astype(v_pool.dtype)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"

    # meta = [side_len, offset=0, start_block=0, len_0..len_{B-1},
    # table[0,0]..table[B-1,M-1]] — the kernel reads the first 3+B slots
    # (identical layout to the per-row dense path), the K/V index maps
    # read the page table tail
    meta = jnp.concatenate([
        jnp.stack([jnp.asarray(side_len, jnp.int32), jnp.int32(0),
                   jnp.int32(0)]),
        cache_len, table.reshape(-1)])

    scale = d ** -0.5
    paired = h_kv % 2 == 0 and d * 2 <= 128 and not _DISABLE_PAIRING
    q4 = q.reshape(b, h_kv, g, d)
    q4 = jnp.pad(q4, ((0, 0), (0, 0), (0, gp - g), (0, 0)))
    if paired:
        # pool pairing is free: adjacent KV heads are contiguous in the
        # packed minor dim, so a pair chunk is just a wider index-map
        # slice — no reshape of the pool ever happens
        rows, r_kv, d_eff = 2 * gp, h_kv // 2, 2 * d
        q3 = q4.reshape(b * r_kv, 2, gp, d)
        gp, d = rows, d_eff
    else:
        r_kv = h_kv
        q3 = q4.reshape(b * h_kv, gp, d)
    R, M = r_kv, m_blocks  # noqa: N806 — closed over by the index maps

    # THE paged line: grid step j of grid row g streams pool block
    # table[g // R, j], read from the prefetched meta at its flattened
    # offset — page gathering by index map, zero extra data movement
    kv_spec = pl.BlockSpec(
        (1, block, d),
        lambda g_, j, m: (m[3 + b + (g_ // R) * M + j], 0, g_ % R))
    if paired:
        q_spec = pl.BlockSpec((1, 2, gp // 2, d // 2),
                              lambda g_, j, m: (g_, 0, 0, 0))
        out_spec = pl.BlockSpec((1, 2, gp // 2, d // 2),
                                lambda g_, j, m: (g_, 0, 0, 0))
        out_shape = jax.ShapeDtypeStruct(
            (b * r_kv, 2, gp // 2, d // 2), q.dtype)
    else:
        q_spec = pl.BlockSpec((1, gp, d), lambda g_, j, m: (g_, 0, 0))
        out_spec = pl.BlockSpec((1, gp, d), lambda g_, j, m: (g_, 0, 0))
        out_shape = jax.ShapeDtypeStruct((b * r_kv, gp, d), q.dtype)
    args = [meta, q3, k_pool, v_pool]
    in_specs = [q_spec, kv_spec, kv_spec]
    if side:
        side_spec = pl.BlockSpec(
            (1, capp, d), lambda g_, j, m: (g_ // R, 0, g_ % R))
        args += [side_k, side_v]
        in_specs += [side_spec, side_spec]

    out = pl.pallas_call(
        functools.partial(
            _decode_kernel, scale=scale, block_k=block,
            num_kb=m_blocks, window=None, with_lse=False, quant=False,
            rows_per_batch=r_kv, paired_q=paired, side=side),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * r_kv, m_blocks),
            in_specs=in_specs,
            out_specs=out_spec,
            scratch_shapes=[
                pltpu.VMEM((gp, 1), jnp.float32),
                pltpu.VMEM((gp, 1), jnp.float32),
                pltpu.VMEM((gp, d), jnp.float32),
            ] + ([pltpu.VMEM((gp, d), q.dtype)] if paired else []),
        ),
        out_shape=out_shape,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(*args)
    if paired:
        d0 = d // 2
        o = out.reshape(b, r_kv * 2, gp // 2, d0)
        return o[:, :, :g].reshape(b, 1, h, d0)
    return out.reshape(b, r_kv, gp, d)[:, :, :g].reshape(b, 1, h, d)


def quantize_kv(k: jnp.ndarray, v: jnp.ndarray):
    """Per-(token, head) symmetric int8 quantization of K/V cache blocks:
    ``[..., D] -> (int8 [..., D], f32 scale [..., 1])``.  Halves the
    bytes the decode step must stream — at long context decode is
    bandwidth-bound (measured 668 GB/s = 82% of the v5e's spec), so the
    ceiling on decode throughput is ~2× the bf16 cache's."""
    def q(x):
        x32 = x.astype(jnp.float32)
        scale = jnp.max(jnp.abs(x32), axis=-1, keepdims=True) / 127.0
        scale = jnp.maximum(scale, 1e-8)
        return (jnp.round(x32 / scale).astype(jnp.int8), scale)

    kq, ks = q(k)
    vq, vs = q(v)
    return kq, ks, vq, vs


def flash_decode_q8(
    q: jnp.ndarray,
    k_cache_q8: jnp.ndarray,
    k_scale: jnp.ndarray,
    v_cache_q8: jnp.ndarray,
    v_scale: jnp.ndarray,
    cache_len: jnp.ndarray | int,
    *,
    window: int | None = None,
    block_k: int = 1024,
    interpret: bool | None = None,
    pos_offset: jnp.ndarray | int = 0,
    return_lse: bool = False,
) -> jnp.ndarray | tuple[jnp.ndarray, jnp.ndarray]:
    """:func:`flash_decode` over an int8-quantized KV cache.

    Args:
      q: ``[B, 1, H, D]`` queries (bf16/f32).
      k_cache_q8 / v_cache_q8: ``[B, S, H_kv, D]`` int8 buffers from
        :func:`quantize_kv`.
      k_scale / v_scale: ``[B, S, H_kv, 1]`` f32 per-(token, head) scales.
      pos_offset / return_lse: as on :func:`flash_decode` (the sharded-
      cache partial-softmax contract composes with quantization).

    Returns ``[B, 1, H, D]`` in ``q.dtype`` (plus ``[B, H]`` lse when
    requested).  Decode streams ~half the cache bytes of the bf16 path
    (scales add D/4096 overhead); measured 1.12× at 8k context."""
    return _flash_decode_impl(
        q, k_cache_q8, k_scale, v_cache_q8, v_scale, cache_len,
        window=window, block_k=block_k, interpret=interpret,
        pos_offset=pos_offset, return_lse=return_lse)


def sp_flash_decode(
    q: jnp.ndarray,
    k_shard: jnp.ndarray,
    v_shard: jnp.ndarray,
    cache_len: jnp.ndarray | int,
    axis_name: str,
    *,
    window: int | None = None,
    block_k: int = 1024,
    interpret: bool | None = None,
    packed_kv_heads: int | None = None,
) -> jnp.ndarray:
    """Sequence-parallel flash decode: the KV cache's SEQUENCE dim is
    sharded over ``axis_name`` (shard i owns global slots
    ``[i·S_loc, (i+1)·S_loc)``); each shard runs :func:`flash_decode` on
    its slice with GLOBAL masking, then partial softmaxes merge with the
    log-sum-exp identity — one tiny ``[B, H]`` all-gather plus one psum
    of the output, no cache movement (the "flash decoding" parallelism,
    decode-side twin of ring attention's training split).

    Call inside a ``shard_map`` over ``axis_name`` with q replicated and
    k/v sequence-sharded (4-D per-head, or packed 3-D with
    ``packed_kv_heads``).  Returns the replicated ``[B, 1, H, D]``.
    """
    from jax import lax

    i = lax.axis_index(axis_name)
    s_loc = k_shard.shape[1]
    out, lse = flash_decode(
        q, k_shard, v_shard, cache_len, window=window, block_k=block_k,
        interpret=interpret, pos_offset=i * s_loc, return_lse=True,
        packed_kv_heads=packed_kv_heads)
    all_lse = lax.all_gather(lse, axis_name)             # [n, B, H]
    new_lse = jax.nn.logsumexp(all_lse, axis=0)          # [B, H]
    w = jnp.exp(lse - new_lse)
    return lax.psum(
        out.astype(jnp.float32) * w[:, None, :, None], axis_name
    ).astype(q.dtype)
