"""GroupNorm with a closed-form custom VJP — the ResNet50 backward fix.

Measured on the v5e (`scripts/resnet_mfu_sweep.py`, round 3): ResNet50
trains at 13.1 ms/step with ``flax.linen.GroupNorm`` but its FORWARD runs
at 69.8% MFU — the entire gap is the backward, where autodiff of the
two-pass stats computation emits broadcast/reduce chains XLA fails to fuse
(~6.3 ms/step of pure GroupNorm backward, bandwidth-bound).  The fix is the
standard closed-form gradient

    x̂  = (x - μ) · rstd
    g   = dy · γ
    dx  = rstd · (g - mean_G(g) - x̂ · mean_G(g · x̂))
    dγ  = Σ_{B,H,W} dy · x̂          dβ = Σ_{B,H,W} dy

which is two group reductions + elementwise — three fusible passes over
the tensor (read x, read dy, write dx) instead of autodiff's many.

Numerics match ``nn.GroupNorm`` (same f32 stats, same eps placement);
``GroupNormFast`` is parameter-compatible (``scale``/``bias`` of shape
[C]), so checkpoints transfer both ways.

The reference's ResNet uses BatchNorm (`rpc/model_parallel_ResNet50.py`,
via torchvision Bottleneck); GroupNorm is this framework's documented
TPU-first default (`tpudist/models/resnet.py`), and this module is why it
is also the fast one.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import linen as nn


def _stats(x32: jnp.ndarray, groups: int, eps: float):
    b, h, w, c = x32.shape
    xg = x32.reshape(b, h * w, groups, c // groups)
    mean = jnp.mean(xg, axis=(1, 3))                        # [B, G]
    var = jnp.mean(jnp.square(xg), axis=(1, 3)) - jnp.square(mean)
    rstd = jax.lax.rsqrt(var + eps)
    return mean, rstd


def _expand(v: jnp.ndarray, shape, groups: int) -> jnp.ndarray:
    """[B, G] group statistic -> broadcastable [B, 1, 1, C]."""
    b, h, w, c = shape
    return jnp.repeat(v, c // groups, axis=1).reshape(b, 1, 1, c)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def group_norm(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray,
               groups: int = 32, eps: float = 1e-6) -> jnp.ndarray:
    """Normalize ``x`` [B, H, W, C] over (H, W, C/groups) per group; affine
    ``scale``/``bias`` are [C].  Stats in f32, output in ``x.dtype``."""
    x32 = x.astype(jnp.float32)
    mean, rstd = _stats(x32, groups, eps)
    xhat = (x32 - _expand(mean, x.shape, groups)) * _expand(
        rstd, x.shape, groups)
    return (xhat * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def _gn_fwd(x, scale, bias, groups, eps):
    x32 = x.astype(jnp.float32)
    mean, rstd = _stats(x32, groups, eps)
    xhat = (x32 - _expand(mean, x.shape, groups)) * _expand(
        rstd, x.shape, groups)
    y = (xhat * scale.astype(jnp.float32)
         + bias.astype(jnp.float32)).astype(x.dtype)
    # save x + the [B, G] scalars, NOT x̂ — recomputing x̂ in the backward
    # is elementwise and fuses, while saving it would double residual HBM
    return y, (x, mean, rstd, scale)


def _gn_bwd(groups, eps, res, dy):
    x, mean, rstd, scale = res
    shape = x.shape
    b, h, w, c = shape
    n = h * w * (c // groups)
    x32 = x.astype(jnp.float32)
    dy32 = dy.astype(jnp.float32)
    xhat = (x32 - _expand(mean, shape, groups)) * _expand(rstd, shape, groups)
    g = dy32 * scale.astype(jnp.float32)

    gg = g.reshape(b, h * w, groups, c // groups)
    gx = (g * xhat).reshape(b, h * w, groups, c // groups)
    m1 = jnp.sum(gg, axis=(1, 3)) / n                        # mean_G(g)
    m2 = jnp.sum(gx, axis=(1, 3)) / n                        # mean_G(g·x̂)
    dx = (_expand(rstd, shape, groups)
          * (g - _expand(m1, shape, groups)
             - xhat * _expand(m2, shape, groups))).astype(x.dtype)
    dscale = jnp.sum(dy32 * xhat, axis=(0, 1, 2)).astype(scale.dtype)
    dbias = jnp.sum(dy32, axis=(0, 1, 2)).astype(scale.dtype)
    return dx, dscale, dbias


group_norm.defvjp(_gn_fwd, _gn_bwd)


class GroupNorm(nn.Module):
    """Drop-in ``nn.GroupNorm`` twin backed by :func:`group_norm` — same
    param names/shapes (``scale``/``bias`` of [C]) AND the same flax
    auto-name prefix (``GroupNorm_N``), so whole-model param trees are
    interchangeable with flax-normed ones."""

    num_groups: int = 32
    epsilon: float = 1e-6
    dtype: jnp.dtype | None = None
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = x.shape[-1]
        if c % self.num_groups:
            raise ValueError(
                f"channels {c} not divisible by num_groups {self.num_groups}")
        scale = self.param("scale", nn.initializers.ones, (c,),
                           self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, (c,),
                          self.param_dtype)
        y = group_norm(x, scale, bias, self.num_groups, self.epsilon)
        return y.astype(self.dtype) if self.dtype is not None else y


GroupNormFast = GroupNorm  # explicit-intent alias
