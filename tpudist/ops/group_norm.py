"""GroupNorm with a closed-form custom VJP — the ResNet50 backward fix.

Measured on the v5e (`scripts/resnet_mfu_sweep.py`, round 3): ResNet50
trains at 13.1 ms/step with ``flax.linen.GroupNorm`` but its FORWARD runs
at 69.8% MFU — the entire gap is the backward, where autodiff of the
two-pass stats computation emits broadcast/reduce chains XLA fails to fuse
(~6.3 ms/step of pure GroupNorm backward, bandwidth-bound).  The fix is the
standard closed-form gradient

    x̂  = (x - μ) · rstd
    g   = dy · γ
    dx  = rstd · (g - mean_G(g) - x̂ · mean_G(g · x̂))
    dγ  = Σ_{B,H,W} dy · x̂          dβ = Σ_{B,H,W} dy

which is two group reductions + elementwise — three fusible passes over
the tensor (read x, read dy, write dx) instead of autodiff's many.

Numerics match ``nn.GroupNorm`` (same f32 stats, same eps placement);
``GroupNormFast`` is parameter-compatible (``scale``/``bias`` of shape
[C]), so checkpoints transfer both ways.

The reference's ResNet uses BatchNorm (`rpc/model_parallel_ResNet50.py`,
via torchvision Bottleneck); GroupNorm is this framework's documented
TPU-first default (`tpudist/models/resnet.py`), and this module is why it
is also the fast one.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import linen as nn


def _stats(x32: jnp.ndarray, groups: int, eps: float):
    b, h, w, c = x32.shape
    xg = x32.reshape(b, h * w, groups, c // groups)
    mean = jnp.mean(xg, axis=(1, 3))                        # [B, G]
    var = jnp.mean(jnp.square(xg), axis=(1, 3)) - jnp.square(mean)
    rstd = jax.lax.rsqrt(var + eps)
    return mean, rstd


def _expand(v: jnp.ndarray, shape, groups: int) -> jnp.ndarray:
    """[B, G] group statistic -> broadcastable [B, 1, 1, C]."""
    b, h, w, c = shape
    return jnp.repeat(v, c // groups, axis=1).reshape(b, 1, 1, c)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def group_norm(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray,
               groups: int = 32, eps: float = 1e-6) -> jnp.ndarray:
    """Normalize ``x`` [B, H, W, C] over (H, W, C/groups) per group; affine
    ``scale``/``bias`` are [C].  Stats in f32, output in ``x.dtype``."""
    x32 = x.astype(jnp.float32)
    mean, rstd = _stats(x32, groups, eps)
    xhat = (x32 - _expand(mean, x.shape, groups)) * _expand(
        rstd, x.shape, groups)
    return (xhat * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def _gn_fwd(x, scale, bias, groups, eps):
    x32 = x.astype(jnp.float32)
    mean, rstd = _stats(x32, groups, eps)
    xhat = (x32 - _expand(mean, x.shape, groups)) * _expand(
        rstd, x.shape, groups)
    y = (xhat * scale.astype(jnp.float32)
         + bias.astype(jnp.float32)).astype(x.dtype)
    # save x + the [B, G] scalars, NOT x̂ — recomputing x̂ in the backward
    # is elementwise and fuses, while saving it would double residual HBM
    return y, (x, mean, rstd, scale)


def _gn_bwd(groups, eps, res, dy):
    x, mean, rstd, scale = res
    shape = x.shape
    b, h, w, c = shape
    n = h * w * (c // groups)
    x32 = x.astype(jnp.float32)
    dy32 = dy.astype(jnp.float32)
    xhat = (x32 - _expand(mean, shape, groups)) * _expand(rstd, shape, groups)
    g = dy32 * scale.astype(jnp.float32)

    gg = g.reshape(b, h * w, groups, c // groups)
    gx = (g * xhat).reshape(b, h * w, groups, c // groups)
    m1 = jnp.sum(gg, axis=(1, 3)) / n                        # mean_G(g)
    m2 = jnp.sum(gx, axis=(1, 3)) / n                        # mean_G(g·x̂)
    dx = (_expand(rstd, shape, groups)
          * (g - _expand(m1, shape, groups)
             - xhat * _expand(m2, shape, groups))).astype(x.dtype)
    dscale = jnp.sum(dy32 * xhat, axis=(0, 1, 2)).astype(scale.dtype)
    dbias = jnp.sum(dy32, axis=(0, 1, 2)).astype(scale.dtype)
    return dx, dscale, dbias


group_norm.defvjp(_gn_fwd, _gn_bwd)


# ---------------------------------------------------------------------------
# Pallas fused path: GroupNorm (+ReLU, +residual-add) in ONE slab-resident
# pass per direction.  MEASURED NEGATIVE RESULT (round 3,
# scripts/resnet_mfu_sweep.py): inside ResNet50 these kernels LOSE to the
# XLA closed-form path — 30.1 ms/step vs 12.9 (fwd 9.5 ms vs 1.24) —
# because XLA fuses the forward norm into conv epilogues at ~zero cost and
# the pallas_call boundary forces the very materialization passes the
# kernel was meant to remove.  They are kept (tested, numerically exact)
# as standalone ops for norm-dominated elementwise stacks where no
# producer fusion exists, and as the documented experiment record; the
# ResNet models deliberately do NOT use them.
# ---------------------------------------------------------------------------


def _group_matrix(c: int, groups: int) -> jnp.ndarray:
    """One-hot [C, G] channel→group map.  Group reductions and expansions
    become tiny matmuls (``[1,C] @ M`` / ``[1,G] @ Mᵀ``) — Mosaic lowers
    these cleanly, whereas a ``[HW, G, C/G]`` reshape (tiny lane dim) does
    not."""
    ci = jax.lax.broadcasted_iota(jnp.int32, (c, groups), 0)
    gi = jax.lax.broadcasted_iota(jnp.int32, (c, groups), 1)
    return (ci // (c // groups) == gi).astype(jnp.float32)


def _fused_fwd_kernel(x_ref, scale_ref, bias_ref, *rest, groups: int,
                      eps: float, mode: str):
    if mode == "add_relu":
        res_ref, y_ref, mean_ref, rstd_ref = rest
    else:
        y_ref, mean_ref, rstd_ref = rest
    x = x_ref[0].astype(jnp.float32)                       # [HW, C]
    hw, c = x.shape
    n = hw * (c // groups)
    m = _group_matrix(c, groups)
    mean_g = (jnp.sum(x, 0, keepdims=True) @ m) / n        # [1, G]
    sumsq_g = jnp.sum(x * x, 0, keepdims=True) @ m
    var_g = sumsq_g / n - mean_g * mean_g
    rstd_g = jax.lax.rsqrt(var_g + eps)
    mean_c = mean_g @ m.T                                  # [1, C]
    rstd_c = rstd_g @ m.T
    y = ((x - mean_c) * rstd_c * scale_ref[...].astype(jnp.float32)
         + bias_ref[...].astype(jnp.float32))
    if mode == "add_relu":
        y = y + res_ref[0].astype(jnp.float32)
    if mode in ("relu", "add_relu"):
        y = jnp.maximum(y, 0.0)
    y_ref[0] = y.astype(y_ref.dtype)
    mean_ref[0] = mean_g
    rstd_ref[0] = rstd_g


def _fused_bwd_kernel(x_ref, dy_ref, scale_ref, bias_ref, mean_ref,
                      rstd_ref, *rest, groups: int, mode: str):
    if mode == "add_relu":
        res_ref, dx_ref, dscale_ref, dbias_ref, dres_ref = rest
    else:
        dx_ref, dscale_ref, dbias_ref = rest
    x = x_ref[0].astype(jnp.float32)                       # [HW, C]
    dy = dy_ref[0].astype(jnp.float32)
    hw, c = x.shape
    n = hw * (c // groups)
    m = _group_matrix(c, groups)
    mean_c = mean_ref[0] @ m.T                             # [1, C]
    rstd_c = rstd_ref[0] @ m.T
    scale = scale_ref[...].astype(jnp.float32)             # [1, C]
    xhat = (x - mean_c) * rstd_c
    if mode in ("relu", "add_relu"):
        pre = xhat * scale + bias_ref[...].astype(jnp.float32)
        if mode == "add_relu":
            pre = pre + res_ref[0].astype(jnp.float32)
        dy = jnp.where(pre > 0.0, dy, 0.0)
    if mode == "add_relu":
        dres_ref[0] = dy.astype(dres_ref.dtype)
    g = dy * scale
    m1_c = ((jnp.sum(g, 0, keepdims=True) @ m) / n) @ m.T
    m2_c = ((jnp.sum(g * xhat, 0, keepdims=True) @ m) / n) @ m.T
    dx = rstd_c * (g - m1_c - xhat * m2_c)
    dx_ref[0] = dx.astype(dx_ref.dtype)
    dscale_ref[0] = jnp.sum(dy * xhat, 0, keepdims=True)   # [1, C] partial
    dbias_ref[0] = jnp.sum(dy, 0, keepdims=True)


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _fused_call_fwd(x, scale, bias, residual, groups, eps, mode):
    from jax.experimental import pallas as pl

    b, h, w, c = x.shape
    hw = h * w
    x3 = x.reshape(b, hw, c)
    args = [x3, scale.reshape(1, c), bias.reshape(1, c)]
    in_specs = [
        pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
        pl.BlockSpec((1, c), lambda i: (0, 0)),
        pl.BlockSpec((1, c), lambda i: (0, 0)),
    ]
    if mode == "add_relu":
        args.append(residual.reshape(b, hw, c))
        in_specs.append(pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)))
    y, mean, rstd = pl.pallas_call(
        functools.partial(_fused_fwd_kernel, groups=groups, eps=eps,
                          mode=mode),
        grid=(b,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, groups), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, groups), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hw, c), x.dtype),
            jax.ShapeDtypeStruct((b, 1, groups), jnp.float32),
            jax.ShapeDtypeStruct((b, 1, groups), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    return y.reshape(b, h, w, c), mean, rstd


def _fused_call_bwd(x, dy, scale, bias, mean, rstd, residual, groups, mode):
    from jax.experimental import pallas as pl

    b, h, w, c = x.shape
    hw = h * w
    args = [x.reshape(b, hw, c), dy.reshape(b, hw, c),
            scale.reshape(1, c), bias.reshape(1, c), mean, rstd]
    in_specs = [
        pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
        pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
        pl.BlockSpec((1, c), lambda i: (0, 0)),
        pl.BlockSpec((1, c), lambda i: (0, 0)),
        pl.BlockSpec((1, 1, groups), lambda i: (i, 0, 0)),
        pl.BlockSpec((1, 1, groups), lambda i: (i, 0, 0)),
    ]
    out_specs = [
        pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
        pl.BlockSpec((1, 1, c), lambda i: (i, 0, 0)),
        pl.BlockSpec((1, 1, c), lambda i: (i, 0, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((b, hw, c), x.dtype),
        jax.ShapeDtypeStruct((b, 1, c), jnp.float32),
        jax.ShapeDtypeStruct((b, 1, c), jnp.float32),
    ]
    if mode == "add_relu":
        args.append(residual.reshape(b, hw, c))
        in_specs.append(pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)))
        out_specs.append(pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((b, hw, c), residual.dtype))
    outs = pl.pallas_call(
        functools.partial(_fused_bwd_kernel, groups=groups, mode=mode),
        grid=(b,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
    )(*args)
    dx = outs[0].reshape(b, h, w, c)
    dscale = jnp.sum(outs[1][:, 0], axis=0).astype(scale.dtype)  # -> [C]
    dbias = jnp.sum(outs[2][:, 0], axis=0).astype(bias.dtype)
    dres = outs[3].reshape(b, h, w, c) if mode == "add_relu" else None
    return dx, dscale, dbias, dres


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def group_norm_act(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray,
                   groups: int = 32, eps: float = 1e-6,
                   mode: str = "relu") -> jnp.ndarray:
    """Fused ``act(group_norm(x))`` (``mode`` in {"plain", "relu"}) as one
    Pallas kernel per direction — minimal HBM traffic (docstring above)."""
    y, _, _ = _fused_call_fwd(x, scale, bias, None, groups, eps, mode)
    return y


def _gna_fwd(x, scale, bias, groups, eps, mode):
    y, mean, rstd = _fused_call_fwd(x, scale, bias, None, groups, eps, mode)
    return y, (x, scale, bias, mean, rstd)


def _gna_bwd(groups, eps, mode, res, dy):
    x, scale, bias, mean, rstd = res
    dx, dscale, dbias, _ = _fused_call_bwd(
        x, dy, scale, bias, mean, rstd, None, groups, mode)
    return dx, dscale, dbias


group_norm_act.defvjp(_gna_fwd, _gna_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def group_norm_add_relu(x: jnp.ndarray, scale: jnp.ndarray,
                        bias: jnp.ndarray, residual: jnp.ndarray,
                        groups: int = 32, eps: float = 1e-6) -> jnp.ndarray:
    """Fused ``relu(group_norm(x) + residual)`` — the Bottleneck tail
    (`model_parallel_ResNet50.py:64-76`'s out += identity; relu) in one
    slab-resident kernel per direction."""
    y, _, _ = _fused_call_fwd(x, scale, bias, residual, groups, eps,
                              "add_relu")
    return y


def _gnar_fwd(x, scale, bias, residual, groups, eps):
    y, mean, rstd = _fused_call_fwd(x, scale, bias, residual, groups, eps,
                                    "add_relu")
    return y, (x, scale, bias, mean, rstd, residual)


def _gnar_bwd(groups, eps, res, dy):
    x, scale, bias, mean, rstd, residual = res
    dx, dscale, dbias, dres = _fused_call_bwd(
        x, dy, scale, bias, mean, rstd, residual, groups, "add_relu")
    return dx, dscale, dbias, dres


group_norm_add_relu.defvjp(_gnar_fwd, _gnar_bwd)


# Above this per-sample-slab f32 size the fused kernels would overflow
# VMEM (the backward holds ~6 slab-sized intermediates); fall back to the
# XLA closed-form path.  Every ResNet50 site at 128 px is <= 1 MB.
_FUSED_SLAB_LIMIT_BYTES = 2 * 1024 * 1024


def _slab_fits(x: jnp.ndarray) -> bool:
    b, h, w, c = x.shape
    return h * w * c * 4 <= _FUSED_SLAB_LIMIT_BYTES


class GroupNorm(nn.Module):
    """Drop-in ``nn.GroupNorm`` twin — same param names/shapes
    (``scale``/``bias`` of [C]) AND the same flax auto-name prefix
    (``GroupNorm_N``), so whole-model param trees are interchangeable with
    flax-normed ones.

    ``fused``:
      * ``"none"`` — :func:`group_norm` (closed-form VJP, XLA-fused);
      * ``"relu"`` — ``relu(gn(x))`` through the slab-resident Pallas
        kernel pair;
      * ``"add_relu"`` — ``relu(gn(x) + residual)`` (pass ``residual``),
        the ResNet Bottleneck tail.
    Oversized slabs (> ~2 MB f32 per sample) silently use the unfused
    closed-form math — identical numerics, just without the traffic win.
    """

    num_groups: int = 32
    epsilon: float = 1e-6
    dtype: jnp.dtype | None = None
    param_dtype: jnp.dtype = jnp.float32
    fused: str = "none"

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 residual: jnp.ndarray | None = None) -> jnp.ndarray:
        c = x.shape[-1]
        if c % self.num_groups:
            raise ValueError(
                f"channels {c} not divisible by num_groups {self.num_groups}")
        if (residual is not None) != (self.fused == "add_relu"):
            raise ValueError(
                f"residual must be passed exactly when fused='add_relu' "
                f"(got fused={self.fused!r})")
        scale = self.param("scale", nn.initializers.ones, (c,),
                           self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, (c,),
                          self.param_dtype)
        g, eps = self.num_groups, self.epsilon
        if self.fused == "none":
            y = group_norm(x, scale, bias, g, eps)
        elif self.fused == "relu":
            if _slab_fits(x):
                y = group_norm_act(x, scale, bias, g, eps, "relu")
            else:
                y = jax.nn.relu(group_norm(x, scale, bias, g, eps))
        elif self.fused == "add_relu":
            if _slab_fits(x):
                y = group_norm_add_relu(x, scale, bias, residual, g, eps)
            else:
                y = jax.nn.relu(
                    group_norm(x, scale, bias, g, eps)
                    + residual.astype(x.dtype))
        else:
            raise ValueError(f"unknown fused mode {self.fused!r}")
        return y.astype(self.dtype) if self.dtype is not None else y


GroupNormFast = GroupNorm  # explicit-intent alias
