"""Losses used across the workload twins.

Covers the reference's loss surface: CrossEntropyLoss
(`mnist_ddp_elastic.py:174`, `server_model_data_parallel.py:91`), NLL over
log_softmax outputs (`mnist_horovod.py:62`, `horovod_mnist_elastic.py:68`),
and MSE on one-hot targets (`model_parallel_ResNet50.py:203,223`).

All are computed from *logits* in float32, with the log-softmax fused into
the reduction by XLA (stable logsumexp form) — returning log-probs from the
model, as the reference's ``Net.forward`` does, would just be an unfused
version of the same graph.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.special import logsumexp


def log_softmax(logits: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    return logits - logsumexp(logits, axis=axis, keepdims=True)


def cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean softmax cross-entropy with integer labels."""
    return jnp.mean(cross_entropy_per_token(logits, labels))


def cross_entropy_per_token(
    logits: jnp.ndarray, labels: jnp.ndarray
) -> jnp.ndarray:
    """UNREDUCED cross-entropy, one value per label — the building block the
    sharded strategies need so they can sum locally and normalise by the
    *global* token count (see :func:`tpudist.parallel.make_sp_train_step`).

    Rank-general: ``logits [..., C]`` with ``labels [...]`` (any leading
    shape — [N, C]/[N] rows or [B, S, V]/[B, S] sequences).  The gather
    must expand labels on the LAST axis; ``labels[:, None]`` on a [B, S]
    batch would broadcast into a [B, S, S] gather of wrong targets whose
    optimum is near-uniform — a silent wrong-loss failure mode."""
    if logits.shape[:-1] != labels.shape:
        raise ValueError(
            f"logits {logits.shape} must be labels shape {labels.shape} "
            f"+ one trailing class axis")
    logp = log_softmax(logits.astype(jnp.float32))
    return -jnp.take_along_axis(
        logp, labels[..., None].astype(jnp.int32), axis=-1
    )[..., 0]


def nll_loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """The reference pairs log_softmax models with F.nll_loss; from logits
    the two compose to exactly :func:`cross_entropy`."""
    return cross_entropy(logits, labels)


def mse_loss(pred: jnp.ndarray, target: jnp.ndarray) -> jnp.ndarray:
    pred = pred.astype(jnp.float32)
    return jnp.mean(jnp.square(pred - target.astype(jnp.float32)))


def accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Fraction correct (sum form is assembled by callers when sharded)."""
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
