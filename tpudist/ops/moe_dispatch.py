"""Fused grouped-matmul kernel for MoE expert MLPs (megablocks-style).

Round-4 measured decomposition of the ragged MoE layer (4096 tokens,
E=8, top-2, d=512, f=2048 on a v5e): the XLA glue is NOT the main cost
once the counting sort is lane-major — the two ``jax.lax.ragged_dot``
calls themselves run at ~1.7× the equal-FLOP dense twin with real
(imbalanced) groups, and they round-trip the [T·k, f] intermediate
through HBM between them (~33 MB each way).  This kernel runs BOTH
expert matmuls in one ``pallas_call`` over block-aligned groups:

* the dispatch layout pads each expert's group start to the row-block
  size, so every [bn, d] input block belongs to EXACTLY one expert —
  the per-block expert id rides scalar-prefetch meta and selects the
  w_up/w_down blocks via their index maps (consecutive blocks of one
  expert keep the weights resident);
* ``h = gelu(xs @ w_up[e])`` stays in VMEM and feeds ``h @ w_down[e]``
  directly — the intermediate never touches HBM;
* the gate weight is folded into the epilogue (``y_slot *= gate_slot``),
  so the combine outside is a pure gather + k-sum.

A previous round-4 design absorbed the row GATHER into this kernel via
per-row async DMA; Mosaic rejects it (VMEM slices must be 8-sublane
aligned — single-row ``memref_slice`` of a [T, d] ref does not lower),
which is why TPU grouped-matmul kernels in the wild take pre-sorted
contiguous inputs.  The gather stays in XLA, where it measures a benign
~37 µs for 8192×512 bf16 rows.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _gmm_kernel(meta_ref, xs_ref, gate_ref, w_up_ref, w_down_ref, y_ref):
    """One grid step = one [bn, d] slot block of one expert: both expert
    matmuls back to back, gate folded into the epilogue."""
    xs = xs_ref[...]                                     # [bn, d]
    h = jax.lax.dot_general(
        xs, w_up_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    h = jax.nn.gelu(h).astype(xs.dtype)                  # [bn, f] in VMEM
    y = jax.lax.dot_general(
        h, w_down_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    y_ref[...] = (y * gate_ref[...]).astype(y_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _fused_moe_diff(x, w_up, w_down, top_idx, top_vals, block_rows,
                    interpret):
    return _fused_moe_fwd_only(x, w_up, w_down, top_idx, top_vals,
                               block_rows, interpret)


def _fused_fwd(x, w_up, w_down, top_idx, top_vals, block_rows, interpret):
    out = _fused_moe_fwd_only(x, w_up, w_down, top_idx, top_vals,
                              block_rows, interpret)
    return out, (x, w_up, w_down, top_idx, top_vals)


def _fused_bwd(block_rows, interpret, res, g):
    """Backward by REMATERIALIZATION through the differentiable ragged
    path (``pallas_call`` has no autodiff rule): one extra forward's
    FLOPs in exchange for a trainable fused dispatch — same trade the
    remat'd transformer blocks make."""
    import numpy as np

    from tpudist.models.moe import _ragged_moe

    x, w_up, w_down, top_idx, top_vals = res
    _, vjp = jax.vjp(
        lambda xx, wu, wd, tv: _ragged_moe(xx, wu, wd, top_idx, tv),
        x, w_up, w_down, top_vals)
    dx, dwu, dwd, dtv = vjp(g)
    d_idx = np.zeros(top_idx.shape, dtype=jax.dtypes.float0)
    return dx, dwu, dwd, d_idx, dtv


_fused_moe_diff.defvjp(_fused_fwd, _fused_bwd)


def fused_moe_mlp(x: jnp.ndarray, w_up: jnp.ndarray, w_down: jnp.ndarray,
                  top_idx: jnp.ndarray, top_vals: jnp.ndarray,
                  *, block_rows: int = 128,
                  interpret: bool | None = None) -> jnp.ndarray:
    """MoE MLP layer through the fused grouped-matmul kernel.

    Same contract as ``tpudist.models.moe._ragged_moe``: ``x [T, d]``,
    stacked expert weights ``w_up [E, d, f]`` / ``w_down [E, f, d]``,
    router choices ``top_idx/top_vals [T, k]``; returns ``[T, d]``.
    Exact (no capacity, no dropping): group starts are padded to
    ``block_rows``; pad slots carry gate 0 and their rows are never read
    by the combine.  Differentiable: the backward rematerializes through
    the ragged XLA path (see ``_fused_bwd``).
    """
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    return _fused_moe_diff(x, w_up, w_down, top_idx, top_vals,
                           block_rows, interpret)


def _fused_moe_fwd_only(x, w_up, w_down, top_idx, top_vals, block_rows,
                        interpret):
    t, d = x.shape
    e, _, f = w_up.shape
    k = top_idx.shape[1]
    n = t * k
    bn = block_rows

    # shared lane-major counting sort, block-aligned group starts
    from tpudist.models.moe import _counting_sort

    pos, order, _, starts, np_pad = _counting_sort(
        top_idx.reshape(-1), e, block_rows=bn)
    nb = np_pad // bn
    xs = x[order // k]                                    # [NP, d] sorted rows
    gate = jnp.zeros((np_pad, 1), jnp.float32).at[pos, 0].set(
        top_vals.reshape(-1).astype(jnp.float32))         # pad slots: gate 0
    # block -> expert id
    block_ids = jnp.zeros((nb,), jnp.int32).at[
        jnp.minimum(starts // bn, nb - 1)].add(1)
    block_expert = jnp.clip(jnp.cumsum(block_ids) - 1, 0, e - 1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,                            # block_expert
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((bn, d), lambda b, m: (b, 0)),
            pl.BlockSpec((bn, 1), lambda b, m: (b, 0)),
            pl.BlockSpec((1, d, f), lambda b, m: (m[b], 0, 0)),
            pl.BlockSpec((1, f, d), lambda b, m: (m[b], 0, 0)),
        ],
        out_specs=pl.BlockSpec((bn, d), lambda b, m: (b, 0)),
    )
    ys = pl.pallas_call(
        _gmm_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((np_pad, d), x.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(block_expert, xs, gate, w_up, w_down)

    # combine: gate already folded in-kernel — gather + sum over choices
    return jnp.sum(ys[pos].reshape(t, k, d), axis=1).astype(x.dtype)
