"""Parallelism as ONE composed mesh, not five strategies.

The entry point is :mod:`tpudist.parallel.mesh`: declare axis sizes in a
:class:`~tpudist.parallel.mesh.MeshSpec` (``dp`` batch replication,
``fsdp`` ZeRO parameter sharding, ``tp`` tensor rules, ``pp`` pipeline
schedule, ``ep`` expert sharding) and
:func:`~tpudist.parallel.mesh.make_composed_train_step` compiles one step
for that point of the composition space — ``MeshSpec(dp=2, fsdp=2, tp=2)``
trains the same model the same way ``MeshSpec(tp=4)`` does, with no
per-strategy wiring.  The per-axis modules remain as the building blocks
the composition reuses (and as standalone references the composed step is
bitwise-tested against): :mod:`data_parallel` explicit ``psum`` DP,
:mod:`tensor_parallel` GSPMD rule programs, :mod:`fsdp` ZeRO specs and the
explicit gather/scatter step, :mod:`expert_parallel` MoE expert sharding,
:mod:`pipeline` compiled GPipe/1F1B/interleaved schedules (``pp`` stays a
schedule in time, not a GSPMD layout — see docs/DESIGN.md "One mesh
spec"), plus :mod:`ps_hybrid` and :mod:`ring_attention` for the
parameter-server and sequence-parallel specials.
"""

from tpudist.parallel.data_parallel import (
    broadcast_params,
    make_dp_eval_step,
    make_dp_train_loop,
    make_dp_train_step,
)
from tpudist.parallel.expert_parallel import (
    make_ep_shard_train_step,
    make_ep_state,
    make_ep_train_step,
    moe_ep_rules,
)
from tpudist.parallel.fsdp import (
    fsdp_specs,
    make_fsdp_state,
    make_fsdp_train_step,
    make_zero3_train_step,
)
from tpudist.parallel.pipeline import (
    StagePacking,
    interleave_params,
    make_1f1b_pipeline_train_step,
    make_interleaved_pipeline_train_step,
    make_packed_pipeline_train_step,
    make_pipeline_forward,
    make_pipeline_train_step,
    make_stacked_pipeline_train_step,
    pack_stage_params,
    stacked_state_specs,
    state_specs_like,
    unpack_stage_params,
)
from tpudist.parallel.mesh import (
    MESH_AXES,
    MeshSpec,
    make_composed_eval_step,
    make_composed_state,
    make_composed_train_step,
    shard_composed_batch,
)
from tpudist.parallel.ps_hybrid import (
    make_ps_hybrid_forward,
    make_ps_hybrid_train_step,
    ps_state_specs,
    sharded_bag_lookup,
)
from tpudist.parallel.ring_attention import (
    make_sp_train_step,
    ring_attention_fn,
    ring_flash_attention_fn,
    sp_forward,
    ulysses_attention_fn,
)
from tpudist.parallel.tensor_parallel import (
    make_spmd_train_step,
    make_tp_state,
    shard_batch,
    shard_tree,
    spec_tree_from_rules,
    transformer_tp_rules,
)

__all__ = [
    "MESH_AXES",
    "MeshSpec",
    "make_composed_eval_step",
    "make_composed_state",
    "make_composed_train_step",
    "shard_composed_batch",
    "broadcast_params",
    "fsdp_specs",
    "make_ep_shard_train_step",
    "make_ep_state",
    "make_ep_train_step",
    "make_fsdp_state",
    "make_fsdp_train_step",
    "make_zero3_train_step",
    "moe_ep_rules",
    "make_sp_train_step",
    "make_spmd_train_step",
    "make_tp_state",
    "ring_attention_fn",
    "ring_flash_attention_fn",
    "sp_forward",
    "ulysses_attention_fn",
    "shard_batch",
    "shard_tree",
    "spec_tree_from_rules",
    "transformer_tp_rules",
    "make_dp_eval_step",
    "make_dp_train_loop",
    "make_dp_train_step",
    "StagePacking",
    "interleave_params",
    "make_1f1b_pipeline_train_step",
    "make_interleaved_pipeline_train_step",
    "make_packed_pipeline_train_step",
    "make_pipeline_forward",
    "make_pipeline_train_step",
    "pack_stage_params",
    "unpack_stage_params",
    "make_ps_hybrid_forward",
    "make_ps_hybrid_train_step",
    "make_stacked_pipeline_train_step",
    "ps_state_specs",
    "sharded_bag_lookup",
    "stacked_state_specs",
    "state_specs_like",
]
