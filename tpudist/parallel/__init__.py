"""Parallelism strategies over mesh axes.

The reference's L3 layer (SURVEY.md §1): DDP / Horovod data parallelism →
:mod:`data_parallel` (explicit ``psum`` over ICI); the RPC micro-batched
pipeline → :mod:`pipeline` (``ppermute`` + ``lax.scan`` schedules); the
parameter-server hybrid → :mod:`ps_hybrid` (model-axis-sharded embedding +
data-parallel dense).  Distributed autograd and DistributedOptimizer have no
counterpart here because ``jax.grad`` + optax work through shardings natively
(SURVEY.md §2.2).
"""

from tpudist.parallel.data_parallel import (
    broadcast_params,
    make_dp_eval_step,
    make_dp_train_loop,
    make_dp_train_step,
)
from tpudist.parallel.expert_parallel import (
    make_ep_shard_train_step,
    make_ep_state,
    make_ep_train_step,
    moe_ep_rules,
)
from tpudist.parallel.fsdp import (
    fsdp_specs,
    make_fsdp_state,
    make_fsdp_train_step,
    make_zero3_train_step,
)
from tpudist.parallel.pipeline import (
    StagePacking,
    interleave_params,
    make_1f1b_pipeline_train_step,
    make_interleaved_pipeline_train_step,
    make_packed_pipeline_train_step,
    make_pipeline_forward,
    make_pipeline_train_step,
    make_stacked_pipeline_train_step,
    pack_stage_params,
    stacked_state_specs,
    state_specs_like,
    unpack_stage_params,
)
from tpudist.parallel.ps_hybrid import (
    make_ps_hybrid_forward,
    make_ps_hybrid_train_step,
    ps_state_specs,
    sharded_bag_lookup,
)
from tpudist.parallel.ring_attention import (
    make_sp_train_step,
    ring_attention_fn,
    ring_flash_attention_fn,
    sp_forward,
    ulysses_attention_fn,
)
from tpudist.parallel.tensor_parallel import (
    make_spmd_train_step,
    make_tp_state,
    shard_batch,
    shard_tree,
    spec_tree_from_rules,
    transformer_tp_rules,
)

__all__ = [
    "broadcast_params",
    "fsdp_specs",
    "make_ep_shard_train_step",
    "make_ep_state",
    "make_ep_train_step",
    "make_fsdp_state",
    "make_fsdp_train_step",
    "make_zero3_train_step",
    "moe_ep_rules",
    "make_sp_train_step",
    "make_spmd_train_step",
    "make_tp_state",
    "ring_attention_fn",
    "ring_flash_attention_fn",
    "sp_forward",
    "ulysses_attention_fn",
    "shard_batch",
    "shard_tree",
    "spec_tree_from_rules",
    "transformer_tp_rules",
    "make_dp_eval_step",
    "make_dp_train_loop",
    "make_dp_train_step",
    "StagePacking",
    "interleave_params",
    "make_1f1b_pipeline_train_step",
    "make_interleaved_pipeline_train_step",
    "make_packed_pipeline_train_step",
    "make_pipeline_forward",
    "make_pipeline_train_step",
    "pack_stage_params",
    "unpack_stage_params",
    "make_ps_hybrid_forward",
    "make_ps_hybrid_train_step",
    "make_stacked_pipeline_train_step",
    "ps_state_specs",
    "sharded_bag_lookup",
    "stacked_state_specs",
    "state_specs_like",
]
