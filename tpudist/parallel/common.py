"""Shared machinery for the parallelism strategies.

Every strategy in this package compiles to the same shape of program: a pure
per-shard step wrapped in ``jax.shard_map`` over the mesh, jitted with
optional donation of the (large) train-state argument.  This helper is that
one pattern, in one place.

``check_vma=False``: the steps use collectives whose transpose under
``jax.grad`` is relied upon explicitly (see the masked-loss notes in
:mod:`tpudist.parallel.ps_hybrid`); varying-manual-axes checking rejects
some of those patterns, so it stays off for all strategies uniformly.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
from jax.sharding import Mesh


def jit_sharded_step(
    fn: Callable,
    mesh: Mesh,
    in_specs: Any,
    out_specs: Any,
    donate_first: bool = True,
) -> Callable:
    """``jit(shard_map(fn, mesh, ...))``, donating the first argument
    (the train state) when ``donate_first``."""
    sharded = jax.shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate_first else ())
