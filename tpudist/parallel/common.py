"""Shared machinery for the parallelism strategies.

Every strategy in this package compiles to the same shape of program: a pure
per-shard step wrapped in ``jax.shard_map`` over the mesh, jitted with
optional donation of the (large) train-state argument.  This helper is that
one pattern, in one place.

``check_vma=False``: the steps use collectives whose transpose under
``jax.grad`` is relied upon explicitly (see the masked-loss notes in
:mod:`tpudist.parallel.ps_hybrid`); varying-manual-axes checking rejects
some of those patterns, so it stays off for all strategies uniformly.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
from jax import lax
from jax.sharding import Mesh


def jit_sharded_step(
    fn: Callable,
    mesh: Mesh,
    in_specs: Any,
    out_specs: Any,
    donate_first: bool = True,
) -> Callable:
    """``jit(shard_map(fn, mesh, ...))``, donating the first argument
    (the train state) when ``donate_first``."""
    sharded = jax.shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate_first else ())


# --------------------------------------------------------------------------
# AD-correct manual collectives (the Megatron "f"/"g" operators)
# --------------------------------------------------------------------------
#
# Under ``shard_map`` with ``check_vma=False``, the transpose of ``psum``
# is ``psum`` — so differentiating a row-parallel matmul's output psum
# would scale cotangents by the axis size.  The classic fix is a pair of
# custom-vjp operators:
#
#   ``psum_fwd_id_bwd``  — psum forward, identity backward ("g"): ends a
#     row-parallel layer (partial sums join; the cotangent is already
#     replicated, so backward passes it through).
#   ``id_fwd_psum_bwd``  — identity forward, psum backward ("f"): starts a
#     column-parallel layer from a replicated activation (forward is a
#     no-op; the backward sums each shard's cotangent contribution).

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def psum_fwd_id_bwd(x, axis_name: str):
    """``psum`` over ``axis_name`` whose VJP is the identity."""
    return lax.psum(x, axis_name)


def _psum_id_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _psum_id_bwd(axis_name, _, ct):
    return (ct,)


psum_fwd_id_bwd.defvjp(_psum_id_fwd, _psum_id_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def id_fwd_psum_bwd(x, axis_name: str):
    """Identity whose VJP is a ``psum`` over ``axis_name``."""
    return x


def _id_psum_fwd(x, axis_name):
    return x, None


def _id_psum_bwd(axis_name, _, ct):
    return (lax.psum(ct, axis_name),)


id_fwd_psum_bwd.defvjp(_id_psum_fwd, _id_psum_bwd)
