"""Data parallelism: explicit gradient allreduce over the mesh's data axis.

This is the TPU-native form of both DDP (`mnist_ddp_elastic.py:58` — gradient
allreduce in backward hooks over gloo) and Horovod's
``DistributedOptimizer`` + ring allreduce (`mnist_horovod.py:53` — SURVEY.md
§2.2): the train step runs SPMD under :func:`jax.shard_map` with the batch
split along ``data`` and params replicated; one ``lax.pmean`` over the axis
is the gradient sync, lowered by XLA to a fused ICI all-reduce.  Horovod's
tensor-fusion buckets come for free — XLA coalesces the whole grad pytree
into large collective ops.

``broadcast_params`` is the ``hvd.broadcast_parameters(root_rank=0)``
equivalent (`mnist_horovod.py:56`): on TPU, params created once on the host
and ``device_put`` with a replicated sharding ARE identical on every device,
so the broadcast is a placement, not a collective protocol.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist.parallel.common import jit_sharded_step

if False:  # typing only; a runtime import would cycle through tpudist.train
    from tpudist.train.state import TrainState  # noqa: F401

# loss_fn(params, batch, rng) -> (loss, aux_dict); batch is a tuple of arrays
LossFn = Callable[[Any, tuple, jax.Array], tuple[jnp.ndarray, dict]]


def broadcast_params(tree: Any, mesh: Mesh) -> Any:
    """Replicate a host/device pytree identically onto every mesh device.

    Device-array leaves are copied first: ``device_put`` may alias the input
    buffer as one replica of the result, and the train step's buffer donation
    would then silently delete the *caller's* array.
    """
    sharding = NamedSharding(mesh, P())

    def put(x):
        if isinstance(x, jax.Array):
            x = jnp.array(x, copy=True)
        return jax.device_put(x, sharding)

    return jax.tree.map(put, tree)


def _dp_step_body(loss_fn: LossFn, axis: str, accum_steps: int = 1):
    """One SPMD data-parallel step: local grads on the batch shard, pmean
    over ``axis`` (THE all-reduce), redundant-but-identical optax update.

    With ``accum_steps > 1`` the local shard is processed as that many
    sequential micro-batches whose gradients average on-device before the
    cross-shard pmean — same numerics as the single pass (mean of equal
    chunk-means = global mean), peak activation memory divided by
    ``accum_steps``.
    """

    def _grads(params, batch, shard_rng):
        if accum_steps == 1:
            return jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch, shard_rng)
        for leaf in jax.tree.leaves(batch):
            if leaf.shape[0] % accum_steps:
                raise ValueError(
                    f"per-shard batch {leaf.shape[0]} not divisible by "
                    f"accum_steps={accum_steps}")
        micro = jax.tree.map(
            lambda x: x.reshape(accum_steps, x.shape[0] // accum_steps,
                                *x.shape[1:]), batch)
        micro0 = jax.tree.map(lambda x: x[0], micro)
        _, aux_struct = jax.eval_shape(loss_fn, params, micro0, shard_rng)
        zeros = lambda tree: jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), tree)

        def body(carry, xs):
            i, mb = xs
            (l, aux), g = jax.value_and_grad(loss_fn, has_aux=True)(
                params, mb, jax.random.fold_in(shard_rng, i))
            g_acc, l_acc, aux_acc = carry
            return (jax.tree.map(jnp.add, g_acc, g), l_acc + l,
                    jax.tree.map(jnp.add, aux_acc, aux)), None

        init = (jax.tree.map(jnp.zeros_like, params), jnp.zeros(()),
                zeros(aux_struct))
        (g, l, aux), _ = lax.scan(
            body, init, (jnp.arange(accum_steps), micro))
        inv = 1.0 / accum_steps
        return (l * inv, jax.tree.map(lambda a: a * inv, aux)), jax.tree.map(
            lambda a: a * inv, g)

    def _step(state, batch):
        # Distinct dropout/augmentation stream per data shard, common stream
        # for anything that must agree across shards.
        shard_rng = jax.random.fold_in(state.rng, lax.axis_index(axis))
        (loss, aux), grads = _grads(state.params, batch, shard_rng)
        grads = lax.pmean(grads, axis)
        metrics = {"loss": lax.pmean(loss, axis), **
                   {k: lax.pmean(v, axis) for k, v in aux.items()}}
        return state.apply_gradients(grads), metrics

    return _step


def make_dp_train_step(
    loss_fn: LossFn,
    mesh: Mesh,
    axis: str = "data",
    donate: bool = True,
    accum_steps: int = 1,
):
    """Build ``train_step(state, *batch) -> (state, metrics)``.

    The returned step is jit-compiled over ``mesh``; per-device it computes
    local grads on its batch shard, ``pmean``s them over ``axis``, and
    applies the optax update redundantly-but-identically on every device —
    the same contract DDP/Horovod give, without a wrapper object or hooks.

    ``accum_steps`` enables gradient accumulation: the local batch shard is
    split into that many sequential micro-batches (shard size must divide),
    trading step latency for ``accum_steps×`` lower activation memory.
    ``aux`` entries returned by ``loss_fn`` must be mean-style scalars —
    they are averaged across micro-batches.
    """
    stepped = jit_sharded_step(
        _dp_step_body(loss_fn, axis, accum_steps), mesh,
        (P(), P(axis)), (P(), P()), donate
    )

    def train_step(state, *batch):
        return stepped(state, batch)

    # the closure hides the jit; expose its lower() so the trainer's
    # one-time cost probe can read cost_analysis()/HLO without another
    # trace path (tpudist.obs.xla.cost_flops, recorder.note_hlo)
    train_step.lower = lambda state, *batch: stepped.lower(state, batch)
    return train_step


def make_dp_train_loop(
    loss_fn: LossFn,
    mesh: Mesh,
    axis: str = "data",
    donate: bool = True,
):
    """Build ``train_loop(state, *batches) -> (state, metrics)`` running N
    optimizer steps in ONE compiled program (``lax.scan`` over a leading
    steps dimension).

    Each batch array is ``[n_steps, global_batch, ...]``, sharded on
    ``axis`` along the batch dimension.  Semantically identical to calling
    :func:`make_dp_train_step` ``n_steps`` times (the rng advances through
    ``apply_gradients`` exactly the same way), but with one host dispatch
    per N steps instead of per step — the idiom that keeps small-model
    training MXU-bound instead of dispatch-bound.  Metrics come back
    stacked, ``[n_steps]`` per entry.
    """
    body = _dp_step_body(loss_fn, axis)

    def _loop(state, batches):
        return lax.scan(body, state, batches)

    stepped = jit_sharded_step(
        _loop, mesh, (P(), P(None, axis)), (P(), P()), donate
    )

    def train_loop(state, *batches):
        return stepped(state, batches)

    train_loop.lower = lambda state, *batches: stepped.lower(state, batches)
    return train_loop


def make_dp_eval_step(
    predict_fn: Callable[[Any, tuple], jnp.ndarray],
    mesh: Mesh,
    axis: str = "data",
):
    """Build ``eval_step(params, *batch_with_labels) -> correct_count``.

    Counts (not fractions) are psum'd; the caller divides by the number of
    samples it actually fed (with ``drop_last=False`` loaders that includes
    wrap-around-padded duplicates — use :func:`make_dp_masked_eval_step`
    with ``ShardedLoader.valid_mask`` for duplicate-exact accuracy, as
    ``Trainer.test`` does).  The reference evaluates the full
    (sampler-sharded) test set on every rank and prints per-rank accuracy
    (`mnist_ddp_elastic.py:117-130`); here every shard evaluates its slice
    once and the global count is exact.
    """

    def _step(params, batch):
        *inputs, labels = batch
        logits = predict_fn(params, tuple(inputs))
        correct = jnp.sum((jnp.argmax(logits, -1) == labels).astype(jnp.int32))
        return lax.psum(correct, axis)

    stepped = jit_sharded_step(
        _step, mesh, (P(), P(axis)), P(), donate_first=False
    )

    def eval_step(params, *batch):
        return stepped(params, batch)

    return eval_step


def make_dp_masked_eval_step(
    predict_fn: Callable[[Any, tuple], jnp.ndarray],
    mesh: Mesh,
    axis: str = "data",
):
    """Exact-count evaluation: ``eval_step(params, *inputs, labels, mask)
    -> (correct, total)`` where ``mask`` (bool, batch-aligned) marks real
    samples — wrap-around padding from ``drop_last=False`` sharding
    (``ShardedLoader.valid_mask``) contributes to neither count, so
    accuracy is exact over the true dataset regardless of padding."""

    def _step(params, batch):
        *inputs, labels, mask = batch
        logits = predict_fn(params, tuple(inputs))
        hit = (jnp.argmax(logits, -1) == labels) & mask
        correct = jnp.sum(hit.astype(jnp.int32))
        total = jnp.sum(mask.astype(jnp.int32))
        return lax.psum(correct, axis), lax.psum(total, axis)

    stepped = jit_sharded_step(
        _step, mesh, (P(), P(axis)), (P(), P()), donate_first=False
    )

    def eval_step(params, *batch):
        return stepped(params, batch)

    return eval_step
