"""Expert parallelism: MoE expert weights sharded over an ``expert`` axis.

Absent from the reference (SURVEY.md §2.3: "EP — NO"); included so the
parallelism alphabet is complete.  Like FSDP and tensor parallelism this is
a *layout* on TPU: the stacked expert weights ``[E, d, f]`` get
``P("expert", None, None)``, the router stays replicated, and GSPMD lowers
the two dispatch einsums of :class:`tpudist.models.moe.MoEMLP` into the
all-to-alls that define expert parallelism — the same collectives a
parameter server (`server_model_data_parallel.py:134-139`) emulated with
RPC, riding ICI instead.

Composes with data parallelism on a 2-D ``(data, expert)`` mesh in one jit:
batch sharded over ``data``, experts over ``expert``.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpudist.parallel.common import jit_sharded_step
from tpudist.parallel.tensor_parallel import (
    Rules,
    make_spmd_train_step,
    make_tp_state,
    spec_tree_from_rules,
)
from tpudist.train.state import TrainState


def moe_ep_rules(axis: str = "expert") -> Rules:
    """Partition rules for :class:`~tpudist.models.moe.MoETransformerLM`:
    expert weight stacks sharded on their expert dim, router replicated,
    everything else (attention, embeddings, norms) replicated — compose with
    :func:`~tpudist.parallel.tensor_parallel.transformer_tp_rules` or
    :func:`~tpudist.parallel.fsdp.fsdp_specs` for richer layouts."""
    return [
        (r"moe/w_up", P(axis, None, None)),
        (r"moe/w_down", P(axis, None, None)),
        (r"moe/router", P()),
    ]


def make_ep_state(
    model_apply: Callable,
    params: Any,
    tx,
    mesh: Mesh,
    axis: str = "expert",
    extra_rules: Rules = (),
    rng: jax.Array | int = 0,
) -> tuple[TrainState, Any]:
    """Shard MoE params over ``axis`` (plus any ``extra_rules``, which win
    on conflict) and build the TrainState; optimizer state inherits the
    shardings.  Returns ``(state, param_specs)``."""
    rules = list(extra_rules) + list(moe_ep_rules(axis))
    return make_tp_state(model_apply, params, tx, mesh, rules=rules, rng=rng)


def make_ep_train_step(
    loss_fn,
    mesh: Mesh,
    param_specs: Any,
    donate: bool = True,
):
    """DP×EP train step — one GSPMD program.  GSPMD keeps expert weights
    device-local but (measured, ``tests/test_moe.py``) lowers the dense
    dispatch as replicate-tokens + all-reduce rather than all-to-all; for
    the guaranteed all-to-all token dispatch use
    :func:`make_ep_shard_train_step`."""
    return make_spmd_train_step(loss_fn, mesh, param_specs, donate)


def make_ep_shard_train_step(
    loss_fn: Callable[[Any, tuple], jnp.ndarray],
    mesh: Mesh,
    state_example,
    data_axis: str = "data",
    expert_axis: str = "expert",
    donate: bool = True,
):
    """Explicit-collective DP×EP step under ``shard_map`` — the canonical
    all-to-all dispatch, asserted rather than hoped for.

    Contract:

    * the model is built with ``ep_axis=expert_axis``
      (:class:`tpudist.models.moe.MoETransformerLM`), so each MoE layer
      routes local tokens to all experts and ships the batches through
      ``lax.all_to_all`` to the expert owners (and back);
    * the BATCH dimension of every batch array is sharded over BOTH axes
      (``P((data_axis, expert_axis))``) — each device holds
      ``B / (nd·ne)`` whole sequences;
    * ``loss_fn(params, batch) -> scalar`` returns this shard's loss
      CONTRIBUTION such that the global loss is the ``psum`` over all
      shards — i.e. per-token sums divided by the GLOBAL token count, plus
      any aux terms divided by the shard count.

    Gradients: expert-sharded leaves receive complete expert-axis
    gradients through the transposed all-to-alls (cotangents route back to
    the expert owners), so they only psum over ``data_axis``; replicated
    leaves (attention, router, embeddings) see local-token partials and
    psum over both axes.
    """
    param_specs = spec_tree_from_rules(
        state_example.params, moe_ep_rules(expert_axis))
    from tpudist.parallel.pipeline import _spec_axes, state_specs_like

    state_specs = state_specs_like(state_example, param_specs)
    spec_leaves = jax.tree.leaves(
        param_specs, is_leaf=lambda x: isinstance(x, P))
    sync_per_leaf = [
        (data_axis,) if expert_axis in _spec_axes(s)
        else (data_axis, expert_axis)
        for s in spec_leaves]

    def _step(state, batch):
        def local_loss(params):
            return loss_fn(params, batch)

        loss, grads = jax.value_and_grad(local_loss)(state.params)
        leaves, treedef = jax.tree.flatten(grads)
        leaves = [lax.psum(g, axes) for g, axes in zip(leaves, sync_per_leaf)]
        grads = jax.tree.unflatten(treedef, leaves)
        metrics = {"loss": lax.psum(loss, (data_axis, expert_axis))}
        return state.apply_gradients(grads), metrics

    stepped = jit_sharded_step(
        _step, mesh, (state_specs, P((data_axis, expert_axis))),
        (state_specs, P()), donate,
    )

    def train_step(state, *batch):
        return stepped(state, batch)

    train_step.lower = lambda state, *batch: stepped.lower(state, batch)
    train_step.jitted = stepped  # for HLO schedule assertions
    return train_step
