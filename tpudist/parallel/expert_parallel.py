"""Expert parallelism: MoE expert weights sharded over an ``expert`` axis.

Absent from the reference (SURVEY.md §2.3: "EP — NO"); included so the
parallelism alphabet is complete.  Like FSDP and tensor parallelism this is
a *layout* on TPU: the stacked expert weights ``[E, d, f]`` get
``P("expert", None, None)``, the router stays replicated, and GSPMD lowers
the two dispatch einsums of :class:`tpudist.models.moe.MoEMLP` into the
all-to-alls that define expert parallelism — the same collectives a
parameter server (`server_model_data_parallel.py:134-139`) emulated with
RPC, riding ICI instead.

Composes with data parallelism on a 2-D ``(data, expert)`` mesh in one jit:
batch sharded over ``data``, experts over ``expert``.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
from jax.sharding import Mesh, PartitionSpec as P

from tpudist.parallel.tensor_parallel import (
    Rules,
    make_spmd_train_step,
    make_tp_state,
)
from tpudist.train.state import TrainState


def moe_ep_rules(axis: str = "expert") -> Rules:
    """Partition rules for :class:`~tpudist.models.moe.MoETransformerLM`:
    expert weight stacks sharded on their expert dim, router replicated,
    everything else (attention, embeddings, norms) replicated — compose with
    :func:`~tpudist.parallel.tensor_parallel.transformer_tp_rules` or
    :func:`~tpudist.parallel.fsdp.fsdp_specs` for richer layouts."""
    return [
        (r"moe/w_up", P(axis, None, None)),
        (r"moe/w_down", P(axis, None, None)),
        (r"moe/router", P()),
    ]


def make_ep_state(
    model_apply: Callable,
    params: Any,
    tx,
    mesh: Mesh,
    axis: str = "expert",
    extra_rules: Rules = (),
    rng: jax.Array | int = 0,
) -> tuple[TrainState, Any]:
    """Shard MoE params over ``axis`` (plus any ``extra_rules``, which win
    on conflict) and build the TrainState; optimizer state inherits the
    shardings.  Returns ``(state, param_specs)``."""
    rules = list(extra_rules) + list(moe_ep_rules(axis))
    return make_tp_state(model_apply, params, tx, mesh, rules=rules, rng=rng)


def make_ep_train_step(
    loss_fn,
    mesh: Mesh,
    param_specs: Any,
    donate: bool = True,
):
    """DP×EP train step — one GSPMD program; the expert-dim shardings in
    ``param_specs`` make the dispatch/return einsums all-to-alls."""
    return make_spmd_train_step(loss_fn, mesh, param_specs, donate)
