"""FSDP / ZeRO-3: parameters and optimizer state sharded over the data axis.

Absent from the reference (SURVEY.md §2.3: "FSDP / ZeRO — NO"), but a
framework a DDP user (`mnist_ddp_elastic.py:58`) migrates to must offer the
memory-scaled variant of data parallelism.  On TPU it is a *layout*, not a
runtime: shard every parameter leaf across the ``data`` axis and jit — GSPMD
inserts the all-gather before each use and turns the gradient reduction into
a reduce-scatter, which is exactly the ZeRO-3 schedule.  Optimizer state
created from sharded params inherits the sharding, so Adam moments are
sharded too (ZeRO-1/2 come for free).

Composable with tensor parallelism: pass ``tp_rules`` and leaves matching a
TP pattern keep their model-axis sharding while the FSDP axis shards one of
the remaining dims — the standard 2-D (fsdp × model) layout.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpudist.parallel.common import jit_sharded_step
from tpudist.parallel.tensor_parallel import (
    Rules,
    make_spmd_train_step,
    shard_tree,
    spec_tree_from_rules,
)
from tpudist.train.state import TrainState


def _shard_leaf_spec(shape: tuple[int, ...], axis: str, axis_size: int,
                     taken: P | None = None) -> P:
    """Spec sharding the largest not-yet-taken dim divisible by ``axis_size``
    over ``axis``; replicated when nothing divides (e.g. small biases)."""
    base = list(taken) if taken is not None else [None] * len(shape)
    base += [None] * (len(shape) - len(base))
    candidates = [
        (shape[d], d) for d in range(len(shape))
        if base[d] is None and shape[d] % axis_size == 0 and shape[d] >= axis_size
    ]
    if not candidates:
        return P(*base) if taken is not None else P()
    _, dim = max(candidates)
    base[dim] = axis
    return P(*base)


def fsdp_specs(
    params: Any,
    mesh: Mesh,
    axis: str = "data",
    tp_rules: Optional[Rules] = None,
) -> Any:
    """PartitionSpec tree sharding every leaf over ``axis``.

    Each leaf gets its largest ``axis_size``-divisible dimension sharded;
    indivisible leaves replicate.  With ``tp_rules``, leaves matching a rule
    start from that model-axis spec and the FSDP axis takes a remaining dim.
    """
    axis_size = mesh.shape[axis]
    tp_specs = (
        spec_tree_from_rules(params, tp_rules) if tp_rules is not None else None
    )

    def spec_for(leaf, tp_spec):
        taken = tp_spec if tp_spec is not None and tuple(tp_spec) else None
        return _shard_leaf_spec(leaf.shape, axis, axis_size, taken)

    if tp_specs is None:
        return jax.tree.map(lambda leaf: spec_for(leaf, None), params)
    return jax.tree.map(spec_for, params, tp_specs)


def make_fsdp_state(
    model_apply: Callable,
    params: Any,
    tx,
    mesh: Mesh,
    axis: str = "data",
    tp_rules: Optional[Rules] = None,
    rng: jax.Array | int = 0,
) -> tuple[TrainState, Any]:
    """Shard ``params`` FSDP-style and build a TrainState whose optimizer
    state inherits the shardings.  Returns ``(state, param_specs)``."""
    specs = fsdp_specs(params, mesh, axis, tp_rules)
    sharded = shard_tree(params, mesh, specs)
    state = TrainState.create(model_apply, sharded, tx, rng=rng)
    return state, specs


def make_fsdp_train_step(
    loss_fn,
    mesh: Mesh,
    param_specs: Any,
    donate: bool = True,
):
    """ZeRO-3 train step, GSPMD-scheduled: identical program to
    :func:`make_spmd_train_step`; with ``param_specs`` from
    :func:`fsdp_specs` the compiler partitions it with params/moments
    stored sharded and all-gathers per use.  Measured caveat
    (``tests/test_fsdp.py``): GSPMD reduces gradients with a full
    ALL-REDUCE rather than a reduce-scatter — transiently materializing
    unsharded gradients (ZeRO-2-style grad memory).  For the guaranteed
    reduce-scatter schedule use :func:`make_zero3_train_step`."""
    return make_spmd_train_step(loss_fn, mesh, param_specs, donate)


def _fsdp_sharded_dim(spec: P, axis: str) -> int | None:
    for i, part in enumerate(spec):
        if part == axis or (isinstance(part, tuple) and axis in part):
            return i
    return None


def make_zero3_train_step(
    loss_fn,
    mesh: Mesh,
    param_specs: Any,
    state_example,
    axis: str = "data",
    donate: bool = True,
):
    """ZeRO-3 with the schedule written out, not inferred: per step each
    device ``all_gather``s the full parameters from the shards, computes
    local gradients on its batch shard, ``psum_scatter``s them straight
    back to shard owners (THE reduce-scatter — full gradients never
    persist), and runs the optimizer on its 1/N shard only.  The HLO
    provably contains all-gather + reduce-scatter on every backend
    (asserted in ``tests/test_fsdp.py``), unlike the GSPMD variant.

    ``param_specs`` must shard each leaf over ``axis`` on at most one
    dimension (what :func:`fsdp_specs` produces without ``tp_rules``);
    replicated leaves fall back to a grad ``pmean``.  ``loss_fn`` has the
    :data:`~tpudist.parallel.tensor_parallel.LossFn` contract (mean loss
    over the LOCAL batch shard, aux dict).
    """
    from tpudist.parallel.pipeline import state_specs_like

    state_specs = state_specs_like(state_example, param_specs)
    n = mesh.shape[axis]
    spec_leaves = jax.tree.leaves(
        param_specs, is_leaf=lambda x: isinstance(x, P))
    dims = [_fsdp_sharded_dim(s, axis) for s in spec_leaves]

    def _gathered(params):
        leaves, treedef = jax.tree.flatten(params)
        full = [
            leaf if d is None else lax.all_gather(
                leaf, axis, axis=d, tiled=True)
            for leaf, d in zip(leaves, dims)]
        return jax.tree.unflatten(treedef, full)

    def _step(state, batch):
        shard_rng = jax.random.fold_in(state.rng, lax.axis_index(axis))

        def shard_loss(local_params):
            # gather INSIDE the differentiated function: the transpose of
            # all_gather is reduce-scatter, so backward lands shard-local
            # gradient slices directly — full grads never materialize
            # outside the transient transpose
            full_params = _gathered(local_params)
            loss, aux = loss_fn(full_params, batch, shard_rng)
            return loss, aux

        (loss, aux), grads = jax.value_and_grad(shard_loss, has_aux=True)(
            state.params)
        # all_gather's transpose is psum_scatter: `grads` is already the
        # local shard of the cross-device gradient SUM; divide for the mean
        # (replicated leaves transposed through identity carry only the
        # local contribution and need the explicit mean)
        leaves, treedef = jax.tree.flatten(grads)
        leaves = [
            lax.pmean(g, axis) if d is None else g / n
            for g, d in zip(leaves, dims)]
        grads = jax.tree.unflatten(treedef, leaves)
        new_state = state.apply_gradients(grads)
        metrics = {"loss": lax.pmean(loss, axis),
                   **{k: lax.pmean(v, axis) for k, v in aux.items()}}
        return new_state, metrics

    stepped = jit_sharded_step(
        _step, mesh, (state_specs, P(axis)), (state_specs, P()), donate,
    )

    def train_step(state, *batch):
        return stepped(state, batch)

    train_step.lower = lambda state, *batch: stepped.lower(state, batch)
    train_step.jitted = stepped  # for HLO schedule assertions
    return train_step
