"""FSDP / ZeRO-3: parameters and optimizer state sharded over the data axis.

Absent from the reference (SURVEY.md §2.3: "FSDP / ZeRO — NO"), but a
framework a DDP user (`mnist_ddp_elastic.py:58`) migrates to must offer the
memory-scaled variant of data parallelism.  On TPU it is a *layout*, not a
runtime: shard every parameter leaf across the ``data`` axis and jit — GSPMD
inserts the all-gather before each use and turns the gradient reduction into
a reduce-scatter, which is exactly the ZeRO-3 schedule.  Optimizer state
created from sharded params inherits the sharding, so Adam moments are
sharded too (ZeRO-1/2 come for free).

Composable with tensor parallelism: pass ``tp_rules`` and leaves matching a
TP pattern keep their model-axis sharding while the FSDP axis shards one of
the remaining dims — the standard 2-D (fsdp × model) layout.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

from tpudist.parallel.tensor_parallel import (
    Rules,
    make_spmd_train_step,
    shard_tree,
    spec_tree_from_rules,
)
from tpudist.train.state import TrainState


def _shard_leaf_spec(shape: tuple[int, ...], axis: str, axis_size: int,
                     taken: P | None = None) -> P:
    """Spec sharding the largest not-yet-taken dim divisible by ``axis_size``
    over ``axis``; replicated when nothing divides (e.g. small biases)."""
    base = list(taken) if taken is not None else [None] * len(shape)
    base += [None] * (len(shape) - len(base))
    candidates = [
        (shape[d], d) for d in range(len(shape))
        if base[d] is None and shape[d] % axis_size == 0 and shape[d] >= axis_size
    ]
    if not candidates:
        return P(*base) if taken is not None else P()
    _, dim = max(candidates)
    base[dim] = axis
    return P(*base)


def fsdp_specs(
    params: Any,
    mesh: Mesh,
    axis: str = "data",
    tp_rules: Optional[Rules] = None,
) -> Any:
    """PartitionSpec tree sharding every leaf over ``axis``.

    Each leaf gets its largest ``axis_size``-divisible dimension sharded;
    indivisible leaves replicate.  With ``tp_rules``, leaves matching a rule
    start from that model-axis spec and the FSDP axis takes a remaining dim.
    """
    axis_size = mesh.shape[axis]
    tp_specs = (
        spec_tree_from_rules(params, tp_rules) if tp_rules is not None else None
    )

    def spec_for(leaf, tp_spec):
        taken = tp_spec if tp_spec is not None and tuple(tp_spec) else None
        return _shard_leaf_spec(leaf.shape, axis, axis_size, taken)

    if tp_specs is None:
        return jax.tree.map(lambda leaf: spec_for(leaf, None), params)
    return jax.tree.map(spec_for, params, tp_specs)


def make_fsdp_state(
    model_apply: Callable,
    params: Any,
    tx,
    mesh: Mesh,
    axis: str = "data",
    tp_rules: Optional[Rules] = None,
    rng: jax.Array | int = 0,
) -> tuple[TrainState, Any]:
    """Shard ``params`` FSDP-style and build a TrainState whose optimizer
    state inherits the shardings.  Returns ``(state, param_specs)``."""
    specs = fsdp_specs(params, mesh, axis, tp_rules)
    sharded = shard_tree(params, mesh, specs)
    state = TrainState.create(model_apply, sharded, tx, rng=rng)
    return state, specs


def make_fsdp_train_step(
    loss_fn,
    mesh: Mesh,
    param_specs: Any,
    donate: bool = True,
):
    """ZeRO-3 train step: identical GSPMD program to
    :func:`make_spmd_train_step`; with ``param_specs`` from
    :func:`fsdp_specs` the compiler's partitioning IS the FSDP schedule
    (all-gather params per use, reduce-scatter grads, local optimizer
    update on each shard)."""
    return make_spmd_train_step(loss_fn, mesh, param_specs, donate)
