"""One mesh-axis spec: declarative dp × fsdp × tp × pp × ep composition.

The strategies in this package are building blocks — explicit data
parallelism (:mod:`data_parallel`), GSPMD sharding-rule programs
(:mod:`tensor_parallel`), ZeRO-style parameter sharding (:mod:`fsdp`),
expert sharding (:mod:`expert_parallel`) and the compiled pipeline
schedules (:mod:`pipeline`).  Each is its own entry point, which is how the
reference suite worked and why it composed at most two axes at a time.

:class:`MeshSpec` replaces strategy selection with axis sizes: declare
``MeshSpec(dp=2, fsdp=2, tp=2)``, build ONE :class:`jax.sharding.Mesh`
with the five canonical axes, and :func:`make_composed_train_step` returns
ONE compiled step for that point of the composition space, assembled from
the same building blocks:

* ``dp``    — batch replication; gradients mean over the axis.
* ``fsdp``  — ZeRO parameter/optimizer sharding; :func:`~tpudist.parallel.
  fsdp.fsdp_specs` picks the largest free divisible dim of every leaf.
* ``tp``    — tensor (Megatron) sharding from path-pattern rules
  (``spec.rules``, e.g. :func:`~tpudist.parallel.tensor_parallel.
  transformer_tp_rules` over the ``tp`` axis).
* ``ep``    — expert sharding from the same rule language
  (:func:`~tpudist.parallel.expert_parallel.moe_ep_rules`).
* ``pp``    — pipeline parallelism.  Unlike the other four, ``pp`` is NOT
  expressed as a GSPMD layout: XLA partitions one program in space, while a
  pipeline is a schedule in TIME (fill/drain, 1F1B ordering, bounded
  activation banking).  The ``pp`` mesh axis therefore carries the
  stage-sharded parameter placement, and the compiled schedule tables from
  :mod:`pipeline` (GPipe / 1F1B / interleaved) order the work.

Rule precedence when two axes want the same tensor dimension: ``tp``/``ep``
rules are applied first (first matching rule wins, as in
:func:`spec_tree_from_rules`), then ``fsdp`` shards the largest dimension
the rules left free (see :func:`~tpudist.parallel.fsdp.fsdp_specs`); a leaf
with no free divisible dimension replicates over ``fsdp``.  ``dp`` never
claims a parameter dimension — it only shards the batch.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# NOTE: the per-strategy building blocks (data_parallel / fsdp / pipeline /
# tensor_parallel) are imported lazily inside the functions that assemble
# them: tensor_parallel reaches tpudist.train at import time, and the
# Trainer there composes THIS module — top-level imports would cycle.
Rules = Sequence  # alias of tensor_parallel.Rules, kept import-light here

MESH_AXES = ("dp", "fsdp", "ep", "pp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Axis sizes + sharding rules: the one declarative knob for how a
    model trains.

    ``rules`` are path-pattern → :class:`PartitionSpec` pairs over the
    CANONICAL axis names (``"tp"``, ``"ep"``) — e.g.
    ``transformer_tp_rules("tp")`` or ``moe_ep_rules("ep")`` (concatenate
    for MoE × TP; first match wins).  ``num_microbatches`` /
    ``virtual_stages`` parameterize the pipeline schedule and are ignored
    at ``pp == 1``.

    Distinct from :class:`tpudist.runtime.mesh.MeshSpec` (a generic
    ``{name: size}`` grid builder): this one fixes the five axis names and
    their composition semantics.
    """

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    pp: int = 1
    ep: int = 1
    rules: Rules = ()
    num_microbatches: int = 1
    virtual_stages: int = 1

    def __post_init__(self) -> None:
        for name in MESH_AXES:
            if getattr(self, name) < 1:
                raise ValueError(f"axis {name!r} must be >= 1, got "
                                 f"{getattr(self, name)}")
        if self.num_microbatches < 1 or self.virtual_stages < 1:
            raise ValueError("num_microbatches and virtual_stages must be >= 1")

    @classmethod
    def parse(cls, text: str, **kwargs) -> "MeshSpec":
        """``MeshSpec.parse("dp=2,fsdp=2,tp=2")`` — the CLI spelling."""
        sizes: dict[str, int] = {}
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, val = part.partition("=")
            if name not in MESH_AXES:
                raise ValueError(
                    f"unknown mesh axis {name!r}; valid: {MESH_AXES}")
            sizes[name] = int(val)
        return cls(**sizes, **kwargs)

    def axis_sizes(self) -> dict[str, int]:
        return {name: getattr(self, name) for name in MESH_AXES}

    @property
    def n_devices(self) -> int:
        return math.prod(self.axis_sizes().values())

    @property
    def n_stages(self) -> int:
        """Stacked depth of the pipeline parameter stack (P·V chunks)."""
        return self.pp * self.virtual_stages

    def data_axes(self) -> tuple[str, ...]:
        """Mesh axes the BATCH dimension shards over.  ``ep`` and ``tp``
        shard parameters/experts, not the batch; the pipeline path keeps
        the batch on ``dp`` alone (``fsdp``/``ep`` are rejected with
        ``pp`` anyway)."""
        return ("dp",) if self.pp > 1 else ("dp", "fsdp")

    def batch_spec(self) -> P:
        return P(self.data_axes())

    def build(self, devices: Sequence[jax.Device] | None = None) -> Mesh:
        """One mesh, all five axes (size-1 axes included so every spec
        compiles against the same axis names)."""
        if devices is None:
            devices = jax.devices()[: self.n_devices]
        if len(devices) != self.n_devices:
            raise ValueError(
                f"{self.axis_sizes()} needs {self.n_devices} devices, got "
                f"{len(devices)}")
        import numpy as np

        grid = np.asarray(devices).reshape(
            tuple(self.axis_sizes().values()))
        return Mesh(grid, MESH_AXES)

    def param_specs(self, params: Any) -> Any:
        """PartitionSpec tree for the non-pipeline axes: ``rules`` claim
        dims for ``tp``/``ep`` first, then ``fsdp`` shards the largest
        remaining divisible dim of every leaf (rule precedence above)."""
        from tpudist.parallel.fsdp import fsdp_specs
        from tpudist.parallel.tensor_parallel import spec_tree_from_rules

        if self.fsdp > 1:
            # fsdp_specs merges rule claims before picking its dim; it
            # needs the mesh only for the axis size, which we know.
            mesh = _FakeAxisSize({"fsdp": self.fsdp})
            return fsdp_specs(params, mesh, axis="fsdp",
                              tp_rules=self.rules or None)
        return spec_tree_from_rules(params, self.rules)


class _FakeAxisSize:
    """Duck-typed stand-in for a Mesh where only ``shape[axis]`` is read —
    lets spec derivation run before (or without) building the real mesh."""

    def __init__(self, sizes: dict[str, int]) -> None:
        self.shape = sizes


def shard_composed_batch(batch: Any, mesh: Mesh, spec: MeshSpec) -> Any:
    """Place batch arrays with their leading dim sharded over the spec's
    data axes (``dp×fsdp``, or ``dp`` under pipelining)."""
    sharding = NamedSharding(mesh, spec.batch_spec())
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def _publish_mesh_gauges(spec: MeshSpec, bubble: float) -> None:
    """Telemetry satellite: axis sizes + the composed step's schedule
    bubble (0.0 for non-pipeline specs) into the obs registry."""
    try:
        from tpudist import obs
    except Exception:  # noqa: BLE001 - obs must never block training
        return
    for name, size in spec.axis_sizes().items():
        obs.gauge(f"mesh/axis_size~axis={name}").set(float(size))
    obs.gauge("train/bubble_fraction").set(float(bubble))


def make_composed_train_step(
    spec: MeshSpec,
    mesh: Mesh,
    loss_fn: Callable | None = None,
    *,
    params: Any = None,
    state_example: Any = None,
    block_fn: Callable | None = None,
    stage_loss_fn: Callable | None = None,
    embed_fn: Callable | None = None,
    head_loss_fn: Callable | None = None,
    state_specs: Any = None,
    grad_sync_axes: Any = None,
    schedule: str = "1f1b",
    donate: bool = True,
):
    """ONE compiled ``train_step(state, x, y) -> (state, metrics)`` for any
    point of the dp × fsdp × tp × pp × ep composition space, reusing the
    per-strategy building blocks instead of adding a sixth code path:

    * ``pp == 1``, everything else 1 → the explicit-collective DP step
      (:func:`make_dp_train_step` over the ``dp`` axis) — bitwise the
      single-strategy data-parallel program.
    * ``pp == 1``, any of fsdp/tp/ep > 1 → the GSPMD global program
      (:func:`make_spmd_train_step`) under ``spec.param_specs(params)``:
      the sharding rules ARE the strategy, one jit covers every
      dp×fsdp×tp×ep combination.  Requires ``loss_fn`` (the
      :data:`~tpudist.parallel.tensor_parallel.LossFn` contract) and
      example ``params``.
    * ``pp > 1`` → a compiled pipeline schedule over the ``pp`` axis with
      dp riding along.  ``tp > 1`` (or ``schedule="gpipe"``) selects the
      stacked fill-drain schedule (:func:`make_stacked_pipeline_train_step`
      — pass ``state_specs``/``grad_sync_axes`` for tensor-parallel
      blocks); otherwise the 1F1B/interleaved schedule
      (:func:`make_1f1b_pipeline_train_step`) with
      ``spec.num_microbatches``/``spec.virtual_stages``, including the
      real-model ``embed_fn``/``head_loss_fn`` mode.  Requires
      ``block_fn`` + ``state_example``.  ``fsdp``/``ep`` under ``pp`` are
      rejected with a clear error (stage-sharded params already partition
      the model; composing ZeRO or expert sharding into the schedule is a
      separate project, not a silent mis-sharding).

    Every returned step exposes ``.lower(state, x, y)`` (the cost-probe /
    MFU hook), ``.jitted``, ``.bubble_fraction`` (0.0 off-pipeline), and
    ``.mesh_spec`` — and publishes the ``mesh/axis_size~axis=`` and
    ``train/bubble_fraction`` gauges at build time.
    """
    from tpudist.parallel.data_parallel import make_dp_train_step
    from tpudist.parallel.pipeline import (
        make_1f1b_pipeline_train_step,
        make_stacked_pipeline_train_step,
    )
    from tpudist.parallel.tensor_parallel import make_spmd_train_step

    for name in MESH_AXES:
        if mesh.shape.get(name) != getattr(spec, name):
            raise ValueError(
                f"mesh axis {name!r} is {mesh.shape.get(name)} but spec "
                f"says {getattr(spec, name)}; build the mesh with "
                f"spec.build()")
    if spec.pp == 1:
        if loss_fn is None:
            raise ValueError("pp == 1 composition requires loss_fn "
                             "(params, batch, rng) -> (loss, aux)")
        if spec.fsdp == spec.tp == spec.ep == 1:
            step = make_dp_train_step(loss_fn, mesh, axis="dp",
                                      donate=donate)
            step.param_specs = None
        else:
            if params is None:
                params = getattr(state_example, "params", None)
            if params is None:
                raise ValueError(
                    "fsdp/tp/ep composition needs example params (pass "
                    "params= or state_example=) to derive sharding specs")
            param_specs = spec.param_specs(params)
            step = make_spmd_train_step(loss_fn, mesh, param_specs, donate)
            step.param_specs = param_specs
        step.bubble_fraction = 0.0
    else:
        if spec.fsdp > 1 or spec.ep > 1:
            raise ValueError(
                f"pp={spec.pp} with fsdp={spec.fsdp}/ep={spec.ep} is not "
                "supported: pipeline stages already shard parameters over "
                "the pp axis; combine pp with dp and tp, or drop pp and "
                "use fsdp×tp×ep")
        if block_fn is None or state_example is None:
            raise ValueError(
                "pp > 1 composition requires block_fn and state_example "
                "(stage-stacked params — see pipeline.py)")
        if spec.tp > 1 or schedule == "gpipe":
            if spec.virtual_stages > 1:
                raise ValueError(
                    "virtual_stages > 1 requires the 1f1b schedule "
                    "(tp == 1)")
            step = make_stacked_pipeline_train_step(
                block_fn, stage_loss_fn, mesh, spec.num_microbatches,
                state_example, data_axis="dp", stage_axis="pp",
                donate=donate, state_specs=state_specs,
                grad_sync_axes=grad_sync_axes)
        elif schedule == "1f1b":
            step = make_1f1b_pipeline_train_step(
                block_fn, stage_loss_fn, mesh, spec.num_microbatches,
                state_example, data_axis="dp", stage_axis="pp",
                donate=donate, virtual_stages=spec.virtual_stages,
                embed_fn=embed_fn, head_loss_fn=head_loss_fn)
        else:
            raise ValueError(f"unknown schedule {schedule!r} "
                             "(expected '1f1b' or 'gpipe')")
        step.param_specs = state_specs
    step.mesh_spec = spec
    _publish_mesh_gauges(spec, step.bubble_fraction)
    return step


def make_composed_state(
    model_apply: Callable,
    params: Any,
    tx,
    spec: MeshSpec,
    mesh: Mesh,
    rng: Any = 0,
):
    """Shard ``params`` by ``spec.param_specs`` onto the composed mesh and
    build the TrainState (optimizer state inherits the shardings — the
    :func:`~tpudist.parallel.tensor_parallel.make_tp_state` recipe, driven
    by axis sizes instead of a strategy choice).  Returns
    ``(state, param_specs)``.  Non-pipeline specs only: stage-stacked
    pipeline states are built by the caller (see pipeline.py)."""
    if spec.pp > 1:
        raise ValueError(
            "make_composed_state is for pp == 1 specs; pipeline states are "
            "stage-stacked trees built per-model (see pipeline.py)")
    from tpudist.parallel.tensor_parallel import shard_tree
    from tpudist.train.state import TrainState

    param_specs = spec.param_specs(params)
    sharded = shard_tree(params, mesh, param_specs)
    state = TrainState.create(model_apply, sharded, tx, rng=rng)
    return state, param_specs


def make_composed_eval_step(
    predict_fn: Callable[[Any, tuple], jnp.ndarray],
    mesh: Mesh,
):
    """Masked exact-count evaluation for ANY non-pipeline composition:
    written as a GSPMD global program (like :func:`make_spmd_train_step`),
    so the same jit serves dp, fsdp, tp and ep layouts — the counts are
    global sums, no explicit collective needed.  Contract matches
    :func:`~tpudist.parallel.data_parallel.make_dp_masked_eval_step`:
    ``eval_step(params, *inputs, labels, mask) -> (correct, total)``."""

    def _step(params, batch):
        *inputs, labels, mask = batch
        logits = predict_fn(params, tuple(inputs))
        hit = (jnp.argmax(logits, -1) == labels) & mask
        return (jnp.sum(hit.astype(jnp.int32)),
                jnp.sum(mask.astype(jnp.int32)))

    with mesh:
        stepped = jax.jit(_step)

    def eval_step(params, *batch):
        with mesh:
            return stepped(params, batch)

    eval_step.jitted = stepped
    return eval_step
