"""Composition-matrix bench body for ``bench.py --only train_mesh_compose``.

Runs :func:`~tpudist.parallel.mesh.make_composed_train_step` at ≥ 6 points
of the dp × fsdp × tp × pp × ep space and BITWISE-compares loss + updated
params against the hand-assembled single-strategy entry point for the same
math at the same global batch (same init, same optimizer, same data — only
the axis names and the entry point differ).  Then trains a real multi-stage
:class:`~tpudist.models.transformer.TransformerLM` through the interleaved
1F1B schedule (P=4, M=16, V=4 — the acceptance point) and reports the
schedule bubble fraction, step time and whether the cost probe
(``.lower`` → ``cost_analysis``) produced FLOPs for the composed step
(``mfu_reported``, the ``xla/step_tflops``/``xla/mfu`` feed).

Separate from ``bench.py`` because the matrix needs 8 devices: the parent
bench runs this module as a subprocess with CPU-device forcing when the
host doesn't have them (``python -m tpudist.parallel.mesh_bench --out f
--force-cpu``), or calls :func:`run_all` inline when it does.  Keep module
import free of jax so ``--force-cpu`` can set platform flags first.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _bitwise_equal(a, b) -> tuple[bool, float]:
    """(bitwise_equal, max_abs_diff) over two pytrees of arrays."""
    import jax
    import numpy as np

    la = list(map(np.asarray, jax.tree.leaves(a)))
    lb = list(map(np.asarray, jax.tree.leaves(b)))
    if len(la) != len(lb):
        return False, float("inf")
    exact = all(x.tobytes() == y.tobytes() for x, y in zip(la, lb))
    diff = max((float(np.max(np.abs(x.astype(np.float64) - y))) if x.size
                else 0.0) for x, y in zip(la, lb))
    return exact, diff


def _run(step, state, batch, steps):
    import jax

    metrics = None
    for _ in range(steps):
        state, metrics = step(state, *batch)
    jax.block_until_ready((state, metrics))
    return state, metrics


def _time_step(step, state, batch, iters=3) -> float:
    """Best-of-N wall seconds for one already-compiled step (donate=False
    combos only — state is reused)."""
    import jax

    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        out = step(state, *batch)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best


def _mfu_reported(step, state, batch) -> bool:
    """Satellite 1 contract: the composed step's ``.lower`` delegate must
    yield cost_analysis FLOPs — the Trainer's ``xla/step_tflops``/
    ``xla/mfu`` feed — under ANY axis combination."""
    from tpudist.obs import xla as obs_xla

    lower = getattr(step, "lower", None)
    if lower is None:
        return False
    try:
        return obs_xla.cost_flops(lower(state, *batch)) is not None
    except Exception:  # noqa: BLE001 - probe must not fail the bench
        return False


def _lm_setup(num_layers=1):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpudist.models import TransformerConfig, TransformerLM
    from tpudist.ops.losses import cross_entropy

    cfg = TransformerConfig(vocab_size=32, num_layers=num_layers,
                            num_heads=2, embed_dim=16, max_seq_len=8)
    model = TransformerLM(cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 8)), jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    params = model.init(jax.random.key(0), tokens)["params"]

    def loss_fn(p, batch, rng):
        toks, tgts = batch
        logits = model.apply({"params": p}, toks)
        return cross_entropy(logits.reshape(-1, logits.shape[-1]),
                             tgts.reshape(-1)), {}

    return cfg, model, params, loss_fn, (tokens, targets)


def _gspmd_row(name, spec, ref_axes, ref_specs_fn, ref_data_axes,
               model, params, loss_fn, batch, steps=2):
    """One GSPMD matrix point: composed step vs the single-strategy
    reference assembled from the same building blocks."""
    import jax
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpudist.parallel.mesh import (
        make_composed_state, make_composed_train_step, shard_composed_batch,
    )
    from tpudist.parallel.tensor_parallel import (
        make_spmd_train_step, shard_tree,
    )
    from tpudist.runtime.mesh import make_mesh
    from tpudist.train.state import TrainState

    devs = jax.devices()[: spec.n_devices]
    tx = optax.sgd(0.1)

    ref_mesh = make_mesh(ref_axes, devs)
    ref_specs = ref_specs_fn(ref_mesh)
    ref_state = TrainState.create(
        model.apply, shard_tree(params, ref_mesh, ref_specs), tx)
    ref_step = make_spmd_train_step(loss_fn, ref_mesh, ref_specs,
                                    donate=False)
    ref_batch = jax.tree.map(
        lambda x: jax.device_put(
            x, NamedSharding(ref_mesh, P(ref_data_axes))), batch)
    ref_state, ref_metrics = _run(ref_step, ref_state, ref_batch, steps)

    mesh = spec.build(devs)
    step = make_composed_train_step(spec, mesh, loss_fn, params=params,
                                    donate=False)
    state, _ = make_composed_state(model.apply, params, tx, spec, mesh)
    cbatch = shard_composed_batch(batch, mesh, spec)
    state, metrics = _run(step, state, cbatch, steps)

    exact, diff = _bitwise_equal(
        (metrics["loss"], state.params),
        (ref_metrics["loss"], ref_state.params))
    return {
        "combo": name, "devices": spec.n_devices, "steps": steps,
        "exact_match": exact, "max_abs_diff": diff,
        "loss": float(metrics["loss"]), "ref_loss": float(ref_metrics["loss"]),
        "step_time_ms": round(_time_step(step, state, cbatch) * 1e3, 3),
        "mfu_reported": _mfu_reported(step, state, cbatch),
        "bubble_fraction": step.bubble_fraction,
    }


def _pipeline_rows():
    """dp×pp (1F1B) and dp×pp×tp (stacked schedule + Megatron block)
    composed points vs the direct pipeline entry points."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    from tpudist.parallel.common import id_fwd_psum_bwd, psum_fwd_id_bwd
    from tpudist.parallel.mesh import MeshSpec, make_composed_train_step
    from tpudist.parallel.pipeline import (
        make_1f1b_pipeline_train_step,
        make_stacked_pipeline_train_step,
        state_specs_like,
    )
    from tpudist.runtime.mesh import make_mesh
    from tpudist.train.state import TrainState

    rows = []
    rng = np.random.default_rng(0)
    M, d, ff = 4, 8, 16
    tx = optax.sgd(0.1)

    def mse(out, y):
        return jnp.mean((out - y) ** 2)

    # -- dp2 × pp2: homogeneous tanh blocks through the 1F1B schedule ----
    P_ = 2
    params = {
        "w": jnp.asarray(rng.standard_normal((P_, d, d)) * 0.3, jnp.float32),
        "b": jnp.zeros((P_, d), jnp.float32),
    }

    def block(p, a):
        return jnp.tanh(a @ p["w"] + p["b"])

    x = jnp.asarray(rng.standard_normal((16, d)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((16, d)), jnp.float32)
    batch = (x, y)

    devs = jax.devices()[:4]
    ref_mesh = make_mesh({"data": 2, "stage": P_}, devs)
    ref_state = TrainState.create(None, params, tx)
    ref_step = make_1f1b_pipeline_train_step(
        block, mse, ref_mesh, M, ref_state, donate=False)
    ref_state, ref_metrics = _run(ref_step, ref_state, batch, 2)

    spec = MeshSpec(dp=2, pp=P_, num_microbatches=M)
    mesh = spec.build(devs)
    state = TrainState.create(None, params, tx)
    step = make_composed_train_step(
        spec, mesh, block_fn=block, stage_loss_fn=mse, state_example=state,
        donate=False)
    state, metrics = _run(step, state, batch, 2)
    exact, diff = _bitwise_equal(
        (metrics["loss"], state.params),
        (ref_metrics["loss"], ref_state.params))
    rows.append({
        "combo": "dp2_pp2", "devices": 4, "steps": 2,
        "exact_match": exact, "max_abs_diff": diff,
        "loss": float(metrics["loss"]), "ref_loss": float(ref_metrics["loss"]),
        "step_time_ms": round(_time_step(step, state, batch) * 1e3, 3),
        "mfu_reported": _mfu_reported(step, state, batch),
        "bubble_fraction": step.bubble_fraction,
    })

    # -- dp2 × pp2 × tp2: stacked schedule, Megatron MLP block ------------
    params3 = {
        "up": jnp.asarray(rng.standard_normal((P_, d, ff)) * 0.3,
                          jnp.float32),
        "down": jnp.asarray(rng.standard_normal((P_, ff, d)) * 0.3,
                            jnp.float32),
    }

    def tp_block(axis):
        def fn(p, a):
            a = id_fwd_psum_bwd(a, axis)
            h = jnp.tanh(a @ p["up"])
            return psum_fwd_id_bwd(h @ p["down"], axis)
        return fn

    devs8 = jax.devices()[:8]
    ref_mesh = make_mesh({"data": 2, "stage": P_, "model": 2}, devs8)
    ref_state = TrainState.create(None, params3, tx)
    ref_specs = state_specs_like(
        ref_state, {"up": P("stage", None, "model"),
                    "down": P("stage", "model", None)})
    ref_step = make_stacked_pipeline_train_step(
        tp_block("model"), mse, ref_mesh, M, ref_state,
        state_specs=ref_specs, grad_sync_axes=("model",), donate=False)
    ref_state, ref_metrics = _run(ref_step, ref_state, batch, 2)

    spec = MeshSpec(dp=2, pp=P_, tp=2, num_microbatches=M)
    mesh = spec.build(devs8)
    state = TrainState.create(None, params3, tx)
    specs = state_specs_like(
        state, {"up": P("pp", None, "tp"), "down": P("pp", "tp", None)})
    step = make_composed_train_step(
        spec, mesh, block_fn=tp_block("tp"), stage_loss_fn=mse,
        state_example=state, state_specs=specs, grad_sync_axes=("tp",),
        donate=False)
    state, metrics = _run(step, state, batch, 2)
    exact, diff = _bitwise_equal(
        (metrics["loss"], state.params),
        (ref_metrics["loss"], ref_state.params))
    rows.append({
        "combo": "dp2_pp2_tp2", "devices": 8, "steps": 2,
        "exact_match": exact, "max_abs_diff": diff,
        "loss": float(metrics["loss"]), "ref_loss": float(ref_metrics["loss"]),
        "step_time_ms": round(_time_step(step, state, batch) * 1e3, 3),
        "mfu_reported": _mfu_reported(step, state, batch),
        "bubble_fraction": step.bubble_fraction,
    })
    return rows


def _ep_row():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tpudist.models import MoEConfig, MoETransformerLM, TransformerConfig
    from tpudist.ops.losses import cross_entropy
    from tpudist.parallel.expert_parallel import (
        make_ep_state, make_ep_train_step, moe_ep_rules,
    )
    from tpudist.parallel.mesh import MeshSpec
    from tpudist.runtime.mesh import make_mesh

    cfg = TransformerConfig(vocab_size=32, num_layers=1, num_heads=2,
                            embed_dim=16, max_seq_len=8)
    model = MoETransformerLM(cfg, MoEConfig(num_experts=2, top_k=1,
                                            capacity_factor=4.0))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 32, (8, 8)), jnp.int32)
    params = model.init(jax.random.key(0), tokens)["params"]

    def loss_fn(p, batch, rng):
        (toks,) = batch
        logits, aux = model.apply({"params": p}, toks)
        ce = cross_entropy(logits[:, :-1].reshape(-1, cfg.vocab_size),
                           toks[:, 1:].reshape(-1))
        return ce + aux, {}

    # reference assembles its own state to keep make_ep_state on its
    # native axis name; composed uses the same rules over the "ep" axis
    def ref_specs_fn(ref_mesh):
        from tpudist.parallel.tensor_parallel import spec_tree_from_rules
        return spec_tree_from_rules(params, moe_ep_rules("expert"))

    spec = MeshSpec(dp=2, ep=2, rules=tuple(moe_ep_rules("ep")))
    return _gspmd_row(
        "dp2_ep2", spec, {"data": 2, "expert": 2}, ref_specs_fn, "data",
        model, params, loss_fn, (tokens,))


def run_matrix() -> list:
    """The ≥6-combo composition matrix, bitwise vs references."""
    import numpy as np

    from tpudist.parallel.fsdp import fsdp_specs
    from tpudist.parallel.mesh import MeshSpec
    from tpudist.parallel.tensor_parallel import (
        spec_tree_from_rules, transformer_tp_rules,
    )

    cfg, model, params, loss_fn, batch = _lm_setup()
    rows = []

    rows.append(_gspmd_row(
        "dp2_tp2",
        MeshSpec(dp=2, tp=2, rules=tuple(transformer_tp_rules("tp"))),
        {"data": 2, "model": 2},
        lambda m: spec_tree_from_rules(params, transformer_tp_rules("model")),
        "data", model, params, loss_fn, batch))

    rows.append(_gspmd_row(
        "fsdp2_tp2",
        MeshSpec(fsdp=2, tp=2, rules=tuple(transformer_tp_rules("tp"))),
        {"fsdp": 2, "model": 2},
        lambda m: fsdp_specs(params, m, axis="fsdp",
                             tp_rules=transformer_tp_rules("model")),
        "fsdp", model, params, loss_fn, batch))

    rows.append(_gspmd_row(
        "dp2_fsdp2_tp2",
        MeshSpec(dp=2, fsdp=2, tp=2,
                 rules=tuple(transformer_tp_rules("tp"))),
        {"data": 2, "fsdp": 2, "model": 2},
        lambda m: fsdp_specs(params, m, axis="fsdp",
                             tp_rules=transformer_tp_rules("model")),
        ("data", "fsdp"), model, params, loss_fn, batch))

    rows.extend(_pipeline_rows())
    rows.append(_ep_row())
    return rows


def run_real_lm(n_stages=4, microbatches=16, virtual=4, dp=2, steps=3):
    """The acceptance point: a REAL multi-stage TransformerLM trained
    end-to-end through the interleaved 1F1B schedule — stage-boundary
    activations flowing through the ppermute ring, embedding and head
    gradients riding the extra-params path, bubble measured from the
    schedule that actually executed (P=4/M=16/V=4 → ≤ 0.08)."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tpudist.models import TransformerConfig, TransformerLM
    from tpudist.models.transformer import DecoderBlock
    from tpudist.ops.losses import cross_entropy
    from tpudist.parallel.mesh import MeshSpec, make_composed_train_step
    from tpudist.parallel.pipeline import interleave_params
    from tpudist.train.state import TrainState

    L = n_stages * virtual
    cfg = TransformerConfig(vocab_size=32, num_layers=L, num_heads=2,
                            embed_dim=16, max_seq_len=8)
    seq = cfg.max_seq_len
    rng = np.random.default_rng(0)
    per_shard = microbatches * 2          # micro-batch of 2 sequences
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (per_shard * dp, seq)), jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)

    flat = TransformerLM(cfg).init(
        jax.random.key(0), tokens[:2])["params"]
    stages = jax.tree.map(
        lambda *xs: jnp.stack(xs), *[flat[f"block{i}"] for i in range(L)])
    stages = interleave_params(stages, n_stages, virtual)
    extra = {k: v for k, v in flat.items() if not k.startswith("block")}
    params = {"stages": stages, "extra": extra}

    block_mod = DecoderBlock(cfg)
    ln_f = nn.LayerNorm(name="ln_f")

    def block_fn(p, a):
        return block_mod.apply({"params": p}, a)

    def embed_fn(ex, x_mb):
        a = jnp.take(ex["tok_embed"]["embedding"], x_mb, axis=0)
        pos = jnp.arange(x_mb.shape[1])
        return a + jnp.take(ex["pos_embed"]["embedding"], pos, axis=0)[None]

    def head_loss_fn(ex, out, y_mb):
        h = ln_f.apply({"params": ex["ln_f"]}, out)
        logits = h @ ex["lm_head"]["kernel"]
        return cross_entropy(logits.reshape(-1, cfg.vocab_size),
                             y_mb.reshape(-1))

    spec = MeshSpec(dp=dp, pp=n_stages, num_microbatches=microbatches,
                    virtual_stages=virtual)
    mesh = spec.build(jax.devices()[: spec.n_devices])
    state = TrainState.create(None, params, optax.sgd(0.1))
    step = make_composed_train_step(
        spec, mesh, block_fn=block_fn, embed_fn=embed_fn,
        head_loss_fn=head_loss_fn, state_example=state, donate=True)

    losses = []
    t_first = time.perf_counter()
    for _ in range(steps):
        t0 = time.perf_counter()
        state, metrics = step(state, tokens, targets)
        jax.block_until_ready(metrics)
        losses.append(float(metrics["loss"]))
    compile_plus_first_s = losses and time.perf_counter() - t_first
    mfu = _mfu_reported(step, state, (tokens, targets))
    t0 = time.perf_counter()
    state, metrics = step(state, tokens, targets)
    jax.block_until_ready(metrics)
    steady_ms = (time.perf_counter() - t0) * 1e3
    losses.append(float(metrics["loss"]))
    return {
        "combo": f"real_lm_pp{n_stages}_dp{dp}_1f1b",
        "devices": spec.n_devices, "P": n_stages, "M": microbatches,
        "V": virtual, "layers": L, "steps": len(losses),
        "global_batch": int(tokens.shape[0]),
        "bubble_fraction": round(step.bubble_fraction, 4),
        "schedule_ticks": int(step.schedule.T),
        "loss_first": losses[0], "loss_last": losses[-1],
        "trained": bool(losses[-1] < losses[0])
        and all(np.isfinite(losses)),
        "step_time_ms": round(steady_ms, 3),
        "mfu_reported": mfu,
    }


def run_all() -> list:
    rows = run_matrix()
    rows.append(run_real_lm())
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None,
                    help="write JSON rows here (one per line); else stdout")
    ap.add_argument("--force-cpu", action="store_true",
                    help="force 8 CPU devices before importing jax")
    args = ap.parse_args(argv)
    if args.force_cpu:
        from tpudist.runtime.simulate import force_cpu_devices
        force_cpu_devices(8)
    rows = run_all()
    text = "\n".join(json.dumps(r) for r in rows) + "\n"
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
